"""``horovod.torch``-compatible API on host torch tensors.

A drop-in migration surface for reference users (horovod/torch/__init__.py,
horovod/torch/mpi_ops.py): the same ``init/rank/size``, collective, and
``DistributedOptimizer`` spellings, executed by this framework's eager
engine over its host data plane.  Torch here is the *host* framework — CPU
tensors in, CPU tensors out, zero-copy to numpy both ways; the TPU compute
path remains JAX (a torch CUDA stream has no TPU analog, and torch/XLA
interop is out of scope — reference parity is the goal of this module).

Autograd parity: each collective is a ``torch.autograd.Function`` whose
backward is the reference's (allreduce -> allreduce,
torch/mpi_ops.py:158-171; allgather -> reduce + narrow by rank offsets,
:289-307; broadcast -> reduce-to-root, zero elsewhere, :371-385).
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Any, Iterable, Optional, Tuple, Union

import numpy as np
import torch

from ..basics import (  # noqa: F401  (re-exported API surface)
    cross_rank,
    cross_size,
    gloo_built,
    gloo_enabled,
    init,
    is_homogeneous,
    is_initialized,
    local_rank,
    local_size,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    rank,
    shutdown,
    size,
)
from ..ops import eager
from ..ops.collectives import Adasum, Average, Max, Min, ReduceOp, Sum  # noqa: F401

__all__ = [
    "init", "shutdown", "is_initialized",
    "rank", "size", "local_rank", "local_size", "cross_rank", "cross_size",
    "is_homogeneous",
    "mpi_built", "mpi_enabled", "mpi_threads_supported",
    "gloo_built", "gloo_enabled", "nccl_built",
    "ReduceOp", "Average", "Sum", "Adasum", "Min", "Max",
    "allreduce", "allreduce_", "allreduce_async", "allreduce_async_",
    "allgather", "allgather_async",
    "broadcast", "broadcast_", "broadcast_async", "broadcast_async_",
    "alltoall",
    "poll", "synchronize", "join", "barrier",
    "DistributedOptimizer",
    "broadcast_parameters", "broadcast_optimizer_state", "broadcast_object",
    "Compression",
    "SyncBatchNorm",
]


# ---------------------------------------------------------------------------
# tensor conversion
# ---------------------------------------------------------------------------


def _check_cpu(t: torch.Tensor) -> None:
    if t.device.type != "cpu":
        raise ValueError(
            "horovod_tpu.interop.torch operates on host (CPU) tensors; got "
            f"device {t.device}.  Move the tensor to CPU first — the TPU "
            "compute path is JAX (see horovod_tpu.ops.collectives)."
        )


def _to_np(t: torch.Tensor) -> np.ndarray:
    """Zero-copy handoff, halves included: bf16 is reinterpreted through a
    uint16 view into an ml_dtypes array (numpy has no native bf16), f16 maps
    to np.float16 directly.  The engines are dtype-native — halves cost
    2 B/elt on the wire and accumulate in f32, the analog of the reference's
    custom fp16 MPI op (half.cc:42-78) — so no f32 upcast happens anywhere."""
    _check_cpu(t)
    t = t.detach()
    if t.dtype == torch.bfloat16:
        import ml_dtypes  # noqa: PLC0415

        return t.contiguous().view(torch.uint16).numpy().view(
            ml_dtypes.bfloat16
        )
    return t.numpy()


def _from_np(a: np.ndarray, like: torch.Tensor) -> torch.Tensor:
    a = np.ascontiguousarray(a)
    if like.dtype == torch.bfloat16:
        import ml_dtypes  # noqa: PLC0415

        if a.dtype != np.dtype(ml_dtypes.bfloat16):
            a = a.astype(ml_dtypes.bfloat16)
        out = torch.from_numpy(a.view(np.uint16)).view(torch.bfloat16)
    else:
        out = torch.from_numpy(a)
        if out.dtype != like.dtype:
            out = out.to(like.dtype)
    if out.shape != like.shape and out.numel() == like.numel():
        # the engine's data plane flattens 0-d scalars to shape (1,)
        out = out.reshape(like.shape)
    return out


class _Handle:
    """Async handle: future + optional in-place destination (reference
    HandleManager int handles, horovod/torch/handle_manager.cc)."""

    def __init__(self, future, inplace_into: Optional[torch.Tensor],
                 like: torch.Tensor):
        self.future = future
        self.inplace_into = inplace_into
        self.like = like

    def result(self) -> torch.Tensor:
        out = _from_np(np.asarray(self.future.result()), self.like)
        if self.inplace_into is not None:
            with torch.no_grad():
                self.inplace_into.copy_(out)
            return self.inplace_into
        return out


def poll(handle: _Handle) -> bool:
    """reference: hvd.poll (torch/mpi_ops.py:458-472)."""
    return handle.future.done()


def synchronize(handle: _Handle) -> torch.Tensor:
    """reference: hvd.synchronize (torch/mpi_ops.py:475-491)."""
    return handle.result()


def join() -> int:
    """reference: hvd.join (torch/mpi_ops.py:494-508)."""
    return eager.join()


def barrier() -> None:
    eager.barrier()


# ---------------------------------------------------------------------------
# collectives (async + autograd wrappers)
# ---------------------------------------------------------------------------


def _resolve_op(average, op) -> ReduceOp:
    """Reference signature compatibility (torch/mpi_ops.py:94-129 +
    util.get_average_backwards_compatibility_fun): the 0.19-era positional
    ``average`` bool and the ``op`` enum are both accepted, never both."""
    if average is not None and op is not None:
        raise ValueError(
            "The op parameter supersedes average. Please provide only one "
            "of them."
        )
    if average is not None and not isinstance(average, bool):
        # Loud failure beats silent averaging: code written against an
        # op-second-positional signature (allreduce(t, Sum)) must not have
        # its reduction silently reinterpreted as average=truthy.
        raise TypeError(
            f"average must be a bool, got {average!r}; pass reduction "
            "operations via the op= keyword (op=hvd.Sum / hvd.Adasum / ...)"
        )
    if op is not None:
        return op
    if average is False:
        return Sum
    return Average


def allreduce_async(
    tensor: torch.Tensor,
    average=None,
    name: Optional[str] = None,
    op: Optional[ReduceOp] = None,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
) -> _Handle:
    """reference torch/mpi_ops.py:132-170 (average= and op= spellings)."""
    op = _resolve_op(average, op)
    fut = eager.allreduce_async(
        _to_np(tensor), op, name,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
    )
    return _Handle(fut, None, tensor)


def allreduce_async_(
    tensor: torch.Tensor,
    average=None,
    name: Optional[str] = None,
    op: Optional[ReduceOp] = None,
    **kw,
) -> _Handle:
    """In-place async allreduce: the result lands back in ``tensor``
    (reference allreduce_async_, torch/mpi_ops.py:174-205)."""
    op = _resolve_op(average, op)
    fut = eager.allreduce_async(_to_np(tensor), op, name, **kw)
    return _Handle(fut, tensor, tensor)


class _AllreduceFn(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, op, name, prescale, postscale):
        ctx.op, ctx.prescale, ctx.postscale = op, prescale, postscale
        return synchronize(
            allreduce_async(tensor, op=op, name=name,
                            prescale_factor=prescale,
                            postscale_factor=postscale)
        )

    @staticmethod
    def backward(ctx, grad):
        # reference _AllreduceFunction.backward (torch/mpi_ops.py:158-171):
        # the gradient of an allreduce is the same allreduce of the grads.
        return (
            synchronize(allreduce_async(
                grad.contiguous(), op=ctx.op,
                prescale_factor=ctx.prescale, postscale_factor=ctx.postscale,
            )),
            None, None, None, None,
        )


def allreduce(
    tensor: torch.Tensor,
    average=None,
    name: Optional[str] = None,
    compression=None,
    op: Optional[ReduceOp] = None,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
) -> torch.Tensor:
    """Differentiable blocking allreduce (reference torch/mpi_ops.py:173-231:
    average=/op= spellings plus wire compression)."""
    op = _resolve_op(average, op)
    if compression is None:
        compression = Compression.none
    wire, dctx = compression.compress(tensor)
    if wire.requires_grad:
        out = _AllreduceFn.apply(
            wire, op, name, prescale_factor, postscale_factor
        )
    else:
        out = synchronize(allreduce_async(
            wire, op=op, name=name, prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
        ))
    return compression.decompress(out, dctx)


def allreduce_(tensor: torch.Tensor, average=None,
               name: Optional[str] = None,
               op: Optional[ReduceOp] = None, **kw) -> torch.Tensor:
    """reference torch/mpi_ops.py:234-259."""
    return synchronize(allreduce_async_(tensor, average, name, op, **kw))


def allgather_async(tensor: torch.Tensor, name: Optional[str] = None) -> _Handle:
    return _Handle(eager.allgather_async(_to_np(tensor), name), None, tensor)


class _AllgatherFn(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, name):
        ctx.dim0 = tensor.shape[0] if tensor.ndim else 1
        return synchronize(allgather_async(tensor, name))

    @staticmethod
    def backward(ctx, grad):
        # reference _AllgatherFunction.backward (torch/mpi_ops.py:289-307):
        # reduce the gathered grads, then narrow out this rank's rows.
        # Rank offsets come from allgathering the per-rank dim-0 sizes.
        my_rows = torch.tensor([ctx.dim0], dtype=torch.int64)
        sizes = synchronize(allgather_async(my_rows, None))
        reduced = synchronize(allreduce_async(grad.contiguous(), op=Sum))
        start = int(sizes[:rank()].sum())
        return reduced.narrow(0, start, ctx.dim0), None


def allgather(tensor: torch.Tensor, name: Optional[str] = None) -> torch.Tensor:
    """Differentiable allgather; ragged dim 0 supported (negotiated sizes,
    reference controller.cc:453-518)."""
    if tensor.requires_grad:
        return _AllgatherFn.apply(tensor, name)
    return synchronize(allgather_async(tensor, name))


def broadcast_async(tensor: torch.Tensor, root_rank: int,
                    name: Optional[str] = None) -> _Handle:
    return _Handle(
        eager.broadcast_async(_to_np(tensor), root_rank, name), None, tensor
    )


def broadcast_async_(tensor: torch.Tensor, root_rank: int,
                     name: Optional[str] = None) -> _Handle:
    return _Handle(
        eager.broadcast_async(_to_np(tensor), root_rank, name), tensor, tensor
    )


class _BroadcastFn(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, root_rank, name):
        ctx.root_rank = root_rank
        return synchronize(broadcast_async(tensor, root_rank, name))

    @staticmethod
    def backward(ctx, grad):
        # reference _BroadcastFunction.backward (torch/mpi_ops.py:371-385):
        # sum grads to the root; non-roots contribute and receive zero.
        reduced = synchronize(allreduce_async(grad.contiguous(), op=Sum))
        if rank() != ctx.root_rank:
            reduced = torch.zeros_like(reduced)
        return reduced, None, None


def broadcast(tensor: torch.Tensor, root_rank: int,
              name: Optional[str] = None) -> torch.Tensor:
    if tensor.requires_grad:
        return _BroadcastFn.apply(tensor, root_rank, name)
    return synchronize(broadcast_async(tensor, root_rank, name))


def broadcast_(tensor: torch.Tensor, root_rank: int,
               name: Optional[str] = None) -> torch.Tensor:
    return synchronize(broadcast_async_(tensor, root_rank, name))


def alltoall(tensor: torch.Tensor, name: Optional[str] = None) -> torch.Tensor:
    return _from_np(eager.alltoall(_to_np(tensor), name), tensor)


# ---------------------------------------------------------------------------
# compression (reference horovod/torch/compression.py)
# ---------------------------------------------------------------------------


class _NoneCompressor:
    @staticmethod
    def compress(t):
        return t, t.dtype

    @staticmethod
    def decompress(t, dtype):
        return t


class _FP16Compressor:
    """Cast to fp16 before the wire (reference Compression.fp16)."""

    @staticmethod
    def compress(t):
        if t.dtype in (torch.float32, torch.float64):
            return t.half(), t.dtype
        return t, t.dtype

    @staticmethod
    def decompress(t, dtype):
        return t.to(dtype) if t.dtype != dtype else t


class Compression:
    none = _NoneCompressor
    fp16 = _FP16Compressor


# ---------------------------------------------------------------------------
# DistributedOptimizer (reference horovod/torch/__init__.py:67-222)
# ---------------------------------------------------------------------------


class _DistributedOptimizer:
    """Wraps a torch optimizer: per-parameter hooks fire allreduce as
    gradients accumulate; ``step()`` synchronizes then applies updates.

    Mirrors the reference's grad-accumulator hook design
    (torch/__init__.py:67-222) using torch's post-accumulate-grad hooks,
    including ``backward_passes_per_step`` gradient accumulation
    (:101-126).
    """

    def __init__(self, optimizer: torch.optim.Optimizer,
                 named_parameters=None,
                 compression=Compression.none,
                 backward_passes_per_step: int = 1,
                 op: ReduceOp = Average):
        self._opt = optimizer
        self._compression = compression
        self._op = op
        self.backward_passes_per_step = backward_passes_per_step
        if named_parameters is not None:
            named = list(named_parameters)
        else:
            named = [
                (f"param.{i}.{j}", p)
                for i, group in enumerate(optimizer.param_groups)
                for j, p in enumerate(group["params"])
            ]
        # Duplicate-name guard (reference torch/__init__.py:90-99).
        names = [n for n, _ in named]
        if len(names) != len(set(names)):
            raise ValueError("parameter names must be unique")
        params_in_opt = {
            id(p) for g in optimizer.param_groups for p in g["params"]
        }
        self._names = {
            id(p): n for n, p in named if id(p) in params_in_opt
        }
        self._handles: dict = {}
        self._passes: dict = {}
        self._should_synchronize = True
        self._synchronized = False
        self._hooks = []
        for group in optimizer.param_groups:
            for p in group["params"]:
                if p.requires_grad:
                    self._hooks.append(
                        p.register_post_accumulate_grad_hook(self._make_hook())
                    )

    def _make_hook(self):
        def hook(p: torch.Tensor):
            self._passes[id(p)] = self._passes.get(id(p), 0) + 1
            if self._passes[id(p)] < self.backward_passes_per_step:
                return
            self._passes[id(p)] = 0
            name = self._names.get(id(p), f"grad.{id(p)}")
            wire, dctx = self._compression.compress(p.grad)
            fut = eager.allreduce_async(
                _to_np(wire), self._op, f"allreduce.{name}",
                prescale_factor=1.0 / self.backward_passes_per_step,
            )
            self._handles[id(p)] = (p, fut, dctx)

        return hook

    def synchronize(self) -> None:
        """Wait for all outstanding grad reductions and write them back
        (reference torch/__init__.py:165-215)."""
        for p, fut, dctx in self._handles.values():
            out = _from_np(np.asarray(fut.result()), p.grad)
            out = self._compression.decompress(out, dctx)
            with torch.no_grad():
                p.grad.copy_(out)
        self._handles.clear()
        self._synchronized = True

    @contextmanager
    def skip_synchronize(self):
        """Make the next ``step()`` skip synchronization — the
        synchronize-then-clip-then-step pattern (reference
        torch/__init__.py:184-202):

            optimizer.synchronize()
            torch.nn.utils.clip_grad_norm_(model.parameters(), 1.0)
            with optimizer.skip_synchronize():
                optimizer.step()
        """
        self._should_synchronize = False
        try:
            yield
        finally:
            self._should_synchronize = True

    def step(self, closure=None):
        if self._should_synchronize:
            if self._synchronized:
                import warnings  # noqa: PLC0415

                warnings.warn(
                    "optimizer.step() called without "
                    "optimizer.skip_synchronize() context after "
                    "optimizer.synchronize(). This can cause training "
                    "slowdown. You may want to consider using "
                    "optimizer.skip_synchronize() context if you use "
                    "optimizer.synchronize() in your code."
                )
            self.synchronize()
        self._synchronized = False
        return self._opt.step(closure)

    def zero_grad(self, *a, **kw):
        if self._handles:
            raise AssertionError(
                "zero_grad called with allreduces in flight — call step() "
                "or synchronize() first (reference torch/__init__.py:217-222)"
            )
        return self._opt.zero_grad(*a, **kw)

    def __getattr__(self, item):
        return getattr(self._opt, item)


class _DistributedAdasumOptimizer:
    """Delta-based Adasum optimizer (reference torch/__init__.py:225-393).

    ``op=Adasum`` changes WHAT is reduced, not just HOW: each rank runs the
    wrapped optimizer's update for a parameter locally, and the parameter
    *delta* (``-lr * f(g)``, where f is the optimizer's own logic) is
    Adasum-allreduced; the new state is ``start + reduced_delta``.  The
    Adasum projection then blends update *directions* — its convergence
    story — instead of raw gradients (math comment at the reference's
    torch/__init__.py:293-307).

    Composition over the wrapped optimizer, like ``_DistributedOptimizer``
    above: the single-parameter local step is taken by temporarily
    narrowing the wrapped optimizer's param_groups to that parameter.
    """

    def __init__(self, optimizer: torch.optim.Optimizer,
                 named_parameters=None,
                 compression=Compression.none,
                 backward_passes_per_step: int = 1):
        self._opt = optimizer
        self._compression = compression
        self.backward_passes_per_step = backward_passes_per_step
        if named_parameters is not None:
            named = list(named_parameters)
        else:
            named = [
                (f"param.{i}.{j}", p)
                for i, group in enumerate(optimizer.param_groups)
                for j, p in enumerate(group["params"])
            ]
        names = [n for n, _ in named]
        if len(names) != len(set(names)):
            raise ValueError("parameter names must be unique")
        # Every optimizer parameter must be named: the hooks below fire for
        # all of them, and an unnamed one would have no start buffer and
        # would silently never be reduced (reference raises the same way,
        # torch/__init__.py:255-259).
        named_ids = {id(p) for _, p in named}
        unnamed = [
            p for group in optimizer.param_groups
            for p in group["params"] if id(p) not in named_ids
        ]
        if unnamed:
            raise ValueError(
                "named_parameters was specified, but one or more model "
                "parameters were not named. Python object ids: "
                + ", ".join(str(id(p)) for p in unnamed)
            )
        self._names = {id(p): n for n, p in named}
        # Reference keeps a per-parameter "starting model" buffer the
        # reduced deltas accumulate into (torch/__init__.py:270-273).
        self._start = {
            id(p): torch.zeros_like(p, requires_grad=False)
            for _, p in named
        }
        self._params = {id(p): p for _, p in named}
        self._handles: dict = {}
        self._passes: dict = {}
        self._hooks = []
        for group in optimizer.param_groups:
            for p in group["params"]:
                if p.requires_grad:
                    self._hooks.append(
                        p.register_post_accumulate_grad_hook(
                            self._make_hook()
                        )
                    )

    def _delta_allreduce_async(self, p: torch.Tensor):
        """Local one-parameter step -> delta -> async Adasum reduce."""
        stashed = []
        for group in self._opt.param_groups:
            stashed.append(group["params"])
            group["params"] = [v for v in group["params"] if v is p]
        start = self._start[id(p)]
        with torch.no_grad():
            start.copy_(p)
        self._opt.step()
        for params, group in zip(stashed, self._opt.param_groups):
            group["params"] = params
        with torch.no_grad():
            p.sub_(start)  # p now holds delta = -lr * f(g)
        name = self._names.get(id(p), f"delta.{id(p)}")
        wire, dctx = self._compression.compress(p.detach())
        fut = eager.allreduce_async(_to_np(wire), Adasum, f"adasum.{name}")
        return fut, dctx

    def _make_hook(self):
        def hook(p: torch.Tensor):
            # Reference torch/__init__.py _make_hook: a second reduction
            # for the same parameter before step() would submit a duplicate
            # in-flight tensor name AND snapshot a delta-holding parameter
            # into the start buffer — fail loudly instead.
            if id(p) in self._handles:
                raise AssertionError(
                    "Gradients were computed more than "
                    "backward_passes_per_step times before call to step(). "
                    "Increase backward_passes_per_step to accumulate "
                    "gradients locally."
                )
            self._passes[id(p)] = self._passes.get(id(p), 0) + 1
            if self._passes[id(p)] < self.backward_passes_per_step:
                return
            self._passes[id(p)] = 0
            self._handles[id(p)] = (p, *self._delta_allreduce_async(p))

        return hook

    def set_backward_passes_per_step(self, passes: int) -> None:
        self.backward_passes_per_step = passes
        self._passes.clear()

    def synchronize(self) -> None:
        # The reference's Adasum optimizer completes reductions only in
        # step() (its synchronize is a no-op, torch/__init__.py:355-356):
        # a delta must be applied to start, never written back to .grad.
        pass

    @contextmanager
    def skip_synchronize(self):
        raise AssertionError(
            "Skipping synchronization is not supported when using Adasum "
            "optimizer."
        )
        yield  # pragma: no cover — contextmanager shape (reference :359-361)

    def step(self, closure=None):
        loss = closure() if closure is not None else None
        for pid, p in self._params.items():
            if pid not in self._handles and p.grad is not None:
                self._handles[pid] = (p, *self._delta_allreduce_async(p))
        for pid, (p, fut, dctx) in self._handles.items():
            delta = _from_np(np.asarray(fut.result()), p)
            delta = self._compression.decompress(delta, dctx)
            start = self._start[pid]
            with torch.no_grad():
                start.add_(delta)
                p.copy_(start)
        self._handles.clear()
        # reference resets the per-parameter accumulation countdown in
        # step() (torch/__init__.py:382) so an early step() doesn't leave
        # a partial count behind
        self._passes.clear()
        return loss

    def zero_grad(self, *a, **kw):
        if self._handles:
            raise AssertionError(
                "zero_grad called with Adasum reductions in flight — call "
                "step() first (reference torch/__init__.py:217-222)"
            )
        return self._opt.zero_grad(*a, **kw)

    def __getattr__(self, item):
        return getattr(self._opt, item)


def DistributedOptimizer(optimizer: torch.optim.Optimizer,
                         named_parameters=None,
                         compression=Compression.none,
                         backward_passes_per_step: int = 1,
                         op: ReduceOp = Average):
    """reference: hvd.DistributedOptimizer (torch/__init__.py:396-449).
    ``op=Adasum`` selects the delta-reducing Adasum optimizer, exactly as
    the reference factory does (:443-449)."""
    if op == Adasum:
        return _DistributedAdasumOptimizer(
            optimizer, named_parameters, compression,
            backward_passes_per_step,
        )
    return _DistributedOptimizer(
        optimizer, named_parameters, compression,
        backward_passes_per_step, op,
    )


# ---------------------------------------------------------------------------
# state replication (reference torch/__init__.py:452-648)
# ---------------------------------------------------------------------------


class _SyncBatchNormFn(torch.autograd.Function):
    """Cross-rank batch norm (reference horovod/torch/sync_batch_norm.py:
    forward allreduces sum/sqsum over the global batch; backward allreduces
    sum_dy / sum_dy_xmu, the standard sync-BN gradient)."""

    @staticmethod
    def forward(ctx, x, weight, bias, eps):
        dims = [0] + list(range(2, x.dim()))  # all but channel
        count = torch.tensor(
            [float(np.prod([x.shape[d] for d in dims]))]
        )
        local_sum = x.sum(dims)
        local_sqsum = (x * x).sum(dims)
        total = synchronize(allreduce_async(count, op=Sum))
        gsum = synchronize(allreduce_async(local_sum, op=Sum))
        gsqsum = synchronize(allreduce_async(local_sqsum, op=Sum))
        n = float(total)
        mean = gsum / n
        var = gsqsum / n - mean * mean
        invstd = torch.rsqrt(var + eps)
        shape = [1, -1] + [1] * (x.dim() - 2)
        xhat = (x - mean.reshape(shape)) * invstd.reshape(shape)
        out = xhat * weight.reshape(shape) + bias.reshape(shape)
        ctx.save_for_backward(xhat, weight, invstd)
        ctx.n = n
        ctx.dims = dims
        return out, mean, var

    @staticmethod
    def backward(ctx, grad_out, _gm, _gv):
        xhat, weight, invstd = ctx.saved_tensors
        dims, n = ctx.dims, ctx.n
        shape = [1, -1] + [1] * (grad_out.dim() - 2)
        sum_dy = synchronize(
            allreduce_async(grad_out.sum(dims).contiguous(), op=Sum)
        )
        sum_dy_xhat = synchronize(
            allreduce_async((grad_out * xhat).sum(dims).contiguous(), op=Sum)
        )
        gx = (
            weight.reshape(shape) * invstd.reshape(shape) / n
        ) * (
            n * grad_out
            - sum_dy.reshape(shape)
            - xhat * sum_dy_xhat.reshape(shape)
        )
        # weight/bias grads stay LOCAL (per-rank), exactly like ordinary
        # parameter grads — DistributedOptimizer reduces them.
        gw = (grad_out * xhat).sum(dims)
        gb = grad_out.sum(dims)
        return gx, gw, gb, None


class SyncBatchNorm(torch.nn.modules.batchnorm._BatchNorm):
    """Batch norm synchronized across all ranks (reference
    hvd.SyncBatchNorm, horovod/torch/sync_batch_norm.py).  Statistics are
    computed over the GLOBAL batch via engine allreduces; eval mode and
    worlds of one fall back to the plain local op."""

    def _check_input_dim(self, x):
        if x.dim() < 2:
            raise ValueError(f"expected at least 2D input, got {x.dim()}D")

    def forward(self, x):
        self._check_input_dim(x)
        if not self.training or size() == 1:
            return super().forward(x)
        out, mean, var = _SyncBatchNormFn.apply(
            x, self.weight, self.bias, self.eps
        )
        if self.track_running_stats:
            with torch.no_grad():
                self.num_batches_tracked += 1
                # momentum=None means cumulative moving average
                # (torch._BatchNorm.forward contract, factor
                # 1/num_batches_tracked), not a fixed 0.1.
                if self.momentum is None:
                    m = 1.0 / float(self.num_batches_tracked)
                else:
                    m = self.momentum
                dims = [0] + list(range(2, x.dim()))
                local_n = float(np.prod([x.shape[d] for d in dims]))
                n = local_n * size()
                # torch convention: running_var stores the UNBIASED variance
                # even though normalization uses the biased one.
                unbiased = var * (n / max(n - 1.0, 1.0))
                self.running_mean.mul_(1 - m).add_(mean, alpha=m)
                self.running_var.mul_(1 - m).add_(unbiased, alpha=m)
        return out


def broadcast_parameters(
    params: Union[dict, Iterable[Tuple[str, torch.Tensor]]],
    root_rank: int = 0,
) -> None:
    """In-place broadcast of a state_dict or named_parameters iterable
    (reference torch/__init__.py:452-508)."""
    if isinstance(params, dict):
        items = sorted(params.items())
    else:
        items = list(params)
    handles = []
    for name, p in items:
        if p is None or not isinstance(p, torch.Tensor):
            continue
        handles.append((p, eager.broadcast_async(
            _to_np(p), root_rank, f"broadcast.{name}"
        )))
    for p, fut in handles:
        with torch.no_grad():
            p.copy_(_from_np(np.asarray(fut.result()), p))


def broadcast_optimizer_state(optimizer: torch.optim.Optimizer,
                              root_rank: int = 0) -> None:
    """Broadcast optimizer state in place (reference
    torch/__init__.py:511-605: tensor state broadcast + scalar state via
    object broadcast)."""
    tensors = []
    scalars = {}
    for pid, pstate in optimizer.state_dict().get("state", {}).items():
        for key, val in pstate.items():
            if isinstance(val, torch.Tensor):
                tensors.append((f"opt.{pid}.{key}", val))
            else:
                scalars[(pid, key)] = val
    broadcast_parameters(tensors, root_rank)
    scalars = broadcast_object(scalars, root_rank)
    sd = optimizer.state_dict()
    for (pid, key), val in scalars.items():
        if pid in sd.get("state", {}):
            sd["state"][pid][key] = val
    optimizer.load_state_dict(sd)


def broadcast_object(obj: Any, root_rank: int = 0) -> Any:
    """reference: hvd.broadcast_object (torch/__init__.py:608-648)."""
    from ..optim import broadcast_object as _bo  # noqa: PLC0415

    return _bo(obj, root_rank=root_rank)
