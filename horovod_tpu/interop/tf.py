"""``horovod.tensorflow``-compatible API on host TF tensors.

A drop-in migration surface for reference users
(horovod/tensorflow/__init__.py, horovod/tensorflow/mpi_ops.py): the same
``init/rank/size``, ``allreduce`` (with the IndexedSlices -> allgather
dispatch, reference tensorflow/__init__.py:74-89), ``DistributedOptimizer``
(:266-311 legacy / keras routing :451-470), ``DistributedGradientTape``
(:474-531), and ``broadcast_variables`` (:166-191), executed by this
framework's eager engine over its host data plane.

TensorFlow here is the *host* framework — CPU tensors in, CPU tensors out.
The TPU compute path remains JAX; this module exists so a reference TF
script ports one-to-one.  Collectives are wrapped in ``tf.py_function`` so
they also run from inside ``tf.function`` graphs (the reference's AsyncOp
kernels are graph ops for the same reason, tensorflow/mpi_ops.cc:287-321).

Gradient parity: ``tf.custom_gradient`` wrappers implement the reference's
registered gradients — allreduce -> allreduce (tensorflow/mpi_ops.py
``_allreduce_grad``), allgather -> reduce + slice by rank offsets,
broadcast -> reduce, zero on non-root ranks.
"""

from __future__ import annotations

from typing import Iterable, Optional

import numpy as np
import tensorflow as tf

from ..basics import (  # noqa: F401  (re-exported API surface)
    cross_rank,
    cross_size,
    gloo_built,
    gloo_enabled,
    init,
    is_homogeneous,
    is_initialized,
    local_rank,
    local_size,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    rank,
    shutdown,
    size,
)
from ..ops import eager
from ..ops.collectives import Adasum, Average, Max, Min, ReduceOp, Sum  # noqa: F401

__all__ = [
    "init", "shutdown", "is_initialized",
    "rank", "size", "local_rank", "local_size", "cross_rank", "cross_size",
    "is_homogeneous",
    "mpi_built", "mpi_enabled", "mpi_threads_supported",
    "gloo_built", "gloo_enabled", "nccl_built",
    "ReduceOp", "Average", "Sum", "Adasum", "Min", "Max",
    "allreduce", "allgather", "broadcast", "alltoall",
    "join", "barrier",
    "broadcast_variables", "broadcast_global_variables",
    "BroadcastGlobalVariablesHook",
    "broadcast_object",
    "DistributedOptimizer", "DistributedGradientTape",
    "Compression",
]


# ---------------------------------------------------------------------------
# tensor conversion + compression
# ---------------------------------------------------------------------------

# Halves ride the wire natively: the engines are dtype-native (bf16/f16 at
# 2 B/elt with f32 accumulation — the analog of the reference's custom fp16
# MPI op, half.cc:42-78), and TF's .numpy() yields ml_dtypes arrays the
# engines accept directly, so Compression.fp16 actually halves wire bytes.


class Compression:
    """Gradient compression (reference tensorflow/compression.py:20-74):
    ``none`` passes through, ``fp16`` casts to half for the wire and back
    after the reduction."""

    class none:  # noqa: N801 — reference spelling
        @staticmethod
        def compress(tensor):
            return tensor, None

        @staticmethod
        def decompress(tensor, ctx):
            return tensor

    class fp16:  # noqa: N801
        @staticmethod
        def compress(tensor):
            ctx = tensor.dtype
            if tensor.dtype.is_floating:
                tensor = tf.cast(tensor, tf.float16)
            return tensor, ctx

        @staticmethod
        def decompress(tensor, ctx):
            if ctx is not None and tensor.dtype != ctx:
                tensor = tf.cast(tensor, ctx)
            return tensor


# ---------------------------------------------------------------------------
# core collectives (graph-safe via py_function, custom gradients)
# ---------------------------------------------------------------------------

def _run_collective(fn, tensor: tf.Tensor, out_dtype=None,
                    preserve_shape: bool = True) -> tf.Tensor:
    """Run ``fn(np_array) -> np_array`` as a graph-safe op in the tensor's
    own dtype (halves stay halves on the wire).  Static shapes are restored
    by the caller (py_function erases them); ``preserve_shape`` puts the
    ELEMENT shape right at runtime — the host data plane flattens 0-d
    scalars to shape (1,) (np.ascontiguousarray quirk; the torch frontend
    reshapes via its `like` tensor, _from_np).  Allgather passes False:
    its dim 0 legitimately changes."""
    in_dtype = tensor.dtype
    out_dtype = out_dtype or in_dtype

    def _impl(x):
        xnp = x.numpy()
        out = np.asarray(fn(xnp))
        if (
            preserve_shape
            and out.shape != np.shape(xnp)
            and out.size == np.size(xnp)
        ):
            out = out.reshape(np.shape(xnp))
        return tf.convert_to_tensor(out)

    result = tf.py_function(_impl, [tensor], Tout=in_dtype)
    if out_dtype != in_dtype:
        result = tf.cast(result, out_dtype)
    return result


def _sanitize_name(name: Optional[str], fallback: str = "var") -> str:
    """TF variable name -> engine wire-name component.  One definition for
    every call site: eager and graph Adasum branches MUST produce identical
    keys for the same variable or cross-rank negotiation stalls."""
    return (name or fallback).replace(":", "_").replace("/", "_")


def _allreduce(tensor, name: Optional[str] = None, op: ReduceOp = Sum,
               prescale_factor: float = 1.0, postscale_factor: float = 1.0):
    """Sum-allreduce primitive (reference tensorflow/mpi_ops.py:93-117;
    averaging happens in framework code, tensorflow/__init__.py:76)."""
    tensor = tf.convert_to_tensor(tensor)
    name = name or eager._auto_name("HorovodAllreduce")

    @tf.custom_gradient
    def _fn(x):
        y = _run_collective(
            lambda v: eager.allreduce(
                v, op=op, name=name,
                prescale_factor=prescale_factor,
                postscale_factor=postscale_factor,
            ),
            x,
        )
        y.set_shape(x.shape)

        def grad(dy):
            # reference _allreduce_grad: the gradient of an allreduce is
            # the same allreduce of the gradients.
            return _allreduce(dy, name + "_grad", op,
                              prescale_factor, postscale_factor)

        return y, grad

    return _fn(tensor)


def allgather(tensor, name: Optional[str] = None):
    """Concatenate along dim 0 across ranks; ragged dim 0 supported
    (reference tensorflow/mpi_ops.py:120-142, sizes negotiated by the
    controller)."""
    tensor = tf.convert_to_tensor(tensor)
    name = name or eager._auto_name("HorovodAllgather")

    @tf.custom_gradient
    def _fn(x):
        y = _run_collective(
            lambda v: eager.allgather(v, name=name), x,
            preserve_shape=False,
        )
        y.set_shape([None] + list(x.shape[1:]))
        # Dynamic shape op, not the static x.shape[0]: under tf.function
        # with an unknown batch dim the static value is None.
        d0 = (tf.cast(tf.shape(x)[0], tf.int64)
              if x.shape.rank else tf.constant(1, tf.int64))

        def grad(dy):
            # reference allgather gradient: reduce the gathered grads and
            # slice out this rank's rows by the negotiated offsets.
            sizes = allgather(tf.reshape(d0, [1]), name + "_sizes")
            reduced = _allreduce(dy, name + "_grad", Sum)
            start = tf.reduce_sum(sizes[: rank()])
            trailing = tf.fill([tf.rank(reduced) - 1],
                               tf.constant(-1, tf.int64))
            begin = tf.concat(
                [[start], tf.zeros([tf.rank(reduced) - 1], tf.int64)], 0
            )
            return tf.slice(reduced, begin, tf.concat([[d0], trailing], 0))

        return y, grad

    return _fn(tensor)


def broadcast(tensor, root_rank: int, name: Optional[str] = None):
    """Broadcast from root (reference tensorflow/mpi_ops.py:145-168)."""
    tensor = tf.convert_to_tensor(tensor)
    name = name or eager._auto_name("HorovodBroadcast")

    @tf.custom_gradient
    def _fn(x):
        y = _run_collective(
            lambda v: eager.broadcast(v, root_rank, name=name), x
        )
        y.set_shape(x.shape)

        def grad(dy):
            # reference broadcast gradient: reduce grads to the root,
            # other ranks contribute but receive zero.
            reduced = _allreduce(dy, name + "_grad", Sum)
            if rank() == root_rank:
                return reduced
            return tf.zeros_like(reduced)

        return y, grad

    return _fn(tensor)


def alltoall(tensor, name: Optional[str] = None):
    tensor = tf.convert_to_tensor(tensor)
    y = _run_collective(lambda v: eager.alltoall(v, name=name), tensor)
    y.set_shape([None] + list(tensor.shape[1:]))
    return y


def join() -> int:
    return eager.join()


def barrier() -> None:
    eager.barrier()


# ---------------------------------------------------------------------------
# user-facing allreduce with IndexedSlices dispatch
# ---------------------------------------------------------------------------

def allreduce(tensor, average=None, device_dense="", device_sparse="",
              compression=Compression.none, op=None,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0):
    """Allreduce a tf.Tensor or tf.IndexedSlices (reference
    tensorflow/__init__.py:43-118).  IndexedSlices become an allgather of
    values+indices; ``Average`` is Sum plus a divide in framework code;
    the ``device_*`` arguments are accepted for source compatibility and
    ignored (there is one host data plane)."""
    del device_dense, device_sparse
    if op is None:
        op = Sum if average is False else Average
    true_op = Sum if op == Average else op

    if isinstance(tensor, tf.IndexedSlices):
        if op == Adasum:
            raise NotImplementedError(
                "The Adasum reduction does not currently support sparse "
                "tensors. As a workaround please pass sparse_as_dense=True "
                "to DistributedOptimizer"
            )
        # reference tensorflow/__init__.py:74-89: two allgathers instead
        # of an allreduce on the represented dense tensor.
        values = allgather(tensor.values)
        indices = allgather(tensor.indices)
        if op == Average:
            values = values / tf.cast(size(), values.dtype)
        return tf.IndexedSlices(values, indices,
                                dense_shape=tensor.dense_shape)

    tensor = tf.convert_to_tensor(tensor)
    compressed, ctx = compression.compress(tensor)
    summed = _allreduce(compressed, None, true_op,
                        prescale_factor, postscale_factor)
    summed = compression.decompress(summed, ctx)
    if op == Average:
        return summed / tf.cast(size(), summed.dtype)
    return summed


# ---------------------------------------------------------------------------
# variable broadcast
# ---------------------------------------------------------------------------

def broadcast_variables(variables: Iterable[tf.Variable],
                        root_rank: int = 0) -> None:
    """Assign every variable its root-rank value (reference
    tensorflow/__init__.py:166-191 broadcast_global_variables /
    broadcast_variables)."""
    for i, var in enumerate(variables):
        name = _sanitize_name(getattr(var, "name", None), f"var.{i}")
        value = broadcast(
            tf.convert_to_tensor(var), root_rank, f"broadcast.{name}"
        )
        var.assign(tf.cast(value, var.dtype))


def broadcast_global_variables(root_rank: int = 0) -> None:
    """TF1-compat spelling: broadcast tf.compat.v1 global variables
    (reference tensorflow/__init__.py:129-147)."""
    try:
        variables = tf.compat.v1.global_variables()
    except AttributeError as exc:  # future TF without compat.v1
        raise NotImplementedError(
            "broadcast_global_variables requires tf.compat.v1; use "
            "broadcast_variables(model.variables, root_rank) instead"
        ) from exc
    broadcast_variables(variables, root_rank)


def broadcast_object(obj, root_rank: int = 0):
    """Arbitrary-object broadcast via the shared pickle path (reference
    torch/__init__.py:608-648; the TF frontend reuses it)."""
    from ..optim import broadcast_object as _bo  # noqa: PLC0415

    return _bo(obj, root_rank=root_rank)


try:
    _SessionRunHook = tf.compat.v1.train.SessionRunHook
except AttributeError:  # future TF without compat.v1
    _SessionRunHook = object


class BroadcastGlobalVariablesHook(_SessionRunHook):
    """tf.estimator / MonitoredSession hook that broadcasts all global
    variables from root_rank on session creation (reference
    tensorflow/__init__.py:194-227).  The broadcast itself runs through
    the eager engine when the session starts — the hook is the TF1-era
    scheduling shim around ``broadcast_variables``."""

    def __init__(self, root_rank: int, device: str = ""):
        super().__init__()
        self.root_rank = root_rank
        del device  # one host data plane (accepted for source compat)
        self._variables = None

    def begin(self):
        self._variables = list(tf.compat.v1.global_variables())

    def after_create_session(self, session, coord):
        del coord
        if not self._variables:
            return
        # Read current values through the session (graph mode has no
        # .numpy()), broadcast on the host values — all submitted async
        # first so N variables share negotiation cycles instead of paying
        # N sequential round-trips — and load results back via var.load.
        values = session.run(self._variables)
        from ..ops import eager  # noqa: PLC0415

        futs = [
            eager.broadcast_async(
                np.asarray(value), self.root_rank, f"bghook.{i}"
            )
            for i, value in enumerate(values)
        ]
        for var, value, fut in zip(self._variables, values, futs):
            var.load(
                np.asarray(fut.result()).reshape(value.shape), session
            )


# ---------------------------------------------------------------------------
# optimizers and tapes
# ---------------------------------------------------------------------------

def _make_allreduce_grads_fn(name, compression, sparse_as_dense, op):
    """reference tensorflow/__init__.py:230-251."""

    def _one(g):
        if g is None:
            return None
        if sparse_as_dense and isinstance(g, tf.IndexedSlices):
            g = tf.convert_to_tensor(g)
        return allreduce(g, compression=compression, op=op)

    def allreduce_grads(grads):
        # Preserve the caller's structure: tape.gradient with a single
        # source returns a bare tensor, not a list.
        if isinstance(grads, (list, tuple)):
            return type(grads)(_one(g) for g in grads)
        return _one(grads)

    return allreduce_grads


try:
    _LegacyOptimizer = tf.compat.v1.train.Optimizer
except AttributeError:
    _LegacyOptimizer = None


if _LegacyOptimizer is not None:
    class _DistributedOptimizer(_LegacyOptimizer):
        """Legacy-graph optimizer wrapper: allreduce inside
        compute_gradients (reference tensorflow/__init__.py:266-311)."""

        def __init__(self, optimizer, name=None, use_locking=False,
                     compression=Compression.none, sparse_as_dense=False,
                     op=Average):
            if name is None:
                name = f"Distributed{type(optimizer).__name__}"
            super().__init__(name=name, use_locking=use_locking)
            self._optimizer = optimizer
            self._allreduce_grads = _make_allreduce_grads_fn(
                name, compression, sparse_as_dense, op
            )

        def compute_gradients(self, *args, **kwargs):
            gradients = self._optimizer.compute_gradients(*args, **kwargs)
            if size() > 1:
                grads, variables = zip(*gradients)
                avg_grads = self._allreduce_grads(grads)
                return list(zip(avg_grads, variables))
            return gradients

        def apply_gradients(self, *args, **kwargs):
            return self._optimizer.apply_gradients(*args, **kwargs)

        def get_slot(self, *args, **kwargs):
            return self._optimizer.get_slot(*args, **kwargs)

        def get_slot_names(self, *args, **kwargs):
            return self._optimizer.get_slot_names(*args, **kwargs)

        def variables(self, *args, **kwargs):
            return self._optimizer.variables(*args, **kwargs)


def _make_distributed_keras_class(base_cls, compression=Compression.none,
                                  sparse_as_dense=False, op=Average):
    """Build the ``Distributed<Base>`` Keras optimizer class: allreduce
    inside apply_gradients (reference _keras/__init__.py:20-87 overrides
    gradient aggregation; modern Keras makes apply_gradients the one
    stable seam).  Also used by tf_keras.load_model as the
    ``custom_objects`` entry that deserializes saved wrapped optimizers
    (reference _keras/__init__.py:113-128)."""
    allreduce_grads = _make_allreduce_grads_fn(
        "DistributedKeras", compression, sparse_as_dense, op
    )

    class _DistributedKerasOptimizer(base_cls):
        _hvd_wrapped = True

        def apply_gradients(self, grads_and_vars, *args, **kwargs):
            if size() > 1:
                grads_and_vars = list(grads_and_vars)
                grads = [g for g, _ in grads_and_vars]
                variables = [v for _, v in grads_and_vars]
                grads = allreduce_grads(grads)
                grads_and_vars = list(zip(grads, variables))
            return super().apply_gradients(grads_and_vars, *args, **kwargs)

    _DistributedKerasOptimizer.__name__ = f"Distributed{base_cls.__name__}"
    return _DistributedKerasOptimizer


def _wrap_keras_optimizer(optimizer, compression, sparse_as_dense, op):
    cls = _make_distributed_keras_class(
        optimizer.__class__, compression, sparse_as_dense, op
    )
    return cls.from_config(optimizer.get_config())


def _var_key(v):
    """Hashable identity for tf and Keras-3 variables alike (Keras's
    backend Variable has no .ref())."""
    ref = getattr(v, "ref", None)
    return ref() if callable(ref) else id(v)


def _snapshot_starts(store: dict, variables):
    """Get-or-create the per-variable delta_start buffers (≙ the
    reference's slots) and snapshot the current values into them.  Shared
    by both Adasum wrappers so the slot protocol lives in one place."""
    starts = []
    for v in variables:
        key = _var_key(v)
        if key not in store:
            store[key] = tf.Variable(
                tf.convert_to_tensor(v), trainable=False
            )
        starts.append(store[key])
    for v, s in zip(variables, starts):
        s.assign(v)
    return starts


def _adasum_reduce_deltas(compression, variables, starts):
    """Adasum-allreduce ``var - start`` per variable and set
    ``var = start + reduced`` (the delta exchange of the reference's
    _DistributedAdasumOptimizer, tensorflow/__init__.py:345-360).

    Eager mode submits every delta asynchronously before draining any —
    the engine negotiates/fuses them in the same cycles instead of paying
    N sequential collective latencies.  Under ``tf.function`` tracing the
    tensors are symbolic, so the graph-safe (py_function) allreduce runs
    per variable."""
    if tf.executing_eagerly():
        from ..ops import eager  # noqa: PLC0415

        pending = []
        for i, (v, s) in enumerate(zip(variables, starts)):
            comp, dctx = compression.compress(v - s)
            # Key = index + identity.  The index disambiguates Keras-3's
            # unscoped duplicate names ('kernel', 'bias', 'kernel', ...),
            # which the engine would reject as duplicate in-flight names;
            # the appended variable name keeps cross-rank mispairing
            # DETECTABLE: if ranks filtered different None grads, their
            # name sets differ and negotiation stalls loudly instead of
            # Adasum-reducing unrelated same-shaped deltas silently.
            ident = _sanitize_name(getattr(v, "name", ""))
            fut = eager.allreduce_async(
                comp.numpy(), Adasum, f"adasum.delta.{i}.{ident}"
            )
            pending.append((v, s, comp.dtype, dctx, fut))
        for v, s, wire_dtype, dctx, fut in pending:
            reduced = tf.reshape(
                tf.cast(tf.convert_to_tensor(np.asarray(fut.result())),
                        wire_dtype),
                v.shape,
            )
            s.assign_add(
                tf.cast(compression.decompress(reduced, dctx), s.dtype)
            )
            v.assign(s)
    else:
        for i, (v, s) in enumerate(zip(variables, starts)):
            comp, dctx = compression.compress(v - s)
            # Same explicit index+identity key as the eager branch: without
            # it the graph branch would fall back to per-process auto
            # sequence names, pairing deltas across ranks only by trace
            # order (asymmetric retracing would mispair silently).
            ident = _sanitize_name(getattr(v, "name", ""))
            reduced = _allreduce(comp, f"adasum.delta.{i}.{ident}",
                                 op=Adasum)
            s.assign_add(
                tf.cast(compression.decompress(reduced, dctx), s.dtype)
            )
            v.assign(s)


class _DistributedAdasumOptimizer:
    """Delta-reducing Adasum wrapper for LEGACY optimizers (reference
    tensorflow/__init__.py:313-407).  The reference builds this from TF1
    slot machinery + ``tf.cond``; the TF2-idiomatic shape is imperative:
    snapshot each variable before the wrapped optimizer's update,
    Adasum-allreduce the update *delta*, and set
    ``var = start + reduced_delta``.  Keras optimizers get a real Keras
    subclass instead (``_make_adasum_keras_class``) so ``model.compile``
    keeps working."""

    _hvd_wrapped = True

    def __init__(self, optimizer, compression=Compression.none):
        self._opt = optimizer
        self._compression = compression
        self._starts = {}  # var.ref() -> delta_start variable (≙ slot)

    def compute_gradients(self, *args, **kwargs):
        # deltas (not grads) are reduced — local grads pass through
        return self._opt.compute_gradients(*args, **kwargs)

    def apply_gradients(self, grads_and_vars, *args, **kwargs):
        if not tf.executing_eagerly():
            # The imperative assign/allreduce sequence below would build
            # dangling graph ops a session never fetches — the local update
            # would apply and ranks would silently diverge.  Refuse loudly.
            raise NotImplementedError(
                "op=Adasum with a legacy optimizer requires eager "
                "execution; under TF1 graph sessions wrap a Keras "
                "optimizer instead (the Adasum Keras subclass), or run "
                "the step eagerly."
            )
        gv = [(g, v) for g, v in grads_and_vars if g is not None]
        variables = [v for _, v in gv]
        starts = _snapshot_starts(self._starts, variables)
        result = self._opt.apply_gradients(gv, *args, **kwargs)
        if size() > 1:
            _adasum_reduce_deltas(self._compression, variables, starts)
        return result

    def minimize(self, loss, global_step=None, var_list=None,
                 gate_gradients=None, aggregation_method=None,
                 colocate_gradients_with_ops=False, name=None,
                 grad_loss=None):
        # Explicit with the TF1 signature, so __getattr__ can't route to
        # the inner optimizer's minimize and bypass the delta exchange —
        # and global_step/name actually reach apply_gradients.
        cg_kwargs = dict(
            var_list=var_list,
            aggregation_method=aggregation_method,
            colocate_gradients_with_ops=colocate_gradients_with_ops,
            grad_loss=grad_loss,
        )
        if gate_gradients is not None:
            cg_kwargs["gate_gradients"] = gate_gradients
        grads_and_vars = self._opt.compute_gradients(loss, **cg_kwargs)
        return self.apply_gradients(
            grads_and_vars, global_step=global_step, name=name
        )

    def get_slot(self, *args, **kwargs):
        return self._opt.get_slot(*args, **kwargs)

    def get_slot_names(self, *args, **kwargs):
        return self._opt.get_slot_names(*args, **kwargs)

    def variables(self, *args, **kwargs):
        return self._opt.variables(*args, **kwargs)

    def __getattr__(self, item):
        return getattr(self._opt, item)


def _make_adasum_keras_class(base_cls, compression=Compression.none):
    """``Adasum<Base>``: a real Keras optimizer subclass (so
    ``model.compile`` accepts it) whose ``apply_gradients`` performs the
    delta-Adasum exchange around the base update."""

    class _AdasumKerasOptimizer(base_cls):
        _hvd_wrapped = True

        def apply_gradients(self, grads_and_vars, *args, **kwargs):
            gv = [(g, v) for g, v in grads_and_vars if g is not None]
            variables = [v for _, v in gv]
            if not hasattr(self, "_hvd_starts"):
                self._hvd_starts = {}
            starts = _snapshot_starts(self._hvd_starts, variables)
            result = super().apply_gradients(gv, *args, **kwargs)
            if size() > 1:
                _adasum_reduce_deltas(compression, variables, starts)
            return result

    _AdasumKerasOptimizer.__name__ = f"Adasum{base_cls.__name__}"
    return _AdasumKerasOptimizer


def DistributedOptimizer(optimizer, name=None, use_locking=False,
                         device_dense="", device_sparse="",
                         compression=Compression.none,
                         sparse_as_dense=False, backward_passes_per_step=1,
                         op=Average):
    """Wrap a TF optimizer so gradients are combined across ranks before
    they are applied (reference tensorflow/__init__.py:408-470)."""
    del device_dense, device_sparse
    if backward_passes_per_step > 1:
        raise ValueError(
            "backward_passes_per_step > 1 is not supported by the TF "
            "frontend; accumulate with optax.MultiSteps on the JAX path"
        )
    if op == Adasum:
        # the reference factory likewise diverts Adasum to the
        # delta-reducing optimizer (tensorflow/__init__.py:453-459); a
        # Keras optimizer gets a Keras subclass so model.compile accepts it
        if not (_LegacyOptimizer is not None
                and isinstance(optimizer, _LegacyOptimizer)) and hasattr(
                    optimizer, "get_config"):
            cls = _make_adasum_keras_class(optimizer.__class__, compression)
            return cls.from_config(optimizer.get_config())
        return _DistributedAdasumOptimizer(optimizer, compression)
    if _LegacyOptimizer is not None and isinstance(optimizer,
                                                   _LegacyOptimizer):
        return _DistributedOptimizer(optimizer, name, use_locking,
                                     compression, sparse_as_dense, op)
    if hasattr(optimizer, "apply_gradients") and hasattr(optimizer,
                                                         "get_config"):
        return _wrap_keras_optimizer(optimizer, compression,
                                     sparse_as_dense, op)
    raise ValueError(
        "Provided optimizer doesn't inherit from either legacy TensorFlow "
        f"or Keras optimizer: {optimizer}"
    )


class _DistributedGradientTape(tf.GradientTape):
    """reference tensorflow/__init__.py:474-493."""

    def __init__(self, tape, compression, sparse_as_dense, op,
                 persistent=False, watch_accessed_variables=True):
        super().__init__(persistent=persistent,
                         watch_accessed_variables=watch_accessed_variables)
        self._tape = tape
        self._allreduce_grads = _make_allreduce_grads_fn(
            "DistributedGradientTape", compression, sparse_as_dense, op
        )

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *args):
        return self._tape.__exit__(*args)

    def watch(self, tensor):
        return self._tape.watch(tensor)

    def gradient(self, target, sources, output_gradients=None):
        gradients = self._tape.gradient(target, sources, output_gradients)
        if size() > 1:
            return self._allreduce_grads(gradients)
        return gradients


def DistributedGradientTape(gradtape, device_dense="", device_sparse="",
                            compression=Compression.none,
                            sparse_as_dense=False, op=Average):
    """Wrap a tf.GradientTape so .gradient() returns rank-combined grads
    (reference tensorflow/__init__.py:495-531)."""
    del device_dense, device_sparse
    return _DistributedGradientTape(
        gradtape, compression, sparse_as_dense, op,
        persistent=getattr(gradtape, "_persistent", False),
    )
