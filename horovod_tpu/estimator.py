"""Estimator API — train-on-a-dataset cluster integration.

TPU-native re-design of the reference's Spark Estimator layer
(horovod/spark/keras/estimator.py:532, horovod/spark/torch/estimator.py:449,
horovod/spark/common/{estimator,params,store}.py): an ``Estimator`` is
configured with a model + optimizer + loss and a :class:`~horovod_tpu
.checkpoint.Store`; ``fit(data)`` runs distributed data-parallel training
and returns a :class:`Model` transformer whose ``transform``/``predict``
runs batched inference.  Where the reference ships training into Spark
executors via ``horovod.spark.run``, the TPU build either trains in-process
over the device mesh (``backend="local"``, the jit/SPMD path) or fans out
worker processes through the launcher (``backend="launcher"``, ≙ Spark
tasks; horovod/spark/runner.py:100-189).

Checkpoints and run metadata persist through the Store exactly as the
reference's estimators persist through LocalStore/HDFSStore
(horovod/spark/common/store.py:30-330), so ``Model.load`` can rehydrate a
trained transformer from the store alone.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .checkpoint import (
    Store,
    latest_checkpoint_step,
    restore_checkpoint,
    save_checkpoint,
)

__all__ = ["Estimator", "Model"]


def _default_loss(logits, labels):
    """Softmax cross-entropy on integer labels (the reference estimators
    default to categorical crossentropy for classifiers)."""
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, labels
    ).mean()


def _tree_np(tree):
    return jax.tree_util.tree_map(np.asarray, tree)


class Estimator:
    """Distributed training estimator (reference: KerasEstimator /
    TorchEstimator ctor params, horovod/spark/common/params.py).

    Parameters mirror the reference's EstimatorParams:

    * ``model`` — a flax ``nn.Module``.
    * ``optimizer`` — an optax ``GradientTransformation`` (wrapped in
      ``DistributedOptimizer`` internally, as the reference wraps the user
      optimizer in ``hvd.DistributedOptimizer``).
    * ``loss`` — ``loss(logits, labels) -> scalar``; default softmax
      cross-entropy with integer labels.
    * ``feature_col`` / ``label_col`` — keys into the ``fit`` data dict
      (≙ feature_cols/label_cols DataFrame columns).
    * ``batch_size``, ``epochs``, ``shuffle`` — loop shape.
    * ``store`` / ``run_id`` — where checkpoints + metadata land.
    * ``backend`` — ``"local"`` (in-process SPMD over the mesh) or
      ``"launcher"`` (worker processes through hvdrun).
    * ``np_workers`` — world size for the launcher backend.
    * ``use_cpu`` — force launcher workers onto CPU devices (the test/dev
      topology); leave False to train on the attached accelerators.
    """

    def __init__(
        self,
        model,
        optimizer: optax.GradientTransformation,
        *,
        loss: Optional[Callable] = None,
        feature_col: str = "features",
        label_col: str = "label",
        batch_size: int = 32,
        epochs: int = 1,
        shuffle: bool = True,
        seed: int = 0,
        store: Optional[Store] = None,
        run_id: str = "default",
        backend: str = "local",
        np_workers: Optional[int] = None,
        use_cpu: bool = False,
        timeout: Optional[float] = 600.0,
        checkpoint_every_epochs: int = 1,
        verbose: bool = False,
    ):
        if backend not in ("local", "launcher") and not callable(backend):
            raise ValueError(
                f"unknown backend {backend!r}: expected 'local', 'launcher' "
                "or a horovod_tpu.cluster executor"
            )
        self.model = model
        self.optimizer = optimizer
        self.loss = loss or _default_loss
        self.feature_col = feature_col
        self.label_col = label_col
        self.batch_size = batch_size
        self.epochs = epochs
        self.shuffle = shuffle
        self.seed = seed
        self.store = store
        self.run_id = run_id
        self.backend = backend
        self.np_workers = np_workers
        self.use_cpu = use_cpu
        self.timeout = timeout
        self.checkpoint_every_epochs = checkpoint_every_epochs
        self.verbose = verbose

    # -- fit ---------------------------------------------------------------

    def fit(self, data: Dict[str, np.ndarray]) -> "Model":
        """Train on ``data`` (dict of equally-long arrays) and return the
        fitted :class:`Model` (reference: Estimator.fit(df) -> Model).
        """
        x = np.asarray(data[self.feature_col])
        y = np.asarray(data[self.label_col])
        if len(x) != len(y):
            raise ValueError(
                f"feature/label length mismatch: {len(x)} vs {len(y)}"
            )
        if self.backend == "local":
            params, history = _train_local(self._config(), x, y)
        elif callable(self.backend):
            # Cluster-manager backend: any horovod_tpu.cluster executor
            # (spark_executor, local_executor, or a custom adapter) — the
            # analog of the reference Estimators training inside Spark
            # tasks (spark/keras/estimator.py over horovod.spark.run).
            params, history = _train_cluster(self._config(), x, y)
        else:
            params, history = _train_launcher(self._config(), x, y)
        if self.store is not None:
            meta = {
                "run_id": self.run_id,
                "epochs": self.epochs,
                "batch_size": self.batch_size,
                "history": history,
                "model": type(self.model).__name__,
            }
            self.store.write_metadata(meta, self.run_id)
        return Model(
            self.model,
            params,
            feature_col=self.feature_col,
            history=history,
            store=self.store,
            run_id=self.run_id,
        )

    def _config(self) -> dict:
        return {
            "model": self.model,
            "optimizer": self.optimizer,
            "loss": self.loss,
            "batch_size": self.batch_size,
            "epochs": self.epochs,
            "shuffle": self.shuffle,
            "seed": self.seed,
            # Resolve through the (possibly subclassed) Store here so the
            # training loops and Model.load agree on the layout.
            "ckpt_dir": (
                self.store.checkpoint_dir(self.run_id)
                if self.store is not None
                else None
            ),
            "run_id": self.run_id,
            "np_workers": self.np_workers,
            "backend_executor": self.backend if callable(self.backend) else None,
            "use_cpu": self.use_cpu,
            "timeout": self.timeout,
            "checkpoint_every_epochs": self.checkpoint_every_epochs,
            "verbose": self.verbose,
        }


# ---------------------------------------------------------------------------
# training loops
# ---------------------------------------------------------------------------


def _build_step(model, tx, loss_fn):
    """One SPMD train step: grads -> DistributedOptimizer (psum) -> update."""

    def step(params, opt_state, xb, yb):
        def lf(p):
            logits = model.apply(p, xb)
            return loss_fn(logits, yb)

        loss, grads = jax.value_and_grad(lf)(params)
        from .ops.collectives import allreduce  # noqa: PLC0415

        loss = allreduce(loss)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step


def _epoch_order(n, epoch, seed, shuffle):
    if not shuffle:
        return np.arange(n)
    return np.random.RandomState(seed + epoch).permutation(n)


def _train_local(cfg: dict, x: np.ndarray, y: np.ndarray):
    """In-process SPMD training over the job mesh (the jit path)."""
    from . import basics
    from .optim import DistributedOptimizer, distribute

    basics.init()
    model, loss_fn = cfg["model"], cfg["loss"]
    tx = DistributedOptimizer(cfg["optimizer"])
    n_dev = max(basics.num_devices(), 1)
    # Global batch must split evenly over the mesh (XLA static shapes).
    bs = cfg["batch_size"]
    if bs % n_dev:
        raise ValueError(
            f"batch_size {bs} not divisible by {n_dev} devices"
        )

    rng = jax.random.PRNGKey(cfg["seed"])
    params = model.init(rng, jnp.asarray(x[:1]))
    opt_state = tx.init(params)
    # distribute()'s default specs shard only the last argument; this step
    # shards both x and y, so pass explicit specs.
    from jax.sharding import PartitionSpec as P

    spmd = distribute(
        _build_step(model, tx, loss_fn),
        in_specs=(P(), P(), P(basics.DP_AXIS), P(basics.DP_AXIS)),
        out_specs=(P(), P(), P()),
    )

    n = len(x)
    steps_per_epoch = n // bs
    if steps_per_epoch == 0:
        raise ValueError(f"dataset of {n} rows < batch_size {bs}")
    history = []
    ckpt_dir = cfg["ckpt_dir"]
    for epoch in range(cfg["epochs"]):
        order = _epoch_order(n, epoch, cfg["seed"], cfg["shuffle"])
        losses = []
        for s in range(steps_per_epoch):
            idx = order[s * bs:(s + 1) * bs]
            params, opt_state, loss = spmd(
                params, opt_state, jnp.asarray(x[idx]), jnp.asarray(y[idx])
            )
            losses.append(float(loss))
        history.append({"epoch": epoch, "loss": float(np.mean(losses))})
        if cfg["verbose"]:
            print(f"[estimator] epoch {epoch}: loss {history[-1]['loss']:.4f}")
        if ckpt_dir and _should_checkpoint(epoch, cfg):
            save_checkpoint(ckpt_dir, {"params": params}, step=epoch + 1)
    return _tree_np(params), history


def _should_checkpoint(epoch: int, cfg: dict) -> bool:
    """Cadence epochs plus ALWAYS the final epoch, so the store's latest
    checkpoint matches the params fit() returns."""
    last = epoch + 1 == cfg["epochs"]
    return last or (epoch + 1) % cfg["checkpoint_every_epochs"] == 0


def _launcher_worker(cfg, x, y):
    """Runs inside each launcher process: rank-sharded epochs through the
    eager DistributedOptimizer path (≙ the reference's per-Spark-task
    training fn, horovod/spark/common/backend.py)."""
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    out_params, history = _train_rank_sharded(cfg, x, y)
    hvd.shutdown()
    return _tree_np(out_params), history


def _train_rank_sharded(cfg, x, y):
    """Per-process data-parallel loop used by the launcher backend."""
    import horovod_tpu as hvd
    from .optim import broadcast_parameters

    model, loss_fn = cfg["model"], cfg["loss"]
    tx = cfg["optimizer"]
    rank, size = hvd.rank(), hvd.size()
    bs = cfg["batch_size"]
    if bs % size:
        raise ValueError(
            f"batch_size {bs} not divisible by {size} workers"
        )
    per_rank = bs // size

    rng = jax.random.PRNGKey(cfg["seed"])
    params = model.init(rng, jnp.asarray(x[:1]))
    params = broadcast_parameters(params, root_rank=0)
    opt_state = tx.init(params)

    @jax.jit
    def local_grads(params, xb, yb):
        def lf(p):
            return loss_fn(model.apply(p, xb), yb)

        return jax.value_and_grad(lf)(params)

    n = len(x)
    steps_per_epoch = n // bs
    if steps_per_epoch == 0:
        raise ValueError(f"dataset of {n} rows < batch_size {bs}")
    history = []
    for epoch in range(cfg["epochs"]):
        order = _epoch_order(n, epoch, cfg["seed"], cfg["shuffle"])
        losses = []
        for s in range(steps_per_epoch):
            base = s * bs + rank * per_rank
            idx = order[base:base + per_rank]
            loss, grads = local_grads(
                params, jnp.asarray(x[idx]), jnp.asarray(y[idx])
            )
            # Enqueue the whole gradient pytree (plus the loss) async so
            # the engine fuses the reduces into a few cycles, the same
            # pattern as optim.broadcast_parameters.
            from .ops import eager  # noqa: PLC0415

            leaves, treedef = jax.tree_util.tree_flatten(_tree_np(grads))
            handles = [
                eager.allreduce_async(l, hvd.Average) for l in leaves
            ]
            loss_h = eager.allreduce_async(np.asarray(loss), hvd.Average)
            grads = jax.tree_util.tree_unflatten(
                treedef, [jnp.asarray(eager.synchronize(h)) for h in handles]
            )
            loss = float(eager.synchronize(loss_h))
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            losses.append(loss)
        history.append({"epoch": epoch, "loss": float(np.mean(losses))})
        if cfg["ckpt_dir"] and _should_checkpoint(epoch, cfg):
            save_checkpoint(cfg["ckpt_dir"], {"params": params},
                            step=epoch + 1)
    return params, history


def _train_launcher(cfg: dict, x: np.ndarray, y: np.ndarray):
    from . import run as hvdrun

    np_workers = cfg["np_workers"] or 2
    results = hvdrun.run(
        _launcher_worker, (cfg, x, y), np=np_workers,
        use_cpu=cfg["use_cpu"], timeout=cfg["timeout"],
    )
    return results[0]


def _train_cluster(cfg: dict, x: np.ndarray, y: np.ndarray):
    """Train inside cluster task slots (reference: the Spark estimators
    launching horovod.spark.run over the executors)."""
    from .cluster import run_on_cluster

    executor = cfg["backend_executor"]
    # The executor may close over unpicklable scheduler handles (a
    # SparkContext); the workers never need it.
    worker_cfg = {k: v for k, v in cfg.items() if k != "backend_executor"}
    np_workers = cfg["np_workers"] or 2
    env = {"JAX_PLATFORMS": "cpu"} if cfg["use_cpu"] else {}
    results = run_on_cluster(
        _launcher_worker, (worker_cfg, x, y), num_proc=np_workers,
        executor=executor, job_timeout=cfg["timeout"], env=env,
    )
    return results[0]


# ---------------------------------------------------------------------------
# Model transformer
# ---------------------------------------------------------------------------


class Model:
    """A fitted model transformer (reference: KerasModel/TorchModel —
    Spark Transformers applying the trained net to a DataFrame).

    ``transform(data)`` appends a prediction column; ``predict(batch)``
    returns raw logits; ``save``/``load`` persist through the Store.
    """

    def __init__(
        self,
        model,
        params,
        *,
        feature_col: str = "features",
        output_col: str = "prediction",
        history: Optional[list] = None,
        store: Optional[Store] = None,
        run_id: str = "default",
        batch_size: int = 1024,
    ):
        self.model = model
        self.params = params
        self.feature_col = feature_col
        self.output_col = output_col
        self.history = history or []
        self.store = store
        self.run_id = run_id
        self.batch_size = batch_size
        self._apply = jax.jit(lambda p, xb: model.apply(p, xb))

    def predict(self, batch: np.ndarray) -> np.ndarray:
        """Raw model outputs for one feature batch."""
        return np.asarray(self._apply(self.params, jnp.asarray(batch)))

    def transform(self, data: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        """Batched inference over a data dict; adds ``output_col`` with the
        argmax class (classifier convention of the reference transformers).
        """
        x = np.asarray(data[self.feature_col])
        outs = []
        for s in range(0, len(x), self.batch_size):
            outs.append(self.predict(x[s:s + self.batch_size]))
        logits = np.concatenate(outs) if outs else np.zeros((0,))
        out = dict(data)
        out[self.output_col] = (
            logits.argmax(-1) if logits.ndim > 1 else logits
        )
        out[self.output_col + "_logits"] = logits
        return out

    # -- persistence through the Store ------------------------------------

    def save(self) -> None:
        if self.store is None:
            raise ValueError("Model has no store; pass store= to Estimator")
        ckpt_dir = self.store.checkpoint_dir(self.run_id)
        step = (latest_checkpoint_step(ckpt_dir) or 0) + 1
        save_checkpoint(ckpt_dir, {"params": self.params}, step=step)
        self.store.write_metadata(
            {"run_id": self.run_id, "history": self.history,
             "model": type(self.model).__name__},
            self.run_id,
        )

    @classmethod
    def load(
        cls,
        model,
        store: Store,
        run_id: str = "default",
        *,
        template_params=None,
        feature_col: str = "features",
    ) -> "Model":
        """Rehydrate from the store (reference: Model.load / load_model
        optimizer-rewrap pattern, horovod/spark/common/estimator.py).

        ``template_params`` is required: a pytree with the checkpoint's
        structure and dtypes, typically ``model.init(rng, example_batch)``.
        """
        ckpt_dir = store.checkpoint_dir(run_id)
        step = latest_checkpoint_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {ckpt_dir}")
        if template_params is None:
            raise ValueError(
                "Model.load requires template_params (a pytree with the "
                "checkpoint's structure, e.g. model.init(rng, example))"
            )
        state = restore_checkpoint(
            ckpt_dir, {"params": template_params}, step=step,
            broadcast=False,
        )
        meta = store.read_metadata(run_id) or {}
        return cls(
            model,
            state["params"],
            feature_col=feature_col,
            history=meta.get("history", []),
            store=store,
            run_id=run_id,
        )
