"""``[tool.hvdtpu-lint]`` configuration from pyproject.toml.

Python 3.11 ships ``tomllib``; this repo supports 3.10, and the linter
must not grow a TOML dependency the container doesn't have — so when
``tomllib`` is unavailable we fall back to a tiny parser that handles
exactly the subset our own config block uses (string and string-list
values under one ``[table]`` header).  Anything fancier in that block
is a configuration error, reported as such.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import List, Optional

TABLE = "tool.hvdtpu-lint"


@dataclass
class LintConfig:
    paths: List[str] = field(default_factory=lambda: [
        "horovod_tpu", "examples", "scripts"
    ])
    baseline: Optional[str] = "horovod_tpu/analysis/baseline.json"
    exclude: List[str] = field(default_factory=list)
    # Per-file analysis cache (content-hash keyed module findings +
    # taint summaries).  ``cache = ""`` in pyproject disables it.
    cache: Optional[str] = ".hvdtpu-lint-cache.json"


def find_pyproject(start: str) -> Optional[str]:
    d = os.path.abspath(start)
    while True:
        cand = os.path.join(d, "pyproject.toml")
        if os.path.isfile(cand):
            return cand
        parent = os.path.dirname(d)
        if parent == d:
            return None
        d = parent


def load_config(root: str) -> LintConfig:
    path = find_pyproject(root)
    cfg = LintConfig()
    if path is None:
        return cfg
    table = _read_table(path, TABLE)
    if table is None:
        return cfg
    if "paths" in table:
        cfg.paths = list(table["paths"])
    if "baseline" in table:
        cfg.baseline = table["baseline"] or None
    if "exclude" in table:
        cfg.exclude = list(table["exclude"])
    if "cache" in table:
        cfg.cache = table["cache"] or None
    return cfg


def _read_table(path: str, name: str) -> Optional[dict]:
    try:
        import tomllib  # noqa: PLC0415

        with open(path, "rb") as f:
            doc = tomllib.load(f)
        node = doc
        for part in _split_table_name(name):
            if not isinstance(node, dict) or part not in node:
                return None
            node = node[part]
        return node if isinstance(node, dict) else None
    except ModuleNotFoundError:
        return _read_table_fallback(path, name)


def _split_table_name(name: str) -> List[str]:
    # tool.hvdtpu-lint -> ["tool", "hvdtpu-lint"] (quoted keys ignored:
    # our table name has none)
    return name.split(".")


_KV_RE = re.compile(r"^\s*([A-Za-z0-9_-]+)\s*=\s*(.+?)\s*$")


def _read_table_fallback(path: str, name: str) -> Optional[dict]:
    """TOML-subset reader: one [header] with string / string-list
    values; quoted with double quotes; lists may span lines."""
    with open(path, "r", encoding="utf-8") as f:
        lines = f.read().splitlines()
    in_table = False
    out: dict = {}
    buf = ""
    key: Optional[str] = None
    for raw in lines:
        line = raw.strip()
        if line.startswith("["):
            if key is not None:
                raise ValueError(
                    f"{path}: unterminated list for {key!r} in [{name}]"
                )
            in_table = line == f"[{name}]"
            continue
        if not in_table or not line or line.startswith("#"):
            continue
        line = _strip_comment(line)
        if not line:
            continue
        if key is not None:  # continuing a multi-line list
            buf += " " + line
            if _balanced(buf):
                out[key] = _parse_value(buf, path, key)
                key, buf = None, ""
            continue
        m = _KV_RE.match(line)
        if not m:
            raise ValueError(f"{path}: unparseable line in [{name}]: "
                             f"{raw!r}")
        k, v = m.group(1), m.group(2)
        if v.startswith("[") and not _balanced(v):
            key, buf = k, v
        else:
            out[k] = _parse_value(v, path, k)
    return out or None


def _balanced(s: str) -> bool:
    return s.count("[") == s.count("]")


def _strip_comment(v: str) -> str:
    """Drop a trailing `# ...` that sits outside double quotes."""
    in_str = False
    for i, ch in enumerate(v):
        if ch == '"':
            in_str = not in_str
        elif ch == "#" and not in_str:
            return v[:i].rstrip()
    return v


def _parse_value(v: str, path: str, key: str):
    v = v.strip()
    if v.startswith("["):
        inner = v[1:-1] if v.endswith("]") else v[1:]
        items = [p.strip() for p in inner.split(",")]
        return [_unquote(p, path, key) for p in items if p]
    return _unquote(v, path, key)


def _unquote(v: str, path: str, key: str) -> str:
    v = v.strip()
    if len(v) >= 2 and v[0] == '"' and v[-1] == '"':
        return v[1:-1]
    raise ValueError(
        f"{path}: [{TABLE}] {key} = {v!r}: only double-quoted strings "
        f"and lists of them are supported"
    )
