"""Mesh-aware SPMD rules (HVD010–HVD013).

PR 8's (slice, host, chip) mesh, PR 9's bucket collectives, and PR 10's
serving plane all run collectives over *named axis subgroups*.  Rank
divergence **within** one of those groups is the same deadlock class
HVD001 rejects for the world — but judging it takes the mesh model
(which axis does this taint vary along? which group does this
collective synchronize?) and the interprocedural taint engine (the
rank read and the collective are rarely in the same function anymore).

Rules here:

* **HVD010** — collective over axis A reachable only under control
  flow tainted with scope S where S diverges within an A-group.
  Interprocedural: the taint may arrive through arguments or returned
  values across several call frames; findings carry the call chain.
* **HVD011** — one collective call site whose axis-name argument can
  evaluate to different axis sets (ternary / boolean selection /
  conflicting assignments): ranks disagreeing about the selector
  submit collectives over *different groups* and both sides hang.
* **HVD012** — impure inputs (clock, random, unordered set iteration,
  rank reads) inside or flowing into a function bound by a determinism
  contract (the serve scheduler's purity invariant, the trace sampler,
  or any ``# hvdtpu: deterministic`` annotation).
* **HVD013** — rank taint reaching a trace/sampling decision: span
  emission guarded by rank-divergent state, or a rank-derived value in
  ``sampled(...)`` arguments (the PR-11 contract: a sampled request's
  spans exist on ALL ranks or NONE).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from . import astutil, lockgraph, meshmodel, taint
from .core import ModuleModel, SEV_ERROR, SEV_WARNING, Finding
from .registry import make_finding, rule

# ---------------------------------------------------------------------------
# shared project analysis (HVD010 + HVD012 both need the closed graph;
# build it once per analyze_paths() model set)
# ---------------------------------------------------------------------------

# Keyed by the model-list object itself (the stored reference keeps the
# list alive, so an id() collision with a dead list is impossible).
_PROJECT_MEMO: List[Tuple[List[ModuleModel], taint.ProjectTaint]] = []
_PROJECT_MEMO_MAX = 2


def _project(models: List[ModuleModel]) -> taint.ProjectTaint:
    for held, pt in _PROJECT_MEMO:
        if held is models:
            return pt
    # Reuse the concurrency family's closed call graph — building one
    # re-indexes every function in every file, the priciest pass.
    pt = taint.ProjectTaint(models, graph=lockgraph.shared_callgraph(models))
    _PROJECT_MEMO.append((models, pt))
    del _PROJECT_MEMO[:-_PROJECT_MEMO_MAX]
    return pt


def _model_by_relpath(models: List[ModuleModel]
                      ) -> Dict[str, ModuleModel]:
    return {m.relpath: m for m in models}


# ---------------------------------------------------------------------------
# HVD010 — axis-scoped taint guards a collective over that axis
# ---------------------------------------------------------------------------


def _fmt_axes(axes: List[str]) -> str:
    return "/".join(sorted(set(axes)))


@rule("HVD010", "subgroup-divergent-collective", SEV_ERROR,
      "collective over axis A guarded by rank taint scoped to A "
      "(interprocedural)", scope="project")
def hvd010(models: List[ModuleModel]) -> List[Finding]:
    """A collective whose submission is conditional on a value that
    differs *within the collective's own group* deadlocks that group:
    some members submit, the rest never arrive.  The mesh-aware part is
    the scope judgement — ``cross_rank()`` taint is uniform inside a
    LOCAL_AXIS group (safe) and divergent inside a CROSS_AXIS one
    (fatal) — and the taint engine part is that the rank read, the
    branch, and the collective may live in three different functions.

    Minimal failing example::

        def reduce_part(flag, x):
            if flag == 0:                    # caller passed rank taint
                return lax.psum(x, "hvd_local")
            return x

        def step(x):
            return reduce_part(hvd.local_rank(), x)   # taints `flag`

    Fix: hoist the collective out of the tainted branch (every group
    member submits; branch on the rank around *uses* of the result), or
    derive the condition from group-uniform state (a broadcast/allreduce
    result, ``size()`` probes).  A world allreduce/broadcast of the
    value launders the taint — its result is identical everywhere."""
    pt = _project(models)
    by_rel = _model_by_relpath(models)
    out: List[Finding] = []
    seen: Set[Tuple] = set()
    for d in taint.divergent_collectives(pt):
        model = by_rel.get(d.module)
        if model is None:
            continue
        if d.direct and d.eager_world \
                and d.axes == [meshmodel.WORLD]:
            # A same-function rank guard around an eager world
            # collective is HVD001's exact territory — one finding per
            # defect.
            continue
        key = (d.module, d.line, d.scope, d.chain, d.via_param)
        if key in seen:
            continue
        seen.add(key)
        axes = _fmt_axes(d.axes)
        if d.via_param is not None:
            chain = " -> ".join(d.chain)
            msg = (
                f"collective '{d.name}' over axis {axes!r} (line "
                f"{d.line}) is guarded (line {d.guard_line}) by "
                f"parameter {d.via_param!r}, which receives "
                f"{d.scope!r}-scoped rank taint ({d.witness}) via "
                f"{chain}: members of the same {axes} group disagree "
                f"about submitting and the group deadlocks"
            )
        elif d.chain:
            chain = " -> ".join(d.chain)
            msg = (
                f"collective '{d.name}' over axis {axes!r} is guarded "
                f"(line {d.guard_line}) by a value carrying "
                f"{d.scope!r}-scoped rank taint from {d.witness} "
                f"(through {chain}): the guard differs within the "
                f"{axes} group and the group deadlocks"
            )
        else:
            msg = (
                f"collective '{d.name}' over axis {axes!r} is guarded "
                f"(line {d.guard_line}) by {d.witness}, whose "
                f"{d.scope!r}-scoped value differs within the {axes} "
                f"group: members disagree about submitting and the "
                f"group deadlocks"
            )
        out.append(make_finding(
            "HVD010", model, d.line, d.col, msg, d.function,
        ))
    return out


# ---------------------------------------------------------------------------
# HVD011 — one call site, several possible axis sets
# ---------------------------------------------------------------------------


def _axis_expr_of(node: ast.Call,
                  model: ModuleModel) -> Optional[ast.expr]:
    """The axis-name argument expression of a recognized collective."""
    if meshmodel.collective_axes(node, model) is None:
        return None
    for kw in node.keywords:
        if kw.arg == "axis_name":
            return kw.value
    name = astutil.call_name(node)
    if name in meshmodel._LAX_COLLECTIVES and len(node.args) >= 2:
        return node.args[1]
    return None


def _selector_variants(expr: ast.expr) -> List[List[str]]:
    """Axis-token alternatives an axis expression can evaluate to.
    Returns >1 entries only for genuine runtime selection (ternary,
    ``or``-chains) — a tuple of axes is ONE hierarchical group spec,
    not a choice."""
    if isinstance(expr, ast.IfExp):
        return (_selector_variants(expr.body)
                + _selector_variants(expr.orelse))
    if isinstance(expr, ast.BoolOp):
        out: List[List[str]] = []
        for v in expr.values:
            out.extend(_selector_variants(v))
        return out
    return [meshmodel.axis_tokens(expr)]


@rule("HVD011", "mismatched-collective-axes", SEV_ERROR,
      "collective whose axis-name argument can denote different axis "
      "sets on the same dataflow path")
def hvd011(model: ModuleModel) -> List[Finding]:
    """A collective whose axis-name argument is *selected* at runtime
    (ternary, ``or`` fallback, or a variable assigned different axis
    constants on different paths) submits over different groups
    depending on the selector.  If ranks can disagree about the
    selector, one subset synchronizes the LOCAL group while another
    synchronizes CROSS — neither completes.  Even rank-uniform
    selection deserves a look: the two schedules compile differently
    and the artifact gate (docs/analysis.md, HLO workflow) will flag
    the divergence per config anyway.

    Minimal failing example::

        axis = "hvd_local" if fast_path else "hvd_cross"
        lax.psum(x, axis)        # two possible groups, one call site

    Fix: make the axis set a static property of the call site — two
    explicit branches each calling with a literal axis (HVD003/HVD010
    then judge the branch condition), or one hierarchical spec
    (``("hvd_local", "hvd_cross")`` is a single group, not a choice)."""
    out: List[Finding] = []
    fmap = astutil.enclosing_function_map(model)
    # (enclosing function, name) -> distinct axis-token sets assigned
    # there.  Scoped per function: two unrelated helpers each binding a
    # constant `axis = ...` of their own are two single-axis call
    # sites, not one divergent selector.
    assigned: Dict[Tuple[str, str],
                   List[Tuple[int, Tuple[str, ...]]]] = {}
    for node in ast.walk(model.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            # A ternary/or-chain on the right-hand side contributes one
            # token set PER alternative — `axis = A if fast else B` is
            # already two groups at the assignment.
            scope_key = (fmap.get(node.lineno, ""),
                         node.targets[0].id)
            for variant in _selector_variants(node.value):
                toks = _variant_tokens(variant)
                if toks is not None:
                    assigned.setdefault(scope_key, []).append(
                        (node.lineno, toks)
                    )
    for node in ast.walk(model.tree):
        if not isinstance(node, ast.Call):
            continue
        expr = _axis_expr_of(node, model)
        if expr is None:
            continue
        variants = _selector_variants(expr)
        token_sets = {tuple(sorted(set(v))) for v in variants}
        token_sets.discard((meshmodel.UNKNOWN_AXIS,))
        where = "selected inline"
        if len(token_sets) <= 1 and isinstance(expr, ast.Name):
            sites = assigned.get(
                (fmap.get(node.lineno, ""), expr.id), [])
            distinct = {t for _, t in sites}
            if len(distinct) > 1:
                token_sets = distinct
                lines = ", ".join(str(ln) for ln, _ in sites)
                where = f"assigned at lines {lines}"
        if len(token_sets) <= 1:
            continue
        name = astutil.call_name(node)
        pretty = " vs ".join(
            "/".join(t) or "?" for t in sorted(token_sets)
        )
        out.append(make_finding(
            "HVD011", model, node.lineno, node.col_offset,
            f"collective '{name}' has axis-name alternatives "
            f"({pretty}, {where}): ranks disagreeing about the "
            f"selector synchronize different groups and neither "
            f"completes — make the axis set static at this call site",
            astutil.context_for_line(model, node.lineno, fmap),
        ))
    return out


def _variant_tokens(toks: List[str]) -> Optional[Tuple[str, ...]]:
    if all(t == meshmodel.UNKNOWN_AXIS for t in toks):
        return None
    return tuple(sorted(set(toks)))


# ---------------------------------------------------------------------------
# HVD012 — impurity inside/into a deterministic contract
# ---------------------------------------------------------------------------


def _unordered_iter_reason(it: ast.expr) -> Optional[str]:
    """Iteration orders that differ across *processes* (PYTHONHASHSEED
    hash order, environment): poison for a deterministic scheduler.
    Dict views are exempt — insertion order is deterministic given the
    same input sequence, which is exactly what the contract demands."""
    if isinstance(it, ast.Set):
        return "a set literal"
    if isinstance(it, ast.Call):
        name = astutil.call_name(it)
        if name in ("set", "frozenset"):
            return f"a {name}() value"
        if name in ("vars", "globals", "locals"):
            return f"{name}()"
    if isinstance(it, ast.Attribute) and it.attr == "environ":
        return "os.environ"
    return None


def _direct_impurities(info: astutil.FunctionInfo,
                       model: ModuleModel) -> List[Tuple[str, int]]:
    out: List[Tuple[str, int]] = []
    for call in astutil.own_calls(info.node):
        why = meshmodel.impurity_of_call(call, model)
        if why is not None:
            out.append((why, call.lineno))
    for node in _own_stmts(info.node):
        if isinstance(node, (ast.For, ast.AsyncFor)):
            reason = _unordered_iter_reason(node.iter)
            if reason is not None:
                out.append((f"iteration over {reason} "
                            f"(hash-order differs per process)",
                            node.lineno))
    return out


def _own_stmts(func: ast.AST) -> List[ast.AST]:
    out: List[ast.AST] = []
    stack: List[ast.AST] = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out


@rule("HVD012", "impure-deterministic-contract", SEV_ERROR,
      "clock/random/hash-order/rank input reaches a function bound by "
      "a determinism contract", scope="project")
def hvd012(models: List[ModuleModel]) -> List[Finding]:
    """Functions under a determinism contract — the serve scheduler
    (every rank must derive the identical admit/evict schedule from the
    same inputs), the trace sampler, anything marked ``# hvdtpu:
    deterministic`` — may compute only from their inputs.  A clock
    read, ``random``, set iteration (hash order differs per process),
    or a rank read anywhere in their call tree makes two ranks derive
    different schedules from identical inputs: the serving HVD001
    deadlock, entering through the side door.

    Minimal failing example::

        # hvdtpu: deterministic
        def pick_slot(queue, slots):
            return random.choice(slots)      # per-process RNG: diverges

    Fix: move the impurity to the caller and pass its result in as data
    (one rank decides, the broadcast schedule carries the decision), or
    derive it deterministically from the inputs (hash of the request
    id).  Iteration: sort before iterating."""
    pt = _project(models)
    graph = pt.graph
    out: List[Finding] = []
    by_rel = _model_by_relpath(models)

    # Contract surface first: impurity only matters where a contract
    # can reach it, so the closure explores forward from the contract
    # functions instead of fixpointing the whole graph (a whole-repo
    # fixpoint was ~half the project-rule wall clock for a handful of
    # contract functions).
    contract_keys: Set[Tuple[str, str]] = set()
    contract_lines: Dict[Tuple[str, str], int] = {}
    for model in models:
        for qn, def_line in meshmodel.contract_functions(model).items():
            contract_keys.add((model.relpath, qn))
            contract_lines[(model.relpath, qn)] = def_line

    impurity_memo: Dict[Tuple[str, str], List[Tuple[str, int]]] = {}

    def impurities_of(key: Tuple[str, str]) -> List[Tuple[str, int]]:
        hit = impurity_memo.get(key)
        if hit is None:
            info = graph.funcs.get(key)
            model = by_rel.get(key[0])
            hit = _direct_impurities(info, model) \
                if info is not None and model is not None else []
            impurity_memo[key] = hit
        return hit

    _MAX_CONTRACT_DEPTH = 6
    for ckey in sorted(contract_keys):
        model = by_rel.get(ckey[0])
        if model is None or ckey not in graph.funcs:
            continue
        qn = ckey[1]
        for what, line in impurities_of(ckey):
            out.append(make_finding(
                "HVD012", model, line, 0,
                f"{what} inside {qn}(), which is bound by a "
                f"determinism contract: its output must be a pure "
                f"function of its inputs on every rank — hoist the "
                f"impurity to the caller and pass the result in",
                qn,
            ))
        # BFS over callees: an impure helper anywhere in the contract
        # function's call tree is the same defect one hop removed.
        seen: Set[Tuple[str, str]] = {ckey}
        frontier: List[Tuple[str, str]] = [ckey]
        depth = 0
        while frontier and depth < _MAX_CONTRACT_DEPTH:
            depth += 1
            nxt: List[Tuple[str, str]] = []
            for key in frontier:
                info = graph.funcs.get(key)
                if info is None:
                    continue
                for call in info.calls:
                    for callee in graph.resolve(key, call):
                        if callee in seen:
                            continue
                        seen.add(callee)
                        nxt.append(callee)
                        for what, _ln in impurities_of(callee):
                            out.append(make_finding(
                                "HVD012", model,
                                contract_lines.get(ckey, 1), 0,
                                f"{qn}() is bound by a determinism "
                                f"contract but reaches {what} via "
                                f"{callee[1]}() [{callee[0]}]: two "
                                f"ranks can derive different schedules "
                                f"from identical inputs",
                                qn,
                            ))
            frontier = nxt

    # Call-site injection: an impure expression passed INTO a contract
    # function is the same defect seen from the caller.
    if contract_keys:
        for key, info in graph.funcs.items():
            model = by_rel.get(key[0])
            if model is None:
                continue
            fmap = None
            for call in astutil.own_calls(info.node):
                desc = astutil.call_descriptor(call, info.type_env)
                targets = graph.resolve(key, desc)
                if not any(t in contract_keys for t in targets):
                    continue
                for arg in list(call.args) + [
                    kw.value for kw in call.keywords
                ]:
                    for sub in astutil.iter_calls(arg):
                        why = meshmodel.impurity_of_call(sub, model)
                        if why is None:
                            continue
                        target = next(t for t in targets
                                      if t in contract_keys)
                        if fmap is None:
                            fmap = astutil.enclosing_function_map(model)
                        out.append(make_finding(
                            "HVD012", model, call.lineno,
                            call.col_offset,
                            f"{why} flows into {target[1]}() "
                            f"[{target[0]}], which is bound by a "
                            f"determinism contract: pass data every "
                            f"rank derives identically instead",
                            astutil.context_for_line(
                                model, call.lineno, fmap),
                        ))
    # One finding per (path, context, message-ish) — the closure can
    # reach the same impurity through several chains.
    seen: Set[Tuple[str, str, int, str]] = set()
    uniq: List[Finding] = []
    for f in out:
        # Full message, not a prefix: BFS findings share a long common
        # prefix ("{qn}() is bound by ... reaches"), and a truncated
        # key would collapse DISTINCT impurities reached from the same
        # contract function into one finding.
        k = (f.path, f.context, f.line, f.message)
        if k in seen:
            continue
        seen.add(k)
        uniq.append(f)
    return uniq


# ---------------------------------------------------------------------------
# HVD013 — taint in the tracing/sampling plane
# ---------------------------------------------------------------------------


@rule("HVD013", "rank-tainted-trace-decision", SEV_WARNING,
      "rank-derived value reaches a trace sampling/emission decision")
def hvd013(model: ModuleModel) -> List[Finding]:
    """The tracing contract (PR 11): the sampling verdict is a pure
    function of (trace_id, rate), so a request's spans exist on ALL
    ranks or NONE and trace-merge's per-rank lanes line up.  Rank taint
    in a ``sampled(...)`` argument, or span emission guarded by a
    rank-divergent condition, produces traces where a request's story
    exists only on some ranks — the merged waterfall silently loses
    exactly the lanes a divergence investigation needs.

    Minimal failing example::

        if hvd.rank() == 0:            # only rank 0's lane exists
            trace.add_span(tid, "decode", t0, t1)

    Fix: emit unconditionally (every rank's lane matters — that is the
    point of the merge) and let the *deterministic* sampling verdict do
    the filtering; derive sampling inputs from the trace id, never the
    rank.  Per-rank file naming in the DUMP path is fine — it names
    the lane, it doesn't choose whether the lane exists."""
    out: List[Finding] = []
    fmap = astutil.enclosing_function_map(model)
    for qn, ft in taint.module_taint_cached(model).items():
        for te in ft.trace_emits:
            scopes = te.taint.scopes
            if not scopes:
                continue
            scope, witness = next(iter(scopes.items()))
            out.append(make_finding(
                "HVD013", model, te.line, te.col,
                f"span emission '{te.name}' is guarded (line "
                f"{te.guard_line}) by {witness} ({scope!r}-scoped): "
                f"the span exists on a rank-chosen subset and "
                f"trace-merge loses those lanes — emit on every rank "
                f"and let the deterministic sampler filter",
                astutil.context_for_line(model, te.line, fmap),
            ))
        for line, vt in ft.sampled_args:
            if not vt.scopes:
                continue
            scope, witness = next(iter(vt.scopes.items()))
            out.append(make_finding(
                "HVD013", model, line, 0,
                f"rank-derived value ({witness}, {scope!r}-scoped) in "
                f"a sampled(...) argument: the sampling verdict must "
                f"be a pure function of the trace id so every rank "
                f"agrees whether this request is traced",
                astutil.context_for_line(model, line, fmap),
            ))
    return out
