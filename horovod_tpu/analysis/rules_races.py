"""Race rules (HVDC108-110): guarded-by inference over the launcher's
own thread architecture, built on :mod:`racer`.

These rules report violations of an *evident* locking protocol: a field
whose post-init accesses overwhelmingly hold one lock has a guard, and
the minority sites outside it are the race windows.  Classes that never
escape to a second thread are exempt wholesale (the RacerD ownership
rule); so are init-only writes and synchronization-primitive fields.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from . import racer
from .core import ModuleModel, SEV_ERROR, SEV_WARNING, Finding
from .lockgraph import shared_callgraph
from .registry import make_finding, rule

# The three rules walk one analysis; memoized per closed graph instance
# (same lifetime discipline as the signal-reachability memo).
_RACE_MEMO: List[tuple] = []


def _analysis(models: List[ModuleModel]) -> racer.RaceAnalysis:
    graph = shared_callgraph(models)
    for held, result in _RACE_MEMO:
        if held is graph:
            return result
    result = racer.analyze(graph)
    del _RACE_MEMO[:]
    _RACE_MEMO.append((graph, result))
    return result


def _model_by_relpath(models: List[ModuleModel],
                      relpath: str) -> ModuleModel:
    for m in models:
        if m.relpath == relpath:
            return m
    raise KeyError(relpath)


def _held_text(held: frozenset) -> str:
    if not held:
        return "no locks"
    return ", ".join(sorted(h.split("::", 1)[-1] for h in held))


@rule("HVDC108", "unguarded-write", SEV_ERROR,
      "write to a field outside its inferred guard lock",
      scope="project")
def hvdc108(models: List[ModuleModel]) -> List[Finding]:
    """A field whose post-init accesses overwhelmingly hold one lock
    has an inferred guard; a *write* outside it races every guarded
    access — lost updates, torn containers (dict resize mid-read), and
    heisenbugs that only fire under load.  Only classes that escape to
    a second thread (spawn threads, register callbacks, subclass
    Thread, or live in a module global) are checked, and ``__init__``
    writes before the object is shared are exempt.

    Minimal failing example::

        class Pump:
            def __init__(self):
                self._lock = threading.Lock()
                self._inflight = {}
                threading.Thread(target=self._run).start()
            def _run(self):
                with self._lock:
                    self._inflight["a"] = 1   # guarded...
            def admit(self, rid):
                with self._lock:
                    self._inflight[rid] = 0   # ...guarded...
            def shutdown(self):
                self._inflight.clear()        # HVDC108: no lock held

    Fix: take the inferred guard around the write (or, if the site is
    provably single-threaded — e.g. after every worker joined —
    baseline it with that reason)."""
    analysis = _analysis(models)
    out: List[Finding] = []
    for report in analysis.reports:
        model = _model_by_relpath(models, report.module)
        for a in report.unguarded_writes:
            out.append(make_finding(
                "HVDC108", model, a.line, 0,
                f"write to {report.cls}.{a.attr} holding "
                f"{_held_text(a.guaranteed)} but its inferred guard is "
                f"{report.guard_display!r} (held at {report.guarded}/"
                f"{report.counted} post-init accesses): write/write "
                f"race with the guarded sites — take "
                f"{report.guard_display!r} here",
                f"{a.func[1]}|{report.cls}.{a.attr}",
            ))
    return out


@rule("HVDC109", "unguarded-read", SEV_WARNING,
      "read of a field outside its inferred guard lock",
      scope="project")
def hvdc109(models: List[ModuleModel]) -> List[Finding]:
    """A read outside a field's inferred guard races the guarded
    writes: it can observe a container mid-mutation (RuntimeError:
    dict changed size during iteration) or a torn multi-field update.
    Warning, not error — some unguarded reads are deliberate snapshots
    where staleness is acceptable; those get a baseline entry saying
    so, which is exactly the documentation a reviewer needs.

    Minimal failing example::

        class Pump:
            def __init__(self):
                self._lock = threading.Lock()
                self._inflight = {}
                threading.Thread(target=self._run).start()
            def _run(self):
                with self._lock:
                    self._inflight["a"] = 1
            def admit(self, rid):
                with self._lock:
                    self._inflight[rid] = 0
            def stats(self):
                return len(self._inflight)    # HVDC109: racy read

    Fix: snapshot under the guard (``with self._lock: n =
    len(self._inflight)``) — or baseline with the reason staleness is
    fine here."""
    analysis = _analysis(models)
    out: List[Finding] = []
    for report in analysis.reports:
        model = _model_by_relpath(models, report.module)
        for a in report.unguarded_reads:
            out.append(make_finding(
                "HVDC109", model, a.line, 0,
                f"read of {report.cls}.{a.attr} holding "
                f"{_held_text(a.guaranteed)} but its inferred guard is "
                f"{report.guard_display!r} (held at {report.guarded}/"
                f"{report.counted} post-init accesses): races the "
                f"guarded writes — snapshot under "
                f"{report.guard_display!r} or baseline why staleness "
                f"is acceptable",
                f"{a.func[1]}|{report.cls}.{a.attr}",
            ))
    return out


@rule("HVDC110", "check-then-act", SEV_WARNING,
      "branch checks a guarded field without the lock its body takes",
      scope="project")
def hvdc110(models: List[ModuleModel]) -> List[Finding]:
    """Checking a guarded field *outside* its lock and then acting on
    it *inside* the lock is not atomic: the world can change between
    the check and the acquisition (the stale-heartbeat/double-ingest
    shape — two supervisors both see a dead shard and both adopt it).
    The check must move inside the critical section, re-validated
    under the lock.

    Minimal failing example::

        class Registry:
            def __init__(self):
                self._lock = threading.Lock()
                self._owners = {}
                threading.Thread(target=self._run).start()
            def _run(self):
                with self._lock:
                    self._owners["s0"] = "a"
            def get_owner(self, s):
                with self._lock:
                    return self._owners.get(s)
            def adopt(self, shard, me):
                if shard not in self._owners:     # check: no lock
                    with self._lock:
                        self._owners[shard] = me  # act: under lock

    Fix: hoist the ``with`` above the ``if`` and re-test inside — the
    double-checked form needs the inner check regardless, so keep only
    the locked one."""
    analysis = _analysis(models)
    out: List[Finding] = []
    for pair in analysis.check_act:
        model = _model_by_relpath(models, pair.module)
        out.append(make_finding(
            "HVDC110", model, pair.test_line, 0,
            f"check of {pair.cls}.{pair.attr} holding "
            f"{_held_text(pair.test_held)} but the act at line "
            f"{pair.act_line} writes it under "
            f"{_held_text(pair.act_held)}: not atomic — the field can "
            f"change between check and lock acquisition; move the "
            f"check inside the critical section",
            f"{pair.func[1]}|{pair.cls}.{pair.attr}",
        ))
    return out
