"""SPMD-correctness rules (HVD0xx).

The invariant behind every rule here is Horovod's core contract
(Sergeev & Del Balso, 2018): **every rank must submit the same
collective schedule in the same order.**  A collective some ranks skip,
reorder, or name differently never completes — the job hangs with no
exception anywhere, which is why these are worth rejecting at commit
time rather than diagnosing from a post-mortem.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from . import astutil
from .core import ModuleModel, SEV_ERROR, SEV_WARNING, Finding
from .registry import make_finding, rule

# ---------------------------------------------------------------------------
# HVD001 — collective under rank-dependent control flow
# ---------------------------------------------------------------------------


class _RankGuardVisitor(ast.NodeVisitor):
    """Finds collective calls lexically reachable only when a
    rank-dependent condition holds: inside the body/orelse of a
    rank-dependent ``if``/``while``, inside a rank-dependent ternary,
    or after a rank-dependent early exit (``if rank()!=0: return``)."""

    def __init__(self, model: ModuleModel):
        self.model = model
        self.findings: List[tuple] = []  # (node, guard_line)
        self._guards: List[int] = []  # lines of active rank guards

    # -- region tracking --

    def _walk_body(self, stmts: List[ast.stmt]) -> None:
        """Visit a statement list, activating a guard for statements
        after a rank-dependent early exit."""
        pushed = 0
        for stmt in stmts:
            if (
                isinstance(stmt, ast.If)
                and astutil.is_rank_dependent(stmt.test)
                and _ends_in_exit(stmt.body)
                and not stmt.orelse
            ):
                # `if rank() != 0: return` — everything after this
                # statement runs on a rank-dependent subset.
                self.visit(stmt.test)
                self._guards.append(stmt.lineno)
                pushed += 1
                for s in stmt.body:
                    self.visit(s)
                continue
            self.visit(stmt)
        for _ in range(pushed):
            self._guards.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # A nested def's body executes when *called*, not where it is
        # defined — guards at the definition site don't apply inside.
        saved, self._guards = self._guards, []
        self._walk_body(node.body)
        self._guards = saved

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Module(self, node: ast.Module) -> None:
        self._walk_body(node.body)

    def visit_For(self, node: ast.For) -> None:
        self.visit(node.target)
        self.visit(node.iter)
        self._walk_body(node.body)
        self._walk_body(node.orelse)

    visit_AsyncFor = visit_For

    def visit_With(self, node: ast.With) -> None:
        for item in node.items:
            self.visit(item)
        self._walk_body(node.body)

    visit_AsyncWith = visit_With

    def visit_Try(self, node: ast.Try) -> None:
        self._walk_body(node.body)
        for handler in node.handlers:
            self._walk_body(handler.body)
        self._walk_body(node.orelse)
        self._walk_body(node.finalbody)

    def visit_If(self, node: ast.If) -> None:
        if astutil.is_rank_dependent(node.test):
            self.visit(node.test)
            self._guards.append(node.lineno)
            for s in node.body + node.orelse:
                self.visit(s)
            self._guards.pop()
        else:
            self.visit(node.test)
            self._walk_body(node.body)
            self._walk_body(node.orelse)

    def visit_While(self, node: ast.While) -> None:
        if astutil.is_rank_dependent(node.test):
            self.visit(node.test)
            self._guards.append(node.lineno)
            for s in node.body + node.orelse:
                self.visit(s)
            self._guards.pop()
        else:
            self.generic_visit(node)

    def visit_IfExp(self, node: ast.IfExp) -> None:
        if astutil.is_rank_dependent(node.test):
            self.visit(node.test)
            self._guards.append(node.lineno)
            self.visit(node.body)
            self.visit(node.orelse)
            self._guards.pop()
        else:
            self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if self._guards and astutil.is_collective_call(node, self.model):
            self.findings.append((node, self._guards[-1]))
        self.generic_visit(node)


def _ends_in_exit(body: List[ast.stmt]) -> bool:
    if not body:
        return False
    last = body[-1]
    if isinstance(last, (ast.Return, ast.Continue, ast.Break)):
        return True
    if isinstance(last, ast.Expr) and isinstance(last.value, ast.Call):
        name = astutil.call_name(last.value)
        return name in ("exit", "_exit", "abort")
    return False


@rule("HVD001", "rank-guarded-collective", SEV_ERROR,
      "collective reachable only under rank-dependent control flow")
def hvd001(model: ModuleModel) -> List[Finding]:
    """A collective issued under a condition that reads the rank runs on
    a strict subset of ranks; the others never submit it, and the subset
    blocks forever waiting for them.

    Minimal failing example::

        if hvd.rank() == 0:
            total = hvd.allreduce(x)   # ranks != 0 never arrive: hang

    Fix: issue the collective unconditionally and branch on the rank
    *around* it (e.g. only rank 0 *uses* the result), or use
    ``broadcast`` from the deciding rank."""
    v = _RankGuardVisitor(model)
    v.visit(model.tree)
    fmap = astutil.enclosing_function_map(model)
    out = []
    for node, guard_line in v.findings:
        name = astutil.call_name(node)
        out.append(make_finding(
            "HVD001", model, node.lineno, node.col_offset,
            f"collective '{name}' is only reached under the "
            f"rank-dependent condition at line {guard_line}; ranks "
            f"outside the branch never submit it and the world "
            f"deadlocks",
            astutil.context_for_line(model, node.lineno, fmap),
        ))
    return out


# ---------------------------------------------------------------------------
# HVD002 — collective while iterating an unordered container
# ---------------------------------------------------------------------------


def _unordered_iter_reason(it: ast.expr) -> Optional[str]:
    """Why this for-loop's iteration order may differ across ranks."""
    if isinstance(it, ast.Set):
        return "a set literal"
    if isinstance(it, ast.Call):
        name = astutil.call_name(it)
        if name in ("set", "frozenset"):
            return f"a {name}() value"
        if name in ("keys", "values", "items") and isinstance(
            it.func, ast.Attribute
        ):
            return f"dict .{name}() (insertion order is build-dependent)"
        if name in ("vars", "globals", "locals"):
            return f"{name}()"
    if isinstance(it, ast.Attribute) and it.attr == "environ":
        return "os.environ"
    return None


@rule("HVD002", "collective-in-unordered-iteration", SEV_WARNING,
      "collective issued while iterating a set/dict view")
def hvd002(model: ModuleModel) -> List[Finding]:
    """Collectives inside a loop over a set (or a dict view whose build
    order is data-dependent) are submitted in container order.  If that
    order differs across ranks — sets hash-order differently under
    ``PYTHONHASHSEED``, dicts follow their build history — unnamed
    collectives pair by the auto ``_seq`` counter and ranks reduce
    *different tensors against each other* (or deadlock).

    Minimal failing example::

        for name in {"w", "b"}:               # set order
            grads[name] = hvd.allreduce(grads[name])

    Fix: ``for name in sorted(...)`` — one deterministic order on every
    rank.  ``sorted()`` wrapping the container is recognized."""
    out = []
    fmap = astutil.enclosing_function_map(model)
    for node in ast.walk(model.tree):
        if not isinstance(node, (ast.For, ast.AsyncFor)):
            continue
        reason = _unordered_iter_reason(node.iter)
        if reason is None:
            continue
        for call in astutil.iter_calls(node):
            if astutil.is_collective_call(call, model):
                name = astutil.call_name(call)
                out.append(make_finding(
                    "HVD002", model, call.lineno, call.col_offset,
                    f"collective '{name}' issued while iterating "
                    f"{reason} (loop at line {node.lineno}); iteration "
                    f"order can differ across ranks — wrap the "
                    f"container in sorted()",
                    astutil.context_for_line(model, call.lineno, fmap),
                ))
    return out


# ---------------------------------------------------------------------------
# HVD003 — unnamed collective inside a conditional
# ---------------------------------------------------------------------------


@rule("HVD003", "unnamed-collective-in-conditional", SEV_WARNING,
      "collective without an explicit name inside a conditional branch")
def hvd003(model: ModuleModel) -> List[Finding]:
    """Unnamed collectives are paired across ranks by an automatic
    per-epoch sequence counter.  Inside a data-dependent conditional the
    counter diverges the first time ranks disagree about the branch:
    every later unnamed collective then pairs tensor N on one rank with
    tensor N+1 on another.  (A *rank*-dependent branch is the stronger
    HVD001.)

    Minimal failing example::

        if loss_spiked:                  # can differ per rank
            g = hvd.allreduce(g)         # unnamed: auto _seq diverges

    Fix: pass ``name=`` so pairing is by name, not submission count —
    or hoist the collective out of the branch.  Conditions that are
    provably identical on every rank (``__name__`` guards,
    ``hvd.size()`` probes, constants) are exempt."""
    out: List[Finding] = []
    fmap = astutil.enclosing_function_map(model)
    seen: Set[int] = set()

    def scan_branch(branch: List[ast.stmt], cond_line: int) -> None:
        for stmt in branch:
            for call in astutil.iter_calls(stmt):
                if id(call) in seen:
                    continue
                if not astutil.is_collective_call(call, model):
                    continue
                seen.add(id(call))
                if astutil.has_name_kwarg(call):
                    continue
                name = astutil.call_name(call)
                out.append(make_finding(
                    "HVD003", model, call.lineno, call.col_offset,
                    f"unnamed collective '{name}' inside the "
                    f"conditional at line {cond_line}: if ranks "
                    f"disagree about the branch, auto-sequence names "
                    f"diverge — pass name=",
                    astutil.context_for_line(model, call.lineno, fmap),
                ))

    for node in ast.walk(model.tree):
        if isinstance(node, ast.If):
            if astutil.is_rank_dependent(node.test):
                continue  # HVD001 territory
            if astutil.is_rank_uniform_test(node.test):
                continue
            scan_branch(node.body, node.lineno)
            scan_branch(node.orelse, node.lineno)
    return out


# ---------------------------------------------------------------------------
# HVD004 — training entry point never syncs initial state
# ---------------------------------------------------------------------------

_SYNC_MARKERS = {
    "broadcast_parameters", "broadcast_optimizer_state",
    "broadcast_object", "broadcast_variables", "broadcast",
    "broadcast_", "sync_state", "sync",
    "BroadcastGlobalVariablesCallback", "BroadcastGlobalVariablesHook",
}
_TRAIN_MARKERS = {"DistributedOptimizer", "DistributedGradientTransform"}


@rule("HVD004", "missing-initial-state-sync", SEV_WARNING,
      "init()+DistributedOptimizer without broadcasting initial state")
def hvd004(model: ModuleModel) -> List[Finding]:
    """A training script that calls ``init()`` and wraps its optimizer
    but never broadcasts/syncs initial state starts every rank from its
    own random initialization: gradients get averaged across *different*
    models, which converges worse or diverges silently — the classic
    forgotten step 4 of the Horovod recipe.

    Minimal failing example::

        hvd.init()
        tx = hvd.DistributedOptimizer(optax.adam(1e-3))
        # ... training loop, no broadcast_parameters / state.sync

    Fix: ``params = hvd.broadcast_parameters(params, root_rank=0)``
    after ``init()`` (or adopt elastic ``state.sync()``)."""
    init_call: Optional[ast.Call] = None
    has_train = False
    has_sync = False
    for node in ast.walk(model.tree):
        if not isinstance(node, ast.Call):
            continue
        name = astutil.call_name(node)
        if name == "init":
            recv = astutil.receiver_name(node)
            if recv is None or recv in model.hvd_aliases:
                if init_call is None:
                    init_call = node
        elif name in _TRAIN_MARKERS:
            has_train = True
        elif name in _SYNC_MARKERS:
            has_sync = True
    # Class references without a call (e.g. callbacks list) count too.
    if not has_sync:
        for node in ast.walk(model.tree):
            if isinstance(node, ast.Attribute) and node.attr in _SYNC_MARKERS:
                has_sync = True
                break
            if isinstance(node, ast.Name) and node.id in _SYNC_MARKERS:
                has_sync = True
                break
    if init_call is None or not has_train or has_sync:
        return []
    fmap = astutil.enclosing_function_map(model)
    return [make_finding(
        "HVD004", model, init_call.lineno, init_call.col_offset,
        "init() + DistributedOptimizer but no initial-state sync: add "
        "broadcast_parameters(..., root_rank=0) (and "
        "broadcast_optimizer_state for stateful optimizers) so every "
        "rank starts from identical weights",
        astutil.context_for_line(model, init_call.lineno, fmap),
    )]


# ---------------------------------------------------------------------------
# HVD005 — rank()/size() at import time
# ---------------------------------------------------------------------------


@rule("HVD005", "topology-read-at-import", SEV_ERROR,
      "rank()/size() called at module import time, before init()")
def hvd005(model: ModuleModel) -> List[Finding]:
    """Module-level ``rank()``/``size()`` runs at import time, before
    any ``init()`` call — it raises ``NotInitializedError`` (or, in
    lazy-init setups, silently captures a stale single-process
    topology that never updates).

    Minimal failing example::

        import horovod_tpu as hvd
        IS_CHIEF = hvd.rank() == 0     # import-time: init() not yet run

    Fix: read the topology inside a function (or after the module-level
    ``init()`` call, which is recognized)."""
    out: List[Finding] = []
    fmap = astutil.enclosing_function_map(model)
    topo_names = astutil.RANK_CALL_NAMES | {
        "size", "local_size", "cross_size", "num_devices",
    }
    init_seen_line: Optional[int] = None

    def scan_stmts(stmts: List[ast.stmt]) -> None:
        nonlocal init_seen_line
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # deferred execution: fine
            if isinstance(stmt, (ast.If, ast.Try, ast.With, ast.For,
                                 ast.While)):
                scan_stmts(_stmt_children(stmt))  # still import-time
                continue
            for call in astutil.iter_calls(stmt):
                name = astutil.call_name(call)
                recv = astutil.receiver_name(call)
                hvdish = (
                    (recv is not None and recv in model.hvd_aliases)
                    or (recv is None and name in model.from_imports)
                )
                if name == "init" and hvdish:
                    if init_seen_line is None:
                        init_seen_line = stmt.lineno
                    continue
                if name in topo_names and hvdish:
                    if init_seen_line is not None:
                        continue  # init() already ran at import time
                    out.append(make_finding(
                        "HVD005", model, call.lineno, call.col_offset,
                        f"'{name}()' at module import time, before "
                        f"init(): raises NotInitializedError (or "
                        f"captures a stale topology) — move it inside "
                        f"a function or after init()",
                        astutil.context_for_line(model, call.lineno,
                                                 fmap),
                    ))

    scan_stmts(model.tree.body)
    return out


def _stmt_children(stmt: ast.stmt) -> List[ast.stmt]:
    out: List[ast.stmt] = []
    for fld in ("body", "orelse", "finalbody"):
        out.extend(getattr(stmt, fld, []) or [])
    for handler in getattr(stmt, "handlers", []) or []:
        out.extend(handler.body)
    return out


# ---------------------------------------------------------------------------
# HVD006 — collective inside an except handler
# ---------------------------------------------------------------------------


@rule("HVD006", "collective-in-except-handler", SEV_ERROR,
      "collective issued from an exception handler")
def hvd006(model: ModuleModel) -> List[Finding]:
    """An except block runs only on ranks where the try body raised —
    a strict subset, chosen by runtime failure.  A collective there can
    never complete: the healthy ranks are already past it (or parked in
    the *next* collective, which now pairs with the wrong op).

    Minimal failing example::

        try:
            step()
        except Exception:
            hvd.allreduce(loss)      # only failed ranks arrive

    Fix: record the failure locally, exit the collective schedule
    deterministically (e.g. ``hvd.join()`` outside the handler, or an
    agreed sentinel allreduce issued by EVERY rank), then recover.
    (Collectives in ``finally`` run on every path and are fine.)"""
    out: List[Finding] = []
    fmap = astutil.enclosing_function_map(model)
    for node in ast.walk(model.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        for stmt in node.body:
            for call in astutil.iter_calls(stmt):
                if astutil.is_collective_call(call, model):
                    name = astutil.call_name(call)
                    out.append(make_finding(
                        "HVD006", model, call.lineno, call.col_offset,
                        f"collective '{name}' inside an except handler "
                        f"(line {node.lineno}): only ranks that raised "
                        f"run it — the rest of the world never "
                        f"arrives",
                        astutil.context_for_line(model, call.lineno,
                                                 fmap),
                    ))
    return out


# ---------------------------------------------------------------------------
# HVD007 — rank-dependent collective name
# ---------------------------------------------------------------------------


@rule("HVD007", "rank-dependent-collective-name", SEV_ERROR,
      "collective name derived from the rank")
def hvd007(model: ModuleModel) -> List[Finding]:
    """Collectives pair across ranks BY NAME: a name containing the
    rank gives every rank a different key, so nothing ever matches and
    every rank hangs waiting for peers that are waiting right back.

    Minimal failing example::

        hvd.allreduce(g, name=f"grad_{hvd.rank()}")   # no two match

    Fix: name by *tensor*, not by rank — the name must be identical on
    every rank (``name="grad_w0"``)."""
    out: List[Finding] = []
    fmap = astutil.enclosing_function_map(model)
    for node in ast.walk(model.tree):
        if not isinstance(node, ast.Call):
            continue
        if not astutil.is_collective_call(node, model):
            continue
        expr = astutil.name_kwarg_expr(node)
        if expr is None:
            continue
        if _mentions_rank(expr):
            out.append(make_finding(
                "HVD007", model, node.lineno, node.col_offset,
                f"collective name {astutil.expr_text(expr)!r} depends "
                f"on the rank: names must be identical on every rank "
                f"or the collective never matches",
                astutil.context_for_line(model, node.lineno, fmap),
            ))
    return out


# ---------------------------------------------------------------------------
# HVD008 — direct collective bypasses the replay-epoch deviation check
# ---------------------------------------------------------------------------

# Coordination-service collectives that reach the wire without passing
# the eager engine's lookup()/deviation check.
_DIRECT_COLLECTIVE_NAMES = {
    "process_allgather", "sync_global_devices", "broadcast_one_to_all",
}
_MULTIHOST_MODULE = "jax.experimental.multihost_utils"
# The engine's own negotiation/data transport: these ARE the sanctioned
# submission path (the deviation check lives upstream of them).
_HVD008_SANCTIONED = {
    ("horovod_tpu/runtime/engine.py", "EagerEngine._exchange"),
    ("horovod_tpu/runtime/engine.py", "EagerEngine._data_allgather"),
}


def _is_direct_collective(call: ast.Call, model: ModuleModel) -> bool:
    name = astutil.call_name(call)
    if name not in _DIRECT_COLLECTIVE_NAMES:
        return False
    recv = astutil.receiver_name(call)
    if recv is not None:
        target = model.module_aliases.get(recv, recv)
        return (
            target == _MULTIHOST_MODULE
            or target.endswith("multihost_utils")
        )
    imported = model.from_imports.get(name)
    return imported is not None and imported[0] == _MULTIHOST_MODULE


@rule("HVD008", "replay-bypassing-collective", SEV_ERROR,
      "direct coordination-service collective bypasses the engine's "
      "replay deviation check")
def hvd008(model: ModuleModel) -> List[Finding]:
    """During a schedule-replay epoch the eager engine exchanges no
    control vectors: correctness rests on every collective submission
    flowing through the engine's ``lookup()``/deviation check, which
    breaks the epoch *before* an unexpected collective reaches the
    wire.  A direct coordination-service collective
    (``multihost_utils.process_allgather`` / ``sync_global_devices`` /
    ``broadcast_one_to_all``) issued from library code while an epoch
    is open interleaves an unscheduled global exchange between the
    memorized replay collectives — if any rank is meanwhile inside a
    replay buffer, submission orders diverge and the job deadlocks
    (HVD001's failure shape, hidden inside the library).

    Minimal failing example::

        from jax.experimental import multihost_utils

        def checkpoint_barrier():
            multihost_utils.sync_global_devices("ckpt")  # bypasses lookup()

    Fix: route through the engine (``hvd.barrier()`` / ``hvd.*``
    collectives) so the submission is negotiated — a cache MISS there
    breaks the replay epoch deterministically — or baseline the site
    with a written justification for why it can never overlap an open
    epoch (engine transport itself, init/teardown-time only, or an
    engine-absent fallback path)."""
    out: List[Finding] = []
    fmap = astutil.enclosing_function_map(model)
    for node in ast.walk(model.tree):
        if not isinstance(node, ast.Call):
            continue
        if not _is_direct_collective(node, model):
            continue
        context = astutil.context_for_line(model, node.lineno, fmap)
        if (model.relpath, context) in _HVD008_SANCTIONED:
            continue
        name = astutil.call_name(node)
        out.append(make_finding(
            "HVD008", model, node.lineno, node.col_offset,
            f"direct coordination-service collective '{name}' is not "
            f"routed through the engine's lookup()/deviation check: "
            f"issued while a replay epoch is open, it interleaves an "
            f"unscheduled exchange with the memorized schedule and can "
            f"deadlock the world",
            context,
        ))
    return out


# ---------------------------------------------------------------------------
# HVD009 — jit of a train step without buffer donation
# ---------------------------------------------------------------------------

# Argument names that mark a jitted function as carrying training state.
_STATE_ARG_NAMES = {
    "params", "param", "opt_state", "optimizer_state", "train_state",
    "state", "weights",
}
# Wrappers whose first positional argument is the actual step function.
_JIT_WRAPPER_NAMES = {"shard_map", "shard_map_compat", "partial", "remat",
                      "checkpoint"}


def _scope_then_module(scope: Optional[ast.AST],
                       model: ModuleModel) -> List[ast.AST]:
    """Search roots for name resolution: the jit call's enclosing
    function first, then the module — a name bound in ANOTHER function
    is a different variable entirely (resolving it would judge the jit
    call against an unrelated same-named callable)."""
    roots: List[ast.AST] = []
    if scope is not None:
        roots.append(scope)
    roots.append(model.tree)
    return roots


def _find_binding(target: str, scope: Optional[ast.AST],
                  model: ModuleModel) -> Optional[ast.AST]:
    """The Assign value / def node `target` resolves to, scope-first."""
    for root in _scope_then_module(scope, model):
        module_level = root is model.tree
        for node in ast.walk(root):
            if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == target
                for t in node.targets
            ) and isinstance(node.value, (ast.Call, ast.Lambda)):
                # At module level only accept top-level statements: an
                # assignment inside some other function binds a
                # different variable.
                if module_level and node not in model.tree.body:
                    continue
                return node.value
        for node in ast.walk(root):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name == target:
                return node
    return None


def _callee_arg_names(expr: ast.expr, model: ModuleModel,
                      scope: Optional[ast.AST] = None
                      ) -> Optional[List[str]]:
    """Positional-argument names of the function a ``jax.jit`` call
    wraps, looking through shard_map/partial wrappers and resolving
    names scope-first (``scope`` = the jit call's enclosing function).
    None = could not resolve (quiet)."""
    for _ in range(4):  # bounded wrapper unwrap
        if isinstance(expr, ast.Lambda):
            return [a.arg for a in expr.args.args]
        if isinstance(expr, (ast.FunctionDef, ast.AsyncFunctionDef)):
            names = [a.arg for a in expr.args.args]
            if names and names[0] in ("self", "cls"):
                return None  # method: not what jit wraps here
            return names
        if isinstance(expr, ast.Call):
            name = astutil.call_name(expr)
            if name in _JIT_WRAPPER_NAMES and expr.args:
                expr = expr.args[0]
                continue
            return None
        if isinstance(expr, ast.Name):
            bound = _find_binding(expr.id, scope, model)
            if bound is None:
                return None
            expr = bound
            continue
        return None
    return None


def _is_jax_jit_call(node: ast.Call, model: ModuleModel) -> bool:
    if astutil.call_name(node) != "jit":
        return False
    recv = astutil.receiver_name(node)
    if recv is not None:
        return model.module_aliases.get(recv, recv) == "jax"
    imported = model.from_imports.get("jit")
    return imported is not None and imported[0] == "jax"


@rule("HVD009", "undonated-train-step", SEV_WARNING,
      "jax.jit of a step function carrying params/opt_state without "
      "donate_argnums")
def hvd009(model: ModuleModel) -> List[Finding]:
    """A jitted train step whose arguments include params/opt_state but
    whose ``jax.jit`` call passes no ``donate_argnums``/``donate_argnames``
    keeps BOTH the input and output copies of the model state live
    across every step: peak HBM grows by a full params+opt_state
    replica, which is the difference between fitting a batch size and
    OOMing — and on the ZeRO-sharded path it silently forfeits the
    memory the sharding just bought.  (XLA only aliases input buffers
    into outputs when the jit call donates them.)

    Minimal failing example::

        step = jax.jit(shard_map(local_step, mesh=mesh, ...))
        # local_step(params, opt_state, batch): state copied every step

    Fix: ``jax.jit(..., donate_argnums=(0, 1))`` for the state
    arguments (then verify the aliasing took with
    ``optim.overlap.audit_donation``), or baseline the site with a
    reason (e.g. an eval-only apply where the state must survive)."""
    out: List[Finding] = []
    fmap = astutil.enclosing_function_map(model)
    # Enclosing function per call node, for scope-first name resolution.
    scopes: Dict[int, ast.AST] = {}

    def index_scopes(node: ast.AST, scope: Optional[ast.AST]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scope = node
        elif isinstance(node, ast.Call):
            if scope is not None:
                scopes[id(node)] = scope
        for child in ast.iter_child_nodes(node):
            index_scopes(child, scope)

    index_scopes(model.tree, None)
    for node in ast.walk(model.tree):
        if not isinstance(node, ast.Call):
            continue
        if not _is_jax_jit_call(node, model):
            continue
        kwarg_names = {kw.arg for kw in node.keywords}
        if {"donate_argnums", "donate_argnames"} & kwarg_names:
            continue
        if None in kwarg_names:  # **kwargs splat: unknown, stay quiet
            continue
        if not node.args:
            continue
        arg_names = _callee_arg_names(node.args[0], model,
                                      scope=scopes.get(id(node)))
        if not arg_names:
            continue
        hits = sorted(set(arg_names) & _STATE_ARG_NAMES)
        if not hits:
            continue
        out.append(make_finding(
            "HVD009", model, node.lineno, node.col_offset,
            f"jax.jit of a step taking {', '.join(hits)} without "
            f"donate_argnums: input and output state copies both stay "
            f"live, doubling peak state memory — donate the state "
            f"arguments",
            astutil.context_for_line(model, node.lineno, fmap),
        ))
    return out


def _mentions_rank(expr: ast.expr) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call) and \
                astutil.call_name(node) in astutil.RANK_CALL_NAMES:
            return True
        if isinstance(node, ast.Attribute) and node.attr == "rank":
            return True
        if isinstance(node, ast.Name) and node.id == "rank":
            return True
    return False
