"""Rule registry: every check self-registers with its catalog entry.

Two families:

* ``HVD0xx`` — SPMD-correctness rules, run on user scripts, examples/
  and the library alike (module-local AST passes).
* ``HVDC1xx`` — concurrency rules, aimed at the library's own
  engine/obs/elastic threads (lock graph + signal-reachability passes;
  some need the whole project, see ``project_rules``).
"""

from __future__ import annotations

from typing import Callable, Dict, List

from .core import ModuleModel, Rule, Finding

# module-local rules: fn(model) -> [Finding]
_MODULE_RULES: List[tuple] = []
# project-wide rules: fn(models: list[ModuleModel]) -> [Finding]
_PROJECT_RULES: List[tuple] = []
_RULES: Dict[str, Rule] = {}


def rule(id: str, name: str, severity: str, summary: str, *,
         scope: str = "module") -> Callable:
    """Register a check.  The decorated function's docstring is the
    rule-catalog entry (it must contain a minimal failing example)."""

    def deco(fn: Callable) -> Callable:
        doc = (fn.__doc__ or "").strip()
        assert doc, f"rule {id} needs a catalog docstring"
        r = Rule(id=id, name=name, severity=severity, summary=summary,
                 doc=doc)
        assert id not in _RULES, f"duplicate rule id {id}"
        _RULES[id] = r
        entry = (r, fn)
        if scope == "module":
            _MODULE_RULES.append(entry)
        elif scope == "project":
            _PROJECT_RULES.append(entry)
        else:  # pragma: no cover - registration bug
            raise ValueError(f"unknown scope {scope!r}")
        return fn

    return deco


def _load() -> None:
    # Import for side effect: the @rule decorators populate the tables.
    from . import rules_spmd  # noqa: F401, PLC0415
    from . import rules_concurrency  # noqa: F401, PLC0415
    from . import rules_mesh  # noqa: F401, PLC0415
    from . import rules_races  # noqa: F401, PLC0415


def all_rules() -> Dict[str, Rule]:
    _load()
    return dict(_RULES)


def run_module_rules(model: ModuleModel) -> List[Finding]:
    _load()
    findings: List[Finding] = []
    for r, fn in _MODULE_RULES:
        findings.extend(fn(model))
    return findings


def run_project_rules(models: List[ModuleModel]) -> List[Finding]:
    _load()
    findings: List[Finding] = []
    for r, fn in _PROJECT_RULES:
        findings.extend(fn(models))
    return findings


def make_finding(rule_id: str, model: ModuleModel, line: int, col: int,
                 message: str, context: str) -> Finding:
    r = _RULES[rule_id]
    return Finding(
        rule=rule_id, severity=r.severity, path=model.relpath,
        line=line, col=col, message=message, context=context,
    )
