"""Interprocedural rank-taint engine (the HVD010/HVD013 substrate).

PR 5's SPMD rules judge one function at a time: ``if rank() == 0:
allreduce(x)`` fires, but the same bug split across a call boundary —

    r = hvd.rank()
    helper(r)                       # caller taints the argument

    def helper(flag):
        if flag == 0:               # helper can't see where flag came from
            lax.psum(x, LOCAL_AXIS)

— is invisible, and the codebase is now full of helpers like that
(bucket reducers, shard_map bodies, serve schedulers).  Following
RacerD (Blackshear et al., 2018) this module stays compositional: ONE
pass per function produces a small, serializable summary — which
values are tainted, what the function returns, which collectives sit
under tainted guards, every outgoing call with per-argument taint —
and a closure over the existing lockgraph call graph stitches the
summaries without whole-program dataflow.  Per-axis-scope taint (the
mesh-aware part, :mod:`meshmodel`) is what keeps subgroup reasoning
sound: ``cross_rank()`` taint is harmless around a LOCAL_AXIS
collective and fatal around a CROSS_AXIS one.

Summaries are plain dicts end to end so the per-file analysis cache
(:mod:`cache`) can persist them keyed by content hash.

Stdlib-only, like the rest of the package.
"""

from __future__ import annotations

import ast
import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import astutil, meshmodel
from .core import ModuleModel
from .lockgraph import CallGraph

# Bounds (RacerD lesson: predictable cost beats completeness).
_MAX_CALL_DEPTH = 4      # nested-call taint recording inside one expr
_MAX_RESOLVE_DEPTH = 5   # cross-function closure recursion
_MAX_HAZARD_HOPS = 4     # param-hazard propagation up the call graph


# ---------------------------------------------------------------------------
# value taint
# ---------------------------------------------------------------------------


@dataclass
class ValueTaint:
    """Taint of one value, before closure.

    ``scopes`` are facts (a rank source reached this value), ``params``
    and ``calls`` are promises resolved against the call graph later:
    the value inherits whatever taint the named caller-parameter or the
    named callee's return value turns out to carry.  ``sanitized``
    records axes a collective laundered downstream of the promises —
    when a promise later binds to concrete taint, matching scopes are
    filtered out (``psum(flag, A)`` makes a rank-tainted ``flag``
    uniform along A even though the taint arrived via a parameter).
    """

    scopes: Dict[str, str] = field(default_factory=dict)   # scope -> witness
    params: Dict[int, str] = field(default_factory=dict)   # index -> name
    calls: List["CallSite"] = field(default_factory=list)
    sanitized: Set[str] = field(default_factory=set)

    def merge(self, other: "ValueTaint") -> None:
        # Merging two values (e.g. `a + b`): an axis is only laundered
        # for the merged value if BOTH sides laundered it — but a side
        # with no promises at all imposes no constraint.  Judged on the
        # PRE-merge state: once other's promises land in self, "did
        # self bring promises of its own" is no longer answerable.
        had_promises = bool(self.params or self.calls or self.sanitized)
        for s, w in other.scopes.items():
            self.scopes.setdefault(s, w)
        for i, n in other.params.items():
            self.params.setdefault(i, n)
        self.calls.extend(other.calls)
        if other.params or other.calls or other.sanitized:
            if had_promises:
                self.sanitized &= other.sanitized
            else:
                self.sanitized = set(other.sanitized)

    def is_empty(self) -> bool:
        return not (self.scopes or self.params or self.calls)

    def drop_scopes(self, axes: Sequence[str]) -> "ValueTaint":
        """Sanitizer application: a collective result is uniform along
        its reduced axes — matching scoped taint is laundered.  A WORLD
        sanitizer (allreduce/broadcast result) clears everything,
        promises included: whatever flowed in, the result is identical
        on every rank."""
        if meshmodel.WORLD in axes:
            return ValueTaint()
        return ValueTaint(
            scopes={s: w for s, w in self.scopes.items() if s not in axes},
            params=dict(self.params),
            calls=list(self.calls),
            sanitized=self.sanitized | set(axes),
        )

    def as_dict(self) -> dict:
        return {
            "scopes": dict(self.scopes),
            "params": {str(i): n for i, n in self.params.items()},
            "calls": [c.as_dict() for c in self.calls],
            "sanitized": sorted(self.sanitized),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ValueTaint":
        return cls(
            scopes=dict(d.get("scopes", {})),
            params={int(i): n for i, n in d.get("params", {}).items()},
            calls=[CallSite.from_dict(c) for c in d.get("calls", [])],
            sanitized=set(d.get("sanitized", [])),
        )


@dataclass
class CallSite:
    """One outgoing call with the taint of every argument — enough to
    bind the callee's parameters at closure time without re-reading the
    caller's AST."""

    kind: str                 # astutil.call_descriptor kind
    target: object            # its data (str or [cls, name] pair)
    line: int
    args: List[ValueTaint] = field(default_factory=list)
    kwargs: Dict[str, ValueTaint] = field(default_factory=dict)

    @property
    def desc(self) -> Tuple[str, object]:
        t = self.target
        return (self.kind, tuple(t) if isinstance(t, list) else t)

    def display(self) -> str:
        t = self.target
        if isinstance(t, (list, tuple)):
            return ".".join(str(p) for p in t)
        return str(t)

    def as_dict(self) -> dict:
        return {
            "kind": self.kind,
            "target": list(self.target)
            if isinstance(self.target, (list, tuple)) else self.target,
            "line": self.line,
            "args": [a.as_dict() for a in self.args],
            "kwargs": {k: v.as_dict() for k, v in self.kwargs.items()},
        }

    @classmethod
    def from_dict(cls, d: dict) -> "CallSite":
        return cls(
            kind=d["kind"], target=d["target"], line=d["line"],
            args=[ValueTaint.from_dict(a) for a in d.get("args", [])],
            kwargs={k: ValueTaint.from_dict(v)
                    for k, v in d.get("kwargs", {}).items()},
        )


@dataclass
class GuardedCollective:
    """A collective lexically reachable only under tainted control flow."""

    name: str
    axes: List[str]
    line: int
    col: int
    guard_line: int
    taint: ValueTaint
    eager_world: bool   # hvd.* world surface (HVD001's beat for direct hits)

    def as_dict(self) -> dict:
        return {
            "name": self.name, "axes": list(self.axes), "line": self.line,
            "col": self.col, "guard_line": self.guard_line,
            "taint": self.taint.as_dict(), "eager_world": self.eager_world,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "GuardedCollective":
        return cls(
            name=d["name"], axes=list(d["axes"]), line=d["line"],
            col=d["col"], guard_line=d["guard_line"],
            taint=ValueTaint.from_dict(d["taint"]),
            eager_world=bool(d.get("eager_world")),
        )


@dataclass
class GuardedTraceEmit:
    """A trace-span emission under tainted control flow (HVD013)."""

    name: str
    line: int
    col: int
    guard_line: int
    taint: ValueTaint

    def as_dict(self) -> dict:
        return {"name": self.name, "line": self.line, "col": self.col,
                "guard_line": self.guard_line,
                "taint": self.taint.as_dict()}

    @classmethod
    def from_dict(cls, d: dict) -> "GuardedTraceEmit":
        return cls(name=d["name"], line=d["line"], col=d["col"],
                   guard_line=d["guard_line"],
                   taint=ValueTaint.from_dict(d["taint"]))


@dataclass
class FuncTaint:
    """One function's compositional taint summary."""

    qualname: str
    module: str
    line: int
    param_names: List[str]
    ret: ValueTaint
    guards: List[GuardedCollective]
    trace_emits: List[GuardedTraceEmit]
    calls: List[CallSite]      # EVERY outgoing call (hazard propagation)
    sampled_args: List[Tuple[int, ValueTaint]]  # line, arg taint to sampled()

    def as_dict(self) -> dict:
        return {
            "qualname": self.qualname, "module": self.module,
            "line": self.line, "param_names": list(self.param_names),
            "ret": self.ret.as_dict(),
            "guards": [g.as_dict() for g in self.guards],
            "trace_emits": [t.as_dict() for t in self.trace_emits],
            "calls": [c.as_dict() for c in self.calls],
            "sampled_args": [[ln, vt.as_dict()]
                             for ln, vt in self.sampled_args],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "FuncTaint":
        return cls(
            qualname=d["qualname"], module=d["module"], line=d["line"],
            param_names=list(d["param_names"]),
            ret=ValueTaint.from_dict(d["ret"]),
            guards=[GuardedCollective.from_dict(g) for g in d["guards"]],
            trace_emits=[GuardedTraceEmit.from_dict(t)
                         for t in d.get("trace_emits", [])],
            calls=[CallSite.from_dict(c) for c in d["calls"]],
            sampled_args=[(ln, ValueTaint.from_dict(vt))
                          for ln, vt in d.get("sampled_args", [])],
        )


# ---------------------------------------------------------------------------
# per-function local analysis
# ---------------------------------------------------------------------------

# Span-emission surface whose reachability must be rank-uniform per
# trace id (the PR-11 contract: a sampled request's spans exist on ALL
# ranks or NONE).
TRACE_EMIT_NAMES: Set[str] = {"add_span", "span"}


class _FunctionTainter:
    """Single forward pass over one function body (nested defs
    excluded — they get their own summaries)."""

    def __init__(self, model: ModuleModel, func: ast.AST, qualname: str):
        self.model = model
        self.func = func
        self.qualname = qualname
        args = getattr(func, "args", None)
        names: List[str] = []
        if args is not None:
            names = [a.arg for a in
                     args.posonlyargs + args.args + args.kwonlyargs]
        self.param_names = names
        self.env: Dict[str, ValueTaint] = {
            n: ValueTaint(params={i: n})
            for i, n in enumerate(names) if n not in ("self", "cls")
        }
        self.ret = ValueTaint()
        self.guards: List[GuardedCollective] = []
        self.trace_emits: List[GuardedTraceEmit] = []
        self.calls: List[CallSite] = []
        self.sampled_args: List[Tuple[int, ValueTaint]] = []
        self._guard_stack: List[Tuple[int, ValueTaint]] = []
        self._seen_calls: Set[int] = set()

    # -- expression taint --------------------------------------------------

    def expr_taint(self, node: Optional[ast.expr],
                   depth: int = 0) -> ValueTaint:
        out = ValueTaint()
        if node is None:
            return out
        src = meshmodel.source_scope(node)
        if src is not None:
            scope, witness = src
            out.scopes[scope] = f"{witness} (line {node.lineno})"
            return out
        if isinstance(node, ast.Name):
            hit = self.env.get(node.id)
            if hit is not None:
                out.merge(hit)
            return out
        if isinstance(node, ast.Call):
            sanitized = meshmodel.sanitizer_axes(node, self.model)
            inner = ValueTaint()
            for a in node.args:
                inner.merge(self.expr_taint(a, depth + 1))
            for kw in node.keywords:
                inner.merge(self.expr_taint(kw.value, depth + 1))
            if sanitized is not None:
                return inner.drop_scopes(sanitized)
            # Unresolved call: its result may carry the callee's taint.
            if depth < _MAX_CALL_DEPTH:
                site = self._record_call(node, register=False)
                if site is not None:
                    out.calls.append(site)
            out.merge(ValueTaint(scopes=inner.scopes,
                                 params=inner.params))
            # Args' own call-promises matter for the RESULT only via the
            # callee's param binding (already inside `site`); keeping
            # them here too would double-resolve, so they are dropped.
            return out
        # Anything composite: union of children.
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                out.merge(self.expr_taint(child, depth + 1))
        return out

    def _record_call(self, node: ast.Call,
                     register: bool = True) -> Optional[CallSite]:
        kind, data = astutil.call_descriptor(node, {})
        if kind == "attr" and not data:
            return None
        site = CallSite(
            kind=kind,
            target=list(data) if isinstance(data, tuple) else data,
            line=node.lineno,
            args=[self.expr_taint(a, 1) for a in node.args],
            kwargs={kw.arg: self.expr_taint(kw.value, 1)
                    for kw in node.keywords if kw.arg is not None},
        )
        if register:
            self.calls.append(site)
        return site

    def _current_guard(self) -> Optional[Tuple[int, ValueTaint]]:
        if not self._guard_stack:
            return None
        line = self._guard_stack[-1][0]
        merged = ValueTaint()
        for _, t in self._guard_stack:
            merged.merge(t)
        return line, merged

    # -- statement walk ----------------------------------------------------

    def run(self) -> FuncTaint:
        self._walk_body(list(getattr(self.func, "body", [])))
        return FuncTaint(
            qualname=self.qualname, module=self.model.relpath,
            line=getattr(self.func, "lineno", 1),
            param_names=self.param_names, ret=self.ret,
            guards=self.guards, trace_emits=self.trace_emits,
            calls=self.calls, sampled_args=self.sampled_args,
        )

    def _walk_body(self, stmts: List[ast.stmt]) -> None:
        pushed = 0
        for stmt in stmts:
            if (
                isinstance(stmt, ast.If)
                and not stmt.orelse
                and _ends_in_exit(stmt.body)
            ):
                taint = self._test_taint(stmt.test)
                if not taint.is_empty():
                    # `if tainted: return` — the rest of this block runs
                    # on a taint-chosen subset.
                    self._guard_stack.append((stmt.lineno, taint))
                    pushed += 1
                    self._walk_body(stmt.body)
                    continue
            self._walk_stmt(stmt)
        for _ in range(pushed):
            self._guard_stack.pop()

    def _test_taint(self, test: ast.expr) -> ValueTaint:
        if astutil.is_rank_uniform_test(test):
            return ValueTaint()
        return self.expr_taint(test)

    def _walk_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # own summary
        if isinstance(stmt, ast.Assign):
            taint = self.expr_taint(stmt.value)
            self._scan_exprs(stmt.value)
            for target in stmt.targets:
                self._bind(target, taint)
            return
        if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            taint = self.expr_taint(stmt.value)
            self._scan_exprs(stmt.value)
            self._bind(stmt.target, taint)
            return
        if isinstance(stmt, ast.AugAssign):
            taint = self.expr_taint(stmt.value)
            self._scan_exprs(stmt.value)
            if isinstance(stmt.target, ast.Name):
                prev = self.env.setdefault(stmt.target.id, ValueTaint())
                prev.merge(taint)
            return
        if isinstance(stmt, ast.Return):
            self.ret.merge(self.expr_taint(stmt.value))
            self._scan_exprs(stmt.value)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            taint = self._test_taint(stmt.test)
            self._scan_exprs(stmt.test)
            if not taint.is_empty():
                self._guard_stack.append((stmt.lineno, taint))
                self._walk_body(stmt.body)
                self._walk_body(stmt.orelse)
                self._guard_stack.pop()
            else:
                self._walk_body(stmt.body)
                self._walk_body(stmt.orelse)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            taint = self.expr_taint(stmt.iter)
            self._scan_exprs(stmt.iter)
            self._bind(stmt.target, taint)
            self._walk_body(stmt.body)
            self._walk_body(stmt.orelse)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._scan_exprs(item.context_expr)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars,
                               self.expr_taint(item.context_expr))
            self._walk_body(stmt.body)
            return
        if isinstance(stmt, ast.Try):
            self._walk_body(stmt.body)
            for handler in stmt.handlers:
                self._walk_body(handler.body)
            self._walk_body(stmt.orelse)
            self._walk_body(stmt.finalbody)
            return
        # Expression statements and everything else: scan for calls.
        self._scan_exprs(stmt)

    def _bind(self, target: ast.expr, taint: ValueTaint) -> None:
        """Assignment targets inherit the value's taint.  Tuple targets
        each get the WHOLE taint — a rank carried inside a returned
        tuple must not launder through unpacking."""
        if isinstance(target, ast.Name):
            self.env[target.id] = taint
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind(elt, taint)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, taint)
        # Attribute/Subscript targets: no env entry (conservatively
        # quiet — tracking self.* would need aliasing).

    def _scan_exprs(self, node: Optional[ast.AST]) -> None:
        """Record call sites + guarded collectives/trace-emits inside a
        statement or expression subtree (deduped: a call reached via
        two statement paths is recorded once)."""
        if node is None:
            return
        for call in astutil.iter_calls(node):
            if id(call) in self._seen_calls:
                continue
            self._seen_calls.add(id(call))
            self._record_call(call)
            self._observe_call(call)

    def _observe_call(self, call: ast.Call) -> None:
        guard = self._current_guard()
        name = astutil.call_name(call)
        axes = meshmodel.collective_axes(call, self.model)
        if axes is not None and guard is not None:
            self.guards.append(GuardedCollective(
                name=name or "<collective>", axes=axes,
                line=call.lineno, col=call.col_offset,
                guard_line=guard[0], taint=guard[1],
                eager_world=astutil.is_collective_call(call, self.model),
            ))
        if name in TRACE_EMIT_NAMES and guard is not None:
            self.trace_emits.append(GuardedTraceEmit(
                name=name, line=call.lineno, col=call.col_offset,
                guard_line=guard[0], taint=guard[1],
            ))
        if name == "sampled" and (call.args or call.keywords):
            merged = ValueTaint()
            for a in call.args:
                merged.merge(self.expr_taint(a, 1))
            for kw in call.keywords:
                merged.merge(self.expr_taint(kw.value, 1))
            # Drop call-promises: a helper() feeding sampled() is judged
            # by HVD013 only on facts, not maybes.
            merged.calls = []
            if not merged.is_empty():
                self.sampled_args.append((call.lineno, merged))


def _ends_in_exit(body: List[ast.stmt]) -> bool:
    if not body:
        return False
    last = body[-1]
    if isinstance(last, (ast.Return, ast.Continue, ast.Break, ast.Raise)):
        return True
    if isinstance(last, ast.Expr) and isinstance(last.value, ast.Call):
        return astutil.call_name(last.value) in ("exit", "_exit", "abort")
    return False


def summarize_module_taint(model: ModuleModel) -> Dict[str, FuncTaint]:
    """qualname -> FuncTaint for every def in the file (qualnames via
    :func:`astutil.iter_defs`, the same convention the call graph keys
    on — summaries stitch by these names)."""
    return {
        qn: _FunctionTainter(model, node, qn).run()
        for qn, node in astutil.iter_defs(model.tree)
    }


# In-memory content-hash memo for the local phase.  The on-disk cache
# (:mod:`cache`) pre-seeds and drains this dict, so a warm run skips
# the per-function walk entirely for unchanged files.
_SUMMARY_MEMO: Dict[str, Dict[str, FuncTaint]] = {}
_SUMMARY_MEMO_MAX = 4096


def content_key(source: str) -> str:
    return hashlib.sha256(source.encode("utf-8", "replace")).hexdigest()


def module_taint_cached(model: ModuleModel) -> Dict[str, FuncTaint]:
    key = content_key(model.source)
    hit = _SUMMARY_MEMO.get(key)
    if hit is not None:
        return hit
    sums = summarize_module_taint(model)
    if len(_SUMMARY_MEMO) >= _SUMMARY_MEMO_MAX:
        _SUMMARY_MEMO.clear()
    _SUMMARY_MEMO[key] = sums
    return sums


def seed_summary_memo(key: str, raw: Dict[str, dict]) -> None:
    """Install deserialized summaries (cache load path)."""
    try:
        _SUMMARY_MEMO[key] = {
            qn: FuncTaint.from_dict(d) for qn, d in raw.items()
        }
    except (KeyError, TypeError, ValueError):
        pass  # stale/foreign cache entry: recompute instead


def dump_summary_memo(key: str) -> Optional[Dict[str, dict]]:
    hit = _SUMMARY_MEMO.get(key)
    if hit is None:
        return None
    return {qn: ft.as_dict() for qn, ft in hit.items()}


# ---------------------------------------------------------------------------
# project closure
# ---------------------------------------------------------------------------


@dataclass
class ResolvedScope:
    """One closed taint fact: scope + where it came from."""

    scope: str
    witness: str
    chain: Tuple[str, ...]   # call-chain attribution, caller-first


class ProjectTaint:
    """Summaries for the whole analyzed set, closed over the call graph."""

    def __init__(self, models: List[ModuleModel],
                 graph: Optional[CallGraph] = None,
                 precomputed: Optional[
                     Dict[str, Dict[str, FuncTaint]]] = None):
        self.models = models
        self.graph = graph or CallGraph(models)
        self.funcs: Dict[Tuple[str, str], FuncTaint] = {}
        for model in models:
            ready = (precomputed or {}).get(model.relpath)
            sums = ready if ready is not None \
                else module_taint_cached(model)
            for qn, ft in sums.items():
                self.funcs[(model.relpath, qn)] = ft
        self._ret_cache: Dict[Tuple[str, str], List[ResolvedScope]] = {}

    # -- return-taint closure ---------------------------------------------

    def return_scopes(self, key: Tuple[str, str],
                      depth: int = 0,
                      _active: Optional[Set[Tuple[str, str]]] = None
                      ) -> List[ResolvedScope]:
        """Closed rank-taint scopes of ``key``'s return value, with the
        producing chain.  Parameter promises stay open here (they bind
        at a concrete call site via :meth:`resolve_value`)."""
        if key in self._ret_cache:
            return self._ret_cache[key]
        ft = self.funcs.get(key)
        if ft is None or depth > _MAX_RESOLVE_DEPTH:
            return []
        active = _active or set()
        if key in active:
            return []  # recursion: stop, facts already counted once
        out = self.resolve_value(
            ft.ret, key, depth=depth, _active=active | {key},
        )
        if depth == 0:
            self._ret_cache[key] = out
        return out

    def resolve_value(self, vt: ValueTaint, caller: Tuple[str, str],
                      binding: Optional[Dict[int, List[ResolvedScope]]]
                      = None,
                      depth: int = 0,
                      _active: Optional[Set[Tuple[str, str]]] = None,
                      ) -> List[ResolvedScope]:
        """Close one ValueTaint: direct scopes, bound parameters, and
        callee returns (transitively)."""
        out: List[ResolvedScope] = []
        seen: Set[Tuple[str, str]] = set()

        def emit(scope: str, witness: str,
                 chain: Tuple[str, ...]) -> None:
            if (scope, witness) in seen:
                return
            seen.add((scope, witness))
            out.append(ResolvedScope(scope, witness, chain))

        for scope, witness in vt.scopes.items():
            emit(scope, witness, ())
        if binding:
            for idx in vt.params:
                for rs in binding.get(idx, []):
                    if rs.scope in vt.sanitized:
                        continue  # laundered by a collective downstream
                    emit(rs.scope, rs.witness, rs.chain)
        if depth >= _MAX_RESOLVE_DEPTH:
            return out
        for site in vt.calls:
            for callee in self.graph.resolve(caller, site.desc):
                callee_ft = self.funcs.get(callee)
                if callee_ft is None:
                    continue
                sub_binding = self._bind_args(site, callee_ft, caller,
                                              depth, _active)
                for rs in self.return_scopes(
                    callee, depth=depth + 1, _active=_active,
                ):
                    if rs.scope in vt.sanitized:
                        continue
                    emit(rs.scope, rs.witness,
                         (_disp(callee),) + rs.chain)
                # Param-flows-to-return: callee returns its own param.
                ret_params = callee_ft.ret.params
                if ret_params and sub_binding:
                    for idx in ret_params:
                        for rs in sub_binding.get(idx, []):
                            if rs.scope in vt.sanitized or \
                                    rs.scope in callee_ft.ret.sanitized:
                                continue
                            emit(rs.scope, rs.witness,
                                 (_disp(callee),) + rs.chain)
        return out

    def _bind_args(self, site: CallSite, callee: FuncTaint,
                   caller: Tuple[str, str], depth: int,
                   _active: Optional[Set[Tuple[str, str]]],
                   ) -> Dict[int, List[ResolvedScope]]:
        """Map callee parameter index -> resolved taint of the argument
        the caller passes there (positional and keyword)."""
        params = callee.param_names
        offset = 1 if params and params[0] in ("self", "cls") else 0
        binding: Dict[int, List[ResolvedScope]] = {}
        for i, arg in enumerate(site.args):
            if arg.is_empty():
                continue
            binding[i + offset] = self.resolve_value(
                arg, caller, depth=depth + 1, _active=_active,
            )
        for kw_name, arg in site.kwargs.items():
            if arg.is_empty() or kw_name not in params:
                continue
            binding[params.index(kw_name)] = self.resolve_value(
                arg, caller, depth=depth + 1, _active=_active,
            )
        return {i: v for i, v in binding.items() if v}


def _disp(key: Tuple[str, str]) -> str:
    return f"{key[1]} [{key[0]}]"


# ---------------------------------------------------------------------------
# findings substrate: guarded collectives, closed
# ---------------------------------------------------------------------------


@dataclass
class DivergentCollective:
    """One closed HVD010 hit, ready for the rule to format."""

    module: str
    function: str       # where the collective lives
    name: str
    axes: List[str]
    line: int
    col: int
    guard_line: int
    scope: str
    witness: str
    chain: Tuple[str, ...]   # producing call chain (empty = same function)
    via_param: Optional[str]  # parameter name the taint entered through
    eager_world: bool
    direct: bool             # taint fully visible inside the function


@dataclass
class _Hazard:
    """A guarded collective whose guard depends on a parameter: the
    finding fires at whatever call site binds that parameter to a
    divergent value.  ``owner`` is the function holding the collective
    (the finding anchors there); ``hops`` is the forwarding chain built
    as the hazard climbs through callers that pass their own params;
    ``sanitized`` carries axes a collective laundered between the
    parameter and the guard — taint scoped to those axes is uniform by
    the time it reaches the branch and must not convict the caller."""

    guard: GuardedCollective
    owner: Tuple[str, str]
    hops: Tuple[str, ...]
    param_name: str
    sanitized: frozenset = frozenset()


def divergent_collectives(pt: ProjectTaint) -> List[DivergentCollective]:
    """Every guarded collective whose guard taint can differ within the
    collective's group — intraprocedural facts first, then parameter
    hazards propagated up the call graph to the sites that actually
    pass tainted values in."""
    out: List[DivergentCollective] = []
    hazards: Dict[Tuple[Tuple[str, str], int], List[_Hazard]] = {}

    for key, ft in pt.funcs.items():
        for g in ft.guards:
            for rs in pt.resolve_value(g.taint, key):
                if not meshmodel.diverges(rs.scope, g.axes):
                    continue
                out.append(DivergentCollective(
                    module=key[0], function=ft.qualname, name=g.name,
                    axes=g.axes, line=g.line, col=g.col,
                    guard_line=g.guard_line, scope=rs.scope,
                    witness=rs.witness, chain=rs.chain, via_param=None,
                    eager_world=g.eager_world, direct=not rs.chain,
                ))
            for idx, pname in g.taint.params.items():
                hazards.setdefault((key, idx), []).append(
                    _Hazard(g, key, (_disp(key),), pname,
                            frozenset(g.taint.sanitized))
                )

    # Propagate parameter hazards to call sites (bounded hops: a caller
    # passing its OWN param forwards the hazard up one more level).
    for _hop in range(_MAX_HAZARD_HOPS):
        if not hazards:
            break  # clean tree: skip the full call-resolution sweep
        new_hazards: Dict[Tuple[Tuple[str, str], int],
                          List[_Hazard]] = {}
        for caller_key, ft in pt.funcs.items():
            for site in ft.calls:
                for callee in pt.graph.resolve(caller_key, site.desc):
                    if callee == caller_key:
                        continue
                    callee_ft = pt.funcs.get(callee)
                    if callee_ft is None:
                        continue
                    params = callee_ft.param_names
                    offset = 1 if params and params[0] in ("self", "cls") \
                        else 0
                    bound: List[Tuple[int, ValueTaint]] = [
                        (i + offset, a) for i, a in enumerate(site.args)
                    ] + [
                        (params.index(k), a)
                        for k, a in site.kwargs.items() if k in params
                    ]
                    for idx, arg in bound:
                        for hz in hazards.get((callee, idx), ()):
                            g = hz.guard
                            for rs in pt.resolve_value(arg, caller_key):
                                if rs.scope in hz.sanitized:
                                    continue  # laundered en route
                                if not meshmodel.diverges(rs.scope,
                                                          g.axes):
                                    continue
                                out.append(DivergentCollective(
                                    module=hz.owner[0],
                                    function=hz.owner[1],
                                    name=g.name, axes=g.axes,
                                    line=g.line, col=g.col,
                                    guard_line=g.guard_line,
                                    scope=rs.scope, witness=rs.witness,
                                    chain=(_disp(caller_key),)
                                    + rs.chain + hz.hops,
                                    via_param=hz.param_name,
                                    eager_world=g.eager_world,
                                    direct=False,
                                ))
                            # Caller forwards its own parameter: the
                            # hazard climbs one level.
                            for pidx, ppname in arg.params.items():
                                new_hazards.setdefault(
                                    (caller_key, pidx), []
                                ).append(_Hazard(
                                    g, hz.owner,
                                    (_disp(caller_key),) + hz.hops,
                                    ppname,
                                    hz.sanitized
                                    | frozenset(arg.sanitized),
                                ))
        if not new_hazards:
            break
        hazards = new_hazards
    # De-dup: the same collective+scope can surface through both a
    # positional and a keyword binding of the same call site.
    seen: Set[Tuple] = set()
    uniq: List[DivergentCollective] = []
    for d in out:
        k = (d.module, d.line, d.col, d.scope, d.chain, d.via_param)
        if k in seen:
            continue
        seen.add(k)
        uniq.append(d)
    return uniq
