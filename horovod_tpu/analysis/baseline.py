"""Baseline handling: the incremental gate.

A baseline entry acknowledges ONE known finding so the CI gate can be
strict about everything else.  Policy (enforced here, documented in
docs/analysis.md): the baseline is for *documented false-positive-prone
cases only* — every entry MUST carry a non-empty ``reason`` explaining
why the finding is not a defect.  True positives get fixed, not
baselined; an entry without a reason is rejected so "baseline it to
shut it up" cannot pass review silently.

Matching is by (rule, path, context) — context is a stable anchor
(enclosing function qualname / lock pair), so line-number drift from
unrelated edits never invalidates the baseline.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

from .core import Finding

BASELINE_SCHEMA = "hvdtpu-lint-baseline-v1"


class BaselineError(ValueError):
    pass


def load_baseline(path: str) -> Dict[Tuple[str, str, str], dict]:
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("schema") != BASELINE_SCHEMA:
        raise BaselineError(
            f"{path}: expected schema {BASELINE_SCHEMA!r}, got "
            f"{doc.get('schema')!r}"
        )
    out: Dict[Tuple[str, str, str], dict] = {}
    for i, entry in enumerate(doc.get("entries", [])):
        for field in ("rule", "path", "context", "reason"):
            if not str(entry.get(field, "")).strip():
                raise BaselineError(
                    f"{path}: entry {i} is missing {field!r} — baseline "
                    f"entries must name the finding AND justify why it "
                    f"is a false positive (fix true positives instead)"
                )
        key = (entry["rule"], entry["path"], entry["context"])
        if key in out:
            raise BaselineError(f"{path}: duplicate entry for {key}")
        out[key] = entry
    return out


def apply_baseline(
    findings: List[Finding],
    baseline: Dict[Tuple[str, str, str], dict],
) -> Tuple[List[Finding], List[dict]]:
    """Mark matched findings; returns (findings, unused_entries)."""
    used: set = set()
    for f in findings:
        if f.status != "new":
            continue
        if f.key() in baseline:
            f.status = "baselined"
            used.add(f.key())
    unused = [e for k, e in baseline.items() if k not in used]
    return findings, unused


def prune_baseline(path: str, stale: List[dict]) -> int:
    """Remove ``stale`` entries (as returned by :func:`apply_baseline`)
    from the baseline file in place, preserving every surviving entry
    byte-for-byte (reasons are curated text).  Returns the number
    removed.  Stale suppressions are drift: an entry whose finding no
    longer fires either acknowledges a fixed defect (remove it) or —
    worse — will silently swallow a *future* finding at the same
    (rule, path, context) that has nothing to do with the original
    justification."""
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("schema") != BASELINE_SCHEMA:
        raise BaselineError(
            f"{path}: expected schema {BASELINE_SCHEMA!r}, got "
            f"{doc.get('schema')!r}"
        )
    stale_keys = {
        (e["rule"], e["path"], e["context"]) for e in stale
    }
    entries = doc.get("entries", [])
    kept = [
        e for e in entries
        if (e.get("rule"), e.get("path"), e.get("context"))
        not in stale_keys
    ]
    removed = len(entries) - len(kept)
    if removed:
        doc["entries"] = kept
        with open(path, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=2, sort_keys=False)
            f.write("\n")
    return removed


def write_baseline(
    path: str,
    findings: List[Finding],
    reason: str,
    existing: Optional[Dict[Tuple[str, str, str], dict]] = None,
) -> int:
    """Emit entries for every non-suppressed finding (dev convenience;
    the loader still rejects empty reasons, so new entries need a real
    justification before the file loads).  Entries already present in
    ``existing`` keep their curated reasons — regenerating over the
    committed baseline must never clobber the human justifications."""
    entries = []
    seen = set()
    existing = existing or {}
    for f in findings:
        if f.status == "suppressed":
            continue
        if f.key() in seen:
            continue
        seen.add(f.key())
        prior = existing.get(f.key())
        entries.append({
            "rule": f.rule,
            "path": f.path,
            "context": f.context,
            "reason": prior["reason"] if prior else reason,
            "message": f.message,
        })
    doc = {"schema": BASELINE_SCHEMA, "entries": entries}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=False)
        fh.write("\n")
    return len(entries)
