"""Per-file analysis cache: content-hash keyed findings + taint summaries.

O'Hearn's continuous-reasoning bar is "runs on every diff": the lint
only stays in the commit loop if the commit loop stays fast.  A full
cold run re-parses and re-walks every file for every rule family; on a
typical diff almost none of that changed.  This cache persists, per
file and keyed by the sha256 of its content,

* the **module-scope findings** (sound to reuse: module rules see only
  that one file), and
* the **taint summaries** (:mod:`taint`'s compositional per-function
  facts — the local phase of the interprocedural closure, also purely
  content-derived).

Project-scope rules (lock graph, HVD010/HVD012 closures) still run
every time — their verdicts depend on *other* files — but they start
from the cached summaries, so the warm path skips every per-function
AST walk for unchanged files.

The cache is advisory everywhere: any corruption, schema drift, or
rule-set change (new rule IDs would make cached finding lists stale)
invalidates it wholesale and the run silently recomputes.  It never
affects findings, only wall clock.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Dict, List, Optional

from .core import Finding

CACHE_SCHEMA = "hvdtpu-lint-cache-v1"
DEFAULT_CACHE_PATH = ".hvdtpu-lint-cache.json"


_SALT_MEMO: Optional[str] = None


def _rules_salt() -> str:
    """Rule IDs + a digest of the analyzer's own sources: editing a
    rule's logic (same IDs) must invalidate cached findings too, or the
    new logic would never run on unchanged files."""
    global _SALT_MEMO
    if _SALT_MEMO is not None:
        return _SALT_MEMO
    import hashlib  # noqa: PLC0415

    # Late import: registry imports the rule modules, which import this
    # package's siblings — keep cache importable standalone.
    from . import registry  # noqa: PLC0415

    h = hashlib.sha256()
    pkg_dir = os.path.dirname(os.path.abspath(__file__))
    for fn in sorted(os.listdir(pkg_dir)):
        if not fn.endswith(".py"):
            continue
        try:
            with open(os.path.join(pkg_dir, fn), "rb") as f:
                h.update(fn.encode())
                h.update(f.read())
        except OSError:
            pass
    _SALT_MEMO = ",".join(sorted(registry.all_rules())) \
        + ":" + h.hexdigest()[:16]
    return _SALT_MEMO


def load_cache(path: str) -> Dict[str, dict]:
    """relpath -> {"key": sha, "module_findings": [...], "taint": {...}}.
    Empty on any mismatch or damage — the cache is advisory."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError, UnicodeDecodeError):
        return {}
    if not isinstance(doc, dict) or doc.get("schema") != CACHE_SCHEMA:
        return {}
    if doc.get("rules") != _rules_salt():
        return {}  # rule set changed: every cached finding list is stale
    files = doc.get("files")
    return files if isinstance(files, dict) else {}


def save_cache(path: str, files: Dict[str, dict]) -> None:
    """Atomic best-effort write (a torn cache must never be loadable).

    ``json.dumps`` (one string), not ``json.dump``: the stream form
    encodes with the pure-Python iterator and was 7 s of a warm run;
    the one-shot form takes the C encoder."""
    doc = {"schema": CACHE_SCHEMA, "rules": _rules_salt(), "files": files}
    try:
        blob = json.dumps(doc, separators=(",", ":"))
        d = os.path.dirname(os.path.abspath(path)) or "."
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".hvdtpu-lint-cache.")
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                f.write(blob)
            os.replace(tmp, path)
        except BaseException:
            os.unlink(tmp)
            raise
    except OSError:
        pass  # read-only checkout / full disk: run stays correct, just cold


def findings_from_entry(entry: dict, relpath: str) -> Optional[List[Finding]]:
    """Deserialize one file's cached module findings; None = unusable."""
    raw = entry.get("module_findings")
    if not isinstance(raw, list):
        return None
    out: List[Finding] = []
    for d in raw:
        try:
            f = Finding(
                rule=str(d["rule"]), severity=str(d["severity"]),
                path=relpath, line=int(d["line"]), col=int(d["col"]),
                message=str(d["message"]), context=str(d["context"]),
            )
        except (KeyError, TypeError, ValueError):
            return None
        out.append(f)
    return out


def entry_for(key: str, module_findings: List[Finding],
              taint_summaries: Optional[Dict[str, dict]]) -> dict:
    return {
        "key": key,
        "module_findings": [
            {"rule": f.rule, "severity": f.severity, "line": f.line,
             "col": f.col, "message": f.message, "context": f.context}
            for f in module_findings
        ],
        "taint": taint_summaries or {},
    }
