"""Core data model for hvdtpu-lint: findings, rules, suppressions.

Design constraints (why this is its own subsystem and not a flake8
plugin): the invariants worth checking here are *distributed-systems*
invariants — every rank must submit the same collective schedule, and
the engine/obs/elastic threads plus the signal-based death hooks must
respect lock discipline — which need project-level passes (a lock
graph, a signal-handler reachability walk) no line-oriented linter
offers.  Following RacerD's lesson (Blackshear et al., 2018), the
analyses are deliberately *syntactic and compositional*: no whole-
program points-to, no inter-procedural dataflow — per-function
summaries stitched by name, which keeps the full-repo run under a
second and the false-positive rate low enough to gate CI on.

Everything in this package is stdlib-only on purpose: the linter must
run in CI images and pre-commit hooks without the jax stack resolving.
"""

from __future__ import annotations

import ast
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

SCHEMA = "hvdtpu-lint-v1"

SEV_ERROR = "error"
SEV_WARNING = "warning"

# `# hvdtpu: disable=HVD001,HVDC102` on the offending line or the line
# directly above it.  `disable=all` silences every rule for that line.
_SUPPRESS_RE = re.compile(
    r"#\s*hvdtpu:\s*disable=([A-Za-z0-9_,\s]+)"
)


@dataclass(frozen=True)
class Rule:
    """One check.  ``doc`` carries the rule catalog entry, including a
    minimal failing example (docs/analysis.md is generated from these,
    so the catalog can never drift from the implementation)."""

    id: str
    name: str
    severity: str
    summary: str
    doc: str


@dataclass
class Finding:
    rule: str
    severity: str
    path: str        # repo-relative, '/'-separated
    line: int
    col: int
    message: str
    # Stable anchor for baseline matching: line numbers shift on every
    # edit, so the baseline keys on (rule, path, context) instead —
    # usually the enclosing function's qualname.
    context: str
    status: str = "new"  # new | baselined | suppressed

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.context)

    def as_dict(self) -> dict:
        return {
            "rule": self.rule,
            "severity": self.severity,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "context": self.context,
            "status": self.status,
        }


def parse_suppressions(source: str) -> Dict[int, Set[str]]:
    """Map line number -> set of rule IDs disabled there.

    Comments are found with the tokenizer, not a regex over raw lines,
    so a ``# hvdtpu:`` inside a string literal never suppresses
    anything."""
    out: Dict[int, Set[str]] = {}
    try:
        tokens = tokenize.generate_tokens(
            iter(source.splitlines(keepends=True)).__next__
        )
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            ids = {p.strip() for p in m.group(1).split(",") if p.strip()}
            out.setdefault(tok.start[0], set()).update(ids)
    except (tokenize.TokenError, SyntaxError, IndentationError):
        pass  # unparseable file: reported as a parse error elsewhere
    return out


def is_suppressed(
    finding: Finding, suppressions: Dict[int, Set[str]]
) -> bool:
    for line in (finding.line, finding.line - 1):
        ids = suppressions.get(line)
        if ids and (finding.rule in ids or "all" in ids):
            return True
    return False


@dataclass
class ModuleModel:
    """One parsed file plus the name-resolution facts every rule needs."""

    path: str          # absolute
    relpath: str       # repo-relative, '/'-separated (finding paths)
    source: str
    tree: ast.Module
    # Names bound to the horovod_tpu package or a submodule of it
    # ("hvd", "horovod_tpu", "collectives", ...).
    hvd_aliases: Set[str] = field(default_factory=set)
    # from-import local name -> (module, original name); module ""
    # for relative imports inside the package itself.
    from_imports: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    # import alias -> full module path ("np" -> "numpy").
    module_aliases: Dict[str, str] = field(default_factory=dict)
    suppressions: Dict[int, Set[str]] = field(default_factory=dict)
    # Memo slot for astutil.enclosing_function_map: every rule family
    # asks for the line->qualname map, and rebuilding it per rule was
    # the single largest cost in a full-surface run.
    fmap_cache: Optional[Dict[int, str]] = field(
        default=None, repr=False, compare=False,
    )

    @property
    def is_package_module(self) -> bool:
        return "horovod_tpu/" in self.relpath or self.relpath.startswith(
            "horovod_tpu"
        )


def load_module(path: str, relpath: str) -> Optional[ModuleModel]:
    """Parse one file; returns None (caller reports) on syntax errors."""
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return None
    model = ModuleModel(
        path=path, relpath=relpath, source=source, tree=tree,
        suppressions=parse_suppressions(source),
    )
    _collect_imports(model)
    return model


_HVD_PREFIXES = ("horovod_tpu",)


def _is_hvd_module(modname: str) -> bool:
    return any(
        modname == p or modname.startswith(p + ".") for p in _HVD_PREFIXES
    )


def _collect_imports(model: ModuleModel) -> None:
    for node in ast.walk(model.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                local = alias.asname or alias.name.split(".")[0]
                model.module_aliases[local] = alias.name
                if _is_hvd_module(alias.name):
                    model.hvd_aliases.add(local)
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            relative = node.level and node.level > 0
            in_pkg = "horovod_tpu" in model.relpath
            hvdish = _is_hvd_module(mod) or (relative and in_pkg)
            for alias in node.names:
                local = alias.asname or alias.name
                model.from_imports[local] = (mod, alias.name)
                # `from horovod_tpu import elastic` / `from . import obs`
                # bind a *module* name.
                if hvdish:
                    model.hvd_aliases.add(local)
