"""Mesh model: what the analyzer knows about named axis subgroups.

PR 8 gave the runtime a (slice, host, chip) mesh and PR 9/10 run
collectives over *named axis subgroups* (``psum(..., LOCAL_AXIS)``
inside ``shard_map`` bodies, ``hierarchical_axes=(local, cross)``).
Rank divergence **within** one of those groups is exactly the HVD001
deadlock class, but the world-collective rules cannot see it: a branch
on ``cross_rank()`` is perfectly safe around a LOCAL_AXIS collective
(every member of a local group shares the cross index) and fatal around
a CROSS_AXIS one.  This module centralizes that judgement:

* canonical **axis scopes** ("world"/"slice"/"cross"/"local"/literal
  axis names) and the mapping from the repo's axis constants to them;
* **rank-source classification** — which calls/env reads produce a
  value that differs across ranks, and along which axis;
* **subgroup-collective recognition** (``lax.psum``/``psum_scatter``/
  ``all_gather``/... plus the hierarchical plane's wrappers) and axis
  extraction from their call sites;
* the **divergence judgement** ``diverges(scope, axes)``;
* **sanitizers** — collectives whose *result* is rank-uniform along the
  reduced axis (an allreduce/broadcast result is the same everywhere:
  branching on it is safe);
* the **deterministic-contract registry** (HVD012): functions whose
  outputs must be a pure function of their inputs on every rank — the
  serve scheduler's documented purity contract and the trace sampler —
  plus the ``# hvdtpu: deterministic`` source annotation, and the
  impure-input classifier used against them.

Stdlib-only, like the rest of the package.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from . import astutil
from .core import ModuleModel

# ---------------------------------------------------------------------------
# axis scopes
# ---------------------------------------------------------------------------

WORLD = "world"

# Literal axis-name values used across the repo (basics.py,
# runtime/device_plane.py) -> canonical scope.  Kept as literals on
# purpose: the analyzer must not import the runtime.
_AXIS_LITERALS: Dict[str, str] = {
    "hvd": WORLD,            # DP_AXIS — the flat data-parallel world
    "hvdtpu_proc": WORLD,    # PROC_AXIS — device-plane process axis
    "hvd_local": "local",    # LOCAL_AXIS
    "hvdtpu_ici": "local",   # ICI_AXIS
    "hvd_cross": "cross",    # CROSS_AXIS
    "hvdtpu_dcn": "cross",   # DCN_AXIS
    "hvd_slice": "slice",    # SLICE_AXIS
}

# Symbolic spellings (Name/attribute references to the axis constants,
# and the conventional parameter names of the hierarchical plane).
_AXIS_SYMBOLS: Dict[str, str] = {
    "DP_AXIS": WORLD, "PROC_AXIS": WORLD,
    "LOCAL_AXIS": "local", "ICI_AXIS": "local", "local_axis": "local",
    "CROSS_AXIS": "cross", "DCN_AXIS": "cross", "cross_axis": "cross",
    "SLICE_AXIS": "slice", "slice_axis": "slice",
}

UNKNOWN_AXIS = "?"


def canon_axis(token: str) -> str:
    """Literal axis string -> canonical scope (unknown literals map to
    themselves: ``psum(x, "model")`` guarded by ``axis_index("model")``
    must still match)."""
    return _AXIS_LITERALS.get(token, token)


def axis_tokens(expr: Optional[ast.expr]) -> List[str]:
    """Canonical axis tokens an axis-name argument can denote."""
    if expr is None:
        return [UNKNOWN_AXIS]
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return [canon_axis(expr.value)]
    if isinstance(expr, (ast.Tuple, ast.List)):
        out: List[str] = []
        for e in expr.elts:
            out.extend(axis_tokens(e))
        return out
    if isinstance(expr, ast.Name):
        return [_AXIS_SYMBOLS.get(expr.id, UNKNOWN_AXIS)]
    if isinstance(expr, ast.Attribute):
        return [_AXIS_SYMBOLS.get(expr.attr, UNKNOWN_AXIS)]
    return [UNKNOWN_AXIS]


def diverges(scope: str, axes: List[str]) -> bool:
    """May a value tainted with ``scope`` differ between members of a
    collective group over ``axes``?

    The mesh-aware part: taint scoped to axis B is *uniform* within a
    group over axis A != B (the group fixes every other coordinate), so
    only a matching axis — or world-scoped taint, which differs along
    every axis — diverges.  Unknown axes stay quiet for scoped taint
    (over-firing on unresolvable axis names would drown the signal) but
    world taint always fires: the world rank differs inside every
    conceivable subgroup."""
    if scope in (WORLD, UNKNOWN_AXIS):
        return True
    if WORLD in axes:
        # A world collective's group is everyone: any per-rank scope
        # varies inside it (local_rank differs across hosts too).
        return True
    return scope in axes


# ---------------------------------------------------------------------------
# rank sources
# ---------------------------------------------------------------------------

# call name -> fixed scope (None: scope comes from the axis argument)
_SOURCE_CALLS: Dict[str, Optional[str]] = {
    "rank": WORLD,
    "device_rank": WORLD,
    "process_index": WORLD,   # jax.process_index()
    "local_rank": "local",
    "cross_rank": "cross",
    "slice_id": "slice",
    "axis_rank": None,
    "axis_index": None,
}

_ENV_SCOPE_RE: List[Tuple[re.Pattern, str]] = [
    (re.compile(r"SLICE", re.I), "slice"),
    (re.compile(r"RANK|PROCESS_INDEX|PMI|PROC_ID", re.I), WORLD),
]


def _env_key_scope(key: str) -> Optional[str]:
    for pat, scope in _ENV_SCOPE_RE:
        if pat.search(key):
            return scope
    return None


def source_scope(node: ast.expr) -> Optional[Tuple[str, str]]:
    """``(scope, witness)`` when ``node`` is a rank source expression:
    a topology call, ``lax.axis_index(axis)``, or an env lookup of a
    rank-shaped key.  ``None`` otherwise."""
    if isinstance(node, ast.Call):
        name = astutil.call_name(node)
        if name in _SOURCE_CALLS:
            fixed = _SOURCE_CALLS[name]
            if fixed is not None:
                return fixed, f"{name}()"
            axis_expr = node.args[0] if node.args else None
            for kw in node.keywords:
                if kw.arg == "axis_name":
                    axis_expr = kw.value
            if axis_expr is None:  # axis_rank() defaults to DP_AXIS
                return WORLD, f"{name}()"
            toks = axis_tokens(axis_expr)
            scope = toks[0] if len(toks) == 1 else UNKNOWN_AXIS
            return scope, f"{name}({astutil.expr_text(axis_expr)})"
        # os.environ.get("HOROVOD_RANK") / os.getenv("...")
        if name in ("get", "getenv") and node.args:
            recv = astutil.expr_text(node.func)
            if "environ" in recv or name == "getenv":
                key = node.args[0]
                if isinstance(key, ast.Constant) and \
                        isinstance(key.value, str):
                    scope = _env_key_scope(key.value)
                    if scope is not None:
                        return scope, f"env[{key.value!r}]"
    if isinstance(node, ast.Subscript):
        base = astutil.expr_text(node.value)
        if "environ" in base:
            sl = node.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                scope = _env_key_scope(sl.value)
                if scope is not None:
                    return scope, f"env[{sl.value!r}]"
    return None


# ---------------------------------------------------------------------------
# subgroup collectives + axis extraction
# ---------------------------------------------------------------------------

# jax.lax collectives whose 2nd positional arg (or axis_name=) is the
# axis-name binding.
_LAX_COLLECTIVES: Set[str] = {
    "psum", "pmean", "pmax", "pmin", "psum_scatter",
    "all_gather", "all_to_all", "ppermute", "pshuffle", "pbroadcast",
}
# horovod_tpu wrappers carrying axis names in kwargs.
_HVD_AXIS_COLLECTIVES: Set[str] = {
    "hierarchical_allreduce", "hierarchical_adasum",
    "hierarchical_reduce_scatter", "hierarchical_all_gather",
    "adasum_allreduce",
}
_HIER_DEFAULT_AXES = ["local", "cross"]


def _laxish(node: ast.Call, model: ModuleModel) -> bool:
    recv = astutil.receiver_name(node)
    if recv is not None:
        target = model.module_aliases.get(recv, recv)
        return target == "lax" or target.endswith(".lax") or recv == "lax"
    name = astutil.call_name(node)
    origin = model.from_imports.get(name or "")
    if origin is not None:
        mod = origin[0]
        return mod == "jax.lax" or mod.endswith(".lax") or mod == "jax"
    return False


def _hvdish(node: ast.Call, model: ModuleModel) -> bool:
    name = astutil.call_name(node)
    if isinstance(node.func, ast.Attribute):
        return True
    origin = model.from_imports.get(name or "")
    if origin is not None:
        mod = origin[0]
        return mod == "" or "horovod_tpu" in mod or mod.startswith(".")
    return model.is_package_module


def collective_axes(node: ast.Call,
                    model: ModuleModel) -> Optional[List[str]]:
    """Canonical axis tokens of a collective call, or ``None`` when the
    call is not a recognized collective.

    ``["world"]`` marks world-group collectives (the eager ``hvd.*``
    surface and lax collectives over the data-parallel axis); anything
    else is a *subgroup* collective."""
    name = astutil.call_name(node)
    if name is None:
        return None
    if name in _LAX_COLLECTIVES and _laxish(node, model):
        axis_expr: Optional[ast.expr] = (
            node.args[1] if len(node.args) >= 2 else None
        )
        for kw in node.keywords:
            if kw.arg == "axis_name":
                axis_expr = kw.value
        return axis_tokens(axis_expr)
    if name in _HVD_AXIS_COLLECTIVES and _hvdish(node, model):
        axes: List[str] = []
        for kw in node.keywords:
            if kw.arg in ("local_axis", "cross_axis", "axis_name"):
                axes.extend(axis_tokens(kw.value))
            elif kw.arg == "hierarchical_axes":
                axes.extend(axis_tokens(kw.value))
        if not axes:
            axes = (list(_HIER_DEFAULT_AXES)
                    if name.startswith("hierarchical_")
                    else [WORLD])
        return axes
    if astutil.is_collective_call(node, model):
        # The eager world surface — unless an explicit axis_name kwarg
        # narrows it to a subgroup (ops.collectives under tracing).
        for kw in node.keywords:
            if kw.arg == "axis_name":
                return axis_tokens(kw.value)
        return [WORLD]
    return None


def is_subgroup(axes: List[str]) -> bool:
    return axes != [WORLD]


# ---------------------------------------------------------------------------
# sanitizers: rank-uniform results
# ---------------------------------------------------------------------------

# World collectives whose RESULT is identical on every rank: assigning
# through one launders any rank taint (the satellite "sanitized by a
# uniform broadcast" case).  allgather/barrier included: the gathered
# tuple is the same everywhere.
_WORLD_SANITIZERS: Set[str] = {
    "allreduce", "allreduce_", "grouped_allreduce", "allgather",
    "broadcast", "broadcast_", "broadcast_object", "broadcast_parameters",
    "broadcast_variables", "broadcast_optimizer_state", "sync_state",
}


def sanitizer_axes(node: ast.Call,
                   model: ModuleModel) -> Optional[List[str]]:
    """Axes along which this call's result is uniform, or None.

    A ``psum(x, A)`` result is uniform along A but still differs across
    the other axes; a world allreduce/broadcast result is uniform
    everywhere (returns ``["world"]``, treated as clearing all taint)."""
    name = astutil.call_name(node)
    if name in _LAX_COLLECTIVES and name not in (
        "psum_scatter", "all_to_all", "ppermute", "pshuffle",
    ) and _laxish(node, model):
        axes = collective_axes(node, model)
        return axes
    if name in _WORLD_SANITIZERS and astutil.is_collective_call(
            node, model):
        for kw in node.keywords:
            if kw.arg == "axis_name":
                return axis_tokens(kw.value)
        return [WORLD]
    if name in ("hierarchical_allreduce", "hierarchical_all_gather") \
            and _hvdish(node, model):
        return collective_axes(node, model)
    return None


# ---------------------------------------------------------------------------
# deterministic contracts (HVD012)
# ---------------------------------------------------------------------------

# Built-in contract surface: the serve scheduler module is documented as
# a pure state machine ("every rank derives the identical schedule" —
# serve/scheduler.py docstring, the serving HVD001 invariant), the page
# allocator's block tables feed the compiled decode step on every rank
# (serve/paged.py — a divergent table desyncs the decode math itself),
# and the trace sampler's verdict must be a pure function of the trace
# id (obs/trace.py, the PR-11 determinism contract).  "*" = every
# function in the module.
CONTRACT_REGISTRY: Dict[str, Set[str]] = {
    "horovod_tpu/serve/scheduler.py": {"*"},
    "horovod_tpu/serve/paged.py": {"*"},
    "horovod_tpu/obs/trace.py": {"sampled"},
}

_CONTRACT_COMMENT_RE = re.compile(r"#\s*hvdtpu:\s*deterministic\b")


def contract_functions(model: ModuleModel) -> Dict[str, int]:
    """qualname -> def line of every function in ``model`` bound by a
    determinism contract (registry match or ``# hvdtpu: deterministic``
    on the def line / the line above)."""
    out: Dict[str, int] = {}
    registered = CONTRACT_REGISTRY.get(model.relpath, set())
    annotated: Set[int] = set()
    for i, line in enumerate(model.source.splitlines(), start=1):
        if _CONTRACT_COMMENT_RE.search(line):
            annotated.add(i)

    for qn, node in astutil.iter_defs(model.tree):
        lines = {node.lineno, node.lineno - 1}
        for deco in node.decorator_list:
            lines.add(deco.lineno - 1)
        if "*" in registered or qn in registered \
                or node.name in registered \
                or lines & annotated:
            out[qn] = node.lineno
    return out


# impure-input classifier: calls whose value differs per rank, per run,
# or per PYTHONHASHSEED — poison for a deterministic scheduler.
_IMPURE_MODULE_CALLS: Set[Tuple[str, str]] = {
    ("time", "time"), ("time", "monotonic"), ("time", "perf_counter"),
    ("time", "time_ns"), ("time", "monotonic_ns"),
    ("datetime", "now"), ("datetime", "utcnow"), ("datetime", "today"),
    ("os", "urandom"), ("uuid", "uuid1"), ("uuid", "uuid4"),
    ("secrets", "token_hex"), ("secrets", "token_bytes"),
    ("random", "random"), ("random", "randint"), ("random", "choice"),
    ("random", "shuffle"), ("random", "sample"), ("random", "uniform"),
    ("random", "randrange"), ("random", "getrandbits"),
}
_IMPURE_BARE_CALLS: Set[str] = {"hash", "id"}


def impurity_of_call(node: ast.Call,
                     model: ModuleModel) -> Optional[str]:
    """Why this call's result is not a deterministic function of its
    inputs, or None.  jax.random is exempt (explicit-key, deterministic
    by construction)."""
    name = astutil.call_name(node)
    recv = astutil.receiver_name(node)
    if recv is not None:
        target = model.module_aliases.get(recv, recv)
        if "jax" in target:
            return None
        base = target.rsplit(".", 1)[-1]
        if (base, name) in _IMPURE_MODULE_CALLS:
            return f"{base}.{name}()"
        # np.random.randint / random.choice / rng-module methods: any
        # call whose dotted receiver PATH contains a `random` segment
        # (the base-name check alone let `np.random.*` through).
        if recv != "self" and isinstance(node.func, ast.Attribute):
            segments = astutil.expr_text(node.func.value).split(".")
            segments[0] = target
            if any(seg == "random" for seg in segments):
                return f"{'.'.join(segments)}.{name}()"
    else:
        if name in _IMPURE_BARE_CALLS and isinstance(node.func, ast.Name):
            return f"{name}() (PYTHONHASHSEED/per-process value)"
        origin = model.from_imports.get(name or "")
        if origin is not None and (origin[0], origin[1]) in (
            ("time", "time"), ("time", "monotonic"),
            ("time", "perf_counter"),
        ):
            return f"{name}() [from {origin[0]}]"
    src = source_scope(node)
    if src is not None:
        return f"rank source {src[1]}"
    return None
