"""hvdtpu-lint: SPMD-correctness and concurrency static analyzer.

Run it::

    python -m horovod_tpu.analysis horovod_tpu/ examples/
    python -m horovod_tpu.analysis --changed        # fast local loop
    python -m horovod_tpu.analysis --list-rules

Two rule families (catalog: ``--list-rules`` / docs/analysis.md):

* ``HVD0xx`` — SPMD schedule correctness: rank-guarded collectives,
  unordered-container iteration, unnamed collectives in conditionals,
  missing initial-state broadcast, import-time topology reads,
  collectives in except handlers, rank-dependent names — and, since
  PR 12, the mesh-aware family: interprocedural axis-scoped rank
  taint guarding subgroup collectives (HVD010), runtime-selected
  collective axis sets (HVD011), impurity inside determinism
  contracts (HVD012), rank-tainted trace decisions (HVD013).
* ``HVDC1xx`` — concurrency discipline: lock-order inversions,
  blocking calls under locks, and the signal-path rules (non-reentrant
  locks, logging, blocking calls, unbounded growth reachable from
  death hooks), plus swallowed shutdown exceptions — and, since PR 20,
  the RacerD-style data-race family (:mod:`horovod_tpu.analysis.racer`):
  per-field guarded-by inference over thread-escaped lock-owning
  classes, reporting unguarded writes (HVDC108), unguarded reads
  against a disciplined write side (HVDC109), and lock-split
  check-then-act pairs (HVDC110).

The compiled-artifact side lives in :mod:`horovod_tpu.analysis.hlo`
(``python -m horovod_tpu.analysis.hlo``): parse scheduled HLO dumps
and assert every rank compiled the identical collective sequence.

Suppress one finding inline with ``# hvdtpu: disable=HVD001`` (same
line or the line above); acknowledge known false positives in
``analysis/baseline.json`` — every entry needs a ``reason``
(``--prune-baseline`` / ``--strict-baseline`` keep the file honest).

This package is stdlib-only (no jax import), so it runs in bare CI
images and pre-commit hooks.
"""

from .cli import analyze_paths, main  # noqa: F401
from .core import SCHEMA, Finding, Rule  # noqa: F401
from .registry import all_rules  # noqa: F401

__all__ = [
    "analyze_paths",
    "main",
    "all_rules",
    "Finding",
    "Rule",
    "SCHEMA",
]
