"""Shared AST helpers: collective-call and rank-dependence detection,
function indexing, and lightweight call extraction.

Name resolution is deliberately syntactic (RacerD-style): a call is "a
collective" because it *looks* like one (``hvd.allreduce``,
``ctx.sync_state``, a bare ``allreduce`` imported from horovod_tpu) —
no type inference.  Over-approximation is tolerable because every rule
supports inline suppression and the baseline.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .core import ModuleModel

# The negotiated/collective surface: every spelling that, when issued by
# a strict subset of ranks (or in a different order), hangs the world.
COLLECTIVE_NAMES: Set[str] = {
    "allreduce",
    "allreduce_",
    "allreduce_async",
    "allreduce_async_",
    "allreduce_sparse",
    "grouped_allreduce",
    "allgather",
    "allgather_async",
    "broadcast",
    "broadcast_",
    "broadcast_async",
    "broadcast_async_",
    "broadcast_parameters",
    "broadcast_optimizer_state",
    "broadcast_object",
    "broadcast_variables",
    "alltoall",
    "reducescatter",
    "barrier",
    "sync_state",
}
# Spellings so generic they count only with a horovod-ish receiver
# (``hvd.join()`` is the collective; ``thread.join()`` / ``"".join()``
# are not).
_HVD_RECEIVER_ONLY: Set[str] = {"join"}

# rank-valued calls: their result differs per rank, so control flow on
# them is rank-divergent by construction.
RANK_CALL_NAMES: Set[str] = {
    "rank", "local_rank", "cross_rank", "device_rank",
}
# Rank-uniform probes: same value on every rank — conditionals on these
# are NOT divergence hazards.
UNIFORM_CALL_NAMES: Set[str] = {
    "size", "local_size", "cross_size", "num_devices", "is_initialized",
    "is_homogeneous", "isinstance", "hasattr", "len",
}


def call_name(node: ast.Call) -> Optional[str]:
    """Trailing name of the called thing: ``hvd.allreduce`` -> 'allreduce',
    ``allreduce`` -> 'allreduce'."""
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def receiver_name(node: ast.Call) -> Optional[str]:
    """Base name of an attribute call's receiver: ``hvd.elastic.run`` ->
    'hvd'; bare-name calls -> None."""
    f = node.func
    if not isinstance(f, ast.Attribute):
        return None
    v = f.value
    while isinstance(v, ast.Attribute):
        v = v.value
    if isinstance(v, ast.Name):
        return v.id
    return None


def expr_text(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - malformed node
        return "<expr>"


def is_collective_call(node: ast.Call, model: ModuleModel) -> bool:
    name = call_name(node)
    if name is None:
        return False
    if name in _HVD_RECEIVER_ONLY:
        recv = receiver_name(node)
        return recv is not None and recv in model.hvd_aliases
    if name not in COLLECTIVE_NAMES:
        return False
    if isinstance(node.func, ast.Attribute):
        return True  # hvd.allreduce / ctx.allreduce / self.allreduce
    # Bare name: only when it was imported from horovod_tpu (or this is
    # a package-internal module where the def itself lives) — a user's
    # unrelated local helper named `broadcast` must not fire.
    origin = model.from_imports.get(name)
    if origin is not None:
        mod, _ = origin
        return mod == "" or "horovod_tpu" in mod or mod.startswith(".")
    return model.is_package_module


def has_name_kwarg(node: ast.Call) -> bool:
    """Whether the collective carries an explicit negotiation name."""
    for kw in node.keywords:
        if kw.arg == "name":
            return True
    # Positional name forms: ctx.allreduce(x, "loss"),
    # eager.allreduce_async(t, op, f"delta.{i}"), eager.allgather(t, "g").
    # Only literal strings / f-strings count — an arbitrary variable in
    # that slot is usually a root_rank or an op.
    for arg in node.args[1:3]:
        if isinstance(arg, ast.JoinedStr):
            return True
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return True
    return False


def name_kwarg_expr(node: ast.Call) -> Optional[ast.expr]:
    for kw in node.keywords:
        if kw.arg == "name":
            return kw.value
    return None


def _is_rank_call(node: ast.Call) -> bool:
    return call_name(node) in RANK_CALL_NAMES


def is_rank_dependent(test: ast.expr) -> bool:
    """True when a conditional's value can differ across ranks because
    it reads the rank: ``hvd.rank() == 0``, ``rank != 0``,
    ``self.rank in world``, ``local_rank() > 0`` ...

    A bare ``rank`` Name / ``.rank`` attribute counts only inside a
    comparison (so ``if self.rank_table:`` and similar don't)."""
    for node in ast.walk(test):
        if isinstance(node, ast.Call) and _is_rank_call(node):
            return True
        if isinstance(node, ast.Compare):
            operands = [node.left, *node.comparators]
            for op in operands:
                if isinstance(op, ast.Name) and op.id == "rank":
                    return True
                if isinstance(op, ast.Attribute) and op.attr == "rank":
                    return True
    return False


def is_rank_uniform_test(test: ast.expr) -> bool:
    """Conditions that provably evaluate identically on every rank:
    ``__name__ == "__main__"``, world-size probes, constants."""
    if isinstance(test, ast.Constant):
        return True
    if isinstance(test, ast.Compare):
        names = [
            n.id for n in ast.walk(test) if isinstance(n, ast.Name)
        ]
        if "__name__" in names:
            return True
    for node in ast.walk(test):
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name in RANK_CALL_NAMES:
                return False
            if name in UNIFORM_CALL_NAMES:
                return True
    return False


# ---------------------------------------------------------------------------
# function indexing + call extraction (shared by the lock-graph and
# signal-reachability passes)
# ---------------------------------------------------------------------------


@dataclass
class FunctionInfo:
    """Per-function facts, collected once per file."""

    qualname: str          # "Class.method" or "func" (nested: "f.<locals>.g")
    module: str            # relpath of the defining module
    node: ast.AST
    cls: Optional[str]     # enclosing class name, if a method
    line: int
    # (kind, data) call sites:
    #   ("bare", name)            f()
    #   ("self", name)            self.f()
    #   ("typed", (cls, name))    x.f() where x's class is known
    #   ("mod", (alias, name))    mod.f() where `mod` is an import alias
    #   ("attr", name)            anything_else.f()
    calls: List[Tuple[str, object]] = field(default_factory=list)
    # receiver name -> inferred class (annotations + constructor calls)
    type_env: Dict[str, str] = field(default_factory=dict)

    @property
    def display(self) -> str:
        return self.qualname


_TYPING_WRAPPERS = {
    "Optional", "List", "Dict", "Tuple", "Union", "Sequence", "Set",
    "FrozenSet", "Iterable", "Iterator", "Callable", "Type", "Any",
    "None", "str", "int", "float", "bool", "bytes", "object",
}


def _annotation_class(ann: ast.expr) -> Optional[str]:
    """Best-effort class name out of an annotation: ``Cls``,
    ``Optional[Cls]``, ``"Cls"`` (string annotation)."""
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return None
    for node in ast.walk(ann):
        if isinstance(node, ast.Name) and node.id not in _TYPING_WRAPPERS:
            return node.id
        if isinstance(node, ast.Attribute) and \
                node.attr not in _TYPING_WRAPPERS:
            return node.attr
    return None


def _env_from_statements(stmts: List[ast.stmt]) -> Dict[str, str]:
    """name -> class from ``x: Cls = ...`` / ``x = Cls(...)``."""
    env: Dict[str, str] = {}
    for stmt in stmts:
        if isinstance(stmt, ast.AnnAssign) and \
                isinstance(stmt.target, ast.Name):
            cls = _annotation_class(stmt.annotation)
            if cls:
                env[stmt.target.id] = cls
        elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and isinstance(stmt.value, ast.Call):
            name = call_name(stmt.value)
            # Constructor heuristic: CapWord call = instance of it.
            if name and name[:1].isupper() and "_" not in name and \
                    name not in _TYPING_WRAPPERS:
                env[stmt.targets[0].id] = name
    return env


def _param_env(func: ast.AST) -> Dict[str, str]:
    env: Dict[str, str] = {}
    args = getattr(func, "args", None)
    if args is None:
        return env
    for a in (args.posonlyargs + args.args + args.kwonlyargs):
        if a.annotation is not None:
            cls = _annotation_class(a.annotation)
            if cls:
                env[a.arg] = cls
    return env


def index_functions(model: ModuleModel) -> Dict[str, FunctionInfo]:
    """qualname -> FunctionInfo for every def in the file (methods and
    nested defs included — signal handlers are often closures)."""
    out: Dict[str, FunctionInfo] = {}
    module_env = _env_from_statements(model.tree.body)

    def visit(node: ast.AST, prefix: str, cls: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = f"{prefix}{child.name}"
                env = dict(module_env)
                env.update(_param_env(child))
                env.update(_env_from_statements(
                    [s for s in ast.walk(child)
                     if isinstance(s, ast.stmt)]
                ))
                info = FunctionInfo(
                    qualname=qn, module=model.relpath, node=child,
                    cls=cls, line=child.lineno, type_env=env,
                )
                info.calls = [
                    call_descriptor(c, env) for c in own_calls(child)
                ]
                out[qn] = info
                visit(child, f"{qn}.<locals>.", cls)
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{child.name}.", child.name)
            else:
                visit(child, prefix, cls)

    visit(model.tree, "", None)
    return out


def iter_defs(tree: ast.AST) -> Iterator[Tuple[str, ast.AST]]:
    """Yield ``(qualname, def-node)`` for every function in a module,
    in source order, using THE qualname convention every pass keys on
    (``Class.method``, nested ``f.<locals>.g``).  One implementation on
    purpose: taint summaries, contract registration and the call graph
    must agree on these names exactly, or cross-references silently
    resolve to nothing."""
    stack: List[Tuple[ast.AST, str]] = [(tree, "")]
    while stack:
        node, prefix = stack.pop(0)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = f"{prefix}{child.name}"
                yield qn, child
                stack.append((child, f"{qn}.<locals>."))
            elif isinstance(child, ast.ClassDef):
                stack.append((child, f"{child.name}."))
            else:
                stack.append((child, prefix))


def own_calls(func: ast.AST) -> List[ast.Call]:
    """Call nodes in a function body EXCLUDING nested def/class/lambda
    bodies: a closure handed to a Thread(target=...) runs on another
    thread (or not at all) — its effects belong to its own summary."""
    out: List[ast.Call] = []
    stack: List[ast.AST] = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out


def call_descriptor(node: ast.Call,
                    env: Dict[str, str]) -> Tuple[str, object]:
    """Classify one call site for name-based resolution."""
    f = node.func
    if isinstance(f, ast.Name):
        return ("bare", f.id)
    if isinstance(f, ast.Attribute):
        v = f.value
        if isinstance(v, ast.Name):
            if v.id == "self":
                return ("self", f.attr)
            cls = env.get(v.id)
            if cls is not None:
                return ("typed", (cls, f.attr))
            return ("mod", (v.id, f.attr))
        return ("attr", f.attr)
    return ("attr", "")


def iter_calls(node: ast.AST) -> Iterator[ast.Call]:
    for child in ast.walk(node):
        if isinstance(child, ast.Call):
            yield child


def enclosing_function_map(
    model: ModuleModel,
) -> Dict[int, str]:
    """line -> qualname of the innermost enclosing function, for
    stable finding contexts.  Memoized on the model: every rule family
    asks for this map and the walk is the priciest per-file pass."""
    if model.fmap_cache is not None:
        return model.fmap_cache
    spans: List[Tuple[int, int, str]] = []

    def visit(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = f"{prefix}{child.name}"
                end = getattr(child, "end_lineno", child.lineno)
                spans.append((child.lineno, end or child.lineno, qn))
                visit(child, f"{qn}.<locals>.")
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{child.name}.")
            else:
                visit(child, prefix)

    visit(model.tree, "")
    out: Dict[int, str] = {}
    # Innermost wins: sort wider spans first so narrower overwrite.
    for start, end, qn in sorted(spans, key=lambda s: -(s[1] - s[0])):
        for line in range(start, end + 1):
            out[line] = qn
    model.fmap_cache = out
    return out


def context_for_line(model: ModuleModel, line: int,
                     fmap: Optional[Dict[int, str]] = None) -> str:
    fmap = fmap if fmap is not None else enclosing_function_map(model)
    return fmap.get(line, "<module>")
