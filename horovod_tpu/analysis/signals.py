"""Signal-handler reachability: which functions can run in async-signal
context, and what they are allowed to do there.

Roots:

* handlers registered with ``signal.signal(sig, fn)``;
* callbacks registered with ``on_death(fn)`` / ``flightrec.on_death(fn)``
  — the shared death-path ``flush()`` runs them *from inside the fatal-
  signal handlers* (obs/flightrec.py), so they inherit the handler's
  constraints.

The PR-4 post-mortem found this class of bug by dying from it: a
SIGTERM landing inside a SIGUSR1 flush re-entered the flush path on the
same thread, and every non-reentrant lock on that path self-deadlocked
the dying rank.  The reachability pass makes that shape un-commitable:
a signal handler can interrupt the owning thread *between any two
bytecodes*, so anything it calls must only take reentrant locks
(HVDC103), must not log through non-reentrant logging handlers
(HVDC104), and must not grow memory without bound (HVDC107).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from . import astutil
from .core import ModuleModel
from .lockgraph import CallGraph

FuncKey = Tuple[str, str]

_DEATH_REGISTRARS = {"on_death"}


def find_roots(graph: CallGraph) -> Dict[FuncKey, str]:
    """root function -> how it becomes signal-reachable."""
    roots: Dict[FuncKey, str] = {}
    for key, info in graph.funcs.items():
        module, qualname = key
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            name = astutil.call_name(node)
            recv = astutil.receiver_name(node)
            handler_args: List[ast.expr] = []
            why = ""
            if name == "signal" and recv == "signal" and \
                    len(node.args) >= 2:
                handler_args = [node.args[1]]
                why = f"registered as a signal handler in {qualname}()"
            elif name in _DEATH_REGISTRARS and node.args:
                handler_args = [node.args[0]]
                why = (
                    f"registered via {name}() in {qualname}() — death "
                    f"callbacks run inside the fatal-signal flush"
                )
            for arg in handler_args:
                for target in _resolve_handler(graph, key, arg):
                    roots.setdefault(target, why)
    return roots


def _resolve_handler(graph: CallGraph, caller: FuncKey,
                     arg: ast.expr) -> List[FuncKey]:
    if isinstance(arg, ast.Name):
        return graph.resolve(caller, ("bare", arg.id))
    if isinstance(arg, ast.Attribute):
        v = arg.value
        if isinstance(v, ast.Name):
            if v.id == "self":
                return graph.resolve(caller, ("self", arg.attr))
            return graph.resolve(caller, ("mod", (v.id, arg.attr)))
        return graph.resolve(caller, ("attr", arg.attr))
    return []


def reachable_from(
    graph: CallGraph, roots: Dict[FuncKey, str]
) -> Dict[FuncKey, List[str]]:
    """BFS closure; value = call chain (qualnames) from a root."""
    out: Dict[FuncKey, List[str]] = {}
    queue: List[Tuple[FuncKey, List[str]]] = []
    for root, why in roots.items():
        chain = [f"{root[1]} ({why})"]
        out[root] = chain
        queue.append((root, chain))
    while queue:
        key, chain = queue.pop(0)
        info = graph.funcs.get(key)
        if info is None:
            continue
        for call in info.calls:
            for callee in graph.resolve(key, call):
                if callee in out:
                    continue
                nchain = chain + [callee[1]]
                out[callee] = nchain
                queue.append((callee, nchain))
    return out


def format_chain(chain: List[str]) -> str:
    return " -> ".join(chain)
