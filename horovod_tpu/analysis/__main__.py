"""``python -m horovod_tpu.analysis`` entry point."""

import sys

from .cli import main

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
