"""Lock-graph builder: syntactic lock acquisition + blocking-call facts.

RacerD-style compositional summaries: for every function we record
(1) which locks its body may acquire and (2) which blocking calls it may
make, then propagate both over the call graph to a fixpoint.  Lock
identity is textual-but-qualified: ``module.py::_registry_lock`` for
module globals, ``module.py::Class.self._lock`` for instance locks —
precise enough for ordering checks without points-to analysis.

"Looks like a lock" = the with-item's expression ends in a name
containing ``lock`` (``self._lock``, ``_registry_lock``,
``self.server.kv_lock``) or is a name we saw assigned from
``threading.Lock()`` / ``RLock()``.  Kind (reentrant or not) is
resolved from those assignments when available.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from . import astutil
from .core import ModuleModel

# Call spellings that can block the calling thread for unbounded (or
# operator-scale) time.  attr-qualified entries match "recv.attr";
# bare entries match a call's trailing name.
BLOCKING_MODULE_CALLS = {
    ("time", "sleep"),
    ("subprocess", "run"), ("subprocess", "call"),
    ("subprocess", "check_call"), ("subprocess", "check_output"),
    ("subprocess", "Popen"),
    ("socket", "create_connection"),
}
BLOCKING_ATTR_NAMES = {
    "wait", "wait_until_finished", "acquire_timeout",
    "recv", "recvfrom", "accept", "connect", "communicate",
    "urlopen", "readline",
}
BLOCKING_BARE_NAMES = {"sleep", "urlopen", "open"}
# `.join()` blocks when it's a thread join; `"".join(parts)` is not.
_THREADISH = ("thread", "proc", "worker", "pump")


@dataclass
class LockSite:
    lock_id: str         # qualified identity
    display: str         # as written ("self._lock")
    line: int
    kind: Optional[str]  # "Lock" | "RLock" | None (unknown)
    with_node: ast.With


@dataclass
class BlockingSite:
    what: str
    line: int


@dataclass
class FuncSummary:
    qualname: str
    module: str
    # Directly (lexically) acquired locks and blocking calls.
    locks: List[LockSite] = field(default_factory=list)
    blocking: List[BlockingSite] = field(default_factory=list)
    # Closed over the call graph (lock_id set / witness map).
    all_locks: Set[str] = field(default_factory=set)
    may_block: Dict[str, str] = field(default_factory=dict)  # what -> via


def lock_kinds(model: ModuleModel) -> Dict[str, str]:
    """Map lock display text -> 'Lock'/'RLock' from assignments like
    ``X = threading.Lock()`` / ``self._x = threading.RLock()``
    (annotated assignments included)."""
    kinds: Dict[str, str] = {}
    for node in ast.walk(model.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target, value = node.targets[0], node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            target, value = node.target, node.value
        else:
            continue
        if not isinstance(value, ast.Call):
            continue
        name = astutil.call_name(value)
        if name not in ("Lock", "RLock"):
            continue
        if isinstance(target, (ast.Name, ast.Attribute)):
            kinds[astutil.expr_text(target)] = name
    return kinds


def _lock_expr(item: ast.withitem,
               kinds: Optional[Dict[str, str]] = None) -> Optional[str]:
    """The with-item's expression text when it looks like a lock: a
    lockish name, or — regardless of name — an expression we saw
    assigned from ``threading.Lock()`` / ``RLock()`` (``self._meta =
    threading.Lock()`` guards just as hard as ``self._lock``)."""
    expr = item.context_expr
    text = astutil.expr_text(expr)
    if kinds and text in kinds:
        return text
    tail = text.rsplit(".", 1)[-1]
    if "lock" in tail.lower() or "mutex" in tail.lower():
        return text
    return None


def _qualify(model: ModuleModel, cls: Optional[str], display: str) -> str:
    if display.startswith("self."):
        return f"{model.relpath}::{cls or '?'}.{display}"
    return f"{model.relpath}::{display}"


def _is_blocking_call(node: ast.Call) -> Optional[str]:
    name = astutil.call_name(node)
    recv = astutil.receiver_name(node)
    if recv is not None and (recv, name) in BLOCKING_MODULE_CALLS:
        return f"{recv}.{name}()"
    if name in BLOCKING_ATTR_NAMES and isinstance(node.func, ast.Attribute):
        return f"{astutil.expr_text(node.func)}()"
    if name in BLOCKING_BARE_NAMES and isinstance(node.func, ast.Name):
        return f"{name}()"
    if name == "join" and isinstance(node.func, ast.Attribute):
        recv_text = astutil.expr_text(node.func.value).lower()
        has_timeout = any(kw.arg == "timeout" for kw in node.keywords)
        if has_timeout or any(t in recv_text for t in _THREADISH):
            return f"{astutil.expr_text(node.func)}()"
    return None


def summarize_module(
    model: ModuleModel,
    funcs: Dict[str, astutil.FunctionInfo],
) -> Dict[str, FuncSummary]:
    kinds = lock_kinds(model)
    out: Dict[str, FuncSummary] = {}
    for qn, info in funcs.items():
        s = FuncSummary(qualname=qn, module=model.relpath)
        own_body = _own_statements(info.node)
        for node in own_body:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    display = _lock_expr(item, kinds)
                    if display is None:
                        continue
                    s.locks.append(LockSite(
                        lock_id=_qualify(model, info.cls, display),
                        display=display,
                        line=node.lineno,
                        kind=kinds.get(display),
                        with_node=node,
                    ))
            if isinstance(node, ast.Call):
                what = _is_blocking_call(node)
                if what is not None:
                    s.blocking.append(BlockingSite(what, node.lineno))
        out[qn] = s
    return out


def _own_statements(func: ast.AST) -> List[ast.AST]:
    """Every node in the function body EXCLUDING nested function/class
    bodies (their effects belong to their own summaries)."""
    out: List[ast.AST] = []
    stack: List[ast.AST] = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out


def nodes_under_with(with_node: ast.With) -> List[ast.AST]:
    """Every node lexically inside the with body (nested defs excluded
    — they don't run while the lock is held)."""
    out: List[ast.AST] = []
    stack: List[ast.AST] = list(with_node.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out


# ---------------------------------------------------------------------------
# project-wide call-graph closure
# ---------------------------------------------------------------------------

# Method names too generic to resolve project-wide by name alone
# (collection/file/str methods): resolving `.get()` to KVStoreClient.get
# would make every dict read a blocking socket call.
GENERIC_ATTRS = {
    "get", "pop", "items", "keys", "values", "update", "clear", "copy",
    "append", "extend", "add", "remove", "discard", "setdefault",
    "count", "index", "sort", "reverse", "split", "strip", "encode",
    "decode", "format", "startswith", "endswith", "lower", "upper",
    "read", "write", "close", "flush", "done", "result", "set",
    "insert", "exists", "touch", "match", "group", "search", "sub",
    "cancel", "total_seconds", "is_alive", "getpid", "name",
    # join/wait: ''.join / os.path.join / Event.wait are everywhere —
    # resolving them to Thread-owning methods by name poisons every
    # chain.  The *direct* blocking-call detector still sees them.
    "join", "wait",
}


class CallGraph:
    """Name-based call resolution across the analyzed module set."""

    def __init__(self, models: List[ModuleModel]):
        self.models = models
        self.funcs: Dict[Tuple[str, str], astutil.FunctionInfo] = {}
        self.summaries: Dict[Tuple[str, str], FuncSummary] = {}
        self.by_module: Dict[str, Dict[str, astutil.FunctionInfo]] = {}
        # bare/method name -> [(module, qualname)]
        self._by_name: Dict[str, List[Tuple[str, str]]] = {}
        self._method_by_name: Dict[str, List[Tuple[str, str]]] = {}
        self._module_by_relpath: Dict[str, ModuleModel] = {}
        for model in models:
            funcs = astutil.index_functions(model)
            self.by_module[model.relpath] = funcs
            self._module_by_relpath[model.relpath] = model
            sums = summarize_module(model, funcs)
            for qn, info in funcs.items():
                key = (model.relpath, qn)
                self.funcs[key] = info
                self.summaries[key] = sums[qn]
                short = qn.rsplit(".", 1)[-1]
                self._by_name.setdefault(short, []).append(key)
                if info.cls is not None:
                    self._method_by_name.setdefault(short, []).append(key)

    # -- resolution --------------------------------------------------------

    def resolve(self, caller: Tuple[str, str],
                call: Tuple[str, object]) -> List[Tuple[str, str]]:
        module, qualname = caller
        model = self._module_by_relpath[module]
        kind, data = call
        if kind == "bare":
            name = str(data)
            if (module, name) in self.funcs:  # top-level def
                return [(module, name)]
            # nested defs / same-module fallback by trailing name
            local = [
                k for k in self._by_name.get(name, ()) if k[0] == module
            ]
            if local:
                return local
            origin = self._module_model(module).from_imports.get(name)
            if origin is not None:
                return self._resolve_import(module, origin)
            return []
        if kind == "self":
            name = str(data)
            info = self.funcs[caller]
            if info.cls is not None and \
                    (module, f"{info.cls}.{name}") in self.funcs:
                return [(module, f"{info.cls}.{name}")]
            # fall through to name-based method match
            return self._method_match(name)
        if kind == "typed":
            cls, name = data  # type: ignore[misc]
            qn = f"{cls}.{name}"
            hits = [
                k for k in self._by_name.get(str(name), ())
                if k[1] == qn
            ]
            if hits:
                return hits
            return self._method_match(str(name))
        if kind == "mod":
            alias, name = data  # type: ignore[misc]
            target_mod = self._resolve_module_alias(module, str(alias))
            if target_mod is not None:
                if (target_mod, str(name)) in self.funcs:
                    return [(target_mod, str(name))]
                return []
            # alias is not a module we analyze: treat as generic attr
            return self._method_match(str(name))
        if kind == "attr":
            return self._method_match(str(data))
        return []

    def _method_match(self, name: str) -> List[Tuple[str, str]]:
        if name in GENERIC_ATTRS:
            return []
        cands = self._method_by_name.get(name, [])
        # Over-approximation bound: a name implemented in many places
        # is too ambiguous to assert anything about.
        return cands if len(cands) <= 3 else []

    def _module_model(self, relpath: str) -> ModuleModel:
        return self._module_by_relpath[relpath]

    def _resolve_module_alias(self, module: str,
                              alias: str) -> Optional[str]:
        model = self._module_model(module)
        # `from . import flightrec` / `import horovod_tpu.obs.flightrec
        # as fr` — match the trailing module-name segment against the
        # analyzed relpaths.
        target = None
        if alias in model.from_imports:
            _, orig = model.from_imports[alias]
            target = orig
        elif alias in model.module_aliases:
            target = model.module_aliases[alias].rsplit(".", 1)[-1]
        if target is None:
            return None
        for relpath in self.by_module:
            if relpath.endswith(f"/{target}.py") or relpath == f"{target}.py":
                return relpath
        return None

    def _resolve_import(self, module: str,
                        origin: Tuple[str, str]) -> List[Tuple[str, str]]:
        _, name = origin
        out = []
        for relpath in self.by_module:
            if (relpath, name) in self.funcs:
                out.append((relpath, name))
        return out

    # -- fixpoint closure --------------------------------------------------

    def close_summaries(self) -> None:
        """Propagate all_locks / may_block over calls to a fixpoint."""
        for key, s in self.summaries.items():
            s.all_locks = {ls.lock_id for ls in s.locks}
            s.may_block = {b.what: "directly" for b in s.blocking}
        changed = True
        rounds = 0
        while changed and rounds < 50:
            changed = False
            rounds += 1
            for key, info in self.funcs.items():
                s = self.summaries[key]
                for call in info.calls:
                    for callee_key in self.resolve(key, call):
                        if callee_key == key:
                            continue
                        cs = self.summaries[callee_key]
                        if not cs.all_locks <= s.all_locks:
                            s.all_locks |= cs.all_locks
                            changed = True
                        for what, _via in cs.may_block.items():
                            if what not in s.may_block:
                                s.may_block[what] = (
                                    f"via {cs.qualname}() "
                                    f"[{cs.module}]"
                                )
                                changed = True

    def callees_in_region(
        self, caller: Tuple[str, str], region: List[ast.AST]
    ) -> List[Tuple[str, str]]:
        """Resolved callees for the calls lexically inside a region."""
        env = self.funcs[caller].type_env
        out: List[Tuple[str, str]] = []
        for node in region:
            if not isinstance(node, ast.Call):
                continue
            out.extend(
                self.resolve(caller, astutil.call_descriptor(node, env))
            )
        return out


# One closed CallGraph per analyzed model set, shared by every project
# rule family (concurrency, mesh-taint, determinism): building it means
# re-indexing every function in every file, so paying that once per run
# instead of once per family halves the full-surface wall clock.
# Keyed by content, not object identity: id() can be recycled across
# analyze_paths() calls and would hand a stale graph to fresh models.
_GRAPH_CACHE: Dict[tuple, CallGraph] = {}


def shared_callgraph(models: List[ModuleModel]) -> CallGraph:
    key = tuple((m.relpath, hash(m.source)) for m in models)
    g = _GRAPH_CACHE.get(key)
    if g is None:
        _GRAPH_CACHE.clear()
        g = CallGraph(models)
        g.close_summaries()
        _GRAPH_CACHE[key] = g
    return g
