"""Guarded-by inference: which lock protects which field, and who cheats.

RacerD's bet (Blackshear et al., 2018), applied to the launcher's own
thread architecture: data races are catchable *compositionally* — per
access site, record the set of locks lexically held; close that set
interprocedurally over the call graph (a helper only ever called with
``self._lock`` held is a guarded site even though it takes no lock
itself); then, per ``(class, field)``, infer the *dominant guard* — the
lock held at the overwhelming majority of post-init accesses — and
report the accesses outside it.  No interleaving exploration, no
points-to: lock identity is the same textual-but-qualified scheme the
lock-order pass uses.

Three analyses feed the HVDC108/109/110 rules in
:mod:`rules_races`:

* **Access collection** — every ``self.<attr>`` read/write in every
  method (container mutations like ``self._q.append`` count as writes),
  each tagged with the locks held *lexically* at the site.
* **Entry-lock closure** — a fixpoint over the call graph computing,
  for every function, the set of locks *guaranteed* held on entry: the
  intersection over all callers of (locks held at the call site ∪ the
  caller's own guarantee).  Thread entry points (``Thread(target=...)``
  targets, registered callbacks, signal handlers) are forced to the
  empty set — a new thread starts with no locks.
* **Escape analysis** — the RacerD ownership rule: a class is only
  *racy* if its instances can reach a second thread (it spawns threads
  from its methods, subclasses ``Thread``, registers ``self`` /
  ``self.method`` with a callback registry, or an instance is bound to
  a module global).  Unescaped classes are never reported; this is the
  single biggest false-positive filter.

Init-only writes are exempt: ``__init__`` runs before the object is
shared (up to the first escape call *inside* ``__init__`` — writes
after ``self._thread.start()`` are counted), and so do helpers whose
only callers are ``__init__``.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from . import astutil, signals
from .core import ModuleModel
from .lockgraph import CallGraph, _lock_expr, _qualify, lock_kinds

FuncKey = Tuple[str, str]          # (module relpath, qualname)
ClassKey = Tuple[str, str]         # (module relpath, class name)
FieldKey = Tuple[str, str, str]    # (module relpath, class, attr)

# Method calls on a field that mutate the receiver in place: a write to
# the field's contents for race purposes (two threads appending to one
# list race exactly like two threads assigning it).
MUTATOR_NAMES = {
    "append", "appendleft", "extend", "extendleft", "add", "insert",
    "remove", "discard", "pop", "popleft", "popitem", "update", "clear",
    "setdefault", "sort", "reverse", "put", "put_nowait",
}

# Calls that hand a callable (or the whole object) to machinery that may
# invoke it on another thread: callback registries, executors, timers.
REGISTRAR_NAMES = {
    "Thread", "Timer", "submit", "start_new_thread", "add_observer",
    "add_callback", "add_done_callback", "add_listener", "register",
    "subscribe", "on_death", "observe", "watch", "spawn", "call_soon",
    "call_later", "schedule",
}

# Field kinds that are synchronization primitives, not shared data: the
# lock IS the guard, threads/events are internally synchronized.
_SYNC_CTORS = {
    "Lock", "RLock", "Condition", "Event", "Semaphore",
    "BoundedSemaphore", "Barrier", "Thread", "Timer", "Queue",
    "SimpleQueue", "LifoQueue", "PriorityQueue", "ThreadPoolExecutor",
    "local",
}
_LOCKISH_RE = re.compile(r"(lock|mutex|cond|cv)s?$", re.IGNORECASE)

# Inference knobs (exported so tests can pin them).  A guard is inferred
# when at least GUARD_FRACTION of the counted accesses hold one lock and
# at least GUARD_MIN_SITES of them do; fields below that bar have no
# discernible discipline to enforce and stay quiet (RacerD reports
# violations of an evident protocol, not the absence of one).
GUARD_FRACTION = 0.7
GUARD_MIN_SITES = 2


@dataclass
class Access:
    """One ``self.<attr>`` touch, with its lexical lock context."""

    module: str
    cls: str
    attr: str
    write: bool
    line: int
    func: FuncKey
    held: FrozenSet[str]          # locks lexically held at the site
    init_exempt: bool = False
    # held ∪ the enclosing function's guaranteed entry locks; filled by
    # analyze() once the fixpoint has run.
    guaranteed: FrozenSet[str] = frozenset()


@dataclass
class CheckActPair:
    """A guarded field read in a branch test whose body writes the same
    field under a lock the test did not hold (check-then-act)."""

    module: str
    cls: str
    attr: str
    test_line: int
    act_line: int
    func: FuncKey
    test_held: FrozenSet[str]
    act_held: FrozenSet[str]


@dataclass
class FieldReport:
    module: str
    cls: str
    attr: str
    guard: str                    # qualified lock id
    guard_display: str            # as written ("self._lock")
    counted: int                  # post-init sites considered
    guarded: int                  # sites holding the guard
    unguarded_writes: List[Access] = field(default_factory=list)
    unguarded_reads: List[Access] = field(default_factory=list)


@dataclass
class RaceAnalysis:
    reports: List[FieldReport] = field(default_factory=list)
    check_act: List[CheckActPair] = field(default_factory=list)
    # class -> why it escapes (diagnostics / tests)
    escapes: Dict[ClassKey, str] = field(default_factory=dict)
    # function -> guaranteed-held lock set (the fixpoint result)
    entry_locks: Dict[FuncKey, FrozenSet[str]] = field(
        default_factory=dict)


def _norm_lock(lock_id: str) -> str:
    """Collapse subscripts/call arguments in a lock identity so the
    shard-striped pattern (``with self._locks[shard]:`` under one index
    name here, another there, or via a ``lock_of(shard)`` helper)
    resolves to ONE guard instead of fragmenting per spelling."""
    out = []
    depth = 0
    for ch in lock_id:
        if ch in "[(":
            if depth == 0:
                out.append(ch + "*")
            depth += 1
        elif ch in "])":
            depth -= 1
            if depth == 0:
                out.append(ch)
        elif depth == 0:
            out.append(ch)
    return "".join(out)


def _self_attr_base(node: ast.expr) -> Optional[str]:
    """``self.x`` / ``self.x[k]`` / ``self.x[k].y`` -> 'x' (the first
    attribute off ``self`` — the field whose contents are reached)."""
    seen_attr: Optional[str] = None
    cur = node
    while True:
        if isinstance(cur, ast.Attribute):
            seen_attr = cur.attr
            cur = cur.value
        elif isinstance(cur, ast.Subscript):
            cur = cur.value
        elif isinstance(cur, ast.Name):
            return seen_attr if cur.id == "self" else None
        else:
            return None


def _direct_self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


class _FuncScan:
    """Everything one lexical walk of a function yields: field
    accesses, held-lock sets per call site, and If-nodes with context
    (for the check-then-act pass)."""

    def __init__(self) -> None:
        self.accesses: List[Access] = []
        # (ast.Call node, frozenset held) in source order
        self.calls: List[Tuple[ast.Call, FrozenSet[str]]] = []
        self.ifs: List[Tuple[ast.If, FrozenSet[str]]] = []


def _scan_function(model: ModuleModel, key: FuncKey,
                   info: astutil.FunctionInfo,
                   kinds: Optional[Dict[str, str]] = None) -> _FuncScan:
    if kinds is None:
        kinds = lock_kinds(model)
    scan = _FuncScan()
    cls = info.cls
    consumed: Set[int] = set()  # Attribute node ids already classified

    def record(attr: Optional[str], write: bool, line: int,
               held: FrozenSet[str]) -> None:
        if attr is None or cls is None:
            return
        scan.accesses.append(Access(
            module=model.relpath, cls=cls, attr=attr, write=write,
            line=line, func=key, held=held,
        ))

    def visit(node: ast.AST, held: FrozenSet[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            return  # nested defs run on their own schedule
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = set(held)
            for item in node.items:
                display = _lock_expr(item, kinds)
                if display is not None:
                    inner.add(_norm_lock(_qualify(model, cls, display)))
                visit(item.context_expr, held)
                if item.optional_vars is not None:
                    visit(item.optional_vars, held)
            inner_f = frozenset(inner)
            for stmt in node.body:
                visit(stmt, inner_f)
            return
        if isinstance(node, ast.If):
            scan.ifs.append((node, held))
        if isinstance(node, ast.Call):
            scan.calls.append((node, held))
            # self._q.append(x): a write to the field the receiver
            # chain bottoms out at.
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in MUTATOR_NAMES:
                base = _self_attr_base(f.value)
                if base is not None:
                    record(base, True, node.lineno, held)
                    for sub in ast.walk(f.value):
                        consumed.add(id(sub))
        if isinstance(node, (ast.Subscript, ast.Attribute)) and \
                isinstance(node.ctx, (ast.Store, ast.Del)):
            # self.x = v / self.x[k] = v / del self.x[k]
            base = _self_attr_base(node)
            if base is not None and id(node) not in consumed:
                record(base, True, node.lineno, held)
                for sub in ast.walk(node):
                    consumed.add(id(sub))
        if isinstance(node, ast.Attribute) and \
                isinstance(node.ctx, ast.Load) and \
                id(node) not in consumed:
            attr = _direct_self_attr(node)
            if attr is not None:
                record(attr, False, node.lineno, held)
                consumed.add(id(node))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    empty: FrozenSet[str] = frozenset()
    for child in ast.iter_child_nodes(info.node):
        visit(child, empty)
    return scan


# ---------------------------------------------------------------------------
# escape analysis + thread entry points
# ---------------------------------------------------------------------------


def _callable_targets(graph: CallGraph, caller: FuncKey,
                      args: List[ast.expr]) -> List[FuncKey]:
    """Resolve callable-valued arguments (``target=self._run``, a bare
    function name, a nested closure) to function keys."""
    out: List[FuncKey] = []
    for arg in args:
        out.extend(signals._resolve_handler(graph, caller, arg))
    return out


def _spawn_args(node: ast.Call) -> List[ast.expr]:
    """The argument expressions of a spawn/registrar call that may hold
    the callable (every positional + target=/function=/callback= kw)."""
    exprs = list(node.args)
    for kw in node.keywords:
        if kw.arg in ("target", "function", "callback", "fn", "func",
                      "cb", "hook", None):
            exprs.append(kw.value)
    return exprs


def find_escapes_and_entries(
    graph: CallGraph,
) -> Tuple[Dict[ClassKey, str], Set[FuncKey]]:
    """Per-class escape witnesses + the thread-entry function set.

    A function is a thread entry when another thread may call it with no
    locks held: ``Thread(target=f)`` targets, executor submissions,
    callback registrations, signal handlers, and — transitively — every
    nested closure defined inside an entry (it runs on the entry's
    thread)."""
    escapes: Dict[ClassKey, str] = {}
    entries: Set[FuncKey] = set()

    def mark_escape(ckey: ClassKey, why: str) -> None:
        escapes.setdefault(ckey, why)

    for key, info in graph.funcs.items():
        module, qualname = key
        model = graph._module_model(module)
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            name = astutil.call_name(node)
            if name not in REGISTRAR_NAMES:
                continue
            spawn_args = _spawn_args(node)
            # the spawning class escapes: its methods (or closures over
            # self) now run on a second thread / foreign callback
            if info.cls is not None and name in (
                    "Thread", "Timer", "submit", "start_new_thread"):
                mark_escape(
                    (module, info.cls),
                    f"spawns a thread in {qualname}() (line "
                    f"{node.lineno})",
                )
            # self or self.m handed to a registry
            for arg in spawn_args:
                if isinstance(arg, ast.Name) and arg.id == "self" and \
                        info.cls is not None:
                    mark_escape(
                        (module, info.cls),
                        f"registers self via {name}() in {qualname}() "
                        f"(line {node.lineno})",
                    )
                attr = _direct_self_attr(arg)
                if attr is not None and info.cls is not None:
                    mark_escape(
                        (module, info.cls),
                        f"hands self.{attr} to {name}() in "
                        f"{qualname}() (line {node.lineno})",
                    )
                # a typed receiver: pump.submit(obj.run) etc.
                if isinstance(arg, ast.Attribute) and \
                        isinstance(arg.value, ast.Name) and \
                        arg.value.id != "self":
                    tcls = info.type_env.get(arg.value.id)
                    if tcls is not None:
                        for ck in _class_keys(graph, tcls):
                            mark_escape(
                                ck,
                                f"{arg.value.id}.{arg.attr} handed to "
                                f"{name}() in {module}::{qualname}()",
                            )
            for target in _callable_targets(graph, key, spawn_args):
                entries.add(target)
                tinfo = graph.funcs.get(target)
                if tinfo is not None and tinfo.cls is not None:
                    mark_escape(
                        (target[0], tinfo.cls),
                        f"{tinfo.qualname} runs on a thread spawned in "
                        f"{module}::{qualname}() (line {node.lineno})",
                    )

    # signal handlers / death callbacks run with arbitrary lock state on
    # whatever thread the interpreter interrupts: entry with ∅ is the
    # conservative choice for guarantee purposes.
    entries.update(signals.find_roots(graph))

    # Thread subclasses: run() is an entry, the class escapes.
    for model in graph.models:
        for node in ast.walk(model.tree):
            if isinstance(node, ast.ClassDef):
                for base in node.bases:
                    text = astutil.expr_text(base)
                    if text.rsplit(".", 1)[-1] == "Thread":
                        mark_escape(
                            (model.relpath, node.name),
                            "subclasses threading.Thread",
                        )
                        run_key = (model.relpath, f"{node.name}.run")
                        if run_key in graph.funcs:
                            entries.add(run_key)
            # module-global instance: `PUMP = IngestPump(...)` at module
            # level is reachable from any importing thread.
            if isinstance(node, ast.Module):
                for stmt in node.body:
                    if isinstance(stmt, ast.Assign) and \
                            isinstance(stmt.value, ast.Call):
                        cname = astutil.call_name(stmt.value)
                        if cname:
                            for ck in _class_keys(graph, cname):
                                mark_escape(
                                    ck,
                                    f"module-global instance in "
                                    f"{model.relpath}",
                                )

    # closures nested inside an entry run on the entry's thread
    changed = True
    while changed:
        changed = False
        for key in list(graph.funcs):
            module, qualname = key
            if key in entries or ".<locals>." not in qualname:
                continue
            outer = qualname.rsplit(".<locals>.", 1)[0]
            if (module, outer) in entries:
                entries.add(key)
                changed = True
    return escapes, entries


def _class_keys(graph: CallGraph, cls_name: str) -> List[ClassKey]:
    out = []
    for (module, qualname), info in graph.funcs.items():
        if info.cls == cls_name and qualname == f"{cls_name}.__init__":
            out.append((module, cls_name))
    if not out:
        # class with no __init__ in the analyzed set: match any method
        seen = set()
        for (module, _qn), info in graph.funcs.items():
            if info.cls == cls_name and (module, cls_name) not in seen:
                seen.add((module, cls_name))
                out.append((module, cls_name))
    return out


# ---------------------------------------------------------------------------
# entry-lock fixpoint (the guarantee closure)
# ---------------------------------------------------------------------------


def compute_entry_locks(
    graph: CallGraph,
    scans: Dict[FuncKey, _FuncScan],
    entries: Set[FuncKey],
) -> Dict[FuncKey, FrozenSet[str]]:
    """For every function, the lock set guaranteed held on entry: the
    intersection (meet) over in-edges of ``held-at-callsite ∪ caller's
    guarantee``.  Entry points and in-edge-less functions get ∅.  The
    lattice is finite and the transfer monotone, so the recompute loop
    converges; the round bound matches the lock-summary closure."""
    in_edges: Dict[FuncKey, List[Tuple[FuncKey, FrozenSet[str]]]] = {}
    for key, scan in scans.items():
        info = graph.funcs[key]
        for call, held in scan.calls:
            desc = astutil.call_descriptor(call, info.type_env)
            for callee in graph.resolve(key, desc):
                if callee != key:
                    in_edges.setdefault(callee, []).append((key, held))

    TOP = None  # "never observed called": unconstrained
    H: Dict[FuncKey, Optional[FrozenSet[str]]] = {}
    for key in graph.funcs:
        if key in entries or not in_edges.get(key):
            H[key] = frozenset()
        else:
            H[key] = TOP
    for _round in range(50):
        changed = False
        for key, edges in in_edges.items():
            if key in entries:
                continue
            contribs = []
            for caller, held in edges:
                hc = H.get(caller)
                if hc is None:
                    continue  # TOP caller constrains nothing
                contribs.append(held | hc)
            if not contribs:
                continue
            new = frozenset.intersection(*contribs)
            if H[key] is None or new != H[key]:
                # meet with the old value keeps the descent monotone
                H[key] = new if H[key] is None else (H[key] & new)
                changed = True
        if not changed:
            break
    # residual TOP = dead cycles; treat as ∅ (same as roots)
    return {k: (v if v is not None else frozenset())
            for k, v in H.items()}


# ---------------------------------------------------------------------------
# per-class field facts + guard inference
# ---------------------------------------------------------------------------


def _class_sync_attrs(graph: CallGraph,
                      ckey: ClassKey) -> Tuple[Set[str], bool]:
    """(attrs that hold synchronization primitives, class-owns-a-lock).
    Detected from ``self.x = threading.Lock()``-shaped assignments in
    any method plus the lockish-name convention."""
    module, cls = ckey
    sync: Set[str] = set()
    owns_lock = False
    for (mod, _qn), info in graph.funcs.items():
        if mod != module or info.cls != cls:
            continue
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Assign) or \
                    len(node.targets) != 1:
                continue
            attr = _direct_self_attr(node.targets[0])
            if attr is None or not isinstance(node.value, ast.Call):
                continue
            cname = astutil.call_name(node.value)
            if cname in _SYNC_CTORS:
                sync.add(attr)
                if cname in ("Lock", "RLock"):
                    owns_lock = True
            elif cname == "defaultdict" and any(
                    isinstance(a, ast.Attribute) and
                    a.attr in ("Lock", "RLock")
                    for a in node.value.args):
                sync.add(attr)       # dict-of-locks (shard striping)
                owns_lock = True
    return sync, owns_lock


def _init_exemptions(graph: CallGraph, scans: Dict[FuncKey, _FuncScan],
                     in_init_only: Set[FuncKey]) -> None:
    """Mark init-only writes exempt in place.  ``__init__`` writes are
    exempt up to the first escape-shaped call inside it (after
    ``self._thread.start()`` the object is shared); helpers called only
    from ``__init__`` are wholly exempt."""
    for key, scan in scans.items():
        info = graph.funcs[key]
        qualname = key[1]
        is_init = info.cls is not None and \
            qualname == f"{info.cls}.__init__"
        if not is_init:
            if key in in_init_only:
                for a in scan.accesses:
                    a.init_exempt = True
            continue
        escape_line = None
        for call, _held in scan.calls:
            name = astutil.call_name(call)
            if name in ("Thread", "Timer", "submit",
                        "start_new_thread") or name == "start":
                if escape_line is None or call.lineno < escape_line:
                    escape_line = call.lineno
            elif name in REGISTRAR_NAMES:
                for arg in _spawn_args(call):
                    if (isinstance(arg, ast.Name) and arg.id == "self") \
                            or _direct_self_attr(arg) is not None:
                        if escape_line is None or \
                                call.lineno < escape_line:
                            escape_line = call.lineno
        for a in scan.accesses:
            if escape_line is None or a.line < escape_line:
                a.init_exempt = True


def _init_only_callees(graph: CallGraph,
                       scans: Dict[FuncKey, _FuncScan]) -> Set[FuncKey]:
    """Methods whose every observed caller is their class's __init__
    (the one-hop "called before sharing" extension of the init rule)."""
    callers: Dict[FuncKey, Set[FuncKey]] = {}
    for key, scan in scans.items():
        info = graph.funcs[key]
        for call, _held in scan.calls:
            desc = astutil.call_descriptor(call, info.type_env)
            for callee in graph.resolve(key, desc):
                if callee != key:
                    callers.setdefault(callee, set()).add(key)
    out: Set[FuncKey] = set()
    for key, cs in callers.items():
        info = graph.funcs.get(key)
        if info is None or info.cls is None:
            continue
        init_key = (key[0], f"{info.cls}.__init__")
        if cs and all(c == init_key for c in cs):
            out.add(key)
    return out


def analyze(graph: CallGraph) -> RaceAnalysis:
    """Run the full race pipeline over a closed call graph."""
    scans: Dict[FuncKey, _FuncScan] = {}
    kinds_by_module: Dict[str, Dict[str, str]] = {}
    for key, info in graph.funcs.items():
        model = graph._module_model(key[0])
        kinds = kinds_by_module.get(key[0])
        if kinds is None:
            kinds = kinds_by_module[key[0]] = lock_kinds(model)
        scans[key] = _scan_function(model, key, info, kinds)

    escapes, entries = find_escapes_and_entries(graph)
    entry_locks = compute_entry_locks(graph, scans, entries)
    _init_exemptions(graph, scans, _init_only_callees(graph, scans))

    # attach guarantees
    for key, scan in scans.items():
        base = entry_locks.get(key, frozenset())
        for a in scan.accesses:
            a.guaranteed = a.held | base

    # group post-init accesses by field, for escaped lock-owning classes
    by_field: Dict[FieldKey, List[Access]] = {}
    class_cache: Dict[ClassKey, Tuple[Set[str], bool]] = {}
    for key, scan in scans.items():
        for a in scan.accesses:
            ckey = (a.module, a.cls)
            if ckey not in class_cache:
                class_cache[ckey] = _class_sync_attrs(graph, ckey)
            sync_attrs, owns_lock = class_cache[ckey]
            if not owns_lock or ckey not in escapes:
                continue
            if a.attr in sync_attrs or _LOCKISH_RE.search(a.attr):
                continue
            by_field.setdefault((a.module, a.cls, a.attr), []).append(a)

    analysis = RaceAnalysis(escapes=escapes, entry_locks=entry_locks)
    guards: Dict[FieldKey, str] = {}
    for fkey, accesses in sorted(by_field.items()):
        counted = [a for a in accesses if not a.init_exempt]
        writes = [a for a in counted if a.write]
        if not writes:
            continue  # immutable after construction: nothing to race
        cover: Dict[str, int] = {}
        wcover: Dict[str, int] = {}
        for a in counted:
            for lock in a.guaranteed:
                cover[lock] = cover.get(lock, 0) + 1
                if a.write:
                    wcover[lock] = wcover.get(lock, 0) + 1
        # A lock qualifies as the guard on either kind of evidence:
        # (a) it covers the overwhelming majority of ALL post-init
        #     accesses (the classic dominant-guard protocol), or
        # (b) it covers the overwhelming majority of the WRITES — the
        #     mutation side is disciplined, so the unguarded reads are
        #     racing it (the stats()/snapshot shape, where one guarded
        #     writer drowns under many lockless readers).
        # Either way at least one write must hold it: a lock that only
        # ever wraps reads is guarding something else.
        qualifying = []
        for lock, wg in wcover.items():
            tg = cover[lock]
            by_total = (tg >= GUARD_MIN_SITES
                        and tg / len(counted) >= GUARD_FRACTION)
            by_writes = wg / len(writes) >= GUARD_FRACTION
            if by_total or by_writes:
                qualifying.append((tg, lock))
        if not qualifying:
            continue  # no discernible discipline to enforce
        guarded, guard = max(qualifying)
        guards[fkey] = guard
        report = FieldReport(
            module=fkey[0], cls=fkey[1], attr=fkey[2],
            guard=guard, guard_display=guard.split("::", 1)[-1],
            counted=len(counted), guarded=guarded,
        )
        for a in counted:
            if guard in a.guaranteed:
                continue
            (report.unguarded_writes if a.write
             else report.unguarded_reads).append(a)
        if report.unguarded_writes or report.unguarded_reads:
            analysis.reports.append(report)

    # check-then-act: guarded field read in a branch test without the
    # guard, written under it inside the branch body.
    for key, scan in scans.items():
        base = entry_locks.get(key, frozenset())
        for if_node, held in scan.ifs:
            test_held = held | base
            test_attrs = {
                a for n in ast.walk(if_node.test)
                if (a := _direct_self_attr(n)) is not None
            }
            if not test_attrs:
                continue
            info = graph.funcs[key]
            if info.cls is None:
                continue
            body_start = if_node.body[0].lineno
            body_end = max(
                getattr(s, "end_lineno", s.lineno) or s.lineno
                for s in if_node.body
            )
            for a in scan.accesses:
                if not a.write or a.attr not in test_attrs:
                    continue
                if not (body_start <= a.line <= body_end):
                    continue
                fkey = (a.module, a.cls, a.attr)
                guard = guards.get(fkey)
                if guard is None:
                    continue
                if guard in test_held or guard not in a.guaranteed:
                    continue
                analysis.check_act.append(CheckActPair(
                    module=a.module, cls=a.cls, attr=a.attr,
                    test_line=if_node.test.lineno, act_line=a.line,
                    func=key, test_held=test_held,
                    act_held=a.guaranteed,
                ))
    analysis.reports.sort(key=lambda r: (r.module, r.cls, r.attr))
    analysis.check_act.sort(key=lambda p: (p.module, p.test_line))
    return analysis
