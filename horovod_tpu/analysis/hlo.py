"""HLO collective-schedule checker: the compiled-artifact gate.

The source-level rules (HVD001/HVD010/...) reject schedules that *look*
divergent; this module checks the property the runtime actually needs,
on the artifact the runtime actually executes: **every rank's compiled
program must issue the same collective sequence** — same op kinds, same
order, same replica groups, same operand bytes.  PR 9 proved the idea
for one program (``optim/overlap.inspect_schedule`` parses the
scheduled module and counts in-backward collectives); this generalizes
it into a standalone checker usable from CI for any compiled step:

* :func:`extract_schedule` — parse ``compiled.as_text()`` (or the text
  of a dumped module) and pull out the ordered collective sequence,
  per computation, with op kind, dtype/element/byte accounting, replica
  groups, and channel ids;
* :func:`diff_schedules` — structural diff of N schedules (one per
  rank, or per config expected to be identical), reporting the first
  divergence in human-readable form;
* a CLI — ``python -m horovod_tpu.analysis.hlo rank0=a.txt rank1=b.txt``
  — exit 0 when all schedules agree, 1 on divergence, 2 on usage
  errors, so the CI gate is one subprocess call.

Stdlib-only, like the rest of the package: the *producer* of the HLO
text needs jax; the checker must run anywhere (including on dumped
artifacts from a TPU job, on a laptop without jax).
"""

from __future__ import annotations

import json
import re
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

HLO_SCHEMA = "hvdtpu-hlo-schedule-v1"

# Ops that synchronize a group: if ranks disagree about any of these —
# presence, order, group shape, payload — some subset blocks forever.
# -start forms are the async halves; their -done twins are completion
# bookkeeping and carry no new schedule information.
COLLECTIVE_OPCODES = (
    "all-reduce-start",
    "all-reduce",
    "reduce-scatter",
    "all-gather-start",
    "all-gather",
    "all-to-all",
    "collective-broadcast",
    "collective-permute-start",
    "collective-permute",
)

_BYTES: Dict[str, int] = {
    "pred": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
    "f8e5m2": 1, "f8e4m3fn": 1, "f8e4m3b11fnuz": 1,
    "f8e5m2fnuz": 1, "f8e4m3fnuz": 1,
    "s4": 1, "u4": 1,  # rounded up; XLA packs two per byte
}

_SHAPE_RE = re.compile(r"([a-z]\w*)\[([0-9,]*)\]")
# Shape = whatever sits between the '=' and the opcode token: tuple
# shapes and tiled layouts ("{0:T(256)}") nest parens/braces too freely
# for a structural match, and _SHAPE_RE re-scans the capture anyway.
_OPCODE_RE = re.compile(
    r"=\s+(?P<shape>\S.*?)\s+"
    r"(?P<opcode>" + "|".join(COLLECTIVE_OPCODES) + r")\("
)
_CHANNEL_RE = re.compile(r"channel_id=(\d+)")
# Both spellings: explicit groups `replica_groups={{0,1},{2,3}}` and the
# iota form `replica_groups=[2,2]<=[4]`.
_GROUPS_RE = re.compile(
    r"replica_groups=(\{\{[^}]*(?:\},\{[^}]*)*\}\}|\{\}|"
    r"\[[0-9,]+\]<=\[[0-9,]+\](?:T\([0-9,]+\))?)"
)
_COMPUTATION_RE = re.compile(
    r"^\s*(?:ENTRY\s+)?%?(?P<name>[\w.\-]+)\s*(?:\([^)]*\))?.*\{\s*$"
)


def _shape_elements(shape_text: str) -> Tuple[int, int]:
    """(elements, bytes) over every array in a result shape (tuples
    summed — an all-reduce over a tuple moves every element)."""
    elements = 0
    nbytes = 0
    for dtype, dims in _SHAPE_RE.findall(shape_text):
        if dtype not in _BYTES:
            continue  # token/opaque types move no payload
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        elements += n
        nbytes += n * _BYTES[dtype]
    return elements, nbytes


@dataclass(frozen=True)
class CollectiveInstr:
    """One collective instruction, position-independent facts only —
    everything that must match across ranks for the schedule to be the
    same program."""

    opcode: str
    shape: str           # normalized result shape (layout stripped)
    elements: int
    nbytes: int
    replica_groups: str  # raw attribute text ("" when absent)
    channel_id: Optional[int]
    computation: str

    def signature(self) -> Tuple:
        return (self.opcode, self.shape, self.replica_groups,
                self.channel_id)

    def display(self) -> str:
        grp = self.replica_groups or "<flat>"
        ch = f", channel={self.channel_id}" \
            if self.channel_id is not None else ""
        return (f"{self.opcode} {self.shape} ({self.nbytes}B) "
                f"groups={grp}{ch} in {self.computation}")

    def as_dict(self) -> dict:
        return {
            "opcode": self.opcode, "shape": self.shape,
            "elements": self.elements, "bytes": self.nbytes,
            "replica_groups": self.replica_groups,
            "channel_id": self.channel_id,
            "computation": self.computation,
        }


@dataclass
class CollectiveSchedule:
    """The ordered collective sequence of one compiled program."""

    label: str
    instrs: List[CollectiveInstr] = field(default_factory=list)

    @property
    def total_bytes(self) -> int:
        return sum(i.nbytes for i in self.instrs)

    def signatures(self) -> List[Tuple]:
        return [i.signature() for i in self.instrs]

    def as_dict(self) -> dict:
        return {
            "schema": HLO_SCHEMA,
            "label": self.label,
            "collectives": [i.as_dict() for i in self.instrs],
            "total_bytes": self.total_bytes,
        }


def _normalize_shape(shape_text: str) -> str:
    """Strip layout annotations: ``f32[8,4]{1,0}`` and ``f32[8,4]{0,1}``
    are the same payload; layout is a backend choice, not a schedule
    property."""
    return re.sub(r"\]\{[^}]*\}", "]", shape_text).strip()


def extract_schedule(text: str, label: str = "") -> CollectiveSchedule:
    """Parse one HLO module's text into its collective sequence.

    Instruction order within a computation IS execution order for
    scheduled modules (``is_scheduled=true`` — what ``compiled
    .as_text()`` prints); for unscheduled modules it is still the
    deterministic def order, which is exactly as comparable across
    ranks.  Collectives inside nested computations (while bodies,
    conditionals) are collected under their computation's name so a
    rank whose loop body differs is caught even when the entry
    computations agree."""
    sched = CollectiveSchedule(label=label)
    computation = "<module>"
    for line in text.splitlines():
        comp = _COMPUTATION_RE.match(line)
        if comp and ("(" in line or line.lstrip().startswith("ENTRY")):
            computation = comp.group("name")
            continue
        m = _OPCODE_RE.search(line)
        if not m:
            continue
        shape = _normalize_shape(m.group("shape"))
        elements, nbytes = _shape_elements(shape)
        ch = _CHANNEL_RE.search(line)
        grp = _GROUPS_RE.search(line)
        sched.instrs.append(CollectiveInstr(
            opcode=m.group("opcode"),
            shape=shape,
            elements=elements,
            nbytes=nbytes,
            replica_groups=grp.group(1) if grp else "",
            channel_id=int(ch.group(1)) if ch else None,
            computation=computation,
        ))
    return sched


def schedule_of(compiled_or_text, label: str = "") -> CollectiveSchedule:
    """Convenience producer-side hook: accepts a lowered/compiled jax
    object or raw text (mirrors ``optim/overlap.inspect_schedule``)."""
    if hasattr(compiled_or_text, "compile"):
        compiled_or_text = compiled_or_text.compile()
    text = (compiled_or_text if isinstance(compiled_or_text, str)
            else compiled_or_text.as_text())
    return extract_schedule(text, label=label)


def diff_schedules(
    schedules: Sequence[CollectiveSchedule],
) -> List[str]:
    """Structural diff against the first schedule (the reference rank).
    Empty list = every program issues the identical collective
    sequence; otherwise each entry is one human-readable divergence.
    """
    if len(schedules) < 2:
        return []
    ref = schedules[0]
    ref_sigs = ref.signatures()
    problems: List[str] = []
    for other in schedules[1:]:
        sigs = other.signatures()
        if sigs == ref_sigs:
            continue
        if len(sigs) != len(ref_sigs):
            problems.append(
                f"{other.label}: {len(sigs)} collective(s) vs "
                f"{len(ref_sigs)} on {ref.label} — ranks disagree about "
                f"HOW MANY collectives the program issues; the extras "
                f"block forever"
            )
        n = min(len(sigs), len(ref_sigs))
        for i in range(n):
            if sigs[i] == ref_sigs[i]:
                continue
            problems.append(
                f"{other.label}: collective #{i} diverges — "
                f"{other.instrs[i].display()} vs "
                f"{ref.instrs[i].display()} on {ref.label}"
            )
            break  # first divergence per pair: the rest is noise
    return problems


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _parse_arg(arg: str) -> Tuple[str, str]:
    """``label=path`` or bare ``path`` (label = path)."""
    if "=" in arg:
        label, path = arg.split("=", 1)
        return label or path, path
    return arg, arg


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse  # noqa: PLC0415

    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.analysis.hlo",
        description="diff the collective schedules of compiled HLO "
                    "dumps: all ranks must compile the same sequence",
    )
    parser.add_argument(
        "dumps", nargs="+", metavar="LABEL=PATH",
        help="HLO text dumps to compare (first one is the reference); "
             "bare paths use the path as the label",
    )
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument(
        "--expect-collectives", type=int, default=0, metavar="N",
        help="fail unless the reference schedule has at least N "
             "collectives (guards against a gate silently comparing "
             "empty programs)",
    )
    args = parser.parse_args(argv)

    schedules: List[CollectiveSchedule] = []
    for arg in args.dumps:
        label, path = _parse_arg(arg)
        try:
            with open(path, "r", encoding="utf-8",
                      errors="replace") as f:
                text = f.read()
        except OSError as e:
            print(f"hvdtpu-hlo: cannot read {path}: {e}",
                  file=sys.stderr)
            return 2
        schedules.append(extract_schedule(text, label=label))

    problems = diff_schedules(schedules)
    ref = schedules[0]
    if len(ref.instrs) < args.expect_collectives:
        problems.insert(0, (
            f"{ref.label}: expected >= {args.expect_collectives} "
            f"collectives, found {len(ref.instrs)} — wrong dump, or "
            f"the program under test lost its collectives"
        ))

    if args.format == "json":
        print(json.dumps({
            "schema": HLO_SCHEMA,
            "schedules": [s.as_dict() for s in schedules],
            "divergences": problems,
        }, indent=2))
    else:
        for s in schedules:
            print(f"{s.label}: {len(s.instrs)} collective(s), "
                  f"{s.total_bytes} payload bytes")
        for p in problems:
            print(f"DIVERGENCE: {p}")
        if not problems:
            print(f"hvdtpu-hlo: {len(schedules)} schedule(s) identical")
    return 1 if problems else 0


if __name__ == "__main__":  # pragma: no cover - exercised via CI gate
    sys.exit(main())
