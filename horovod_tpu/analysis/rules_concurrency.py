"""Concurrency rules (HVDC1xx), aimed at the library's own thread and
signal architecture: engine background thread, obs snapshot/stream
threads, elastic heartbeat/monitor threads, and the flight recorder's
death hooks.

The lock rules follow RacerD's bet (Blackshear et al., 2018): lock-
discipline bugs are catchable *syntactically* from per-function
summaries — no interleaving exploration — if you accept a conservative
notion of "may acquire" and "may block".
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from . import astutil, signals
from .core import ModuleModel, SEV_ERROR, SEV_WARNING, Finding
from .lockgraph import CallGraph, nodes_under_with, shared_callgraph
from .registry import make_finding, rule

FuncKey = Tuple[str, str]

# Built once per run (the CLI analyzes one model set per process);
# Project rules share ONE closed call graph per model set — the memo
# lives in lockgraph.shared_callgraph so the mesh-taint family reuses
# the same graph instead of re-indexing every file.
def _graph(models: List[ModuleModel]) -> CallGraph:
    return shared_callgraph(models)


def _model_by_relpath(models: List[ModuleModel],
                      relpath: str) -> ModuleModel:
    for m in models:
        if m.relpath == relpath:
            return m
    raise KeyError(relpath)


# ---------------------------------------------------------------------------
# HVDC101 — inconsistent lock acquisition order
# ---------------------------------------------------------------------------


@rule("HVDC101", "lock-order-inversion", SEV_ERROR,
      "two locks acquired in opposite orders on different paths",
      scope="project")
def hvdc101(models: List[ModuleModel]) -> List[Finding]:
    """Thread A holding lock L1 while taking L2, and thread B holding
    L2 while taking L1, deadlock the moment both run — classically
    between the engine cycle thread and a teardown path.  The pass
    builds held-while-acquiring edges from each ``with``-body (including
    locks acquired by functions it calls) and flags any pair reachable
    in both orders.

    Minimal failing example::

        def a():
            with _table_lock:
                with _stats_lock: ...
        def b():
            with _stats_lock:
                with _table_lock: ...   # inversion: deadlock window

    Fix: pick one global order (document it where the locks are
    defined) and restructure the odd path out — usually by narrowing
    the outer critical section until the second acquisition is outside
    it."""
    graph = _graph(models)
    # edge (outer, inner) -> witness (module, line, via)
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    for key, summary in graph.summaries.items():
        for site in summary.locks:
            region = nodes_under_with(site.with_node)
            inner: Dict[str, Tuple[int, str]] = {}
            for node in region:
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for inner_site in summary.locks:
                        if inner_site.with_node is node and \
                                inner_site.lock_id != site.lock_id:
                            inner.setdefault(
                                inner_site.lock_id,
                                (node.lineno, "directly"),
                            )
            for callee in graph.callees_in_region(key, region):
                cs = graph.summaries[callee]
                for lock_id in cs.all_locks:
                    if lock_id != site.lock_id:
                        inner.setdefault(
                            lock_id,
                            (site.line, f"via {cs.qualname}()"),
                        )
            for lock_id, (line, via) in inner.items():
                edges.setdefault(
                    (site.lock_id, lock_id),
                    (key[0], line, via),
                )
    out: List[Finding] = []
    reported: Set[Tuple[str, str]] = set()
    for (a, b), (module, line, via) in sorted(edges.items()):
        if (b, a) not in edges or (b, a) in reported:
            continue
        reported.add((a, b))
        other_mod, other_line, other_via = edges[(b, a)]
        model = _model_by_relpath(models, module)
        out.append(make_finding(
            "HVDC101", model, line, 0,
            f"lock order inversion: {_short(a)} -> {_short(b)} here "
            f"({via}), but {_short(b)} -> {_short(a)} at "
            f"{other_mod}:{other_line} ({other_via}) — a deadlock "
            f"window the moment both paths run concurrently",
            f"order:{_short(a)}<->{_short(b)}",
        ))
    return out


def _short(lock_id: str) -> str:
    return lock_id.split("::", 1)[-1]


# ---------------------------------------------------------------------------
# HVDC102 — blocking call while holding a lock
# ---------------------------------------------------------------------------


@rule("HVDC102", "blocking-call-under-lock", SEV_WARNING,
      "blocking call (sleep/subprocess/socket/join/IO) under a lock",
      scope="project")
def hvdc102(models: List[ModuleModel]) -> List[Finding]:
    """A blocking call made while holding a lock turns every other
    thread that touches the lock into a hostage of the slow operation —
    the engine cycle loop stalls behind a 30 s socket timeout, or a
    heartbeat thread freezes behind a thread join.  (This is how the
    launcher's monitor can declare a perfectly healthy rank dead.)

    Minimal failing example::

        with self._lock:
            self._thread.join(timeout=30)   # everyone else now waits

    Fix: snapshot/flip state under the lock, then do the slow work
    outside it (pop-then-join, copy-then-publish)."""
    graph = _graph(models)
    out: List[Finding] = []
    for key, summary in graph.summaries.items():
        model = _model_by_relpath(models, key[0])
        for site in summary.locks:
            region = nodes_under_with(site.with_node)
            hits: List[Tuple[int, str]] = []
            for node in region:
                if isinstance(node, ast.Call):
                    from .lockgraph import _is_blocking_call  # noqa: PLC0415

                    what = _is_blocking_call(node)
                    if what is not None:
                        hits.append((node.lineno, what))
            seen_callees: Set[FuncKey] = set()
            for callee in graph.callees_in_region(key, region):
                if callee in seen_callees or callee == key:
                    continue
                seen_callees.add(callee)
                cs = graph.summaries[callee]
                if not cs.may_block:
                    continue
                # One finding per blocking callee, first witness only —
                # a full cross-product of witnesses is noise.
                what, via = sorted(cs.may_block.items())[0]
                hits.append((
                    site.line,
                    f"{what} inside {cs.qualname}() [{cs.module}]"
                    + (f" ({via})" if via != "directly" else ""),
                ))
            for line, what in sorted(set(hits)):
                out.append(make_finding(
                    "HVDC102", model, line, 0,
                    f"blocking call {what} while holding "
                    f"{site.display!r} (acquired line {site.line}): "
                    f"every thread contending this lock stalls behind "
                    f"it — move the slow work outside the critical "
                    f"section",
                    f"{summary.qualname}|{site.display}",
                ))
    return out


# ---------------------------------------------------------------------------
# HVDC103/104/107 — signal-path constraints
# ---------------------------------------------------------------------------


# Four rules walk the same reachability set; computing roots re-walks
# every function's AST, so share one result per graph instance.
_REACH_MEMO: List[tuple] = []


def _signal_reachability(models: List[ModuleModel]):
    graph = _graph(models)
    for held, reach in _REACH_MEMO:
        if held is graph:
            return graph, reach
    roots = signals.find_roots(graph)
    reach = signals.reachable_from(graph, roots)
    del _REACH_MEMO[:]
    _REACH_MEMO.append((graph, reach))
    return graph, reach


@rule("HVDC103", "nonreentrant-lock-in-signal-path", SEV_ERROR,
      "signal-reachable code takes a non-reentrant threading.Lock",
      scope="project")
def hvdc103(models: List[ModuleModel]) -> List[Finding]:
    """A signal handler runs on whatever thread the interpreter picks,
    *between any two bytecodes* — including while that same thread
    holds the lock the handler is about to take.  A plain
    ``threading.Lock`` then self-deadlocks the dying rank exactly when
    its black box matters most (the PR-4 SIGTERM-inside-SIGUSR1 flush
    deadlock).  Locks on any path reachable from a registered signal
    handler or death callback must be ``threading.RLock``.

    Minimal failing example::

        _lock = threading.Lock()          # not reentrant
        def _flush(): ...
        def handler(signum, frame):
            with _lock:                   # interrupted owner == us
                _flush()
        signal.signal(signal.SIGTERM, handler)

    Fix: ``threading.RLock()`` for every lock on the death path (and
    keep those critical sections tiny)."""
    graph, reach = _signal_reachability(models)
    out: List[Finding] = []
    for key, chain in sorted(reach.items()):
        summary = graph.summaries.get(key)
        if summary is None:
            continue
        model = _model_by_relpath(models, key[0])
        for site in summary.locks:
            if site.kind != "Lock":
                continue  # RLock fine; unknown kind: stay quiet
            out.append(make_finding(
                "HVDC103", model, site.line, 0,
                f"{site.display!r} is a non-reentrant threading.Lock "
                f"acquired on a signal-reachable path "
                f"[{signals.format_chain(chain)}]: a signal landing on "
                f"the owning thread self-deadlocks — use "
                f"threading.RLock",
                f"{summary.qualname}|{site.display}",
            ))
    return out


_LOG_RECEIVERS = {"LOG", "log", "logger", "logging"}
_LOG_METHODS = {"debug", "info", "warning", "error", "exception",
                "critical"}


@rule("HVDC104", "logging-in-signal-path", SEV_WARNING,
      "signal-reachable code logs via the logging module",
      scope="project")
def hvdc104(models: List[ModuleModel]) -> List[Finding]:
    """``logging`` handlers serialize on an internal non-reentrant
    lock: a signal handler logging while the interrupted thread was
    mid-``LOG.info`` deadlocks the same way HVDC103 does — and stream
    handlers may write to a file descriptor the dying process already
    closed.  The death path writes its evidence through the flight
    recorder's dump (atomic file replace), never through ``logging``.

    Minimal failing example::

        def on_sigterm(signum, frame):
            LOG.warning("dying")          # logging lock may be held
        signal.signal(signal.SIGTERM, on_sigterm)

    Fix: record into the flight-recorder ring (lock-free slot write
    under an RLock) and let the dump carry the message."""
    graph, reach = _signal_reachability(models)
    out: List[Finding] = []
    for key, chain in sorted(reach.items()):
        info = graph.funcs.get(key)
        if info is None:
            continue
        model = _model_by_relpath(models, key[0])
        from .lockgraph import _own_statements  # noqa: PLC0415

        for node in _own_statements(info.node):
            if not isinstance(node, ast.Call):
                continue
            name = astutil.call_name(node)
            recv = astutil.receiver_name(node)
            if name in _LOG_METHODS and recv in _LOG_RECEIVERS:
                out.append(make_finding(
                    "HVDC104", model, node.lineno, 0,
                    f"{recv}.{name}() on a signal-reachable path "
                    f"[{signals.format_chain(chain)}]: the logging "
                    f"module's handler lock is not reentrant — record "
                    f"to the flight recorder instead",
                    f"{info.qualname}",
                ))
    return out


@rule("HVDC106", "blocking-call-in-signal-path", SEV_WARNING,
      "signal-reachable code makes an unbounded blocking call",
      scope="project")
def hvdc106(models: List[ModuleModel]) -> List[Finding]:
    """The death path races the kill escalation: the launcher gives a
    dying rank ``--dump-grace-secs`` (default 5 s) between SIGTERM and
    SIGKILL.  A sleep, subprocess, or socket wait on that path spends
    the grace budget on *not writing the black box* — and a handler
    parked in a blocking syscall can't be interrupted by further
    signals the way running bytecode can.

    Minimal failing example::

        def _flush():
            time.sleep(1.0)            # burns the dump grace window
            dump()
        on_death(_flush)

    Fix: bound or remove the wait; if the call is genuinely required
    and bounded (e.g. a best-effort final publish with a timeout), keep
    it and carry a baseline entry saying so.  Ring/metrics dump file
    writes are exempt: writing the dump is the point."""
    graph, reach = _signal_reachability(models)
    out: List[Finding] = []
    for key, chain in sorted(reach.items()):
        summary = graph.summaries.get(key)
        if summary is None:
            continue
        model = _model_by_relpath(models, key[0])
        for b in summary.blocking:
            if b.what == "open()":
                continue  # dumps are the death path's purpose
            out.append(make_finding(
                "HVDC106", model, b.line, 0,
                f"blocking call {b.what} on a signal-reachable path "
                f"[{signals.format_chain(chain)}]: it spends the dump "
                f"grace window and defers further signal delivery — "
                f"bound it or move it off the death path",
                f"{summary.qualname}",
            ))
    return out


@rule("HVDC107", "unbounded-growth-in-signal-path", SEV_WARNING,
      "signal-reachable loop grows a container without bound",
      scope="project")
def hvdc107(models: List[ModuleModel]) -> List[Finding]:
    """The death path may run when the process is *already* dying of
    OOM; a flush that accumulates into an unbounded container
    (``while True: buf.append(...)``) can fail the very allocation it
    needs to write the black box.  Death-path work must be O(capacity):
    preallocated slots, bounded snapshots.

    Minimal failing example::

        def _flush():
            events = []
            while True:
                events.append(ring.next())    # grows until OOM

    Fix: iterate a bounded snapshot (the flight recorder's ring is
    fixed-capacity for exactly this reason)."""
    graph, reach = _signal_reachability(models)
    out: List[Finding] = []
    for key, chain in sorted(reach.items()):
        info = graph.funcs.get(key)
        if info is None:
            continue
        model = _model_by_relpath(models, key[0])
        for node in ast.walk(info.node):
            if not isinstance(node, ast.While):
                continue
            if not (isinstance(node.test, ast.Constant)
                    and node.test.value):
                continue  # only `while True:`-shaped loops
            if _loop_has_exit(node):
                continue
            for call in astutil.iter_calls(node):
                if astutil.call_name(call) in ("append", "extend") and \
                        isinstance(call.func, ast.Attribute):
                    out.append(make_finding(
                        "HVDC107", model, call.lineno, 0,
                        f"unbounded accumulation in a while-True loop "
                        f"on a signal-reachable path "
                        f"[{signals.format_chain(chain)}]: the death "
                        f"path may be running out of memory already — "
                        f"bound the loop",
                        f"{info.qualname}",
                    ))
    return out


def _loop_has_exit(node: ast.While) -> bool:
    for child in ast.walk(node):
        if isinstance(child, (ast.Break, ast.Return)):
            return True
    return False


# ---------------------------------------------------------------------------
# HVDC105 — broad except swallowing shutdown exceptions
# ---------------------------------------------------------------------------

_SHUTDOWN_TYPES = {
    "HorovodShutdownError", "RankDroppedError",
    "WorkersAvailableException",
}
_BROAD_TYPES = {"Exception", "BaseException", "RuntimeError"}
# Calls whose failure modes include the typed shutdown exceptions the
# elastic recovery loop keys on.
_SHUTDOWN_RAISERS = astutil.COLLECTIVE_NAMES | {
    "rendezvous", "sync", "result", "synchronize",
}


@rule("HVDC105", "shutdown-exception-swallowed", SEV_ERROR,
      "broad except around collectives swallows shutdown errors")
def hvdc105(model: ModuleModel) -> List[Finding]:
    """``HorovodShutdownError`` (and subclasses) is the signal the
    elastic recovery loop keys on: it must PROPAGATE from a failed
    collective up to ``elastic.run``'s retry loop.  A broad
    ``except Exception:`` (or bare ``except:``, or
    ``except RuntimeError:`` — the shutdown types subclass it) that
    discards the exception converts "world broke, roll back and
    re-rendezvous" into "carry on with a half-finished collective" —
    the rank then diverges from the re-formed world or hangs.

    Minimal failing example::

        try:
            total = hvd.allreduce(grad)
        except Exception:
            total = grad                 # shutdown error swallowed:
                                         # rank skips the recovery path

    Fix: catch the shutdown types first and re-raise (or let them fly)::

        except HorovodShutdownError:
            raise
        except Exception:
            total = grad

    Handlers that re-raise, or that *use* the caught exception (store
    it, wrap it, set it on a future), are not flagged."""
    out: List[Finding] = []
    fmap = astutil.enclosing_function_map(model)
    for node in ast.walk(model.tree):
        if not isinstance(node, ast.Try):
            continue
        # Does the try body submit anything that raises shutdown types?
        raiser: Optional[str] = None
        for stmt in node.body:
            for call in astutil.iter_calls(stmt):
                name = astutil.call_name(call)
                if name in _SHUTDOWN_RAISERS and (
                    astutil.is_collective_call(call, model)
                    or name not in astutil.COLLECTIVE_NAMES
                ):
                    raiser = name
                    break
            if raiser:
                break
        if raiser is None:
            continue
        narrowed = False
        for handler in node.handlers:
            caught = _caught_names(handler)
            if caught & _SHUTDOWN_TYPES:
                narrowed = True  # typed handler runs first: fine
                continue
            broad = (handler.type is None) or (caught & _BROAD_TYPES)
            if not broad or narrowed:
                continue
            if _handler_handles(handler):
                continue
            label = ", ".join(sorted(caught)) if caught else "bare except"
            out.append(make_finding(
                "HVDC105", model, handler.lineno, handler.col_offset,
                f"'{label}' swallows HorovodShutdownError raised by "
                f"'{raiser}' in the try body: elastic recovery needs "
                f"it to propagate — add `except HorovodShutdownError: "
                f"raise` above, or re-raise it here",
                astutil.context_for_line(model, handler.lineno, fmap),
            ))
    return out


def _caught_names(handler: ast.ExceptHandler) -> Set[str]:
    out: Set[str] = set()
    t = handler.type
    if t is None:
        return out
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    for e in elts:
        if isinstance(e, ast.Name):
            out.add(e.id)
        elif isinstance(e, ast.Attribute):
            out.add(e.attr)
    return out


def _handler_handles(handler: ast.ExceptHandler) -> bool:
    """The handler is fine when it re-raises or meaningfully uses the
    caught exception (defers it, wraps it, sets it on a future)."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
    var = handler.name
    if var:
        for node in ast.walk(handler):
            if isinstance(node, ast.Name) and node.id == var and \
                    isinstance(node.ctx, ast.Load):
                return True
    return False
