"""hvdtpu-lint CLI: ``python -m horovod_tpu.analysis [paths] ...``.

Exit codes: 0 = clean (every finding suppressed or baselined),
1 = new findings, 2 = usage/configuration error.
"""

from __future__ import annotations

import argparse
import json
import multiprocessing
import os
import subprocess
import sys
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import baseline as baseline_mod
from . import cache as cache_mod
from . import registry
from . import taint
from .config import LintConfig, load_config
from .core import SCHEMA, Finding, ModuleModel, is_suppressed, load_module


def _iter_py_files(paths: Sequence[str], exclude: Sequence[str],
                   root: str) -> List[str]:
    out: List[str] = []
    seen: Set[str] = set()
    excl = [os.path.normpath(os.path.join(root, e)) for e in exclude]

    def excluded(p: str) -> bool:
        np_ = os.path.normpath(p)
        return any(np_ == e or np_.startswith(e + os.sep) for e in excl)

    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(ap) and ap.endswith(".py"):
            if not excluded(ap) and ap not in seen:
                seen.add(ap)
                out.append(ap)
        elif os.path.isdir(ap):
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = [
                    d for d in sorted(dirnames)
                    if d != "__pycache__"
                    and not excluded(os.path.join(dirpath, d))
                ]
                for fn in sorted(filenames):
                    fp = os.path.join(dirpath, fn)
                    if fn.endswith(".py") and not excluded(fp) \
                            and fp not in seen:
                        seen.add(fp)
                        out.append(fp)
    return out


def _changed_files(root: str) -> List[str]:
    """Working-tree changes vs HEAD plus untracked files — the local
    pre-commit loop's file set.

    ``--name-status`` (not ``--name-only``) so deletions are dropped
    and renames contribute their NEW path: a plain name listing hands
    back paths that no longer exist (the D side of a delete, the old
    side of a rename), which then crash the per-file loop.

    ``-z`` so records are NUL-separated: the text form C-quotes paths
    containing tabs/newlines/non-ASCII, which a tab-split mangles into
    a path that isn't on disk.  A ``-z`` record is ``status NUL path``
    (two paths for R/C, old then new)."""
    files: Set[str] = set()
    try:
        res = subprocess.run(
            ["git", "diff", "--name-status", "-z", "-M", "HEAD"],
            cwd=root, capture_output=True, text=True,
            timeout=30, check=True,
        )
        toks = res.stdout.split("\0")
        i = 0
        while i < len(toks):
            status = toks[i]
            i += 1
            if not status:
                continue  # trailing NUL
            npaths = 2 if status[:1] in ("R", "C") else 1
            rec = toks[i:i + npaths]
            i += npaths
            if len(rec) < npaths:
                break  # torn record: trust only complete ones
            if status.startswith("D"):
                continue  # deleted: nothing on disk to lint
            # R100 old new / C90 src dst: the last path is the one that
            # exists in the working tree now.
            files.add(rec[-1])
        res = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard", "-z"],
            cwd=root, capture_output=True, text=True,
            timeout=30, check=True,
        )
        files.update(t for t in res.stdout.split("\0") if t)
    except (OSError, subprocess.SubprocessError) as e:
        # exit 2: environment/usage error — never 1, which the
        # documented contract reserves for "new findings".
        print(f"hvdtpu-lint: --changed needs git: {e}",
              file=sys.stderr)
        raise SystemExit(2)
    # Belt and braces: a checkout can still race the diff (a file
    # deleted between the two git calls, an unmerged path) — only paths
    # that exist right now are lintable.
    return sorted(
        f for f in files
        if f.endswith(".py") and os.path.isfile(os.path.join(root, f))
    )


def _lint_one(job: Tuple[str, str]) -> Tuple[str, Optional[dict]]:
    """``--jobs`` worker: one file's module findings plus its taint
    local phase, returned in **cache-entry shape** (plain JSON types).

    That shape is the whole trick: the result pickles cheaply across
    the process boundary, the parent validates it with the exact same
    ``findings_from_entry``/``seed_summary_memo`` path a warm on-disk
    cache hit takes, and it slots verbatim into the merged cache — so
    parallelism cannot make the cache incoherent without also breaking
    the (well-tested) cache read path."""
    path, rel = job
    model = load_module(path, rel)
    if model is None:
        return rel, None  # parse error: the parent re-reports it
    key = taint.content_key(model.source)
    module_findings = registry.run_module_rules(model)
    taint.module_taint_cached(model)  # force the local phase for dump
    return rel, cache_mod.entry_for(
        key, module_findings, taint.dump_summary_memo(key))


def analyze_paths(
    paths: Sequence[str],
    *,
    root: Optional[str] = None,
    exclude: Sequence[str] = (),
    rules: Optional[Set[str]] = None,
    cache_path: Optional[str] = None,
    jobs: int = 1,
) -> List[Finding]:
    """Library entry point: lint ``paths`` (files or directories),
    returning findings with suppression status applied (baseline is the
    CLI's job).

    ``cache_path`` (optional) points at the per-file analysis cache:
    unchanged files reuse their module-scope findings and taint
    summaries by content hash; project-scope rules always re-run (their
    verdicts span files) but start from the cached summaries.

    ``jobs`` > 1 fans the per-file work (module rules + taint local
    phase) for cache MISSES out over worker processes; cache hits and
    project-scope rules stay in-process, so the on-disk cache and the
    interprocedural closures behave identically to a serial run.
    """
    root = os.path.abspath(root or os.getcwd())
    files = _iter_py_files(paths, exclude, root)
    cached = cache_mod.load_cache(cache_path) if cache_path else {}
    new_cache: Dict[str, dict] = {}
    models: List[ModuleModel] = []
    findings: List[Finding] = []
    for path in files:
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        model = load_module(path, rel)
        if model is None:
            findings.append(Finding(
                rule="PARSE", severity="error", path=rel, line=1, col=0,
                message="file does not parse; fix the syntax error "
                        "first", context="<module>",
            ))
            continue
        models.append(model)
    dirty = False
    misses: List[ModuleModel] = []
    for model in models:
        key = taint.content_key(model.source)
        entry = cached.get(model.relpath)
        module_findings: Optional[List[Finding]] = None
        if entry is not None and entry.get("key") == key:
            module_findings = cache_mod.findings_from_entry(
                entry, model.relpath)
            raw_taint = entry.get("taint")
            if isinstance(raw_taint, dict) and raw_taint:
                taint.seed_summary_memo(key, raw_taint)
        else:
            entry = None
        if module_findings is None:
            misses.append(model)
            continue
        findings.extend(module_findings)
        if cache_path:
            new_cache[model.relpath] = (key, module_findings, entry)
    worker_entries: Dict[str, dict] = {}
    if jobs > 1 and len(misses) > 1:
        try:
            # fork where available (Linux): the workers inherit the
            # imported rule modules instead of re-importing them.
            try:
                ctx = multiprocessing.get_context("fork")
            except ValueError:
                ctx = multiprocessing.get_context()
            nproc = min(jobs, len(misses))
            with ctx.Pool(nproc) as pool:
                results = pool.map(
                    _lint_one,
                    [(m.path, m.relpath) for m in misses],
                    chunksize=max(1, len(misses) // (nproc * 4)),
                )
            worker_entries = {
                rel: entry for rel, entry in results
                if entry is not None
            }
        except OSError:
            worker_entries = {}  # no fds / sandboxed: serial fallback
    for model in misses:
        key = taint.content_key(model.source)
        entry: Optional[dict] = worker_entries.get(model.relpath)
        module_findings = None
        if entry is not None and entry.get("key") == key:
            # Same validation path as an on-disk cache hit; anything
            # malformed falls through to in-process recompute.
            module_findings = cache_mod.findings_from_entry(
                entry, model.relpath)
            raw_taint = entry.get("taint")
            if isinstance(raw_taint, dict) and raw_taint:
                taint.seed_summary_memo(key, raw_taint)
        else:
            entry = None
        if module_findings is None:
            module_findings = registry.run_module_rules(model)
            entry = None
        dirty = True
        findings.extend(module_findings)
        if cache_path:
            new_cache[model.relpath] = (key, module_findings, entry)
    findings.extend(registry.run_project_rules(models))
    if cache_path and dirty:
        # Dump AFTER the project rules: their closures force the taint
        # local phase for every model, so the summaries exist now.
        # All-hit runs skip the write entirely, and hit entries are
        # carried over verbatim — re-serializing identical summaries
        # was most of the warm-path cost.  MERGE with the prior cache:
        # a --changed run analyzes a file subset and must not clobber
        # the other files' entries; entries whose file left the disk
        # are dropped.
        merged = {
            rel: entry for rel, entry in cached.items()
            if os.path.isfile(os.path.join(root, rel))
        }
        for rel, (key, module_findings, prior) in new_cache.items():
            merged[rel] = prior if prior is not None else \
                cache_mod.entry_for(
                    key, module_findings,
                    taint.dump_summary_memo(key),
                )
        cache_mod.save_cache(cache_path, merged)
    if rules:
        findings = [f for f in findings if f.rule in rules or
                    f.rule == "PARSE"]
    by_rel: Dict[str, ModuleModel] = {m.relpath: m for m in models}
    for f in findings:
        model = by_rel.get(f.path)
        if model is not None and is_suppressed(f, model.suppressions):
            f.status = "suppressed"
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def _format_text(findings: List[Finding]) -> str:
    lines = []
    for f in findings:
        if f.status != "new":
            continue
        lines.append(
            f"{f.path}:{f.line}:{f.col}: {f.rule} [{f.severity}] "
            f"{f.message}"
        )
    counts = _counts(findings)
    lines.append(
        f"hvdtpu-lint: {counts['new']} new finding(s), "
        f"{counts['baselined']} baselined, "
        f"{counts['suppressed']} suppressed"
    )
    return "\n".join(lines)


def _counts(findings: List[Finding]) -> Dict[str, int]:
    return {
        "total": len(findings),
        "new": sum(1 for f in findings if f.status == "new"),
        "baselined": sum(1 for f in findings if f.status == "baselined"),
        "suppressed": sum(
            1 for f in findings if f.status == "suppressed"
        ),
    }


def _format_json(findings: List[Finding]) -> str:
    rules = registry.all_rules()
    doc = {
        "schema": SCHEMA,
        "rules": {
            rid: {
                "name": r.name,
                "severity": r.severity,
                "summary": r.summary,
            }
            for rid, r in sorted(rules.items())
        },
        "findings": [f.as_dict() for f in findings],
        "summary": _counts(findings),
    }
    return json.dumps(doc, indent=2)


def _list_rules() -> str:
    lines = []
    for rid, r in sorted(registry.all_rules().items()):
        lines.append(f"{rid}  {r.severity:<7}  {r.name}: {r.summary}")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.analysis",
        description="hvdtpu-lint: SPMD-correctness and concurrency "
                    "static analyzer for horovod_tpu code",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files/directories to lint (default: [tool.hvdtpu-lint] "
             "paths from pyproject.toml)",
    )
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument(
        "--baseline", metavar="FILE", default=None,
        help="baseline JSON; known findings listed there (with a "
             "reason) don't fail the run (default: from pyproject)",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore any configured baseline (report everything)",
    )
    parser.add_argument(
        "--prune-baseline", action="store_true",
        help="remove baseline entries whose finding no longer fires "
             "(full-surface runs only: a partial view cannot judge "
             "staleness)",
    )
    parser.add_argument(
        "--strict-baseline", action="store_true",
        help="exit 1 when the baseline carries stale entries (CI drift "
             "gate; full-surface runs only)",
    )
    parser.add_argument(
        "--jobs", "-j", type=int, default=1, metavar="N",
        help="analyze files with N worker processes (per-file rules + "
             "taint local phase; project-scope rules stay in-process); "
             "0 = one per CPU",
    )
    parser.add_argument(
        "--no-cache", action="store_true",
        help="skip the per-file analysis cache (content-hash keyed "
             "module findings + taint summaries)",
    )
    parser.add_argument(
        "--changed", action="store_true",
        help="lint only files changed vs HEAD (plus untracked) — the "
             "fast local pre-commit loop",
    )
    parser.add_argument(
        "--rules", metavar="IDS", default=None,
        help="comma-separated rule IDs to run (default: all)",
    )
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    parser.add_argument(
        "--write-baseline", metavar="FILE", default=None,
        help="write current findings as a baseline skeleton (reasons "
             "must be filled in before the file loads)",
    )
    parser.add_argument(
        "--root", default=None,
        help="repo root for relative paths/config (default: cwd)",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        print(_list_rules())
        return 0

    root = os.path.abspath(args.root or os.getcwd())
    # Staleness is only decidable on the full surface with every rule:
    # a --changed/--rules/explicit-path run sees a subset, so "entry
    # didn't match" means "entry wasn't looked at", not "fixed".
    partial_view = bool(args.changed or args.rules or args.paths)
    if (args.prune_baseline or args.strict_baseline) and partial_view:
        which = "--prune-baseline" if args.prune_baseline \
            else "--strict-baseline"
        print(f"hvdtpu-lint: {which} needs a full-surface run — drop "
              f"--changed/--rules/explicit paths", file=sys.stderr)
        return 2
    try:
        cfg: LintConfig = load_config(root)
    except ValueError as e:
        # Config errors are exit-code 2, same as every other usage
        # error — never 1, which scripts read as "findings".
        print(f"hvdtpu-lint: bad [tool.hvdtpu-lint] config: {e}",
              file=sys.stderr)
        return 2
    paths = list(args.paths) or list(cfg.paths)
    if args.changed:
        changed = _changed_files(root)
        # intersect with the configured lint surface
        surface = [
            os.path.normpath(p) for p in paths
        ]

        def in_surface(rel: str) -> bool:
            np_ = os.path.normpath(rel)
            return any(
                np_ == s or np_.startswith(s + os.sep) for s in surface
            ) or np_ in surface
        paths = [f for f in changed if in_surface(f)]
        if not paths:
            print("hvdtpu-lint: no changed python files under the lint "
                  "surface; nothing to do")
            return 0

    rules_filter: Optional[Set[str]] = None
    if args.rules:
        known = set(registry.all_rules())
        rules_filter = {r.strip() for r in args.rules.split(",")
                        if r.strip()}
        unknown = rules_filter - known
        if unknown:
            print(f"hvdtpu-lint: unknown rule id(s): "
                  f"{', '.join(sorted(unknown))}", file=sys.stderr)
            return 2

    cache_path: Optional[str] = None
    if cfg.cache and not args.no_cache:
        cache_path = cfg.cache if os.path.isabs(cfg.cache) else \
            os.path.join(root, cfg.cache)

    jobs = args.jobs
    if jobs < 0:
        print(f"hvdtpu-lint: --jobs must be >= 0, got {jobs}",
              file=sys.stderr)
        return 2
    if jobs == 0:
        jobs = os.cpu_count() or 1

    try:
        findings = analyze_paths(
            paths, root=root, exclude=cfg.exclude, rules=rules_filter,
            cache_path=cache_path, jobs=jobs,
        )
    except ValueError as e:  # config errors
        print(f"hvdtpu-lint: {e}", file=sys.stderr)
        return 2

    loaded_baseline: dict = {}
    stale_rc = 0
    baseline_path = args.baseline or cfg.baseline
    if baseline_path and not args.no_baseline:
        bp = baseline_path if os.path.isabs(baseline_path) else \
            os.path.join(root, baseline_path)
        if os.path.isfile(bp):
            try:
                bl = baseline_mod.load_baseline(bp)
            except (baseline_mod.BaselineError, OSError,
                    json.JSONDecodeError) as e:
                print(f"hvdtpu-lint: bad baseline: {e}", file=sys.stderr)
                return 2
            loaded_baseline = bl
            findings, unused = baseline_mod.apply_baseline(findings, bl)
            # Unused entries are only meaningful on a full-surface,
            # all-rules run; a --changed run sees a file subset and a
            # --rules run a rule subset — both would cry wolf.
            if unused and not partial_view:
                if args.prune_baseline:
                    removed = baseline_mod.prune_baseline(bp, unused)
                    for e in unused:
                        print(
                            f"hvdtpu-lint: pruned stale baseline entry: "
                            f"{e['rule']} {e['path']} {e['context']}",
                            file=sys.stderr,
                        )
                    print(f"hvdtpu-lint: removed {removed} stale "
                          f"baseline entr(y/ies) from {baseline_path}",
                          file=sys.stderr)
                else:
                    for e in unused:
                        print(
                            f"hvdtpu-lint: note: baseline entry no "
                            f"longer matches anything (fixed? remove "
                            f"it): {e['rule']} {e['path']} "
                            f"{e['context']}",
                            file=sys.stderr,
                        )
                    if args.strict_baseline:
                        print(
                            f"hvdtpu-lint: --strict-baseline: "
                            f"{len(unused)} stale baseline entr(y/ies) "
                            f"— run --prune-baseline (or delete them) "
                            f"so dead suppressions cannot swallow "
                            f"future findings", file=sys.stderr,
                        )
                        stale_rc = 1

    if args.write_baseline:
        n = baseline_mod.write_baseline(
            args.write_baseline, findings,
            reason="",  # intentionally invalid: forces a human reason
            existing=loaded_baseline,  # keep curated reasons
        )
        print(f"hvdtpu-lint: wrote {n} baseline entr(y/ies) to "
              f"{args.write_baseline}; fill in every NEW entry's "
              f"'reason' before committing (empty reasons are rejected "
              f"on load; existing entries kept theirs)")

    out = _format_json(findings) if args.format == "json" else \
        _format_text(findings)
    print(out)
    if any(f.status == "new" for f in findings):
        return 1
    return stale_rc
