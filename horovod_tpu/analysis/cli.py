"""hvdtpu-lint CLI: ``python -m horovod_tpu.analysis [paths] ...``.

Exit codes: 0 = clean (every finding suppressed or baselined),
1 = new findings, 2 = usage/configuration error.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import Dict, List, Optional, Sequence, Set

from . import baseline as baseline_mod
from . import registry
from .config import LintConfig, load_config
from .core import SCHEMA, Finding, ModuleModel, is_suppressed, load_module


def _iter_py_files(paths: Sequence[str], exclude: Sequence[str],
                   root: str) -> List[str]:
    out: List[str] = []
    seen: Set[str] = set()
    excl = [os.path.normpath(os.path.join(root, e)) for e in exclude]

    def excluded(p: str) -> bool:
        np_ = os.path.normpath(p)
        return any(np_ == e or np_.startswith(e + os.sep) for e in excl)

    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(ap) and ap.endswith(".py"):
            if not excluded(ap) and ap not in seen:
                seen.add(ap)
                out.append(ap)
        elif os.path.isdir(ap):
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = [
                    d for d in sorted(dirnames)
                    if d != "__pycache__"
                    and not excluded(os.path.join(dirpath, d))
                ]
                for fn in sorted(filenames):
                    fp = os.path.join(dirpath, fn)
                    if fn.endswith(".py") and not excluded(fp) \
                            and fp not in seen:
                        seen.add(fp)
                        out.append(fp)
    return out


def _changed_files(root: str) -> List[str]:
    """Working-tree changes vs HEAD plus untracked files — the local
    pre-commit loop's file set."""
    files: Set[str] = set()
    for args in (
        ["git", "diff", "--name-only", "HEAD"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        try:
            res = subprocess.run(
                args, cwd=root, capture_output=True, text=True,
                timeout=30, check=True,
            )
        except (OSError, subprocess.SubprocessError) as e:
            # exit 2: environment/usage error — never 1, which the
            # documented contract reserves for "new findings".
            print(f"hvdtpu-lint: --changed needs git: {e}",
                  file=sys.stderr)
            raise SystemExit(2)
        files.update(
            line.strip() for line in res.stdout.splitlines()
            if line.strip()
        )
    return sorted(f for f in files if f.endswith(".py"))


def analyze_paths(
    paths: Sequence[str],
    *,
    root: Optional[str] = None,
    exclude: Sequence[str] = (),
    rules: Optional[Set[str]] = None,
) -> List[Finding]:
    """Library entry point: lint ``paths`` (files or directories),
    returning findings with suppression status applied (baseline is the
    CLI's job)."""
    root = os.path.abspath(root or os.getcwd())
    files = _iter_py_files(paths, exclude, root)
    models: List[ModuleModel] = []
    findings: List[Finding] = []
    for path in files:
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        model = load_module(path, rel)
        if model is None:
            findings.append(Finding(
                rule="PARSE", severity="error", path=rel, line=1, col=0,
                message="file does not parse; fix the syntax error "
                        "first", context="<module>",
            ))
            continue
        models.append(model)
    for model in models:
        findings.extend(registry.run_module_rules(model))
    findings.extend(registry.run_project_rules(models))
    if rules:
        findings = [f for f in findings if f.rule in rules or
                    f.rule == "PARSE"]
    by_rel: Dict[str, ModuleModel] = {m.relpath: m for m in models}
    for f in findings:
        model = by_rel.get(f.path)
        if model is not None and is_suppressed(f, model.suppressions):
            f.status = "suppressed"
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def _format_text(findings: List[Finding]) -> str:
    lines = []
    for f in findings:
        if f.status != "new":
            continue
        lines.append(
            f"{f.path}:{f.line}:{f.col}: {f.rule} [{f.severity}] "
            f"{f.message}"
        )
    counts = _counts(findings)
    lines.append(
        f"hvdtpu-lint: {counts['new']} new finding(s), "
        f"{counts['baselined']} baselined, "
        f"{counts['suppressed']} suppressed"
    )
    return "\n".join(lines)


def _counts(findings: List[Finding]) -> Dict[str, int]:
    return {
        "total": len(findings),
        "new": sum(1 for f in findings if f.status == "new"),
        "baselined": sum(1 for f in findings if f.status == "baselined"),
        "suppressed": sum(
            1 for f in findings if f.status == "suppressed"
        ),
    }


def _format_json(findings: List[Finding]) -> str:
    rules = registry.all_rules()
    doc = {
        "schema": SCHEMA,
        "rules": {
            rid: {
                "name": r.name,
                "severity": r.severity,
                "summary": r.summary,
            }
            for rid, r in sorted(rules.items())
        },
        "findings": [f.as_dict() for f in findings],
        "summary": _counts(findings),
    }
    return json.dumps(doc, indent=2)


def _list_rules() -> str:
    lines = []
    for rid, r in sorted(registry.all_rules().items()):
        lines.append(f"{rid}  {r.severity:<7}  {r.name}: {r.summary}")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.analysis",
        description="hvdtpu-lint: SPMD-correctness and concurrency "
                    "static analyzer for horovod_tpu code",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files/directories to lint (default: [tool.hvdtpu-lint] "
             "paths from pyproject.toml)",
    )
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument(
        "--baseline", metavar="FILE", default=None,
        help="baseline JSON; known findings listed there (with a "
             "reason) don't fail the run (default: from pyproject)",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore any configured baseline (report everything)",
    )
    parser.add_argument(
        "--changed", action="store_true",
        help="lint only files changed vs HEAD (plus untracked) — the "
             "fast local pre-commit loop",
    )
    parser.add_argument(
        "--rules", metavar="IDS", default=None,
        help="comma-separated rule IDs to run (default: all)",
    )
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    parser.add_argument(
        "--write-baseline", metavar="FILE", default=None,
        help="write current findings as a baseline skeleton (reasons "
             "must be filled in before the file loads)",
    )
    parser.add_argument(
        "--root", default=None,
        help="repo root for relative paths/config (default: cwd)",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        print(_list_rules())
        return 0

    root = os.path.abspath(args.root or os.getcwd())
    try:
        cfg: LintConfig = load_config(root)
    except ValueError as e:
        # Config errors are exit-code 2, same as every other usage
        # error — never 1, which scripts read as "findings".
        print(f"hvdtpu-lint: bad [tool.hvdtpu-lint] config: {e}",
              file=sys.stderr)
        return 2
    paths = list(args.paths) or list(cfg.paths)
    if args.changed:
        changed = _changed_files(root)
        # intersect with the configured lint surface
        surface = [
            os.path.normpath(p) for p in paths
        ]

        def in_surface(rel: str) -> bool:
            np_ = os.path.normpath(rel)
            return any(
                np_ == s or np_.startswith(s + os.sep) for s in surface
            ) or np_ in surface
        paths = [f for f in changed if in_surface(f)]
        if not paths:
            print("hvdtpu-lint: no changed python files under the lint "
                  "surface; nothing to do")
            return 0

    rules_filter: Optional[Set[str]] = None
    if args.rules:
        known = set(registry.all_rules())
        rules_filter = {r.strip() for r in args.rules.split(",")
                        if r.strip()}
        unknown = rules_filter - known
        if unknown:
            print(f"hvdtpu-lint: unknown rule id(s): "
                  f"{', '.join(sorted(unknown))}", file=sys.stderr)
            return 2

    try:
        findings = analyze_paths(
            paths, root=root, exclude=cfg.exclude, rules=rules_filter,
        )
    except ValueError as e:  # config errors
        print(f"hvdtpu-lint: {e}", file=sys.stderr)
        return 2

    loaded_baseline: dict = {}
    baseline_path = args.baseline or cfg.baseline
    if baseline_path and not args.no_baseline:
        bp = baseline_path if os.path.isabs(baseline_path) else \
            os.path.join(root, baseline_path)
        if os.path.isfile(bp):
            try:
                bl = baseline_mod.load_baseline(bp)
            except (baseline_mod.BaselineError, OSError,
                    json.JSONDecodeError) as e:
                print(f"hvdtpu-lint: bad baseline: {e}", file=sys.stderr)
                return 2
            loaded_baseline = bl
            findings, unused = baseline_mod.apply_baseline(findings, bl)
            # Unused entries are only meaningful on a full-surface,
            # all-rules run; a --changed run sees a file subset and a
            # --rules run a rule subset — both would cry wolf.
            if unused and not args.changed and not args.paths \
                    and not args.rules:
                for e in unused:
                    print(
                        f"hvdtpu-lint: note: baseline entry no longer "
                        f"matches anything (fixed? remove it): "
                        f"{e['rule']} {e['path']} {e['context']}",
                        file=sys.stderr,
                    )

    if args.write_baseline:
        n = baseline_mod.write_baseline(
            args.write_baseline, findings,
            reason="",  # intentionally invalid: forces a human reason
            existing=loaded_baseline,  # keep curated reasons
        )
        print(f"hvdtpu-lint: wrote {n} baseline entr(y/ies) to "
              f"{args.write_baseline}; fill in every NEW entry's "
              f"'reason' before committing (empty reasons are rejected "
              f"on load; existing entries kept theirs)")

    out = _format_json(findings) if args.format == "json" else \
        _format_text(findings)
    print(out)
    return 1 if any(f.status == "new" for f in findings) else 0
