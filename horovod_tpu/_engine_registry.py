"""Process-wide handle to the eager engine (native background runtime).

Kept in its own module so :mod:`horovod_tpu.basics` can tear the engine down
on :func:`horovod_tpu.shutdown` without importing the engine eagerly (the
jit-only path never pays for it)."""

from __future__ import annotations

import threading
from typing import Optional

_lock = threading.Lock()
_engine = None


def get_engine():
    """Lazily start the eager engine (reference: InitializeHorovodOnce
    spawning the background thread, horovod/common/operations.cc:604-650).

    Engine selection via ``HVDTPU_EAGER_ENGINE``:

    * ``native`` — the C++ engine (cpp/hvdtpu via runtime/native.py); error
      if the library isn't built.
    * ``python`` — the pure-Python engine (runtime/engine.py).
    * ``auto`` (default) — native when the library is built and the world
      spans >1 process (a world of one short-circuits in Python for free);
      Python otherwise.
    """
    global _engine
    with _lock:
        if _engine is None:
            import os  # noqa: PLC0415

            choice = os.environ.get("HVDTPU_EAGER_ENGINE", "auto").lower()
            _engine = _make_engine(choice)
        return _engine


def _make_engine(choice: str):
    from .basics import global_topology  # noqa: PLC0415

    world = global_topology().process_count
    if choice == "native" or (choice == "auto" and world > 1):
        from .runtime import native  # noqa: PLC0415

        if native.native_available():
            import atexit  # noqa: PLC0415

            eng = native.NativeEngine()
            # Same guarantee the Python engine gives itself in start(): a
            # script that exits without hvd.shutdown() still performs the
            # coordinated shutdown cycle instead of vanishing mid-negotiation
            # and killing its peers with transport errors.
            atexit.register(eng.shutdown)
            return eng
        if choice == "native":
            raise RuntimeError(
                "HVDTPU_EAGER_ENGINE=native but the native library is not "
                f"built at {native.LIB_PATH}; run `make -C cpp`."
            )
    from .runtime.engine import EagerEngine  # noqa: PLC0415

    return EagerEngine.start()


def peek_engine() -> Optional[object]:
    return _engine


def shutdown_engine() -> None:
    global _engine
    # Swap the handle out under the lock, but run the (blocking) engine
    # teardown OUTSIDE it: shutdown joins the background thread with a
    # 30 s bound, and holding the registry lock across that would stall
    # every concurrent get_engine()/enqueue for the whole wait
    # (hvdtpu-lint HVDC102).
    with _lock:
        engine, _engine = _engine, None
    if engine is not None:
        engine.shutdown()
