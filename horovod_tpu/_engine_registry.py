"""Process-wide handle to the eager engine (native background runtime).

Kept in its own module so :mod:`horovod_tpu.basics` can tear the engine down
on :func:`horovod_tpu.shutdown` without importing the engine eagerly (the
jit-only path never pays for it)."""

from __future__ import annotations

import threading
from typing import Optional

_lock = threading.Lock()
_engine = None


def get_engine():
    """Lazily start the eager engine (reference: InitializeHorovodOnce
    spawning the background thread, horovod/common/operations.cc:604-650)."""
    global _engine
    with _lock:
        if _engine is None:
            from .runtime.engine import EagerEngine  # noqa: PLC0415

            _engine = EagerEngine.start()
        return _engine


def peek_engine() -> Optional[object]:
    return _engine


def shutdown_engine() -> None:
    global _engine
    with _lock:
        if _engine is not None:
            _engine.shutdown()
            _engine = None
