"""Checkpoint / resume and the Store abstraction.

The reference has no checkpoint engine of its own; its pattern (SURVEY.md
§5.4) is "rank 0 checkpoints through the framework, everyone else restores
by broadcast": ``broadcast_parameters`` / ``broadcast_optimizer_state``
(horovod/torch/__init__.py:452-605), the Keras/TF broadcast hooks, and the
Spark estimators persisting through a ``Store``
(horovod/spark/common/store.py:30-330).  The TPU build makes that pattern a
first-class module:

* :class:`Store` / :class:`LocalStore` — where checkpoints and run metadata
  live (the estimator layer builds on this, mirroring LocalStore/HDFSStore).
* :func:`save_checkpoint` — orbax-backed pytree save.  Rank 0 writes, other
  ranks wait at a barrier (the reference's rank-0 checkpoint discipline).
* :func:`restore_checkpoint` — rank 0 reads, then the state is broadcast to
  every rank (the broadcast-on-start primitive), so a resumed job starts
  bit-identical everywhere even if the filesystem is not shared.
"""

from __future__ import annotations

import json
import os
import shutil
import time
from typing import Any, Optional

import jax
import numpy as np

from .basics import rank, size
from .obs import get_registry

__all__ = [
    "Store",
    "LocalStore",
    "save_checkpoint",
    "save_checkpoint_async",
    "AsyncSave",
    "restore_checkpoint",
    "latest_checkpoint_step",
]


class Store:
    """Filesystem-layout contract for run artifacts (reference:
    horovod/spark/common/store.py Store — checkpoint/metadata paths keyed
    off a prefix; subclasses own the actual filesystem).
    """

    def __init__(self, prefix_path: str):
        self.prefix_path = str(prefix_path)

    # -- paths (reference store.py get_checkpoint_path/get_*_data_path) --
    def checkpoint_dir(self, run_id: str = "default") -> str:
        return os.path.join(self.prefix_path, run_id, "checkpoints")

    def metadata_path(self, run_id: str = "default") -> str:
        return os.path.join(self.prefix_path, run_id, "metadata.json")

    def logs_dir(self, run_id: str = "default") -> str:
        return os.path.join(self.prefix_path, run_id, "logs")

    # -- filesystem ops subclasses implement --
    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def makedirs(self, path: str) -> None:
        raise NotImplementedError

    def read_bytes(self, path: str) -> bytes:
        raise NotImplementedError

    def write_bytes(self, path: str, data: bytes) -> None:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    # -- metadata helpers used by the estimator layer --
    def write_metadata(self, meta: dict, run_id: str = "default") -> None:
        path = self.metadata_path(run_id)
        self.makedirs(os.path.dirname(path))
        self.write_bytes(path, json.dumps(meta, indent=2).encode())

    def read_metadata(self, run_id: str = "default") -> Optional[dict]:
        path = self.metadata_path(run_id)
        if not self.exists(path):
            return None
        return json.loads(self.read_bytes(path).decode())


class LocalStore(Store):
    """Local (or NFS-mounted) filesystem store (reference LocalStore,
    horovod/spark/common/store.py)."""

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def makedirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def read_bytes(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def write_bytes(self, path: str, data: bytes) -> None:
        # The shared atomic helper (obs/pathspec.py): per-call-unique
        # tmp name + os.replace + tmp cleanup on failure, the same
        # discipline shard writes and every obs artifact use — a crash
        # mid-save can never leave a torn file (or a stale ``.tmp``
        # that two concurrent writers would race on) for a later
        # reader to select.
        from .obs.pathspec import write_bytes_atomic  # noqa: PLC0415

        write_bytes_atomic(path, data)

    def delete(self, path: str) -> None:
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)


def _barrier() -> None:
    """All-rank sync point; no-op in a single-process world.

    Uses the eager engine's barrier only when the engine is already
    running (it owns all cross-process traffic then); otherwise a
    coordination-service sync, so a jit-only job checkpointing doesn't
    spawn the engine as a side effect.
    """
    if size() <= 1:
        return
    from ._engine_registry import peek_engine  # noqa: PLC0415

    if peek_engine() is not None:
        from .ops import eager  # noqa: PLC0415

        eager.barrier()
        return
    from jax.experimental import multihost_utils  # noqa: PLC0415

    multihost_utils.sync_global_devices("hvdtpu_checkpoint")


def _step_dir(directory: str, step: int) -> str:
    return os.path.join(directory, f"step_{step:010d}")


def _rank0_checkpointer(async_: bool = False):
    """An orbax checkpointer that only involves THIS process.

    Orbax's default checkpointers run global barriers across every jax
    process (sync_global_processes), which deadlocks the rank-0-writes
    pattern — ranks != 0 never enter save().  Restricting
    active_processes={me} keeps orbax's atomicity/async machinery without
    the cross-process sync; our own engine barrier provides the job-wide
    ordering instead.  ``async_=True`` forces the AsyncCheckpointer even
    single-process (the background-write path of
    :func:`save_checkpoint_async`).
    """
    import orbax.checkpoint as ocp  # noqa: PLC0415

    me = jax.process_index()
    if jax.process_count() <= 1:
        if async_:
            return ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
        return ocp.StandardCheckpointer()
    return ocp.AsyncCheckpointer(
        ocp.StandardCheckpointHandler(),
        multiprocessing_options=ocp.options.MultiprocessingOptions(
            primary_host=me, active_processes={me}
        ),
    )


def save_checkpoint(
    directory: str,
    state: Any,
    step: int,
    *,
    keep: Optional[int] = None,
) -> str:
    """Save a pytree checkpoint; rank 0 writes, all ranks synchronize.

    ``state`` is any pytree of arrays (params, optimizer state, rng, ...).
    ``directory`` is a local (or NFS-mounted) path — pair with
    ``LocalStore.checkpoint_dir(run_id)`` for estimator-style layouts.
    Checkpoint bytes always go through orbax on the filesystem; the Store
    abstraction covers run *metadata*, not tensor data.
    ``keep``: retain at most this many newest step directories (>= 1).
    Returns the step directory path.
    """
    return save_checkpoint_async(directory, state, step,
                                 keep=keep).wait()


class AsyncSave:
    """Handle for an in-flight :func:`save_checkpoint_async`.

    ``wait()`` finalizes the save — rank 0 blocks until orbax's
    background write commits, applies retention, and closes the
    checkpointer; every rank then passes the job barrier.  Call it
    before the next save to the same directory (or at shutdown); until
    then training steps overlap the checkpoint I/O.
    """

    def __init__(self, path, ckptr=None, directory=None, keep=None,
                 error=None):
        self.path = path
        self._ckptr = ckptr
        self._directory = directory
        self._keep = keep
        self._error = error  # a save() failure deferred to wait()
        self._finalized = False

    def wait(self) -> str:
        if self._finalized:
            # repeat wait() must not silently bless a failed save
            if self._error is not None:
                raise self._error
            return self.path
        t_wait = time.monotonic()
        try:
            if self._ckptr is not None:  # rank 0
                try:
                    self._ckptr.wait_until_finished()
                    if self._keep is not None:
                        steps = sorted(_list_step_dirs(self._directory))
                        for old in steps[: max(len(steps) - self._keep,
                                               0)]:
                            shutil.rmtree(
                                _step_dir(self._directory, old),
                                ignore_errors=True,
                            )
                except Exception as exc:
                    self._error = exc
                finally:
                    try:
                        self._ckptr.close()
                    except Exception:
                        pass
        finally:
            # a failed background write must still release the peers:
            # without the barrier in the finally, ranks != 0 (whose
            # handles have no checkpointer) would block forever while
            # rank 0 raises
            _barrier()
            self._finalized = True
        # Commit-status propagation (ADVICE r5 #2): without this, a
        # failed rank-0 save raised on rank 0 only — every other rank
        # returned the step path and trained on believing the commit
        # point exists.  After the release barrier, rank 0 broadcasts
        # its outcome; survivors turn a non-None outcome into their own
        # raise, so the commit contract is all-or-nothing on EVERY rank.
        if size() > 1:
            from .optim import broadcast_object  # noqa: PLC0415

            summary = (
                f"{type(self._error).__name__}: {self._error}"
                if self._error is not None and rank() == 0 else None
            )
            summary = broadcast_object(summary, root_rank=0)
            if summary is not None and self._error is None:
                self._error = RuntimeError(
                    f"checkpoint save of {self.path!r} failed on rank 0 "
                    f"({summary}); no rank may treat this step as "
                    f"committed"
                )
        metrics = get_registry()
        metrics.histogram("checkpoint.commit_wait_ms").observe(
            (time.monotonic() - t_wait) * 1e3
        )
        from .obs import flightrec as _flightrec  # noqa: PLC0415

        if self._error is not None:
            metrics.counter("checkpoint.save_errors").inc()
            _flightrec.record(
                "ckpt.error", name=os.path.basename(self.path),
                detail=str(self._error)[:200],
            )
            raise self._error
        metrics.counter("checkpoint.saves_committed").inc()
        _flightrec.record("ckpt.commit", name=os.path.basename(self.path))
        return self.path


def save_checkpoint_async(
    directory: str,
    state: Any,
    step: int,
    *,
    keep: Optional[int] = None,
) -> AsyncSave:
    """:func:`save_checkpoint` without stalling the training loop.

    Rank 0 hands the pytree to an orbax ``AsyncCheckpointer`` (device
    arrays are snapshotted, then written by a background thread) and
    returns immediately; the returned handle's ``wait()`` is the commit
    point — retention and the job-wide barrier happen there, so the
    reference's rank-0-writes/all-ranks-sync contract still holds, just
    deferred.  The TPU-native goodput move the reference has no analog
    for: steps keep running while the checkpoint streams out.
    """
    if keep is not None and keep < 1:
        raise ValueError(f"keep must be >= 1, got {keep}")
    path = _step_dir(directory, step)
    get_registry().counter("checkpoint.saves_started").inc()
    # Flight recorder: a rank that dies between begin and commit leaves
    # the half-open pair in its ring — the post-mortem's proof the death
    # landed inside checkpoint I/O.
    from .obs import flightrec as _flightrec  # noqa: PLC0415

    _flightrec.record("ckpt.begin", name=f"step{step}", cycle=step)
    if rank() != 0:
        return AsyncSave(path)
    try:
        from .testing.faults import maybe_fail  # noqa: PLC0415

        # Chaos point "ckpt_write": a deterministic stand-in for the disk
        # full / permission lost / orbax failure the deferred-error path
        # exists for (HVDTPU_FAULT_SPEC="ckpt_write:step=N:rank=0").
        maybe_fail("ckpt_write", step=step)
        os.makedirs(directory, exist_ok=True)
        ckptr = _rank0_checkpointer(async_=True)
        # orbax refuses to overwrite; force=True matches the reference's
        # framework-checkpoint overwrite behavior on re-save of a step.
        ckptr.save(
            os.path.abspath(path),
            jax.tree_util.tree_map(np.asarray, state),
            force=True,
        )
    except Exception as exc:
        # rank 0 failing before a handle exists must not strand ranks
        # != 0 in wait()'s barrier — defer the raise to wait(), after
        # the barrier releases everyone
        return AsyncSave(path, error=exc)
    return AsyncSave(path, ckptr, directory, keep)


def _list_step_dirs(directory: str):
    if not os.path.isdir(directory):
        return []
    steps = []
    for name in os.listdir(directory):
        if name.startswith("step_"):
            try:
                steps.append(int(name[len("step_"):]))
            except ValueError:
                continue
    return steps


def latest_checkpoint_step(directory: str) -> Optional[int]:
    steps = _list_step_dirs(directory)
    return max(steps) if steps else None


def restore_checkpoint(
    directory: str,
    target: Any,
    step: Optional[int] = None,
    *,
    broadcast: bool = True,
) -> Any:
    """Restore a checkpoint and (by default) broadcast it from rank 0.

    ``target`` is a pytree of the expected structure/shapes (abstract or
    concrete).  ``step=None`` restores the latest.  With ``broadcast=True``
    only rank 0 needs the files — every other rank receives the state over
    the wire (reference broadcast_parameters-on-start,
    horovod/torch/__init__.py:452-530), which also guarantees bit-identical
    resume across ranks on non-shared filesystems.
    """
    t_restore = time.monotonic()
    needs_files = rank() == 0 or not broadcast or size() <= 1
    if step is None:
        # Resolve "latest" only where the files are required to exist; on a
        # non-shared filesystem the other ranks have no checkpoint dir and
        # receive the resolved step (or the failure) from rank 0.
        if needs_files:
            step = latest_checkpoint_step(directory)
        if broadcast and size() > 1:
            from .optim import broadcast_object  # noqa: PLC0415

            step = broadcast_object(step, root_rank=0)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory!r}")
    state = None
    if needs_files:
        ckptr = _rank0_checkpointer()
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype)
            if not isinstance(x, jax.ShapeDtypeStruct)
            else x,
            target,
        )
        state = ckptr.restore(os.path.abspath(_step_dir(directory, step)),
                              abstract)
        ckptr.close()
    if broadcast and size() > 1:
        from .optim import broadcast_object  # noqa: PLC0415

        state = broadcast_object(state, root_rank=0)
    metrics = get_registry()
    metrics.counter("checkpoint.restores").inc()
    metrics.histogram("checkpoint.restore_ms").observe(
        (time.monotonic() - t_restore) * 1e3
    )
    return state
