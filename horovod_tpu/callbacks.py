"""Training-loop callbacks.

Reference: the shared Keras callback implementations
(horovod/_keras/callbacks.py:20-185): BroadcastGlobalVariablesCallback,
MetricAverageCallback, LearningRateScheduleCallback,
LearningRateWarmupCallback.  The TPU build targets functional training
loops (flax/optax), so each callback exists in the idiomatic form:

* broadcast  -> :func:`horovod_tpu.broadcast_parameters` called at start
  (wrapped here as a callback object for loop frameworks that want one);
* metric averaging -> :func:`metric_average` (an eager allreduce, and a
  jit-safe variant);
* LR schedules -> **optax schedule constructors** with the reference's
  exact warmup/staircase semantics, because in JAX the schedule must be a
  traced function of the step, not a mutable callback.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax.numpy as jnp
import optax

from .basics import DP_AXIS, size
from .ops.collectives import Average, allreduce

__all__ = [
    "BroadcastGlobalVariablesCallback",
    "MetricAverageCallback",
    "metric_average",
    "LearningRateScheduleCallback",
    "LearningRateWarmupCallback",
    "warmup_schedule",
    "multiplier_schedule",
]


def metric_average(value, name: Optional[str] = None, *, axis_name: str = DP_AXIS):
    """Average a metric across workers (reference: MetricAverageCallback,
    _keras/callbacks.py:46-72, which allreduces epoch metrics).

    Inside jit/shard_map this lowers to a psum; outside it routes through
    the eager engine — hvd.allreduce performs that dispatch itself."""
    return allreduce(
        value, op=Average, axis_name=axis_name, name=name or "metric"
    )


class BroadcastGlobalVariablesCallback:
    """Broadcast initial state from root once, at the first step
    (reference: _keras/callbacks.py:20-44, fires on_batch_end of batch 0)."""

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank
        self._done = False

    def __call__(self, params):
        from .optim import broadcast_parameters  # noqa: PLC0415

        if self._done:
            return params
        self._done = True
        return broadcast_parameters(params, self.root_rank)


class MetricAverageCallback:
    """Average a dict of metrics across workers at epoch end
    (reference: _keras/callbacks.py:46-72)."""

    def __call__(self, metrics: dict) -> dict:
        return {k: metric_average(v, name=k) for k, v in metrics.items()}


def warmup_schedule(
    base_lr: float,
    *,
    warmup_epochs: float = 5.0,
    steps_per_epoch: int,
    scale: Optional[float] = None,
    momentum_correction: bool = False,
) -> optax.Schedule:
    """The reference's LearningRateWarmupCallback as an optax schedule
    (_keras/callbacks.py:116-185): ramp lr from ``base_lr`` to
    ``base_lr * scale`` (default: world size — the linear scaling rule from
    Goyal et al., which the callback cites) over ``warmup_epochs`` epochs,
    with the same exponential-in-epoch interpolation::

        lr = base_lr * scale^(epoch / warmup_epochs)   clipped at scale
    """
    del momentum_correction  # torch-specific; optax momentum is stateless in lr
    target_scale = float(scale) if scale is not None else float(size())

    def schedule(step):
        epoch = step / steps_per_epoch
        frac = jnp.minimum(epoch / warmup_epochs, 1.0)
        return base_lr * jnp.power(target_scale, frac)

    return schedule


def multiplier_schedule(
    base_lr: float,
    multiplier: Callable[[float], float] | Sequence[tuple[float, float]],
    *,
    steps_per_epoch: int,
    staircase: bool = True,
) -> optax.Schedule:
    """The reference's LearningRateScheduleCallback (_keras/callbacks.py:74-114):
    lr = base_lr * multiplier(epoch).  ``multiplier`` may be a python
    function of epoch (evaluated at trace time per step via jnp ops is not
    possible for arbitrary python; so list form) or a list of
    (start_epoch, multiplier) breakpoints applied in order."""
    if callable(multiplier):
        # Sample the python function per epoch over a generous horizon and
        # turn it into a piecewise-constant schedule (staircase) — keeps
        # arbitrary python logic out of the traced step.
        horizon = 1000
        values = [float(multiplier(e)) for e in range(horizon)]
        table = jnp.asarray(values) * base_lr

        def schedule(step):
            epoch = step // steps_per_epoch if staircase else step / steps_per_epoch
            idx = jnp.clip(jnp.asarray(epoch, jnp.int32), 0, horizon - 1)
            return table[idx]

        return schedule

    points = sorted(multiplier)

    def schedule(step):
        epoch = step / steps_per_epoch
        mult = jnp.asarray(1.0)
        for start, m in points:
            mult = jnp.where(epoch >= start, m, mult)
        return base_lr * mult

    return schedule


# Class-style aliases so reference call sites port mechanically.
LearningRateWarmupCallback = warmup_schedule
LearningRateScheduleCallback = multiplier_schedule
