"""Env knob parsing (reference: horovod/common/utils/env_parser.cc and the
canonical knob list at common.h:62-88).

All runtime configuration converges on environment variables, exactly as in
the reference (SURVEY.md §5.6): the launcher maps CLI flags onto env vars
for every rank; the engine reads them at startup."""

from __future__ import annotations

import os

# Canonical knob names (HVDTPU_* ≙ HOROVOD_* of common.h:62-88).
FUSION_THRESHOLD = "HVDTPU_FUSION_THRESHOLD"
DEFAULT_FUSION_BYTES = 64 * 1024 * 1024  # reference operations.cc:419
CYCLE_TIME = "HVDTPU_CYCLE_TIME"
TIMELINE = "HVDTPU_TIMELINE"
TIMELINE_MARK_CYCLES = "HVDTPU_TIMELINE_MARK_CYCLES"
STALL_CHECK_TIME = "HVDTPU_STALL_CHECK_TIME_SECONDS"
STALL_SHUTDOWN_TIME = "HVDTPU_STALL_SHUTDOWN_TIME_SECONDS"
STALL_CHECK_DISABLE = "HVDTPU_STALL_CHECK_DISABLE"
CACHE_CAPACITY = "HVDTPU_CACHE_CAPACITY"
HIERARCHICAL_ALLREDUCE = "HVDTPU_HIERARCHICAL_ALLREDUCE"
# Multi-slice topology (ICI within a slice, DCN between slices).  The
# slice partition is discovered from the platform when it can be
# (jax Device.slice_index on real multislice deployments) and forced
# otherwise: NUM_SLICES partitions the world into that many contiguous
# equal blocks of processes; SLICE_SIZE is the same knob expressed as
# processes-per-slice (the forced partition that lets every multislice
# code path run on a CPU dev world).  NUM_SLICES wins when both are set.
NUM_SLICES = "HVDTPU_NUM_SLICES"
SLICE_SIZE = "HVDTPU_SLICE_SIZE"
# Wire dtype for the cross-slice (DCN) leg of hierarchical allreduce:
# none (negotiated dtype), bf16, or fp16 (ops/compression.py).  Only the
# 1/local_size shard that crosses DCN is cast; ICI phases stay exact.
DCN_COMPRESSION = "HVDTPU_DCN_COMPRESSION"
AUTOTUNE = "HVDTPU_AUTOTUNE"
AUTOTUNE_LOG = "HVDTPU_AUTOTUNE_LOG"
# Sampling-window knobs (reference common.h:67-69
# HOROVOD_AUTOTUNE_{WARMUP_SAMPLES,STEPS_PER_SAMPLE,BAYES_OPT_MAX_SAMPLES}).
AUTOTUNE_WARMUP_SAMPLES = "HVDTPU_AUTOTUNE_WARMUP_SAMPLES"
AUTOTUNE_STEPS_PER_SAMPLE = "HVDTPU_AUTOTUNE_STEPS_PER_SAMPLE"
AUTOTUNE_BAYES_OPT_MAX_SAMPLES = "HVDTPU_AUTOTUNE_BAYES_OPT_MAX_SAMPLES"
AUTOTUNE_GP_NOISE = "HVDTPU_AUTOTUNE_GAUSSIAN_PROCESS_NOISE"
# Online-tuner drift detector (no reference analog: the reference tunes
# once and freezes, parameter_manager.cc SetAutoTuning(false); ours keeps
# scoring after convergence and re-opens the GP search when throughput
# regresses by DRIFT_THRESHOLD (fraction) for DRIFT_SAMPLES consecutive
# score windows — elastic world changes and workload phase changes move
# the optimum, and a frozen tuner would hold a stale incumbent forever).
AUTOTUNE_DRIFT_THRESHOLD = "HVDTPU_AUTOTUNE_DRIFT_THRESHOLD"
AUTOTUNE_DRIFT_SAMPLES = "HVDTPU_AUTOTUNE_DRIFT_SAMPLES"
# Backward-overlap gradient plane (optim/overlap.py): gradient-bucket
# size cap in MB for the jit path's in-backward bucketed collectives,
# and the default overlap mode bench.py/--overlap resolves through.
# Unlike fusion_mb, the bucket size is baked into the compiled program
# (moving it forces an XLA recompile), so it is swept offline
# (autotune.grad_bucket_candidates) rather than tuned live.
GRAD_BUCKET_MB = "HVDTPU_GRAD_BUCKET_MB"
DEFAULT_GRAD_BUCKET_MB = 16.0
OVERLAP = "HVDTPU_OVERLAP"
# Steady-state schedule replay (GSPMD-style static schedule, recreated
# dynamically): after REPLAY_CYCLES consecutive cycles whose executed
# schedule is bitwise-identical on every rank, the engine stops
# exchanging control vectors and replays the memorized fused schedule,
# re-validated by a one-scalar epoch-check lane on the first fused
# buffer of each cycle.  SCHEDULE_REPLAY=0 (--no-schedule-replay) opts
# out; any deviation breaks the epoch back to full negotiation.
SCHEDULE_REPLAY = "HVDTPU_SCHEDULE_REPLAY"
SCHEDULE_REPLAY_CYCLES = "HVDTPU_SCHEDULE_REPLAY_CYCLES"
DEFAULT_REPLAY_CYCLES = 50
LOG_LEVEL = "HVDTPU_LOG_LEVEL"
# Device-resident eager data plane (no reference analog by name: the
# reference's equivalent switch is compile-time HOROVOD_GPU_ALLREDUCE).
EAGER_DEVICE = "HVDTPU_EAGER_DEVICE"
# Per-rank metrics dump target (obs/registry.py); a dir, a {rank}
# template, or a plain path that gets a rank tag inserted.
METRICS_DUMP = "HVDTPU_METRICS_DUMP"
# Live telemetry plane (obs/stream.py + obs/live.py): per-rank metric
# snapshot period in seconds (<= 0 or unset disables streaming) and the
# launcher KV endpoint the snapshots are published to over the
# HMAC-signed PUT path (falls back to HVDTPU_ELASTIC_KV under the
# elastic launcher, which reuses its rendezvous store).
LIVE_STATS = "HVDTPU_LIVE_STATS_SECS"
LIVE_KV = "HVDTPU_LIVE_KV"
# Straggler attribution alert threshold in milliseconds: a collective
# whose first-to-last arrival skew exceeds this warns and counts an
# engine.straggler.alerts event (0/unset = record silently).
ALERT_SKEW = "HVDTPU_ALERT_SKEW_MS"
# Flight recorder (obs/flightrec.py): where each rank dumps its
# in-memory event ring on any death path (same dir/{rank}/plain-path
# forms as METRICS_DUMP; unset = ring records but never dumps), and the
# ring capacity in events (default 512).  The launcher sets the dump
# target itself when the user did not, so crashed jobs always leave a
# black box for obs/postmortem.py.
FLIGHTREC_DUMP = "HVDTPU_FLIGHTREC_DUMP"
FLIGHTREC_CAPACITY = "HVDTPU_FLIGHTREC_CAPACITY"
# Sharded checkpoint + peer-replica recovery tier (ckpt/): CKPT_DIR is
# the sharded-manifest directory the elastic State tier saves to and
# falls back to on restore when no live peer holds a valid replica;
# CKPT_REPLICA turns on the in-memory replica push after every commit
# (each rank mirrors its committed shard to its ring neighbor's key
# over the HMAC-signed KV path, chunked at CKPT_REPLICA_CHUNK_KB);
# CKPT_COMMIT_TIMEOUT bounds the manifest-commit wait on every rank.
CKPT_DIR = "HVDTPU_CKPT_DIR"
CKPT_REPLICA = "HVDTPU_CKPT_REPLICA"
CKPT_REPLICA_CHUNK_KB = "HVDTPU_CKPT_REPLICA_CHUNK_KB"
DEFAULT_REPLICA_CHUNK_KB = 1024
CKPT_COMMIT_TIMEOUT = "HVDTPU_CKPT_COMMIT_TIMEOUT_SECS"
DEFAULT_CKPT_COMMIT_TIMEOUT = 120.0
# Request-level distributed tracing (obs/trace.py): TRACE is the
# per-rank span dump target (same dir/{rank}/plain-path forms as
# METRICS_DUMP, stem "spans"; unset = tracing off, zero hot-path cost).
# TRACE_SAMPLE_RATE is the fraction of requests traced (default 1.0);
# the sampling decision is a pure function of the trace id, so every
# rank and the launcher reach the SAME verdict with no coordination —
# the HVD001 invariant applies to sampling decisions.  TRACE_CAPACITY
# bounds the in-memory span ring per process (default 8192).
TRACE = "HVDTPU_TRACE"
TRACE_SAMPLE_RATE = "HVDTPU_TRACE_SAMPLE_RATE"
TRACE_CAPACITY = "HVDTPU_TRACE_CAPACITY"
# Serving plane (serve/): fleet-wide model geometry the `hvdrun
# --elastic --serve` launcher forwards to every serving rank (the
# python -m horovod_tpu.serve worker reads them as flag fallbacks).
# SERVE_SEED must be identical on every rank — the replicated-params
# determinism the identical-schedule invariant rests on.
SERVE_MODEL = "HVDTPU_SERVE_MODEL"
SERVE_SLOTS = "HVDTPU_SERVE_SLOTS"
SERVE_MAX_LEN = "HVDTPU_SERVE_MAX_LEN"
SERVE_SEED = "HVDTPU_SERVE_SEED"
# Paged KV memory + width-sharded fleets (serve/paged.py, ISSUE 15):
# KV_MODE paged|contiguous, PAGE_SIZE token rows per page, KV_PAGES
# the page-pool size (unset = worst case), WIDTH >= 1 carves the
# world into size//WIDTH serving groups (each independently serving
# its log partition) with each rank's paged decode shard_mapped over
# WIDTH local devices.  All fleet-wide: the block tables and the
# schedule must be identical on every rank of a group.
SERVE_KV_MODE = "HVDTPU_SERVE_KV_MODE"
SERVE_PAGE_SIZE = "HVDTPU_SERVE_PAGE_SIZE"
SERVE_KV_PAGES = "HVDTPU_SERVE_KV_PAGES"
SERVE_WIDTH = "HVDTPU_SERVE_WIDTH"
# Weight hot-swap (serve/hotswap.py): WEIGHTS_DIR is the sharded-
# checkpoint directory a concurrently-training publisher commits
# versions into (unset = hot-swap off); SWAP_POLL_STEPS is the
# leader's manifest-poll cadence in serving steps.  OUT_TTL bounds how
# long the ingest pump retains a FINISHED request's compacted result
# doc for late client polls (request-log compaction, frontend.py).
SERVE_WEIGHTS_DIR = "HVDTPU_SERVE_WEIGHTS_DIR"
SERVE_SWAP_POLL_STEPS = "HVDTPU_SERVE_SWAP_POLL_STEPS"
SERVE_OUT_TTL = "HVDTPU_SERVE_OUT_TTL_SECS"
DEFAULT_SERVE_OUT_TTL = 300.0
# Sharded front door + tenant QoS (ISSUE 16): FRONTENDS is the
# launcher-side shard count F (F ingest pumps, rid-hash routed;
# workers learn it from the serve/frontdoor doc, not this env);
# TENANT_BUDGET arms tenant-aware weighted-fair admission with this
# many tokens per tenant per budget window (fleet-wide — every rank
# must derive the identical admission policy).
SERVE_FRONTENDS = "HVDTPU_SERVE_FRONTENDS"
SERVE_TENANT_BUDGET = "HVDTPU_SERVE_TENANT_BUDGET"
# SLO objectives (obs/slo.py, ISSUE 17): latency targets for one SLO
# class (SLO_CLASS, default "interactive") — TTFT/TPOT ceilings in ms
# and the objective fraction (default 0.99 = 1% error budget).  Fleet-
# wide like the QoS policy: every rank judges the same objectives, so
# they travel the launcher-forwarded env.
SERVE_SLO_CLASS = "HVDTPU_SERVE_SLO_CLASS"
SERVE_SLO_TTFT_MS = "HVDTPU_SERVE_SLO_TTFT_MS"
SERVE_SLO_TPOT_MS = "HVDTPU_SERVE_SLO_TPOT_MS"
SERVE_SLO_OBJECTIVE = "HVDTPU_SERVE_SLO_OBJECTIVE"
# Autoscale (serve/autoscale.py): launcher-local knobs; carried as env
# so config files can set them and operators can see them in ps.  The
# envelope ceiling MAX_WORKERS also sizes the launcher's slot
# allocation (standby ranks need hosts the moment a grow admits them).
SERVE_AUTOSCALE = "HVDTPU_SERVE_AUTOSCALE"
MAX_WORKERS = "HVDTPU_MAX_WORKERS"
SCALE_UP_QUEUE = "HVDTPU_SCALE_UP_QUEUE"
SCALE_DOWN_IDLE_SECS = "HVDTPU_SCALE_DOWN_IDLE_SECS"
SCALE_COOLDOWN_SECS = "HVDTPU_SCALE_COOLDOWN_SECS"
# Training-health plane (obs/health.py, obs/divergence.py, ISSUE 18):
# HEALTH arms the in-graph numerics bundle + anomaly judge ("on"/"off",
# default off — off must leave the compiled step HLO byte-identical);
# HEALTH_CHECK_STEPS is the divergence sentinel's cadence N (digest
# allgather every N steps, default 100); DIVERGENCE_ACTION is what a
# confirmed divergence does: warn | dump | halt.  Fleet-wide: the
# sentinel's exchange is itself a collective, so every rank must derive
# the identical cadence and action (HVD001 applies to the checker too).
HEALTH = "HVDTPU_HEALTH"
HEALTH_CHECK_STEPS = "HVDTPU_HEALTH_CHECK_STEPS"
DIVERGENCE_ACTION = "HVDTPU_DIVERGENCE_ACTION"


def resolve_rank(default=None):
    """This process's rank per the launcher env contract: HVDTPU_RANK
    (static jobs) first, then HVDTPU_ELASTIC_RANK (elastic workers).
    The single definition both the fault injector and the metrics dump
    use — the two must never disagree about which rank a process is."""
    for name in ("HVDTPU_RANK", "HVDTPU_ELASTIC_RANK"):
        value = os.environ.get(name)
        if value not in (None, ""):
            return int(value)
    return default


# The launcher process inherits the job's dump env (METRICS_DUMP,
# FLIGHTREC_DUMP from the user's shell) but has no HVDTPU_RANK, so an
# env-driven artifact dump in the launcher would resolve to rank 0 and
# CLOBBER worker rank 0's evidence.  Launchers self-identify here; their
# artifacts get a distinct "launcher" tag the aggregators ignore.
_is_launcher = False


def mark_launcher() -> None:
    global _is_launcher
    _is_launcher = True


def artifact_rank() -> str:
    """The rank tag per-rank artifact dumps (metrics, flight recorder)
    file under: the resolved rank for workers, ``launcher`` for a
    marked launcher process.  An explicit rank env wins over the
    launcher mark — a process that is both (in-process API tests, or a
    worker driving a sub-job) is a worker first."""
    rank = resolve_rank(None)
    if rank is None and _is_launcher:
        return "launcher"
    return str(rank if rank is not None else 0)


def env_int(name: str, default: int) -> int:
    value = os.environ.get(name)
    return int(value) if value not in (None, "") else default


def env_float(name: str, default: float) -> float:
    value = os.environ.get(name)
    return float(value) if value not in (None, "") else default


def env_bool(name: str, default: bool = False) -> bool:
    value = os.environ.get(name)
    if value in (None, ""):
        return default
    return value.lower() in ("1", "true", "yes", "on")
