"""Shared utilities: logging, env parsing."""

from .logging import get_logger, log  # noqa: F401
from .env import env_bool, env_float, env_int  # noqa: F401
