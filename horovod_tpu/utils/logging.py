"""Logging (reference: horovod/common/logging.cc — LOG(level, rank) macros
to stderr, controlled by HOROVOD_LOG_LEVEL / HOROVOD_LOG_HIDE_TIME).

Maps onto python logging with the same env contract, HVDTPU_-prefixed."""

from __future__ import annotations

import logging
import os
import sys

_LEVELS = {
    "trace": logging.DEBUG - 5,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}

_configured = False


def _configure() -> None:
    global _configured
    if _configured:
        return
    level_name = os.environ.get("HVDTPU_LOG_LEVEL", "warning").lower()
    level = _LEVELS.get(level_name, logging.WARNING)
    handler = logging.StreamHandler(sys.stderr)
    if os.environ.get("HVDTPU_LOG_HIDE_TIME", "0") in ("1", "true"):
        fmt = "[%(levelname)s] %(name)s: %(message)s"
    else:
        fmt = "%(asctime)s [%(levelname)s] %(name)s: %(message)s"
    handler.setFormatter(logging.Formatter(fmt))
    root = logging.getLogger("horovod_tpu")
    root.addHandler(handler)
    root.setLevel(level)
    root.propagate = False
    _configured = True


def get_logger(name: str = "horovod_tpu") -> logging.Logger:
    _configure()
    if not name.startswith("horovod_tpu"):
        name = f"horovod_tpu.{name}"
    return logging.getLogger(name)


def log(level: str, msg: str, *args) -> None:
    get_logger().log(_LEVELS.get(level, logging.INFO), msg, *args)
