"""Process/topology bootstrap for horovod_tpu.

TPU-native analog of the reference's ``HorovodBasics`` ctypes layer
(reference: horovod/common/basics.py:22-66) and the C init path
(horovod/common/operations.cc:604-650).  Where the reference spawns a
background MPI/Gloo controller thread per process, the TPU build wires up
``jax.distributed`` (the JAX coordination service plays the role of the Gloo
HTTP rendezvous, reference horovod/common/gloo/gloo_context.cc:113-157) and
builds named device meshes over which XLA collectives compile.

Rank semantics
--------------
The reference runs one process per accelerator, so ``rank() == device``.
On TPU one process owns several chips, so the concepts split:

* ``rank()`` / ``size()``            -- process-level (one per host by default).
  This is what the eager per-op engine coordinates over, exactly like the
  reference controller negotiates over MPI ranks.
* ``local_rank()`` / ``local_size()`` -- process index within the host
  (reference: horovod/common/mpi/mpi_controller.cc:25-81 local_comm split).
* ``cross_rank()`` / ``cross_size()`` -- one-process-per-host axis
  (reference Communicator::CROSS, horovod/common/common.h:111-115).
* ``num_devices()`` / ``device_rank()`` -- chip-level; this is the width of
  the data-parallel mesh axis the jit path psums over, and the number that
  matters for scaling efficiency.

Environment contract (set by ``hvdrun``, mirroring HOROVOD_RANK/... set by
the reference launcher, horovod/run/gloo_run.py:143-165):

    HVDTPU_RANK / HVDTPU_SIZE
    HVDTPU_LOCAL_RANK / HVDTPU_LOCAL_SIZE
    HVDTPU_CROSS_RANK / HVDTPU_CROSS_SIZE
    HVDTPU_COORDINATOR        host:port of the jax.distributed coordinator
    HVDTPU_CONTROLLER_PORT    base port for the eager-engine controller mesh
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import numpy as np

__all__ = [
    "init",
    "shutdown",
    "is_initialized",
    "rank",
    "size",
    "local_rank",
    "local_size",
    "cross_rank",
    "cross_size",
    "num_devices",
    "device_rank",
    "is_homogeneous",
    "mesh",
    "global_topology",
    "DP_AXIS",
    "CROSS_AXIS",
    "LOCAL_AXIS",
]

# Canonical mesh axis names.  DP_AXIS is the flat data-parallel axis every
# collective defaults to (the analog of Communicator::GLOBAL); CROSS/LOCAL
# form the 2D hierarchical mesh (DCN x ICI), the analog of the reference's
# cross/local communicators used by NCCLHierarchicalAllreduce
# (horovod/common/ops/nccl_operations.cc:162-300).
DP_AXIS = "hvd"
CROSS_AXIS = "hvd_cross"
LOCAL_AXIS = "hvd_local"


class NotInitializedError(RuntimeError):
    def __init__(self) -> None:
        super().__init__(
            "horovod_tpu has not been initialized; call horovod_tpu.init() first."
        )


@dataclass
class Topology:
    """Static view of the job, fixed at init() (SPMD world is static;
    the reference's dynamic Join story is handled at the op layer)."""

    process_rank: int
    process_count: int
    local_rank: int
    local_size: int
    cross_rank: int
    cross_size: int
    devices: Sequence[jax.Device] = field(default_factory=list)
    homogeneous: bool = True
    # Whether init() started jax.distributed itself; shutdown() only tears
    # down what it owns (≙ the reference's MPIContextManager negotiating
    # MPI_Init/Finalize ownership, horovod/common/mpi/mpi_context.cc).
    owns_jax_distributed: bool = False

    @property
    def num_devices(self) -> int:
        return len(self.devices)


_state_lock = threading.Lock()
_topology: Optional[Topology] = None
_mesh_cache: dict = {}


def _env_int(name: str, default: int) -> int:
    value = os.environ.get(name)
    return int(value) if value not in (None, "") else default


def init(comm=None) -> Topology:
    """Initialize the framework (reference: horovod_init, operations.cc:663).

    Safe to call more than once (the reference spin-waits on
    initialization_done, operations.cc:646-648; here re-init is a no-op).

    ``comm`` is accepted for API compatibility with the reference's
    sub-communicator init (horovod/common/basics.py:33-65) but only the
    default (whole-world) communicator is supported on TPU, where process
    membership is fixed by the coordination service.
    """
    global _topology
    with _state_lock:
        if _topology is not None:
            return _topology
        if comm is not None and comm not in ([], None):
            raise ValueError(
                "horovod_tpu.init(comm=...) sub-communicators are not supported; "
                "the TPU world is defined by the coordination service."
            )

        world = _env_int("HVDTPU_SIZE", 1)
        proc = _env_int("HVDTPU_RANK", 0)
        coordinator = os.environ.get("HVDTPU_COORDINATOR")

        # Some site setups (PJRT plugin registration hooks) overwrite
        # jax_platforms at interpreter start, clobbering the JAX_PLATFORMS
        # the launcher exported for its workers.  Re-assert the env intent
        # through the config API before any backend is instantiated.
        env_platforms = os.environ.get("JAX_PLATFORMS")
        if env_platforms and (jax.config.jax_platforms or "") != env_platforms:
            try:
                jax.config.update("jax_platforms", env_platforms)
            except Exception:
                pass  # backend already up; leave the platform alone

        owns_distributed = False
        if world > 1 and not _jax_distributed_active():
            if coordinator is None:
                raise RuntimeError(
                    "HVDTPU_SIZE > 1 but HVDTPU_COORDINATOR is unset; launch with "
                    "hvdrun or set the rendezvous environment explicitly."
                )
            # Multi-process CPU worlds (the test/dev topology, SURVEY.md §4)
            # need a CPU collectives backend; jax's is gloo — the very
            # library the reference uses for its CPU data path.
            platforms = (jax.config.jax_platforms or "").split(",")
            if "cpu" in platforms:
                try:
                    jax.config.update(
                        "jax_cpu_collectives_implementation", "gloo"
                    )
                except Exception:  # already initialized or unknown option
                    pass
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=world,
                process_id=proc,
                initialization_timeout=_env_int("HVDTPU_START_TIMEOUT", 300),
            )
            owns_distributed = True

        devices = tuple(jax.devices())
        local_devices = tuple(jax.local_devices())
        # Homogeneity check: the reference allgathers local sizes and flags
        # mixed hosts (mpi_controller.cc:46-81).  Here device counts per
        # process are visible globally through the platform client.
        per_proc = {}
        for d in devices:
            per_proc[d.process_index] = per_proc.get(d.process_index, 0) + 1
        homogeneous = len(set(per_proc.values())) <= 1

        _topology = Topology(
            process_rank=proc if world > 1 else 0,
            process_count=world if world > 1 else 1,
            local_rank=_env_int("HVDTPU_LOCAL_RANK", 0),
            local_size=_env_int("HVDTPU_LOCAL_SIZE", 1),
            cross_rank=_env_int("HVDTPU_CROSS_RANK", proc if world > 1 else 0),
            cross_size=_env_int("HVDTPU_CROSS_SIZE", world if world > 1 else 1),
            devices=devices,
            homogeneous=homogeneous,
            owns_jax_distributed=owns_distributed,
        )
        del local_devices

    # Arm the observability plane: first registry use installs the
    # HVDTPU_METRICS_DUMP exit hook, so every initialized rank leaves a
    # metrics dump even on the jit-only path that never starts an engine.
    from .obs import get_registry  # noqa: PLC0415

    get_registry().gauge("process.rank").set(
        _topology.process_rank
    )
    # Black box: arm the flight recorder's death-path hooks (excepthook,
    # threading.excepthook, SIGTERM/SIGABRT/SIGUSR1) so a rank killed by
    # a signal — including the launcher's own escalation — still flushes
    # its event ring, the metrics dump and the final live delta.
    from .obs import flightrec as _flightrec  # noqa: PLC0415

    _flightrec.install_death_hooks()
    _flightrec.record(
        "init", name=f"rank{_topology.process_rank}",
        detail=f"world={_topology.process_count}",
    )
    # Live telemetry streaming (obs/stream.py): a no-op unless the
    # launcher exported HVDTPU_LIVE_STATS_SECS + a KV endpoint.
    from .obs import stream as _obs_stream  # noqa: PLC0415

    _obs_stream.maybe_start_from_env()

    # Start the native eager engine NOW in multi-process worlds (reference
    # behavior: InitializeHorovodOnce spawns the background thread at init,
    # operations.cc:604-650).  Every rank's engine must cycle for
    # negotiation and stall inspection to work even when this rank hasn't
    # enqueued anything yet.  Only the native engine starts eagerly — it
    # negotiates over its own TCP mesh; the pure-Python fallback rides jax
    # collectives, which must not run concurrently with main-thread jit
    # collectives, so it stays lazy (started on first eager op).
    if world > 1:
        choice = os.environ.get("HVDTPU_EAGER_ENGINE", "auto").lower()
        if choice != "python":
            from .runtime import native  # noqa: PLC0415

            if choice == "native" or native.native_available():
                from . import _engine_registry  # noqa: PLC0415

                _engine_registry.get_engine()
    return _topology


def _jax_distributed_active() -> bool:
    try:
        from jax._src import distributed  # noqa: PLC0415

        return distributed.global_state.client is not None
    except Exception:  # pragma: no cover - internal layout shift
        return jax.process_count() > 1


def shutdown() -> None:
    """Tear down state (reference: horovod_shutdown, operations.cc:688).

    Stops the eager engine if running; leaves the JAX runtime alive (XLA
    client shutdown is owned by the process, as MPI_Finalize ownership is
    negotiated in the reference's MPIContextManager)."""
    global _topology
    from . import _engine_registry  # noqa: PLC0415

    # Engine teardown happens OUTSIDE the state lock: it joins the
    # background thread (bounded 30 s), and a wedged engine holding
    # _state_lock that long would freeze every concurrent rank()/init()
    # caller behind the teardown (hvdtpu-lint HVDC102).  Ordering is
    # safe: the engine's own shutdown path never reads the topology
    # state this lock guards.
    _engine_registry.shutdown_engine()
    with _state_lock:
        # The jax.distributed coordination service is deliberately left
        # running: rank 0 hosts it, and tearing it down here would kill
        # peers still mid-collective (uneven shutdown is normal — that's
        # what Join is for).  JAX owns its teardown at process exit, like
        # the reference leaves MPI_Finalize to the owning context
        # (mpi/mpi_context.cc MPIContextManager).
        _topology = None
        _mesh_cache.clear()


def is_initialized() -> bool:
    return _topology is not None


def global_topology() -> Topology:
    if _topology is None:
        raise NotInitializedError()
    return _topology


def rank() -> int:
    """Process rank (reference: horovod_rank, operations.cc:696)."""
    return global_topology().process_rank


def size() -> int:
    """Process count (reference: horovod_size, operations.cc:708)."""
    return global_topology().process_count


def local_rank() -> int:
    """Rank within the host (reference: horovod_local_rank, operations.cc:702)."""
    return global_topology().local_rank


def local_size() -> int:
    """Processes on this host (reference: horovod_local_size, operations.cc:714)."""
    return global_topology().local_size


def cross_rank() -> int:
    return global_topology().cross_rank


def cross_size() -> int:
    return global_topology().cross_size


def num_devices() -> int:
    """Total chips in the job == width of the DP mesh axis."""
    return global_topology().num_devices


def device_rank(device: Optional[jax.Device] = None) -> int:
    """Global index of a chip in the DP mesh (first local chip by default)."""
    topo = global_topology()
    if device is None:
        device = jax.local_devices()[0]
    return list(topo.devices).index(device)


def is_homogeneous() -> bool:
    """Reference: horovod_is_homogeneous (operations.cc:720)."""
    return global_topology().homogeneous


# -- feature probes (reference horovod_mpi_built/_enabled, horovod_gloo_*,
# horovod_nccl_built, horovod_mpi_threads_supported — operations.cc:726-799,
# basics.py:131-210).  The TPU build's transports are XLA collectives and
# the native TCP engine; the reference-named probes answer for migrating
# scripts that gate on them. --


def xla_collectives_built() -> bool:
    """The jit/SPMD data path (≙ nccl_built): always compiled in."""
    return True


def native_engine_built() -> bool:
    """The C++ eager engine (≙ gloo_built): True when the shared library
    is present."""
    from .runtime import native  # noqa: PLC0415

    return native.native_available()


def mpi_built() -> bool:
    """MPI does not exist in the TPU design (coordination is
    jax.distributed); always False, so reference scripts take their gloo
    branch, whose semantics the engine provides."""
    return False


mpi_enabled = mpi_built


def mpi_threads_supported() -> bool:
    """Reference basics.mpi_threads_supported: meaningless without MPI;
    False (scripts use it only to decide multi-comm setups)."""
    return False


def gloo_built() -> bool:
    """≙ reference gloo_built: the engine's TCP data path stands in for
    gloo and is available whenever the package is (native or Python)."""
    return True


gloo_enabled = gloo_built


def nccl_built() -> bool:
    """≙ reference nccl_built: the device collective path here is XLA over
    ICI, reported through xla_collectives_built; NCCL itself: False."""
    return False


def ccl_built() -> bool:
    return False


def ddl_built() -> bool:
    return False


def mesh(shape: str = "flat") -> jax.sharding.Mesh:
    """Build (and cache) the named device mesh collectives compile over.

    ``flat``          -> 1D mesh, axis DP_AXIS over every chip.
    ``hierarchical``  -> 2D mesh (CROSS_AXIS=hosts, LOCAL_AXIS=chips/host),
                         the TPU analog of the reference's local/cross
                         communicators (mpi/mpi_context.cc; used by
                         NCCLHierarchicalAllreduce, nccl_operations.cc:162-300).
                         Collectives over LOCAL_AXIS ride ICI; CROSS_AXIS
                         rides DCN.
    """
    topo = global_topology()
    if shape in _mesh_cache:
        return _mesh_cache[shape]
    devices = np.asarray(topo.devices, dtype=object)
    if shape == "flat":
        m = jax.sharding.Mesh(devices, (DP_AXIS,))
    elif shape == "hierarchical":
        hosts = topo.cross_size if topo.process_count > 1 else 1
        if len(devices) % max(hosts, 1) != 0:
            raise ValueError(
                f"cannot build hierarchical mesh: {len(devices)} devices over "
                f"{hosts} hosts is uneven"
            )
        per = len(devices) // max(hosts, 1)
        m = jax.sharding.Mesh(
            devices.reshape(hosts, per), (CROSS_AXIS, LOCAL_AXIS)
        )
    else:
        raise ValueError(f"unknown mesh shape {shape!r}")
    _mesh_cache[shape] = m
    return m
