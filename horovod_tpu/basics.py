"""Process/topology bootstrap for horovod_tpu.

TPU-native analog of the reference's ``HorovodBasics`` ctypes layer
(reference: horovod/common/basics.py:22-66) and the C init path
(horovod/common/operations.cc:604-650).  Where the reference spawns a
background MPI/Gloo controller thread per process, the TPU build wires up
``jax.distributed`` (the JAX coordination service plays the role of the Gloo
HTTP rendezvous, reference horovod/common/gloo/gloo_context.cc:113-157) and
builds named device meshes over which XLA collectives compile.

Rank semantics
--------------
The reference runs one process per accelerator, so ``rank() == device``.
On TPU one process owns several chips, so the concepts split:

* ``rank()`` / ``size()``            -- process-level (one per host by default).
  This is what the eager per-op engine coordinates over, exactly like the
  reference controller negotiates over MPI ranks.
* ``local_rank()`` / ``local_size()`` -- process index within the host
  (reference: horovod/common/mpi/mpi_controller.cc:25-81 local_comm split).
* ``cross_rank()`` / ``cross_size()`` -- one-process-per-host axis
  (reference Communicator::CROSS, horovod/common/common.h:111-115).
* ``num_devices()`` / ``device_rank()`` -- chip-level; this is the width of
  the data-parallel mesh axis the jit path psums over, and the number that
  matters for scaling efficiency.

Environment contract (set by ``hvdrun``, mirroring HOROVOD_RANK/... set by
the reference launcher, horovod/run/gloo_run.py:143-165):

    HVDTPU_RANK / HVDTPU_SIZE
    HVDTPU_LOCAL_RANK / HVDTPU_LOCAL_SIZE
    HVDTPU_CROSS_RANK / HVDTPU_CROSS_SIZE
    HVDTPU_COORDINATOR        host:port of the jax.distributed coordinator
    HVDTPU_CONTROLLER_PORT    base port for the eager-engine controller mesh
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import numpy as np

__all__ = [
    "init",
    "shutdown",
    "is_initialized",
    "rank",
    "size",
    "local_rank",
    "local_size",
    "cross_rank",
    "cross_size",
    "num_devices",
    "device_rank",
    "is_homogeneous",
    "slice_id",
    "num_slices",
    "slice_size",
    "slice_of_rank",
    "mesh",
    "global_topology",
    "DP_AXIS",
    "CROSS_AXIS",
    "LOCAL_AXIS",
    "SLICE_AXIS",
]

# Canonical mesh axis names.  DP_AXIS is the flat data-parallel axis every
# collective defaults to (the analog of Communicator::GLOBAL); CROSS/LOCAL
# form the 2D hierarchical mesh (DCN x ICI), the analog of the reference's
# cross/local communicators used by NCCLHierarchicalAllreduce
# (horovod/common/ops/nccl_operations.cc:162-300).
DP_AXIS = "hvd"
CROSS_AXIS = "hvd_cross"
LOCAL_AXIS = "hvd_local"
# Outermost axis of the 3-level (slice, host, chip) multislice mesh:
# collectives over SLICE_AXIS ride DCN, everything inside a slice rides
# ICI (the fabric split NCCLHierarchicalAllreduce reasons about,
# nccl_operations.cc:218-229, mapped onto TPU pods).
SLICE_AXIS = "hvd_slice"


class NotInitializedError(RuntimeError):
    def __init__(self) -> None:
        super().__init__(
            "horovod_tpu has not been initialized; call horovod_tpu.init() first."
        )


@dataclass
class Topology:
    """Static view of the job, fixed at init() (SPMD world is static;
    the reference's dynamic Join story is handled at the op layer)."""

    process_rank: int
    process_count: int
    local_rank: int
    local_size: int
    cross_rank: int
    cross_size: int
    devices: Sequence[jax.Device] = field(default_factory=list)
    homogeneous: bool = True
    # Slice partition of the job (ICI within a slice, DCN between):
    # devices split into num_slices contiguous equal groups; slice_id is
    # the group this process's devices live in.  1 slice = single-pod
    # job, every fabric-aware path degenerates to flat.
    num_slices: int = 1
    slice_id: int = 0
    # Whether init() started jax.distributed itself; shutdown() only tears
    # down what it owns (≙ the reference's MPIContextManager negotiating
    # MPI_Init/Finalize ownership, horovod/common/mpi/mpi_context.cc).
    owns_jax_distributed: bool = False

    @property
    def num_devices(self) -> int:
        return len(self.devices)


_state_lock = threading.Lock()
_topology: Optional[Topology] = None
_mesh_cache: dict = {}


def _env_int(name: str, default: int) -> int:
    value = os.environ.get(name)
    return int(value) if value not in (None, "") else default


def resolve_slice_partition(
    world: int,
    proc: int,
    devices: Sequence,
    env: Optional[dict] = None,
) -> tuple:
    """Resolve the slice partition of the job -> ``(num_slices, slice_id)``.

    Priority (each level validated, invalid values downgrade to the next
    with one warning rather than killing the job):

    1. ``HVDTPU_NUM_SLICES``  — forced count of contiguous process blocks.
    2. ``HVDTPU_SLICE_SIZE``  — forced processes-per-slice (the CPU/dev
       simulation knob: a 4-proc world with SLICE_SIZE=2 behaves like
       two 2-host slices, so every multislice code path is testable on
       a laptop).
    3. Platform discovery — ``jax.Device.slice_index`` is populated on
       real multislice TPU deployments; distinct values define slices.
    4. Single slice.

    A forced partition must divide the world evenly (equal slices are
    what make the hierarchical schedule's shard math rank-symmetric).
    Pure function of its inputs so the partition logic is unit-testable
    without re-initializing a topology.
    """
    from .utils.logging import get_logger  # noqa: PLC0415

    log = get_logger("basics")
    e = os.environ if env is None else env

    def _val(name):
        raw = e.get(name)
        try:
            return int(raw) if raw not in (None, "") else 0
        except ValueError:
            log.warning("%s=%r is not an integer; ignoring", name, raw)
            return 0

    # The unit a forced partition divides: processes in a real multi-proc
    # world, devices in a single-process world (where SLICE_SIZE means
    # chips-per-slice — the 8-virtual-device in-process test topology).
    units = world if world > 1 else max(len(devices), 1)
    n = _val("HVDTPU_NUM_SLICES")
    if n <= 0:
        ssize = _val("HVDTPU_SLICE_SIZE")
        if ssize > 0:
            if units % ssize:
                log.warning(
                    "HVDTPU_SLICE_SIZE=%d does not divide the %d-unit "
                    "world; running single-slice", ssize, units,
                )
            else:
                n = units // ssize
    if n > 1:
        if units % n:
            log.warning(
                "forced slice count %d does not divide the %d-unit world; "
                "running single-slice", n, units,
            )
            return 1, 0
        return n, (proc // (world // n)) if world > 1 else 0
    if n == 1:
        return 1, 0
    # Platform discovery: slice_index exists (and differs) only on real
    # multislice TPU deployments.
    try:
        indices = sorted(
            {getattr(d, "slice_index", None) for d in devices} - {None}
        )
    except TypeError:
        indices = []
    if len(indices) > 1:
        mine = sorted(
            {
                getattr(d, "slice_index", None)
                for d in devices
                if getattr(d, "process_index", 0) == proc
            }
            - {None}
        )
        if len(mine) == 1:
            return len(indices), indices.index(mine[0])
        log.warning(
            "process %d spans multiple slices %s; treating the job as "
            "single-slice (hierarchical collectives need slice-aligned "
            "processes)", proc, mine,
        )
    return 1, 0


def init(comm=None) -> Topology:
    """Initialize the framework (reference: horovod_init, operations.cc:663).

    Safe to call more than once (the reference spin-waits on
    initialization_done, operations.cc:646-648; here re-init is a no-op).

    ``comm`` is accepted for API compatibility with the reference's
    sub-communicator init (horovod/common/basics.py:33-65) but only the
    default (whole-world) communicator is supported on TPU, where process
    membership is fixed by the coordination service.
    """
    global _topology
    with _state_lock:
        if _topology is not None:
            return _topology
        if comm is not None and comm not in ([], None):
            raise ValueError(
                "horovod_tpu.init(comm=...) sub-communicators are not supported; "
                "the TPU world is defined by the coordination service."
            )

        world = _env_int("HVDTPU_SIZE", 1)
        proc = _env_int("HVDTPU_RANK", 0)
        coordinator = os.environ.get("HVDTPU_COORDINATOR")

        # Some site setups (PJRT plugin registration hooks) overwrite
        # jax_platforms at interpreter start, clobbering the JAX_PLATFORMS
        # the launcher exported for its workers.  Re-assert the env intent
        # through the config API before any backend is instantiated.
        env_platforms = os.environ.get("JAX_PLATFORMS")
        if env_platforms and (jax.config.jax_platforms or "") != env_platforms:
            try:
                jax.config.update("jax_platforms", env_platforms)
            except Exception:
                pass  # backend already up; leave the platform alone

        owns_distributed = False
        if world > 1 and not _jax_distributed_active():
            if coordinator is None:
                raise RuntimeError(
                    "HVDTPU_SIZE > 1 but HVDTPU_COORDINATOR is unset; launch with "
                    "hvdrun or set the rendezvous environment explicitly."
                )
            # Multi-process CPU worlds (the test/dev topology, SURVEY.md §4)
            # need a CPU collectives backend; jax's is gloo — the very
            # library the reference uses for its CPU data path.
            platforms = (jax.config.jax_platforms or "").split(",")
            if "cpu" in platforms:
                try:
                    jax.config.update(
                        "jax_cpu_collectives_implementation", "gloo"
                    )
                except Exception:  # already initialized or unknown option
                    pass
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=world,
                process_id=proc,
                initialization_timeout=_env_int("HVDTPU_START_TIMEOUT", 300),
            )
            owns_distributed = True

        devices = tuple(jax.devices())
        local_devices = tuple(jax.local_devices())
        # Homogeneity check: the reference allgathers local sizes and flags
        # mixed hosts (mpi_controller.cc:46-81).  Here device counts per
        # process are visible globally through the platform client.
        per_proc = {}
        for d in devices:
            per_proc[d.process_index] = per_proc.get(d.process_index, 0) + 1
        homogeneous = len(set(per_proc.values())) <= 1

        eff_world = world if world > 1 else 1
        eff_proc = proc if world > 1 else 0
        n_slices, slice_i = resolve_slice_partition(
            eff_world, eff_proc, devices
        )
        _topology = Topology(
            process_rank=proc if world > 1 else 0,
            process_count=world if world > 1 else 1,
            local_rank=_env_int("HVDTPU_LOCAL_RANK", 0),
            local_size=_env_int("HVDTPU_LOCAL_SIZE", 1),
            cross_rank=_env_int("HVDTPU_CROSS_RANK", proc if world > 1 else 0),
            cross_size=_env_int("HVDTPU_CROSS_SIZE", world if world > 1 else 1),
            devices=devices,
            homogeneous=homogeneous,
            num_slices=n_slices,
            slice_id=slice_i,
            owns_jax_distributed=owns_distributed,
        )
        del local_devices
        # The hierarchical knob without a multi-slice topology is a
        # no-op; one clear line beats silent downgrade (the flat XLA
        # psum is already torus-optimal within a single slice, so this
        # is a downgrade in name only — but the user should know).
        from .utils import env as envmod  # noqa: PLC0415

        if n_slices < 2 and envmod.env_bool(envmod.HIERARCHICAL_ALLREDUCE):
            from .utils.logging import get_logger  # noqa: PLC0415

            get_logger("basics").warning(
                "--hierarchical-allreduce requested but this topology "
                "has a single slice; flat allreduce is already optimal "
                "on one ICI domain — knob downgraded (force a partition "
                "with HVDTPU_NUM_SLICES/HVDTPU_SLICE_SIZE to test the "
                "two-fabric path)"
            )

    # Arm the observability plane: first registry use installs the
    # HVDTPU_METRICS_DUMP exit hook, so every initialized rank leaves a
    # metrics dump even on the jit-only path that never starts an engine.
    from .obs import get_registry  # noqa: PLC0415

    get_registry().gauge("process.rank").set(
        _topology.process_rank
    )
    # Black box: arm the flight recorder's death-path hooks (excepthook,
    # threading.excepthook, SIGTERM/SIGABRT/SIGUSR1) so a rank killed by
    # a signal — including the launcher's own escalation — still flushes
    # its event ring, the metrics dump and the final live delta.
    from .obs import flightrec as _flightrec  # noqa: PLC0415

    _flightrec.install_death_hooks()
    _flightrec.record(
        "init", name=f"rank{_topology.process_rank}",
        detail=f"world={_topology.process_count}",
    )
    # Live telemetry streaming (obs/stream.py): a no-op unless the
    # launcher exported HVDTPU_LIVE_STATS_SECS + a KV endpoint.
    from .obs import stream as _obs_stream  # noqa: PLC0415

    _obs_stream.maybe_start_from_env()

    # Start the native eager engine NOW in multi-process worlds (reference
    # behavior: InitializeHorovodOnce spawns the background thread at init,
    # operations.cc:604-650).  Every rank's engine must cycle for
    # negotiation and stall inspection to work even when this rank hasn't
    # enqueued anything yet.  Only the native engine starts eagerly — it
    # negotiates over its own TCP mesh; the pure-Python fallback rides jax
    # collectives, which must not run concurrently with main-thread jit
    # collectives, so it stays lazy (started on first eager op).
    if world > 1:
        choice = os.environ.get("HVDTPU_EAGER_ENGINE", "auto").lower()
        if choice != "python":
            from .runtime import native  # noqa: PLC0415

            if choice == "native" or native.native_available():
                from . import _engine_registry  # noqa: PLC0415

                _engine_registry.get_engine()
    return _topology


def _jax_distributed_active() -> bool:
    try:
        from jax._src import distributed  # noqa: PLC0415

        return distributed.global_state.client is not None
    except Exception:  # pragma: no cover - internal layout shift
        return jax.process_count() > 1


def shutdown() -> None:
    """Tear down state (reference: horovod_shutdown, operations.cc:688).

    Stops the eager engine if running; leaves the JAX runtime alive (XLA
    client shutdown is owned by the process, as MPI_Finalize ownership is
    negotiated in the reference's MPIContextManager)."""
    global _topology
    from . import _engine_registry  # noqa: PLC0415

    # Engine teardown happens OUTSIDE the state lock: it joins the
    # background thread (bounded 30 s), and a wedged engine holding
    # _state_lock that long would freeze every concurrent rank()/init()
    # caller behind the teardown (hvdtpu-lint HVDC102).  Ordering is
    # safe: the engine's own shutdown path never reads the topology
    # state this lock guards.
    _engine_registry.shutdown_engine()
    with _state_lock:
        # The jax.distributed coordination service is deliberately left
        # running: rank 0 hosts it, and tearing it down here would kill
        # peers still mid-collective (uneven shutdown is normal — that's
        # what Join is for).  JAX owns its teardown at process exit, like
        # the reference leaves MPI_Finalize to the owning context
        # (mpi/mpi_context.cc MPIContextManager).
        _topology = None
        _mesh_cache.clear()


def is_initialized() -> bool:
    return _topology is not None


def global_topology() -> Topology:
    if _topology is None:
        raise NotInitializedError()
    return _topology


def rank() -> int:
    """Process rank (reference: horovod_rank, operations.cc:696)."""
    return global_topology().process_rank


def size() -> int:
    """Process count (reference: horovod_size, operations.cc:708)."""
    return global_topology().process_count


def local_rank() -> int:
    """Rank within the host (reference: horovod_local_rank, operations.cc:702)."""
    return global_topology().local_rank


def local_size() -> int:
    """Processes on this host (reference: horovod_local_size, operations.cc:714)."""
    return global_topology().local_size


def cross_rank() -> int:
    return global_topology().cross_rank


def cross_size() -> int:
    return global_topology().cross_size


def num_devices() -> int:
    """Total chips in the job == width of the DP mesh axis."""
    return global_topology().num_devices


def device_rank(device: Optional[jax.Device] = None) -> int:
    """Global index of a chip in the DP mesh (first local chip by default)."""
    topo = global_topology()
    if device is None:
        device = jax.local_devices()[0]
    return list(topo.devices).index(device)


def is_homogeneous() -> bool:
    """Reference: horovod_is_homogeneous (operations.cc:720)."""
    return global_topology().homogeneous


def slice_id() -> int:
    """Which slice this process's devices live in (0 on single-slice
    jobs).  Slices are the DCN-connected partitions of a multislice job;
    everything within a slice shares ICI."""
    return global_topology().slice_id


def num_slices() -> int:
    """Number of DCN-connected slices in the job (1 = single-pod)."""
    return global_topology().num_slices


def slice_size() -> int:
    """Ranks per slice (the ``local_size`` of the two-fabric hierarchy:
    the cross-slice phase of hierarchical allreduce carries
    1/slice_size of the bytes).  On the single-process dev topology —
    where the forced partition splits DEVICES, not processes — this is
    chips per slice, and it is always >= 1."""
    topo = global_topology()
    if topo.num_slices <= 1:
        return topo.process_count
    if (
        topo.process_count > 1
        and topo.process_count % topo.num_slices == 0
    ):
        return topo.process_count // topo.num_slices
    if topo.num_devices % topo.num_slices == 0:
        return max(topo.num_devices // topo.num_slices, 1)
    return 1


def slice_of_rank(rank: int) -> int:
    """Slice containing process ``rank`` (contiguous-block partition —
    the single mapping the engine, the straggler tagger and the launcher
    blacklist all share, so a slice-level verdict can never name a
    different slice than the data plane ran on)."""
    topo = global_topology()
    if topo.num_slices <= 1 or topo.process_count % topo.num_slices:
        return 0
    return int(rank) // (topo.process_count // topo.num_slices)


# -- feature probes (reference horovod_mpi_built/_enabled, horovod_gloo_*,
# horovod_nccl_built, horovod_mpi_threads_supported — operations.cc:726-799,
# basics.py:131-210).  The TPU build's transports are XLA collectives and
# the native TCP engine; the reference-named probes answer for migrating
# scripts that gate on them. --


def xla_collectives_built() -> bool:
    """The jit/SPMD data path (≙ nccl_built): always compiled in."""
    return True


def native_engine_built() -> bool:
    """The C++ eager engine (≙ gloo_built): True when the shared library
    is present."""
    from .runtime import native  # noqa: PLC0415

    return native.native_available()


def mpi_built() -> bool:
    """MPI does not exist in the TPU design (coordination is
    jax.distributed); always False, so reference scripts take their gloo
    branch, whose semantics the engine provides."""
    return False


mpi_enabled = mpi_built


def mpi_threads_supported() -> bool:
    """Reference basics.mpi_threads_supported: meaningless without MPI;
    False (scripts use it only to decide multi-comm setups)."""
    return False


def gloo_built() -> bool:
    """≙ reference gloo_built: the engine's TCP data path stands in for
    gloo and is available whenever the package is (native or Python)."""
    return True


gloo_enabled = gloo_built


def nccl_built() -> bool:
    """≙ reference nccl_built: the device collective path here is XLA over
    ICI, reported through xla_collectives_built; NCCL itself: False."""
    return False


def ccl_built() -> bool:
    return False


def ddl_built() -> bool:
    return False


def slice_grid(
    devices: Sequence, num_slices: int, hosts: int
) -> np.ndarray:
    """Reshape a flat device list into the 3-level (slice, host, chip)
    view: contiguous device blocks per slice, contiguous per host within
    it.  ``hosts`` is the number of host groups WITHIN one slice (1 when
    the host level degenerates, e.g. a single-process dev world forced
    into chip-level slices).  Pure function for unit-testability."""
    devices = np.asarray(devices, dtype=object)
    total = devices.size
    if num_slices < 1 or total % num_slices:
        raise ValueError(
            f"cannot partition {total} devices into {num_slices} slices"
        )
    per_slice = total // num_slices
    if hosts < 1 or per_slice % hosts:
        raise ValueError(
            f"cannot split a {per_slice}-device slice over {hosts} hosts"
        )
    return devices.reshape(num_slices, hosts, per_slice // hosts)


def mesh(shape: str = "flat") -> jax.sharding.Mesh:
    """Build (and cache) the named device mesh collectives compile over.

    ``flat``          -> 1D mesh, axis DP_AXIS over every chip.
    ``hierarchical``  -> 2D mesh (CROSS_AXIS=hosts, LOCAL_AXIS=chips/host),
                         the TPU analog of the reference's local/cross
                         communicators (mpi/mpi_context.cc; used by
                         NCCLHierarchicalAllreduce, nccl_operations.cc:162-300).
                         Collectives over LOCAL_AXIS ride ICI; CROSS_AXIS
                         rides DCN.
    ``slice``         -> 3D mesh (SLICE_AXIS=slices, CROSS_AXIS=hosts
                         within a slice, LOCAL_AXIS=chips/host): the full
                         two-fabric view of a multislice job.  SLICE_AXIS
                         collectives ride DCN; the inner two axes ride
                         ICI.  Requires a multi-slice topology (forced
                         via HVDTPU_NUM_SLICES/HVDTPU_SLICE_SIZE on dev
                         worlds, discovered on real multislice TPU).
    """
    topo = global_topology()
    if shape in _mesh_cache:
        return _mesh_cache[shape]
    devices = np.asarray(topo.devices, dtype=object)
    if shape == "flat":
        m = jax.sharding.Mesh(devices, (DP_AXIS,))
    elif shape == "hierarchical":
        hosts = topo.cross_size if topo.process_count > 1 else 1
        if len(devices) % max(hosts, 1) != 0:
            raise ValueError(
                f"cannot build hierarchical mesh: {len(devices)} devices over "
                f"{hosts} hosts is uneven"
            )
        per = len(devices) // max(hosts, 1)
        m = jax.sharding.Mesh(
            devices.reshape(hosts, per), (CROSS_AXIS, LOCAL_AXIS)
        )
    elif shape == "slice":
        if topo.num_slices < 2:
            raise ValueError(
                "mesh('slice') needs a multi-slice topology; force one "
                "with HVDTPU_NUM_SLICES / HVDTPU_SLICE_SIZE on dev worlds"
            )
        hosts = (
            topo.process_count // topo.num_slices
            if topo.process_count > 1
            and topo.process_count % topo.num_slices == 0
            else 1
        )
        m = jax.sharding.Mesh(
            slice_grid(devices, topo.num_slices, hosts),
            (SLICE_AXIS, CROSS_AXIS, LOCAL_AXIS),
        )
    else:
        raise ValueError(f"unknown mesh shape {shape!r}")
    _mesh_cache[shape] = m
    return m
