"""Resumable benchmark campaigns: a sweep as ONE durable session.

A campaign is a declarative spec — a grid over the knobs the machinery
grew (overlap mode, gradient bucket size via
``autotune.grad_bucket_candidates()``, hierarchical allreduce, schedule
replay, serve axes) — expanded into points and executed one ``bench.py``
subprocess per point.  The design constraints, in order:

* **Durability** — a ``campaign.json`` journal under the record dir is
  rewritten atomically (obs/pathspec.py's write-then-rename idiom)
  after EVERY point, so a mid-campaign crash, watchdog kill (rc=86) or
  injected SIGABRT loses at most the in-flight point: the journal on
  disk is always a complete, parseable account of every finished point.
* **Resume** — restarting with the same spec (matched by content hash)
  skips ``done`` points and retries ``degraded``/``failed`` ones up to
  ``retry_degraded`` extra attempts; a changed spec is refused rather
  than silently mixed (``--force-new`` starts over).
* **Isolation** — each point is its own process: a point that hangs or
  dies cannot take the campaign (or the other points' results) with
  it.  bench.py's persistent compilation cache (``.jax_cache``) makes
  compiled-step reuse automatic across points that share a compile
  key; the journal records per point whether its executable was
  ``reused`` or ``cold`` — bucket size recompiles, replay/hierarchical
  toggles do not — so a sweep's wall-clock is attributable.
* **Deterministic chaos** — ``testing.faults.maybe_fail("campaign_point",
  step=<1-based point index>)`` runs between the previous point's
  commit and the next launch: ``action=abort`` dies exactly there
  (what CI's resume gate seeds), advisory ``action=degrade`` forces the
  point down the degraded-record path without running it.

No jax import anywhere in this module: the campaign driver must outlive
backends that hang on import.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional

from ..obs.pathspec import write_json_atomic

__all__ = ["load_spec", "expand_points", "run_campaign", "main",
           "JOURNAL_SCHEMA", "JOURNAL_NAME", "CampaignError"]

JOURNAL_SCHEMA = "hvdtpu-campaign-v1"
JOURNAL_NAME = "campaign.json"

# Grace the outer kill adds past the point's own --total-budget-secs:
# bench.py bounds its own wall clock across retries; the outer timeout
# must be strictly larger so the campaign never kills a point that
# would have recovered (the hw_sweep.sh lesson, kept).
OUTER_TIMEOUT_GRACE_SECS = 120

# Axes that map to bench.py CLI flags and BAKE INTO the compiled
# program — two points differing here cannot share an executable.
_COMPILE_ARG_AXES = {
    "overlap": "--overlap",
    "grad_bucket_mb": "--grad-bucket-mb",
}
# Axes that map to environment knobs the engine reads at RUNTIME — the
# compiled program is identical across their values.
_RUNTIME_ENV_AXES = {
    "hierarchical": "HVDTPU_HIERARCHICAL_ALLREDUCE",
    "replay": "HVDTPU_SCHEDULE_REPLAY",
}


class CampaignError(RuntimeError):
    """A spec/journal problem the operator must resolve (exit 2)."""


# ------------------------------------------------------------------ spec

def load_spec(path: str) -> dict:
    try:
        with open(path) as f:
            spec = json.load(f)
    except (OSError, ValueError) as exc:
        raise CampaignError(f"unreadable campaign spec {path}: {exc}")
    if not isinstance(spec, dict):
        raise CampaignError(f"campaign spec {path} must be a JSON object")
    spec.setdefault("name", os.path.splitext(os.path.basename(path))[0])
    spec.setdefault("base_args", [])
    spec.setdefault("axes", {})
    spec.setdefault("points", [])
    spec.setdefault("retry_degraded", 1)
    spec.setdefault("point_budget_secs", 1440)
    if not isinstance(spec["base_args"], list) or not all(
            isinstance(a, str) for a in spec["base_args"]):
        raise CampaignError("spec base_args must be a list of strings")
    if not isinstance(spec["axes"], dict):
        raise CampaignError("spec axes must be an object")
    if not isinstance(spec["points"], list):
        raise CampaignError("spec points must be a list")
    if spec["points"] and spec["axes"]:
        raise CampaignError(
            "spec has both axes and points; a campaign is either a "
            "grid or an explicit point list, not a mix")
    return spec


def spec_sha(spec: dict) -> str:
    """Content hash over the fields that define WHAT the campaign runs
    (not how patiently): the resume identity."""
    ident = {k: spec.get(k) for k in ("name", "base_args", "axes")}
    return hashlib.sha256(
        json.dumps(ident, sort_keys=True).encode()).hexdigest()[:16]


def _axis_values(axes: dict, key: str) -> Optional[List]:
    vals = axes.get(key)
    if vals is None:
        return None
    if vals == "auto" and key == "grad_bucket_mb":
        from ..runtime.autotune import grad_bucket_candidates  # noqa: PLC0415

        return list(grad_bucket_candidates())
    if not isinstance(vals, list) or not vals:
        raise CampaignError(
            f"axis {key!r} must be a non-empty list (or 'auto' for "
            f"grad_bucket_mb), got {vals!r}")
    return vals


def _knob_token(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, float) and v == int(v):
        return str(int(v))
    return str(v)


def _explicit_points(spec: dict) -> List[dict]:
    """An explicit point list (the retired hw_sweep.sh shape: named
    heterogeneous configs, not a grid).  Each entry: {"name", "args",
    "env"?}.  Order is preserved — a hardware plan runs its headline
    number first."""
    points = []
    seen = set()
    for i, raw in enumerate(spec["points"]):
        if not isinstance(raw, dict) or not raw.get("name"):
            raise CampaignError(
                f"spec points[{i}] must be an object with a 'name'")
        pid = str(raw["name"])
        if pid in seen:
            raise CampaignError(f"duplicate point name {pid!r}")
        seen.add(pid)
        extra = raw.get("args", [])
        env = raw.get("env", {})
        if not isinstance(extra, list) or not all(
                isinstance(a, str) for a in extra):
            raise CampaignError(
                f"points[{i}].args must be a list of strings")
        argv = list(spec["base_args"]) + list(extra)
        # Every explicit arg is conservatively compile-relevant: an
        # unclassified knob must never be credited with reuse.
        compile_key = " ".join(argv)
        point = {
            "id": pid,
            "knobs": {"args": " ".join(extra)},
            "argv": argv,
            "env": {str(k): str(v) for k, v in env.items()},
            "compile_key": hashlib.sha256(
                compile_key.encode()).hexdigest()[:12],
        }
        if raw.get("budget_secs"):
            point["budget_secs"] = int(raw["budget_secs"])
        points.append(point)
    return points


def expand_points(spec: dict) -> List[dict]:
    """Cartesian product of the axes, as [{id, knobs, argv, env,
    compile_key}].  A point with ``overlap=off`` drops the bucket-size
    axis (the knob is inert without overlap) and the resulting
    duplicates collapse, so a 2x3 grid over {overlap, bucket} yields
    1 + 3 points, not 6.  Unknown axes pass through as ``--axis-name
    value`` bench flags and count as compile-relevant (conservative:
    an unclassified knob must never be credited with executable
    reuse).  A spec with an explicit ``points`` list (the retired
    hw_sweep.sh shape) bypasses the grid entirely."""
    if spec.get("points"):
        return _explicit_points(spec)
    axes = spec["axes"]
    grids: List[List] = [[{}]]

    def _cross(key: str, values: List) -> None:
        grids[0] = [dict(p, **{key: v}) for p in grids[0] for v in values]

    for key in axes:
        vals = _axis_values(axes, key)
        if vals is not None:
            _cross(key, vals)
    points: Dict[str, dict] = {}
    for knobs in grids[0]:
        if knobs.get("overlap") == "off":
            knobs = {k: v for k, v in knobs.items()
                     if k != "grad_bucket_mb"}
        argv = list(spec["base_args"])
        env: Dict[str, str] = {}
        compile_knobs = {}
        for key in sorted(knobs):
            v = knobs[key]
            if key in _COMPILE_ARG_AXES:
                argv += [_COMPILE_ARG_AXES[key], _knob_token(v)]
                compile_knobs[key] = _knob_token(v)
            elif key in _RUNTIME_ENV_AXES:
                env[_RUNTIME_ENV_AXES[key]] = _knob_token(v)
            elif isinstance(v, bool):
                if v:
                    argv.append("--" + key.replace("_", "-"))
                compile_knobs[key] = _knob_token(v)
            else:
                argv += ["--" + key.replace("_", "-"), _knob_token(v)]
                compile_knobs[key] = _knob_token(v)
        pid = ",".join(f"{k}={_knob_token(v)}" for k, v in sorted(
            knobs.items())) or "default"
        compile_key = "|".join(
            [" ".join(spec["base_args"])]
            + [f"{k}={v}" for k, v in sorted(compile_knobs.items())])
        points[pid] = {
            "id": pid,
            "knobs": {k: _knob_token(v) for k, v in sorted(knobs.items())},
            "argv": argv,
            "env": env,
            "compile_key": hashlib.sha256(
                compile_key.encode()).hexdigest()[:12],
        }
    return [points[pid] for pid in sorted(points)]


# --------------------------------------------------------------- journal

def _journal_path(record_dir: str) -> str:
    return os.path.join(record_dir, JOURNAL_NAME)


def load_journal(record_dir: str) -> Optional[dict]:
    path = _journal_path(record_dir)
    try:
        with open(path) as f:
            doc = json.load(f)
    except OSError:
        return None
    except ValueError as exc:
        # A torn journal would mean the atomic-write contract broke —
        # refuse to guess what completed rather than re-run (or skip)
        # the wrong points.
        raise CampaignError(f"corrupt campaign journal {path}: {exc}")
    if not isinstance(doc, dict) or doc.get("schema") != JOURNAL_SCHEMA:
        raise CampaignError(
            f"{path} is not a {JOURNAL_SCHEMA} journal; move it aside "
            f"or pass --force-new")
    return doc


def _new_journal(spec: dict, points: List[dict]) -> dict:
    return {
        "schema": JOURNAL_SCHEMA,
        "name": spec["name"],
        "spec_sha": spec_sha(spec),
        "spec": {k: spec[k] for k in ("name", "base_args", "axes",
                                      "retry_degraded",
                                      "point_budget_secs")},
        "created": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "updated": None,
        "order": [p["id"] for p in points],
        "points": {
            p["id"]: {
                "status": "pending",
                "attempts": 0,
                "knobs": p["knobs"],
                "compile_key": p["compile_key"],
            }
            for p in points
        },
    }


def _commit(record_dir: str, journal: dict) -> None:
    journal["updated"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    write_json_atomic(_journal_path(record_dir), journal)


# ---------------------------------------------------------------- runner

def _parse_result_line(stdout: str) -> Optional[dict]:
    """The last stdout line must be a strict JSON OBJECT (no bare
    scalars, no NaN/Infinity) — a traceback tail must not corrupt the
    journal (the hw_sweep.sh validation rule, kept)."""
    lines = [ln for ln in (stdout or "").splitlines() if ln.strip()]
    if not lines:
        return None

    def _no_const(c):
        raise ValueError(c)

    try:
        doc = json.loads(lines[-1], parse_constant=_no_const)
    except ValueError:
        return None
    return doc if isinstance(doc, dict) else None


def subprocess_runner(point: dict, spec: dict, *, bench_cmd: List[str],
                      record_dir: str) -> dict:
    """Run one point as a child process; returns {rc, parsed, tail}.
    The child inherits the campaign's record dir so its own degraded-
    record path (bench.py's always-land-a-record rule) files next to
    the journal."""
    budget = int(point.get("budget_secs") or spec["point_budget_secs"])
    cmd = list(bench_cmd) + list(point["argv"])
    # Size the child's own wall-clock budget inside the outer kill
    # window — but only for the real bench (a test stub has no flag).
    if ("--total-budget-secs" not in point["argv"] and bench_cmd
            and os.path.basename(bench_cmd[-1]).startswith("bench")):
        cmd += ["--total-budget-secs", str(budget)]
    env = dict(os.environ)
    env.update(point["env"])
    env["HVDTPU_BENCH_RECORD_DIR"] = record_dir
    # The campaign owns chaos at its own seam; a fault spec aimed at
    # campaign_point must not leak into the child and fire nowhere.
    env.pop("HVDTPU_FAULT_SPEC", None)
    try:
        proc = subprocess.run(
            cmd, env=env, capture_output=True, text=True,
            timeout=budget + OUTER_TIMEOUT_GRACE_SECS,
        )
        rc, stdout, stderr = proc.returncode, proc.stdout, proc.stderr
    except subprocess.TimeoutExpired as exc:
        rc = 124
        stdout = (exc.stdout or b"").decode("utf-8", "replace") \
            if isinstance(exc.stdout, bytes) else (exc.stdout or "")
        stderr = ("campaign outer timeout after "
                  f"{budget + OUTER_TIMEOUT_GRACE_SECS}s")
    except OSError as exc:
        return {"rc": 127, "parsed": None, "tail": str(exc)}
    return {
        "rc": rc,
        "parsed": _parse_result_line(stdout),
        "tail": (stderr or "").strip()[-2000:],
    }


def _point_status(result: dict) -> str:
    parsed = result.get("parsed")
    if result.get("rc") == 0 and isinstance(parsed, dict):
        return "degraded" if parsed.get("degraded") else "done"
    return "failed"


def run_campaign(spec: dict, record_dir: str, *,
                 bench_cmd: Optional[List[str]] = None,
                 runner=None, force_new: bool = False,
                 max_points: int = 0,
                 log=lambda msg: print(msg, file=sys.stderr)) -> dict:
    """Execute (or resume) a campaign; returns the final journal.

    ``runner(point, spec)`` is injectable for tests; the default shells
    out to ``bench_cmd`` (default: ``python bench.py`` at the repo
    root) per point.
    """
    from ..testing import faults  # noqa: PLC0415

    points = expand_points(spec)
    if not points:
        raise CampaignError("campaign spec expands to zero points")
    os.makedirs(record_dir, exist_ok=True)
    journal = None if force_new else load_journal(record_dir)
    if journal is not None and journal.get("spec_sha") != spec_sha(spec):
        raise CampaignError(
            f"journal {_journal_path(record_dir)} belongs to a different "
            f"spec (sha {journal.get('spec_sha')} != {spec_sha(spec)}); "
            f"finish that campaign, move it aside, or pass --force-new")
    resumed = journal is not None
    if journal is None:
        journal = _new_journal(spec, points)
        _commit(record_dir, journal)
    if bench_cmd is None:
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        bench_cmd = [sys.executable, os.path.join(repo_root, "bench.py")]
    if runner is None:
        def runner(point, spec):
            return subprocess_runner(point, spec, bench_cmd=bench_cmd,
                                     record_dir=record_dir)

    max_attempts = 1 + int(spec["retry_degraded"])
    # Compile keys already paid for: any previously RUN point's
    # executable is in bench.py's persistent cache, whatever its status
    # (a degraded CPU run still compiled).
    warm_keys = {
        e["compile_key"] for e in journal["points"].values()
        if e.get("attempts", 0) > 0
    }
    ran = skipped = 0
    log(f"campaign {journal['name']}: {len(points)} points"
        + (" (resumed)" if resumed else ""))
    for idx, point in enumerate(points, start=1):
        entry = journal["points"][point["id"]]
        status = entry.get("status")
        if status == "done":
            skipped += 1
            continue
        if status in ("degraded", "failed") \
                and entry.get("attempts", 0) >= max_attempts:
            log(f"  [{idx}/{len(points)}] {point['id']}: {status} after "
                f"{entry['attempts']} attempts — retry budget spent")
            skipped += 1
            continue
        if max_points and ran >= max_points:
            break
        # The chaos seam: between the previous point's committed journal
        # and this point's launch.  action=abort dies exactly here;
        # advisory action=degrade forces this point down the
        # degraded-record path without running it.
        advice = faults.maybe_fail("campaign_point", step=idx,
                                   name=point["id"])
        reuse = "reused" if point["compile_key"] in warm_keys else "cold"
        if advice == "degrade":
            entry.update({
                "status": "degraded",
                "attempts": entry.get("attempts", 0) + 1,
                "rc": 0,
                "compile": reuse,
                "record": {"degraded": True,
                           "why": "injected campaign_point degrade"},
                "forced_degraded": True,
            })
            warm_keys.add(point["compile_key"])
            _commit(record_dir, journal)
            ran += 1
            log(f"  [{idx}/{len(points)}] {point['id']}: DEGRADED "
                f"(injected)")
            continue
        log(f"  [{idx}/{len(points)}] {point['id']}: running "
            f"({reuse} executable)")
        t0 = time.time()
        result = runner(point, spec)
        entry.update({
            "status": _point_status(result),
            "attempts": entry.get("attempts", 0) + 1,
            "rc": result.get("rc"),
            "compile": reuse,
            "elapsed_secs": round(time.time() - t0, 2),
            "record": result.get("parsed"),
        })
        if entry["status"] == "failed" and result.get("tail"):
            entry["tail"] = result["tail"]
        else:
            entry.pop("tail", None)
        warm_keys.add(point["compile_key"])
        _commit(record_dir, journal)
        ran += 1
        log(f"  [{idx}/{len(points)}] {point['id']}: "
            f"{entry['status'].upper()} rc={entry['rc']} "
            f"({entry.get('elapsed_secs', 0)}s)")
    return journal


def summarize_journal(journal: dict) -> dict:
    counts = {"done": 0, "degraded": 0, "failed": 0, "pending": 0}
    reused = 0
    for entry in journal["points"].values():
        counts[entry.get("status", "pending")] = counts.get(
            entry.get("status", "pending"), 0) + 1
        if entry.get("compile") == "reused":
            reused += 1
    return {
        "campaign": journal["name"],
        "spec_sha": journal["spec_sha"],
        "points": len(journal["points"]),
        "compile_reused": reused,
        **counts,
    }


# ------------------------------------------------------------------- CLI

def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m horovod_tpu.bench.campaign",
        description="Run (or resume) a resumable benchmark campaign "
                    "from a declarative sweep spec.")
    p.add_argument("--spec", required=True,
                   help="campaign spec JSON (name, base_args, axes, "
                        "retry_degraded, point_budget_secs)")
    p.add_argument("--record-dir", default=None,
                   help="where campaign.json and the per-point records "
                        "land (default: repo root)")
    p.add_argument("--bench", default=None,
                   help="bench command to run per point (default: "
                        "'<python> bench.py'); split on whitespace")
    p.add_argument("--force-new", action="store_true",
                   help="discard an existing journal and start over")
    p.add_argument("--max-points", type=int, default=0,
                   help="run at most N points this session (0 = all)")
    p.add_argument("--dry-run", action="store_true",
                   help="print the expanded points and exit")
    args = p.parse_args(argv)

    try:
        spec = load_spec(args.spec)
        points = expand_points(spec)
        if args.dry_run:
            for point in points:
                print(json.dumps(point))
            return 0
        record_dir = args.record_dir
        if record_dir is None:
            from ..obs.trend import repo_record_dir  # noqa: PLC0415

            record_dir = repo_record_dir()
        journal = run_campaign(
            spec, record_dir,
            bench_cmd=args.bench.split() if args.bench else None,
            force_new=args.force_new, max_points=args.max_points,
        )
    except CampaignError as exc:
        print(f"campaign error: {exc}", file=sys.stderr)
        return 2
    summary = summarize_journal(journal)
    summary["journal"] = _journal_path(record_dir)
    print(json.dumps(summary))
    return 1 if summary["failed"] else 0


if __name__ == "__main__":
    sys.exit(main())
