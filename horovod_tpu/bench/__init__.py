"""horovod_tpu.bench — the benchmark campaign plane.

ROADMAP item 4's stated prerequisite: ten PRs of machinery (replay,
two-fabric collectives, overlap/ZeRO-1, paged serving, width fleets)
have never been measured together, because every sweep so far was an
ad-hoc shell loop a flaky tunnel could zero.  This package turns a
sweep into ONE durable session:

* **campaign.py** — a declarative spec (grid over overlap mode x
  gradient bucket size x hierarchical x replay, plus serve axes)
  expanded into points, each run as its own ``bench.py`` subprocess
  and committed atomically into a ``campaign.json`` journal.  A crash,
  watchdog kill (rc=86) or injected abort loses at most the in-flight
  point; restarting with the same spec skips committed points and
  retries degraded ones up to a budget.

Entry points: ``python -m horovod_tpu.bench.campaign --spec SPEC`` or
``python bench.py --campaign SPEC``; ``scripts/perf_report.py`` renders
the journal + the historical record trajectory.
"""

# No eager submodule import: `python -m horovod_tpu.bench.campaign`
# would re-execute an already-imported module (runpy warns), and the
# package must stay importable without pulling the campaign driver in.
__all__ = ["campaign"]
