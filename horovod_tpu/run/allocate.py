"""Host/slot allocation.

Reference: horovod/run/gloo_run.py:54-112 (`_allocate`) — parse a hosts
string like ``h1:2,h2:2`` into per-process SlotInfo carrying the three
communicator coordinates (rank / local_rank / cross_rank and their sizes,
≙ Communicator GLOBAL/LOCAL/CROSS, horovod/common/common.h:111-115).

On TPU the local axis maps to processes within one host (sharing a slice's
ICI domain) and the cross axis to one process per host (DCN)."""

from __future__ import annotations

import functools
import re
from dataclasses import dataclass
from typing import Dict, List


@dataclass(frozen=True)
class HostSlots:
    hostname: str
    slots: int


@functools.lru_cache(maxsize=1)
def _local_names() -> tuple:
    # getfqdn() can block on DNS; both names are process-invariant, so
    # resolve once (launch paths call is_local_host per host and per slot).
    import socket

    return ("localhost", "127.0.0.1", socket.gethostname(), socket.getfqdn())


def is_local_host(name: str) -> bool:
    """One definition of "this machine" for every launcher component."""
    return name in _local_names()


def routable_ip(probe_host: str) -> str:
    """The local address a remote host would reach us on.  A connected UDP
    socket never sends a packet but makes the kernel pick the outbound
    interface — immune to the Debian /etc/hosts 127.0.1.1 hostname trap
    that gethostbyname(gethostname()) falls into.  Shared by the launcher
    (KV-store address) and the native engine's mesh rendezvous."""
    import socket

    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect((probe_host, 9))
        return s.getsockname()[0]
    except OSError:
        try:
            return socket.gethostbyname(socket.gethostname())
        except OSError:
            return "127.0.0.1"
    finally:
        s.close()


@dataclass(frozen=True)
class SlotInfo:
    hostname: str
    rank: int
    size: int
    local_rank: int
    local_size: int
    cross_rank: int
    cross_size: int


def parse_hosts(hosts: str) -> List[HostSlots]:
    """``"h1:2,h2:2"`` -> [HostSlots(h1,2), HostSlots(h2,2)] (reference
    runner.py hosts arg; also accepts bare hostnames meaning 1 slot)."""
    out: List[HostSlots] = []
    for part in hosts.split(","):
        part = part.strip()
        if not part:
            continue
        m = re.match(r"^(?P<host>[^:]+)(:(?P<slots>\d+))?$", part)
        if m is None:
            raise ValueError(f"bad host specification: {part!r}")
        out.append(
            HostSlots(m.group("host"), int(m.group("slots") or 1))
        )
    if not out:
        raise ValueError("empty hosts specification")
    return out


def parse_hostfile(path: str) -> List[HostSlots]:
    """Hostfile lines ``hostname slots=N`` (reference runner.py:553-565)."""
    out: List[HostSlots] = []
    with open(path) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            m = re.match(r"^(?P<host>\S+)(\s+slots\s*=\s*(?P<slots>\d+))?$", line)
            if m is None:
                raise ValueError(f"bad hostfile line: {line!r}")
            out.append(HostSlots(m.group("host"), int(m.group("slots") or 1)))
    return out


def slice_assignment(np: int, num_slices: int) -> List[int]:
    """rank -> slice id for a forced multislice partition: ``num_slices``
    contiguous equal blocks of ranks (the same contiguous-block rule
    ``basics.slice_of_rank`` applies inside the workers, so the launcher
    and the data plane always agree which slice a rank is in).

    Raises when the partition cannot be even — the launcher should
    refuse a bad ``--num-slices`` before spawning anything, not let every
    worker discover it independently."""
    if num_slices < 1:
        raise ValueError(f"num_slices must be >= 1, got {num_slices}")
    if np % num_slices:
        raise ValueError(
            f"--num-slices {num_slices} does not divide np={np}: slices "
            f"must be equal (the hierarchical schedule's cross-fabric "
            f"shard math is only rank-symmetric over equal slices)"
        )
    per = np // num_slices
    return [r // per for r in range(np)]


def allocate(hosts: List[HostSlots], np: int) -> List[SlotInfo]:
    """Fill slots host-by-host up to ``np`` processes (reference
    gloo_run.py:54-112: ranks assigned in host order; local_rank within
    host; cross_rank = index of host among hosts that have this
    local_rank)."""
    total = sum(h.slots for h in hosts)
    if np > total:
        raise ValueError(
            f"requested np={np} processes but hosts provide only {total} "
            f"slots"
        )
    # slots actually used per host, in order
    used: List[HostSlots] = []
    remaining = np
    for h in hosts:
        take = min(h.slots, remaining)
        if take > 0:
            used.append(HostSlots(h.hostname, take))
        remaining -= take
        if remaining == 0:
            break

    # For a given local_rank, the cross communicator is the set of hosts
    # that have that slot; cross_rank is this host's index *within that
    # set* (not the global host index — they differ when hosts have
    # heterogeneous slot counts).
    cross_sizes: Dict[int, int] = {}
    for h in used:
        for lr in range(h.slots):
            cross_sizes[lr] = cross_sizes.get(lr, 0) + 1

    slots: List[SlotInfo] = []
    rank = 0
    cross_seen: Dict[int, int] = {}
    for h in used:
        for lr in range(h.slots):
            cross_rank = cross_seen.get(lr, 0)
            cross_seen[lr] = cross_rank + 1
            slots.append(
                SlotInfo(
                    hostname=h.hostname,
                    rank=rank,
                    size=np,
                    local_rank=lr,
                    local_size=h.slots,
                    cross_rank=cross_rank,
                    cross_size=cross_sizes[lr],
                )
            )
            rank += 1
    return slots
