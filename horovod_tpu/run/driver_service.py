"""Driver/task services: NIC discovery across hosts.

Reference: horovod/run/driver/driver_service.py:128-197 + task services —
the launcher starts a lightweight task server on every host over ssh, task
``i`` probes task ``i+1``'s candidate addresses, and the driver intersects
the interfaces that worked, yielding the NICs every host can reach
(exported as ``NCCL_SOCKET_IFNAME`` / gloo iface).  The TPU build needs
the same answer for one address: which interface should the
``jax.distributed`` coordinator and the engine's TCP mesh bind so every
host can reach them (≙ ``HVDTPU_COORDINATOR``).

Design here: a :class:`TaskServer` (plain TCP, JSON protocol) serves its
host's candidate addresses and performs connect-probes on request; the
driver runs ring-probing — host ``i`` verifies host ``i+1``'s candidates —
and intersects the interface names that were reachable everywhere.
Payloads are HMAC-signed with a per-job secret like the reference's
(horovod/run/common/util/secret.py), so a stray process can't inject
addresses.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import os
import secrets as _secrets
import socket
import threading
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "local_addresses",
    "TaskServer",
    "probe",
    "discover_common_interfaces",
    "make_secret",
]


def make_secret() -> str:
    """Per-job HMAC key (reference secret.make_secret_key)."""
    return _secrets.token_hex(16)


def _sign(key: str, payload: bytes) -> bytes:
    return hmac.new(key.encode(), payload, hashlib.sha256).hexdigest().encode()


def _pack(key: str, obj) -> bytes:
    payload = json.dumps(obj).encode()
    return _sign(key, payload) + b"\n" + payload + b"\n"


def _unpack(key: str, raw: bytes):
    sig, _, payload = raw.partition(b"\n")
    payload = payload.rstrip(b"\n")
    if not hmac.compare_digest(sig, _sign(key, payload)):
        raise ValueError("bad message signature (wrong or missing job secret)")
    return json.loads(payload.decode())


def local_addresses() -> Dict[str, List[str]]:
    """Interface -> IPv4 addresses, loopback excluded (reference
    driver_service get_local_addresses via psutil.net_if_addrs)."""
    import psutil  # noqa: PLC0415  (baked into the reference's deps too)

    out: Dict[str, List[str]] = {}
    for iface, addrs in psutil.net_if_addrs().items():
        for a in addrs:
            if a.family == socket.AF_INET and not a.address.startswith("127."):
                out.setdefault(iface, []).append(a.address)
    return out


class TaskServer:
    """Per-host prober (reference task_service): answers
    ``addresses`` (its candidate NICs) and ``probe`` (connect to a list of
    host:port candidates, report which worked)."""

    def __init__(self, key: str, port: int = 0):
        self.key = key
        self._srv = socket.create_server(("", port))
        self.port = self._srv.getsockname()[1]
        self._stop = False
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self) -> None:
        while not self._stop:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            with conn:
                # Any per-request failure (malformed payload, client gone
                # mid-sendall) must not kill the accept loop — the server
                # would silently stop answering while still accepting.
                try:
                    with conn.makefile("rb") as f:
                        req = _unpack(self.key, f.readline() + f.readline())
                    if req.get("op") == "addresses":
                        resp = {"addresses": local_addresses()}
                    elif req.get("op") == "probe":
                        ok = []
                        for iface, addr, port in req["candidates"]:
                            if _can_connect(addr, port):
                                ok.append(iface)
                        resp = {"reachable": ok}
                    else:
                        resp = {"error": f"unknown op {req.get('op')!r}"}
                    conn.sendall(_pack(self.key, resp))
                except Exception:
                    continue

    def close(self) -> None:
        self._stop = True
        try:
            self._srv.close()
        except OSError:
            pass


def _can_connect(addr: str, port: int, timeout: float = 2.0) -> bool:
    try:
        with socket.create_connection((addr, port), timeout=timeout):
            return True
    except OSError:
        return False


def probe(host: str, port: int, key: str, request: dict, timeout: float = 10.0):
    """One signed request/response against a TaskServer.

    Both directions are a two-line frame (signature, payload) read with
    readline — never recv-to-EOF, since either side may hold makefile
    references that delay the FIN.
    """
    with socket.create_connection((host, port), timeout=timeout) as conn:
        conn.sendall(_pack(key, request))
        conn.shutdown(socket.SHUT_WR)
        with conn.makefile("rb") as f:
            raw = f.readline() + f.readline()
    return _unpack(key, raw)


def discover_common_interfaces(
    tasks: Sequence[Tuple[str, int]],
    key: str,
    *,
    probe_port: Optional[int] = None,
) -> List[str]:
    """Ring-probe NIC discovery (reference driver_service.py:128-197).

    ``tasks``: (host, task_server_port) per host, in rank order.  Each task
    ``i`` asks task ``i+1`` for its candidate addresses, then task ``i``
    connect-probes them (we drive both legs from the driver, like the
    reference's _run_probe fan-out).  Returns interface names reachable
    from every neighbor — the NICs safe for the coordinator/engine mesh.
    """
    n = len(tasks)
    if n == 0:
        return sorted(local_addresses())
    if n == 1:
        # Ask the (possibly remote) task server — answering from the
        # driver's own NICs would report the wrong host.
        host, port = tasks[0]
        addrs = probe(host, port, key, {"op": "addresses"})["addresses"]
        return sorted(addrs)
    common: Optional[set] = None
    for i in range(n):
        nxt = (i + 1) % n
        host_i, port_i = tasks[i]
        host_n, port_n = tasks[nxt]
        addrs = probe(host_n, port_n, key, {"op": "addresses"})["addresses"]
        candidates = [
            [iface, a, port_n] for iface, lst in addrs.items() for a in lst
        ]
        if probe_port is not None:
            candidates = [[i_, a, probe_port] for i_, a, _ in candidates]
        reach = probe(
            host_i, port_i, key, {"op": "probe", "candidates": candidates}
        )["reachable"]
        common = set(reach) if common is None else common & set(reach)
        if not common:
            break
    return sorted(common or [])


def _task_server_main() -> int:
    """Remote task-server entry (``python -m horovod_tpu.run.driver_service``):
    serve until the launcher closes our stdin (≙ the ssh channel), the same
    lifetime coupling the reference's task services use."""
    import sys  # noqa: PLC0415

    key = os.environ.get("HVDTPU_NIC_SECRET")
    if not key:
        print("HVDTPU_NIC_SECRET not set", file=sys.stderr)
        return 2
    srv = TaskServer(key)
    print(f"HVDTPU_TASK_PORT={srv.port}", flush=True)
    try:
        sys.stdin.read()  # blocks until the launcher tears the channel down
    except KeyboardInterrupt:
        pass
    srv.close()
    return 0


if __name__ == "__main__":  # pragma: no cover - exercised via subprocess
    raise SystemExit(_task_server_main())
