"""Process execution with tree-safe termination.

Reference: horovod/run/common/util/safe_shell_exec.py (219 LoC) — run a
command in its own process group, forward termination to the whole tree,
stream output; and gloo_run's threaded per-slot execution with job-level
failure propagation (gloo_run.py:168-234, 294-304)."""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass
from typing import Dict, IO, List, Optional

GRACEFUL_TERM_SECS = 5.0


def _stream(pipe: IO[bytes], sink, prefix: bytes) -> None:
    """Pump a child pipe to our stdout/stderr, rank-prefixed like
    horovodrun's `[1]<stdout>` tagging."""
    try:
        for line in iter(pipe.readline, b""):
            sink.buffer.write(prefix + line)
            sink.flush()
    except ValueError:
        pass  # sink closed during interpreter shutdown
    finally:
        pipe.close()


@dataclass
class _Proc:
    rank: int
    popen: subprocess.Popen
    threads: List[threading.Thread]


class ProcessSet:
    """Launch N local commands; kill the whole set if any fails
    (reference gloo_run.py:294-304) or on SIGINT/SIGTERM."""

    def __init__(self):
        self._procs: List[_Proc] = []
        self._lock = threading.Lock()

    def install_signal_handlers(self) -> None:
        """Forward SIGTERM/SIGHUP to the worker tree before dying —
        children run in their own sessions, so without this a scheduler
        killing the launcher would orphan every worker (reference
        gloo_run.py registers the same propagation)."""

        def _handler(signum, frame):
            del frame
            self.terminate()
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)

        for sig in (signal.SIGTERM, signal.SIGHUP):
            try:
                signal.signal(sig, _handler)
            except ValueError:
                pass  # not the main thread (e.g. run() from a worker)

    def launch(
        self,
        rank: int,
        cmd: List[str],
        env: Dict[str, str],
        tag_output: bool = True,
    ) -> None:
        popen = subprocess.Popen(
            cmd,
            env=env,
            stdout=subprocess.PIPE if tag_output else None,
            stderr=subprocess.PIPE if tag_output else None,
            start_new_session=True,  # own process group for tree kill
        )
        threads = []
        if tag_output:
            for pipe, sink in ((popen.stdout, sys.stdout), (popen.stderr, sys.stderr)):
                t = threading.Thread(
                    target=_stream,
                    args=(pipe, sink, f"[{rank}]".encode()),
                    daemon=True,
                )
                t.start()
                threads.append(t)
        with self._lock:
            self._procs.append(_Proc(rank, popen, threads))

    def wait(self, timeout: Optional[float] = None) -> Dict[int, int]:
        """Wait for all; on first non-zero exit, terminate the rest and
        raise.  Returns {rank: returncode} when all succeed."""
        deadline = time.time() + timeout if timeout else None
        results: Dict[int, int] = {}
        try:
            while True:
                with self._lock:
                    procs = list(self._procs)
                pending = [p for p in procs if p.rank not in results]
                if not pending:
                    return results
                for p in pending:
                    rc = p.popen.poll()
                    if rc is not None:
                        results[p.rank] = rc
                        if rc != 0:
                            self.terminate()
                            raise RuntimeError(
                                f"Process {p.rank} exited with code {rc}; "
                                f"terminating remaining workers "
                                f"(launcher failure propagation)."
                            )
                if deadline and time.time() > deadline:
                    self.terminate()
                    raise TimeoutError("launcher wait() timed out")
                time.sleep(0.05)
        except KeyboardInterrupt:
            self.terminate()
            raise

    def terminate(self) -> None:
        """SIGTERM the process groups, escalate to SIGKILL (reference
        safe_shell_exec's event-driven tree termination)."""
        with self._lock:
            procs = list(self._procs)
        for p in procs:
            if p.popen.poll() is None:
                try:
                    os.killpg(os.getpgid(p.popen.pid), signal.SIGTERM)
                except (ProcessLookupError, PermissionError):
                    pass
        deadline = time.time() + GRACEFUL_TERM_SECS
        for p in procs:
            while p.popen.poll() is None and time.time() < deadline:
                time.sleep(0.05)
            if p.popen.poll() is None:
                try:
                    os.killpg(os.getpgid(p.popen.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass


def make_ssh_command(host: str, cmd: List[str], env: Dict[str, str], ssh_port: Optional[int]) -> List[str]:
    """Wrap a worker command for remote execution (reference
    gloo_run.py:168-234 get_remote_command: env exported inline over ssh)."""
    exports = " ".join(
        f"{k}={_shquote(v)}" for k, v in sorted(env.items())
    )
    remote = f"cd {_shquote(os.getcwd())} && env {exports} {' '.join(_shquote(c) for c in cmd)}"
    ssh = ["ssh", "-o", "StrictHostKeyChecking=no"]
    if ssh_port:
        ssh += ["-p", str(ssh_port)]
    return ssh + [host, remote]


def _shquote(s: str) -> str:
    import shlex

    return shlex.quote(str(s))
