"""Process execution with tree-safe termination.

Reference: horovod/run/common/util/safe_shell_exec.py (219 LoC) — run a
command in its own process group, forward termination to the whole tree,
stream output; and gloo_run's threaded per-slot execution with job-level
failure propagation (gloo_run.py:168-234, 294-304)."""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass
from typing import Dict, IO, List, Optional

GRACEFUL_TERM_SECS = 5.0


def _stream(pipe: IO[bytes], sink, prefix: bytes,
            tee: Optional[IO[bytes]] = None) -> None:
    """Pump a child pipe to our stdout/stderr (when ``sink`` is set),
    rank-prefixed like horovodrun's `[1]<stdout>` tagging; optionally tee
    the raw (unprefixed) lines to a per-rank capture file (reference
    MultiFile, gloo_run.py:130-143,204-217).  A closed console (e.g. the
    launcher piped into `head`) stops only the console leg — the capture
    file keeps draining, that durability being what --output-filename is
    for."""
    sink_ok = sink is not None
    tee_ok = tee is not None
    try:
        for line in iter(pipe.readline, b""):
            if sink_ok:
                try:
                    sink.buffer.write(prefix + line)
                    sink.flush()
                except (ValueError, OSError):
                    # console gone (interpreter shutdown, or BrokenPipeError
                    # when the launcher's stdout is piped into a consumer
                    # that exited) — keep the capture leg alive
                    sink_ok = False
            if tee_ok:
                try:
                    tee.write(line)
                    tee.flush()
                except OSError:
                    tee_ok = False  # e.g. disk full; keep the console leg
            if not sink_ok and not tee_ok:
                break  # no destination left; stop pumping
    finally:
        pipe.close()
        if tee is not None:
            try:
                tee.close()
            except OSError:
                pass


@dataclass
class _Proc:
    rank: int
    popen: subprocess.Popen
    threads: List[threading.Thread]


class ProcessSet:
    """Launch N local commands; kill the whole set if any fails
    (reference gloo_run.py:294-304) or on SIGINT/SIGTERM."""

    def __init__(self):
        self._procs: List[_Proc] = []
        self._lock = threading.Lock()

    def install_signal_handlers(self) -> None:
        """Forward SIGTERM/SIGHUP to the worker tree before dying —
        children run in their own sessions, so without this a scheduler
        killing the launcher would orphan every worker (reference
        gloo_run.py registers the same propagation)."""

        def _handler(signum, frame):
            del frame
            # Restore the default disposition here: the handler runs on the
            # main thread, and signal.signal() refuses any other thread.
            signal.signal(signum, signal.SIG_DFL)
            # terminate() takes self._lock, which the interrupted main
            # thread may already hold (wait() polls under it) — and Python
            # locks are not reentrant, so calling it here could deadlock.
            # Do the work on a fresh thread and re-raise once it finishes.
            def _term_and_reraise():
                self.terminate()
                os.kill(os.getpid(), signum)

            threading.Thread(target=_term_and_reraise, daemon=True).start()

        for sig in (signal.SIGTERM, signal.SIGHUP):
            try:
                signal.signal(sig, _handler)
            except ValueError:
                pass  # not the main thread (e.g. run() from a worker)

    def launch(
        self,
        rank: int,
        cmd: List[str],
        env: Dict[str, str],
        tag_output: bool = True,
        stdin_data: Optional[bytes] = None,
        output_dir: Optional[str] = None,
        num_proc: int = 1,
    ) -> None:
        """``output_dir``: when set, each stream also lands in
        ``<output_dir>/rank.<padded>/stdout|stderr`` (reference
        --output-filename, gloo_run.py:204-217)."""
        capture = tag_output or output_dir is not None
        popen = subprocess.Popen(
            cmd,
            env=env,
            stdin=subprocess.PIPE if stdin_data is not None else None,
            stdout=subprocess.PIPE if capture else None,
            stderr=subprocess.PIPE if capture else None,
            start_new_session=True,  # own process group for tree kill
        )
        if stdin_data is not None:
            popen.stdin.write(stdin_data)
            popen.stdin.close()
        threads = []
        if capture:
            tees: Dict[str, Optional[IO[bytes]]] = {"stdout": None, "stderr": None}
            if output_dir is not None:
                pad = max(len(str(num_proc - 1)), 1)
                rank_dir = os.path.join(output_dir, f"rank.{rank:0{pad}d}")
                os.makedirs(rank_dir, exist_ok=True)
                for name in tees:
                    tees[name] = open(  # noqa: SIM115 — closed by _stream
                        os.path.join(rank_dir, name), "wb"
                    )
            for pipe, sink, name in (
                (popen.stdout, sys.stdout, "stdout"),
                (popen.stderr, sys.stderr, "stderr"),
            ):
                t = threading.Thread(
                    target=_stream,
                    args=(
                        pipe,
                        sink if tag_output else None,
                        f"[{rank}]".encode(),
                        tees[name],
                    ),
                    daemon=True,
                )
                t.start()
                threads.append(t)
        with self._lock:
            self._procs.append(_Proc(rank, popen, threads))

    # -- per-rank lifecycle (elastic launcher) ---------------------------
    # wait() keeps the reference's all-or-nothing contract (first failure
    # kills the job); the elastic monitor instead polls exits rank by
    # rank, discards the dead entry, and relaunches into the same set.

    def poll_exits(self) -> List[tuple]:
        """Reap newly exited workers: returns ``[(rank, returncode)]``
        and removes them from the set (their stream pumps drain on their
        own).  Non-destructive to still-running workers."""
        done: List[tuple] = []
        with self._lock:
            remaining = []
            for p in self._procs:
                rc = p.popen.poll()
                if rc is None:
                    remaining.append(p)
                else:
                    done.append((p.rank, rc))
            self._procs = remaining
        return done

    def alive_ranks(self) -> List[int]:
        with self._lock:
            return sorted(
                p.rank for p in self._procs if p.popen.poll() is None
            )

    def terminate_rank(self, rank: int, *, grace: float = 0.0) -> None:
        """Tree-kill one worker (heartbeat/progress-dead path: the
        process is still alive as far as the OS knows, but the job has
        declared it lost); its exit then surfaces through poll_exits().

        ``grace > 0`` escalates instead of executing: SIGUSR1 (the
        flight recorder's dump-only signal — even a rank that somehow
        survives SIGTERM leaves its black box), then SIGTERM (the
        recorder's handler flushes and re-raises), then SIGKILL after
        ``grace`` seconds on a watchdog thread — the monitor loop never
        blocks.  A rank whose main thread is wedged inside a C call
        can't run Python signal handlers; the SIGKILL backstop is what
        bounds that case, at the cost of its dump (documented in
        docs/postmortem.md).  ``grace=0`` is the old immediate
        SIGKILL."""
        with self._lock:
            procs = [p for p in self._procs if p.rank == rank]

        def _kill(pg_procs):
            for p in pg_procs:
                if p.popen.poll() is None:
                    try:
                        os.killpg(os.getpgid(p.popen.pid), signal.SIGKILL)
                    except (ProcessLookupError, PermissionError):
                        pass

        if grace <= 0:
            _kill(procs)
            return
        for p in procs:
            if p.popen.poll() is None:
                for sig in (signal.SIGUSR1, signal.SIGTERM):
                    try:
                        os.killpg(os.getpgid(p.popen.pid), sig)
                    except (ProcessLookupError, PermissionError):
                        break

        def _watchdog():
            deadline = time.time() + grace
            for p in procs:
                while p.popen.poll() is None and time.time() < deadline:
                    time.sleep(0.05)
            _kill(procs)

        threading.Thread(target=_watchdog, daemon=True,
                         name=f"hvdtpu_kill_rank{rank}").start()

    def wait(self, timeout: Optional[float] = None) -> Dict[int, int]:
        """Wait for all; on first non-zero exit, terminate the rest and
        raise.  Returns {rank: returncode} when all succeed."""
        deadline = time.time() + timeout if timeout else None
        results: Dict[int, int] = {}
        try:
            while True:
                with self._lock:
                    procs = list(self._procs)
                pending = [p for p in procs if p.rank not in results]
                if not pending:
                    return results
                for p in pending:
                    rc = p.popen.poll()
                    if rc is not None:
                        results[p.rank] = rc
                        if rc != 0:
                            self.terminate()
                            raise RuntimeError(
                                f"Process {p.rank} exited with code {rc}; "
                                f"terminating remaining workers "
                                f"(launcher failure propagation)."
                            )
                if deadline and time.time() > deadline:
                    self.terminate()
                    raise TimeoutError("launcher wait() timed out")
                time.sleep(0.05)
        except KeyboardInterrupt:
            self.terminate()
            raise

    def terminate(self) -> None:
        """SIGTERM the process groups, escalate to SIGKILL (reference
        safe_shell_exec's event-driven tree termination)."""
        with self._lock:
            procs = list(self._procs)
        for p in procs:
            if p.popen.poll() is None:
                try:
                    os.killpg(os.getpgid(p.popen.pid), signal.SIGTERM)
                except (ProcessLookupError, PermissionError):
                    pass
        deadline = time.time() + GRACEFUL_TERM_SECS
        for p in procs:
            while p.popen.poll() is None and time.time() < deadline:
                time.sleep(0.05)
            if p.popen.poll() is None:
                try:
                    os.killpg(os.getpgid(p.popen.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass


# Env vars whose values must never appear on a command line (`ps` exposes
# argv to every local user); they travel over the ssh channel's stdin.
SENSITIVE_ENV = ("HVDTPU_SECRET", "HVDTPU_NIC_SECRET")


def make_ssh_command(
    host: str, cmd: List[str], env: Dict[str, str], ssh_port: Optional[int]
) -> tuple:
    """Wrap a worker command for remote execution (reference
    gloo_run.py:168-234 get_remote_command: env exported inline over ssh).

    Returns ``(argv, stdin_data)``: sensitive values (the per-job HMAC
    secret) are read by the remote shell from stdin — inlining them in the
    argv would leak them via the process list on both ends."""
    public = {k: v for k, v in env.items() if k not in SENSITIVE_ENV}
    secret_items = [(k, env[k]) for k in SENSITIVE_ENV if k in env]
    exports = " ".join(f"{k}={_shquote(v)}" for k, v in sorted(public.items()))
    prelude = ""
    stdin_data: Optional[bytes] = None
    if secret_items:
        reads = "; ".join(
            f"IFS= read -r {k} && export {k}" for k, _ in secret_items
        )
        prelude = f"{reads}; "
        stdin_data = (
            "".join(f"{v}\n" for _, v in secret_items).encode() or None
        )
    remote = (
        f"{prelude}cd {_shquote(os.getcwd())} && env {exports} "
        f"{' '.join(_shquote(c) for c in cmd)}"
    )
    ssh = ["ssh", "-o", "StrictHostKeyChecking=no"]
    if ssh_port:
        ssh += ["-p", str(ssh_port)]
    return ssh + [host, remote], stdin_data


def _shquote(s: str) -> str:
    import shlex

    return shlex.quote(str(s))
