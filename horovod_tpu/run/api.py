"""Python launch API: run a function on N distributed workers.

Reference: horovod.run.run (horovod/run/runner.py:719-808) — pickles the
function, serves it over a KV store, launches workers that fetch/execute
it, collects per-rank results, returns them ordered by rank."""

from __future__ import annotations

import os
import sys
from typing import Any, List, Optional

import cloudpickle

from .rendezvous import KVStoreClient, KVStoreServer
from .runner import launch_job

_SCOPE = "runfunc"


def _pickle_func(func, args, kwargs) -> bytes:
    """Serialize by value when the defining module won't be importable in
    the workers (e.g. a test file or a script outside PYTHONPATH) — the
    reference sidesteps this by requiring an importable module; pickling by
    value makes run() self-contained."""
    module_name = getattr(func, "__module__", None)
    module = sys.modules.get(module_name) if module_name else None
    registered = False
    if (
        module is not None
        and module_name not in ("__main__", "builtins")
        and not module_name.startswith("horovod_tpu")
        and module_name not in sys.stdlib_module_names
    ):
        try:
            cloudpickle.register_pickle_by_value(module)
            registered = True
        except Exception:
            pass
    try:
        return cloudpickle.dumps((func, args, kwargs))
    finally:
        if registered:
            cloudpickle.unregister_pickle_by_value(module)


def _parse_host_slots(hosts: Optional[str], hostfile: Optional[str]) -> list:
    from .allocate import parse_hostfile, parse_hosts

    if hostfile:
        return parse_hostfile(hostfile)
    if hosts:
        return parse_hosts(hosts)
    return []


def run(
    func,
    args: tuple = (),
    kwargs: Optional[dict] = None,
    *,
    np: int = 1,
    hosts: Optional[str] = None,
    hostfile: Optional[str] = None,
    env: Optional[dict] = None,
    start_timeout: Optional[float] = None,
    timeout: Optional[float] = None,
    use_cpu: bool = False,
) -> List[Any]:
    """Execute ``func(*args, **kwargs)`` on ``np`` distributed workers and
    return the list of per-rank results (rank order).

    ``start_timeout`` bounds world formation; ``timeout`` is a whole-job
    watchdog.  ``use_cpu`` forces JAX_PLATFORMS=cpu in the workers — the
    launcher-level analog of the reference CI's "multi-process on localhost
    stands in for multi-node" strategy (SURVEY.md §4).
    """
    from .allocate import is_local_host, routable_ip

    host_slots = _parse_host_slots(hosts, hostfile)
    all_local = all(is_local_host(h.hostname) for h in host_slots)
    server = KVStoreServer(bind_all=not all_local)
    port = server.start()
    try:
        payload = _pickle_func(func, args, kwargs or {})
        if all_local:
            server_addr = f"127.0.0.1:{port}"
        else:
            probe = next(
                (h.hostname for h in host_slots if not is_local_host(h.hostname)),
                "127.0.0.1",
            )
            server_addr = f"{routable_ip(probe)}:{port}"
        client = KVStoreClient(f"127.0.0.1:{port}", secret=server.secret)
        client.put(_SCOPE, "func", payload)

        worker_env = dict(env or {})
        worker_env["HVDTPU_RUN_FUNC_ADDR"] = server_addr
        from .rendezvous import SECRET_ENV  # noqa: PLC0415

        worker_env[SECRET_ENV] = server.secret
        if use_cpu:
            worker_env.setdefault("JAX_PLATFORMS", "cpu")

        command = [sys.executable, "-m", "horovod_tpu.run.task_fn"]
        try:
            launch_job(
                command,
                np,
                hosts=hosts,
                hostfile=hostfile,
                env=worker_env,
                start_timeout=start_timeout,
                job_timeout=timeout,
            )
        except RuntimeError as launch_err:
            # A failing worker exits non-zero, which surfaces here before
            # the result loop — but it published its real traceback to the
            # KV store first.  Prefer that over the generic exit-code error.
            for rank in range(np):
                try:
                    blob = client.get(_SCOPE, f"result_{rank}")
                except Exception:
                    blob = None
                if blob is None:
                    continue
                ok, value = cloudpickle.loads(blob)
                if not ok:
                    raise RuntimeError(
                        f"rank {rank} raised during run():\n{value}"
                    ) from launch_err
            raise
        results = []
        for rank in range(np):
            blob = client.wait(_SCOPE, f"result_{rank}", timeout=30)
            ok, value = cloudpickle.loads(blob)
            if not ok:
                raise RuntimeError(
                    f"rank {rank} raised during run():\n{value}"
                )
            results.append(value)
        return results
    finally:
        server.stop()
