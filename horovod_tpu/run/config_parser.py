"""CLI-args <-> env-var mapping and YAML config layering.

Reference: horovod/run/common/util/config_parser.py (set_env_from_args,
args<->yaml key maps) and runner.py:163-218,446-450 (the override-action
trick: explicit CLI flags win over the config file, which wins over
defaults).

Env contract consumed by the core (utils/env.py) — every knob the
reference exposes has an HVDTPU_ equivalent here (SURVEY.md §5.6)."""

from __future__ import annotations

import argparse
from typing import Dict, Optional

from ..utils import env as envmod

# arg attribute -> (env var, yaml section.key)
_ARG_ENV_MAP = {
    "fusion_threshold_mb": (envmod.FUSION_THRESHOLD, "params.fusion-threshold-mb"),
    "cycle_time_ms": (envmod.CYCLE_TIME, "params.cycle-time-ms"),
    "cache_capacity": (envmod.CACHE_CAPACITY, "params.cache-capacity"),
    "hierarchical_allreduce": (
        envmod.HIERARCHICAL_ALLREDUCE,
        "params.hierarchical-allreduce",
    ),
    "num_slices": (envmod.NUM_SLICES, "params.num-slices"),
    "dcn_compression": (envmod.DCN_COMPRESSION, "params.dcn-compression"),
    # --no-schedule-replay writes "0" into the positive env knob (see
    # the inversion in set_env_from_args): one env var, default-on.
    "no_schedule_replay": (envmod.SCHEDULE_REPLAY, "params.no-schedule-replay"),
    "schedule_replay_cycles": (
        envmod.SCHEDULE_REPLAY_CYCLES,
        "params.schedule-replay-cycles",
    ),
    "ckpt_dir": (envmod.CKPT_DIR, "checkpoint.dir"),
    "ckpt_replica": (envmod.CKPT_REPLICA, "checkpoint.replica"),
    "ckpt_replica_chunk_kb": (
        envmod.CKPT_REPLICA_CHUNK_KB,
        "checkpoint.replica-chunk-kb",
    ),
    "ckpt_commit_timeout_secs": (
        envmod.CKPT_COMMIT_TIMEOUT,
        "checkpoint.commit-timeout-secs",
    ),
    "timeline_filename": (envmod.TIMELINE, "timeline.filename"),
    "timeline_mark_cycles": (envmod.TIMELINE_MARK_CYCLES, "timeline.mark-cycles"),
    "metrics_dump": (envmod.METRICS_DUMP, "metrics.dump"),
    "flightrec_dump": (envmod.FLIGHTREC_DUMP, "metrics.flightrec-dump"),
    "live_stats_secs": (envmod.LIVE_STATS, "metrics.live-stats-secs"),
    "alert_skew_ms": (envmod.ALERT_SKEW, "metrics.alert-skew-ms"),
    "trace": (envmod.TRACE, "trace.target"),
    "trace_sample_rate": (envmod.TRACE_SAMPLE_RATE, "trace.sample-rate"),
    "no_stall_check": (envmod.STALL_CHECK_DISABLE, "stall-check.disable"),
    "stall_check_warning_time_seconds": (
        envmod.STALL_CHECK_TIME,
        "stall-check.warning-time-seconds",
    ),
    "stall_check_shutdown_time_seconds": (
        envmod.STALL_SHUTDOWN_TIME,
        "stall-check.shutdown-time-seconds",
    ),
    "autotune": (envmod.AUTOTUNE, "autotune.enabled"),
    "autotune_log_file": (envmod.AUTOTUNE_LOG, "autotune.log-file"),
    "autotune_warmup_samples": (
        envmod.AUTOTUNE_WARMUP_SAMPLES,
        "autotune.warmup-samples",
    ),
    "autotune_steps_per_sample": (
        envmod.AUTOTUNE_STEPS_PER_SAMPLE,
        "autotune.steps-per-sample",
    ),
    "autotune_bayes_opt_max_samples": (
        envmod.AUTOTUNE_BAYES_OPT_MAX_SAMPLES,
        "autotune.bayes-opt-max-samples",
    ),
    "autotune_gaussian_process_noise": (
        envmod.AUTOTUNE_GP_NOISE,
        "autotune.gaussian-process-noise",
    ),
    "autotune_drift_threshold": (
        envmod.AUTOTUNE_DRIFT_THRESHOLD,
        "autotune.drift-threshold",
    ),
    "autotune_drift_samples": (
        envmod.AUTOTUNE_DRIFT_SAMPLES,
        "autotune.drift-samples",
    ),
    "log_level": (envmod.LOG_LEVEL, "logging.level"),
    "serve_model": (envmod.SERVE_MODEL, "serve.model"),
    "serve_slots": (envmod.SERVE_SLOTS, "serve.slots"),
    "serve_max_len": (envmod.SERVE_MAX_LEN, "serve.max-len"),
    "serve_seed": (envmod.SERVE_SEED, "serve.seed"),
    "serve_kv_mode": (envmod.SERVE_KV_MODE, "serve.kv-mode"),
    "serve_page_size": (envmod.SERVE_PAGE_SIZE, "serve.page-size"),
    "serve_kv_pages": (envmod.SERVE_KV_PAGES, "serve.kv-pages"),
    "serve_width": (envmod.SERVE_WIDTH, "serve.width"),
    "serve_weights_dir": (envmod.SERVE_WEIGHTS_DIR, "serve.weights-dir"),
    "serve_swap_poll_steps": (
        envmod.SERVE_SWAP_POLL_STEPS,
        "serve.swap-poll-steps",
    ),
    "serve_frontends": (envmod.SERVE_FRONTENDS, "serve.frontends"),
    "serve_tenant_budget": (
        envmod.SERVE_TENANT_BUDGET,
        "serve.tenant-budget",
    ),
    "slo_ttft_ms": (envmod.SERVE_SLO_TTFT_MS, "serve.slo-ttft-ms"),
    "slo_tpot_ms": (envmod.SERVE_SLO_TPOT_MS, "serve.slo-tpot-ms"),
    "slo_objective": (envmod.SERVE_SLO_OBJECTIVE, "serve.slo-objective"),
    "slo_class": (envmod.SERVE_SLO_CLASS, "serve.slo-class"),
    "serve_autoscale": (envmod.SERVE_AUTOSCALE, "serve.autoscale"),
    "max_workers": (envmod.MAX_WORKERS, "serve.max-workers"),
    "scale_up_queue": (envmod.SCALE_UP_QUEUE, "serve.scale-up-queue"),
    "scale_down_idle_secs": (
        envmod.SCALE_DOWN_IDLE_SECS,
        "serve.scale-down-idle-secs",
    ),
    "scale_cooldown_secs": (
        envmod.SCALE_COOLDOWN_SECS,
        "serve.scale-cooldown-secs",
    ),
    "health": (envmod.HEALTH, "metrics.health"),
    "health_check_steps": (
        envmod.HEALTH_CHECK_STEPS,
        "metrics.health-check-steps",
    ),
    "divergence_action": (
        envmod.DIVERGENCE_ACTION,
        "metrics.divergence-action",
    ),
}


def set_env_from_args(env: Dict[str, str], args: argparse.Namespace) -> Dict[str, str]:
    """Write HVDTPU_* entries for every set arg (reference
    config_parser.set_env_from_args, called at runner.py:693-695)."""
    for attr, (env_name, _) in _ARG_ENV_MAP.items():
        value = getattr(args, attr, None)
        # `is`-checks: 0 is a legitimate explicit value (e.g.
        # --fusion-threshold-mb 0 disables fusion) and 0 == False in python.
        if value is None or value is False:
            continue
        if attr == "fusion_threshold_mb":
            value = int(value) * 1024 * 1024
        if attr == "no_schedule_replay":
            # negative flag onto the positive default-on env knob
            value = "0"
        if value is True:
            value = "1"
        env[env_name] = str(value)
    return env


class _StoreOverrideAction(argparse.Action):
    """Tracks which args the user set explicitly so config-file values
    don't clobber them (reference runner.py:163-218)."""

    def __call__(self, parser, namespace, values, option_string=None):
        setattr(namespace, self.dest, values)
        overrides = getattr(namespace, "_explicit_args", set())
        overrides.add(self.dest)
        namespace._explicit_args = overrides


class _StoreTrueOverrideAction(_StoreOverrideAction):
    def __init__(self, option_strings, dest, **kwargs):
        kwargs.pop("nargs", None)
        super().__init__(option_strings, dest, nargs=0, **kwargs)

    def __call__(self, parser, namespace, values, option_string=None):
        super().__call__(parser, namespace, True, option_string)


def apply_config_file(args: argparse.Namespace, path: Optional[str]) -> None:
    """Layer a YAML config under explicit CLI args (reference
    runner.py:446-450: `read_config_file` + `validate_config_args`)."""
    if not path:
        return
    import yaml  # PyYAML ships with the baked image

    with open(path) as f:
        config = yaml.safe_load(f) or {}
    explicit = getattr(args, "_explicit_args", set())
    for attr, (_, yaml_key) in _ARG_ENV_MAP.items():
        section, key = yaml_key.split(".")
        if section in config and key in (config[section] or {}):
            if attr not in explicit:
                setattr(args, attr, config[section][key])
