"""`hvdrun` CLI (reference: horovod/run/runner.py:221-452 arg surface,
bin/horovodrun).

Usage::

    python -m horovod_tpu.run -np 4 python train.py
    python -m horovod_tpu.run -np 8 -H host1:4,host2:4 python train.py

Every runtime knob maps onto an HVDTPU_* env var for all ranks
(config_parser.py); a YAML --config-file layers under explicit CLI flags.
"""

from __future__ import annotations

import argparse
import os
import socket
import sys
from typing import Dict, List, Optional

from ..utils import env as envmod
from ..utils.logging import get_logger
from . import config_parser
from .allocate import (
    SlotInfo,
    allocate,
    is_local_host,
    parse_hostfile,
    parse_hosts,
)
from .blacklist import HostBlacklist
from .config_parser import _StoreOverrideAction, _StoreTrueOverrideAction
from .exec import ProcessSet, make_ssh_command

LOG = get_logger("run")

# Fixed default for remote coordinators, where the launcher cannot probe a
# free port on the target host; overridable with --coordinator-port.
DEFAULT_COORDINATOR_PORT = 29500


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(
        prog="hvdrun",
        description=(
            "Launch a horovod_tpu distributed job: one process per slot, "
            "wired to a shared JAX coordination service."
        ),
    )
    parser.add_argument("-v", "--version", action="store_true", dest="version")
    parser.add_argument(
        "-np", "--num-proc", type=int, dest="np",
        help="Total number of worker processes.",
    )
    parser.add_argument(
        "-H", "--hosts", action=_StoreOverrideAction, dest="hosts",
        help='Host list with slots, e.g. "h1:2,h2:2". Default: localhost '
             "with np slots.",
    )
    parser.add_argument(
        "-hostfile", "--hostfile", action=_StoreOverrideAction, dest="hostfile",
        help='Hostfile with lines "hostname slots=N".',
    )
    parser.add_argument(
        "--ssh-port", type=int, action=_StoreOverrideAction, dest="ssh_port"
    )
    parser.add_argument(
        "--coordinator-port", type=int, action=_StoreOverrideAction,
        dest="coordinator_port", default=None,
        help=f"Port for the jax.distributed coordinator on the first host "
             f"(default: probe a free port locally, {DEFAULT_COORDINATOR_PORT} "
             f"when the first host is remote).",
    )
    parser.add_argument(
        "--start-timeout", type=int, action=_StoreOverrideAction,
        dest="start_timeout", default=None,
        help="Seconds each rank waits for the whole world to check in at "
             "the coordination service before failing startup (reference "
             "runner.py:573-583; enforced as the jax.distributed "
             "initialization timeout, default 300).",
    )
    parser.add_argument(
        "--config-file", action=_StoreOverrideAction, dest="config_file"
    )
    parser.add_argument(
        "--check-build", action="store_true", dest="check_build",
        help="Print capability report and exit (reference runner.py:115-150).",
    )
    parser.add_argument(
        "--discover-nics", action="store_true", dest="discover_nics",
        help="Start a task server on every host (-H/--hostfile), ring-probe "
             "interface reachability, print the NICs usable by every host, "
             "and exit (reference driver/task NIC discovery, "
             "driver_service.py:128-197).",
    )
    parser.add_argument("--verbose", action="store_true", dest="verbose")

    elastic = parser.add_argument_group("elastic fault tolerance")
    elastic.add_argument(
        "--elastic", action="store_true", dest="elastic",
        help="Launch in elastic mode: per-rank failure detection, host "
             "blacklisting, bounded respawn into a re-minted rendezvous "
             "epoch (workers use the horovod_tpu.elastic API).",
    )
    elastic.add_argument(
        "--min-workers", type=int, action=_StoreOverrideAction,
        dest="min_workers", default=None,
        help="Smallest world the elastic job may shrink to once the "
             "respawn budget is spent (default: np — never shrink).",
    )
    elastic.add_argument(
        "--max-workers", type=int, action=_StoreOverrideAction,
        dest="max_workers", default=None,
        help="Largest world the job may grow to (default: np).  Ranks "
             "np..max_workers-1 are standby slots the autoscale "
             "controller can admit under load; the host list must "
             "carry slots for all of them.",
    )
    elastic.add_argument(
        "--max-elastic-retries", type=int, action=_StoreOverrideAction,
        dest="max_elastic_retries", default=None,
        help="Total failed-rank respawns across the job (default 3).",
    )
    elastic.add_argument(
        "--blacklist-cooldown-secs", type=float,
        action=_StoreOverrideAction,
        dest="blacklist_cooldown_secs", default=None,
        help="Base host-blacklist cooldown; doubles per repeat failure "
             "(default 10).",
    )
    elastic.add_argument(
        "--progress-timeout-secs", type=float,
        action=_StoreOverrideAction,
        dest="progress_timeout_secs", default=None,
        help="Steady-state progress-beat budget: a rank whose process "
             "heartbeat lives but whose collectives-completed counter "
             "has not advanced for this long is declared deadlocked and "
             "respawned (default 300; 0 disables).",
    )
    elastic.add_argument(
        "--progress-grace-secs", type=float,
        action=_StoreOverrideAction,
        dest="progress_grace_secs", default=None,
        help="The same budget while the worker reports an init/compile "
             "phase (default 0 = never kill during those phases; long "
             "XLA compiles are legitimate).",
    )
    elastic.add_argument(
        "--dump-grace-secs", type=float,
        action=_StoreOverrideAction,
        dest="dump_grace_secs", default=None,
        help="When the monitor kills a hung rank (heartbeat/progress "
             "lost), send SIGUSR1+SIGTERM first so its flight recorder "
             "can dump, and SIGKILL only after this many seconds "
             "(default 5; 0 = immediate SIGKILL, no black box).",
    )
    parser.add_argument(
        "--output-filename", action=_StoreOverrideAction,
        dest="output_filename", default=None,
        help="Also write every rank's output to "
             "<output_filename>/rank.<rank>/<stdout|stderr> (rank "
             "zero-padded; reference gloo_run.py:204-217).",
    )

    params = parser.add_argument_group("tunable parameters")
    params.add_argument(
        "--fusion-threshold-mb", type=int, action=_StoreOverrideAction,
        dest="fusion_threshold_mb", default=None,
    )
    params.add_argument(
        "--cycle-time-ms", type=float, action=_StoreOverrideAction,
        dest="cycle_time_ms", default=None,
    )
    params.add_argument(
        "--cache-capacity", type=int, action=_StoreOverrideAction,
        dest="cache_capacity", default=None,
    )
    params.add_argument(
        "--hierarchical-allreduce", action=_StoreTrueOverrideAction,
        dest="hierarchical_allreduce", default=None,
        help="Pin the two-fabric (slice-aware) allreduce schedule on: "
             "reduce-scatter on ICI, cross-slice exchange on "
             "1/slice_size of the bytes over DCN, gather back on ICI.  "
             "Needs a multi-slice topology (--num-slices or discovered); "
             "single-slice worlds log a downgrade warning and stay flat. "
             "Without this flag the autotuner still explores the "
             "hierarchical schedule on multi-slice topologies.",
    )
    params.add_argument(
        "--num-slices", type=int, action=_StoreOverrideAction,
        dest="num_slices", default=None,
        help="Slice partition of the world: that many contiguous equal "
             "blocks of ranks (ICI within a block, DCN between).  Real "
             "multislice TPU jobs are discovered automatically; this "
             "forces a partition (CPU/dev simulation, or overriding "
             "discovery).  Must divide -np.",
    )
    params.add_argument(
        "--dcn-compression", action=_StoreOverrideAction,
        dest="dcn_compression", default=None,
        choices=["none", "bf16", "fp16"],
        help="Wire dtype for the cross-slice (DCN) leg of hierarchical "
             "allreduce; only the 1/slice_size shard that crosses the "
             "slow fabric is cast, ICI phases stay exact (default none).",
    )
    params.add_argument(
        "--no-schedule-replay", action=_StoreTrueOverrideAction,
        dest="no_schedule_replay", default=None,
        help="Disable the steady-state schedule-replay fast path (after "
             "K bitwise-identical cycles the engine skips negotiation "
             "entirely and replays the memorized fused schedule; this "
             "flag keeps the per-cycle control-vector exchange instead).",
    )
    params.add_argument(
        "--schedule-replay-cycles", type=int, action=_StoreOverrideAction,
        dest="schedule_replay_cycles", default=None,
        help="Consecutive bitwise-identical cycles before a replay "
             "epoch opens (default 50).",
    )

    serve = parser.add_argument_group("serving")
    serve.add_argument(
        "--serve", action="store_true", dest="serve",
        help="Serving mode: implies --elastic, arms the request ingest "
             "pump on the rendezvous store (clients submit over the "
             "signed KV protocol, horovod_tpu.serve.ServeClient), and "
             "defaults the worker command to `python -m "
             "horovod_tpu.serve` — a continuous-batching inference "
             "fleet where a dead rank respawns and replays its "
             "in-flight requests instead of dropping traffic.",
    )
    serve.add_argument(
        "--serve-model", action=_StoreOverrideAction, dest="serve_model",
        default=None,
        help="gpt() model family entry every serving rank builds "
             "(HVDTPU_SERVE_MODEL, default nano).",
    )
    serve.add_argument(
        "--serve-slots", type=int, action=_StoreOverrideAction,
        dest="serve_slots", default=None,
        help="Decode slot pool size per rank — the max simultaneous "
             "in-flight requests (HVDTPU_SERVE_SLOTS, default 4).",
    )
    serve.add_argument(
        "--serve-max-len", type=int, action=_StoreOverrideAction,
        dest="serve_max_len", default=None,
        help="Slot KV-cache length in tokens (HVDTPU_SERVE_MAX_LEN; "
             "default: the model's max_len).",
    )
    serve.add_argument(
        "--serve-seed", type=int, action=_StoreOverrideAction,
        dest="serve_seed", default=None,
        help="Params init seed AND the per-request sampling root — "
             "identical on every rank by construction "
             "(HVDTPU_SERVE_SEED, default 0).  Sampled tokens are "
             "keyed on (request id, emission index, this seed), so "
             "the stream survives elastic replay bit-exactly.",
    )
    serve.add_argument(
        "--serve-width", type=int, action=_StoreOverrideAction,
        dest="serve_width", default=None,
        help="Width-sharded serving fleet (HVDTPU_SERVE_WIDTH, default "
             "0 = replicated standbys): the world splits into "
             "np//width serving GROUPS, each independently serving its "
             "partition of the request log — doubling np doubles "
             "sustained tokens/sec instead of adding hot standbys — "
             "and each rank's paged decode step is shard_mapped over "
             "width devices of its (replica, width) mesh view "
             "(Megatron tensor parallelism: per-shard KV pages hold "
             "only that shard's heads).  Requires the paged KV mode.",
    )
    serve.add_argument(
        "--serve-page-size", type=int, action=_StoreOverrideAction,
        dest="serve_page_size", default=None,
        help="KV page size in token rows (HVDTPU_SERVE_PAGE_SIZE, "
             "default 16): paged KV allocates cache in pages as "
             "positions actually advance, so memory tracks tokens "
             "written, not slots x max-len worst case.",
    )
    serve.add_argument(
        "--serve-kv-pages", type=int, action=_StoreOverrideAction,
        dest="serve_kv_pages", default=None,
        help="KV page-pool size (HVDTPU_SERVE_KV_PAGES; default: the "
             "worst case, slots x pages-per-slot).  Admission capacity "
             "is judged in free pages: a bounded pool admits MORE "
             "short requests than the contiguous design's slot count "
             "would, and rejects a request whose worst case can never "
             "fit.",
    )
    serve.add_argument(
        "--serve-kv-mode", action=_StoreOverrideAction,
        dest="serve_kv_mode", default=None, choices=["paged", "contiguous"],
        help="KV cache layout (HVDTPU_SERVE_KV_MODE, default paged); "
             "contiguous keeps the PR-10 worst-case-row pool (the "
             "PR-14 waste baseline) for A/B comparison.",
    )
    serve.add_argument(
        "--serve-weights-dir", action=_StoreOverrideAction,
        dest="serve_weights_dir", default=None,
        help="Weight hot-swap source (HVDTPU_SERVE_WEIGHTS_DIR): a "
             "sharded-checkpoint directory a concurrently-training job "
             "publishes committed versions into "
             "(horovod_tpu.serve.hotswap.publish_weights).  The fleet "
             "polls it between decode steps and flips atomically on a "
             "version-stamped step — exactly one weight version is "
             "served at every step, and a failed or dying swap rolls "
             "the whole fleet back to the incumbent.",
    )
    serve.add_argument(
        "--serve-swap-poll-steps", type=int, action=_StoreOverrideAction,
        dest="serve_swap_poll_steps", default=None,
        help="Serving steps between hot-swap manifest polls "
             "(HVDTPU_SERVE_SWAP_POLL_STEPS, default 16).",
    )
    serve.add_argument(
        "--frontends", type=int, action=_StoreOverrideAction,
        dest="serve_frontends", default=None,
        help="Front-door shard count F (HVDTPU_SERVE_FRONTENDS, "
             "default 1): F launcher-resident frontend pumps each own "
             "the request-log partition crc32(rid) %% F; clients route "
             "by the same pure hash.  A dead frontend's shards are "
             "adopted by the lowest survivor (heartbeat takeover) and "
             "the serving epoch is re-minted — in-flight requests "
             "replay from the durable log with zero drops.",
    )
    serve.add_argument(
        "--serve-tenant-budget", type=int, action=_StoreOverrideAction,
        dest="serve_tenant_budget", default=None,
        help="Tenant-aware admission (HVDTPU_SERVE_TENANT_BUDGET, "
             "default off = plain FCFS): per-tenant token budget per "
             "scheduling window.  Requests carry tenant + SLO class "
             "(interactive/standard/batch); the scheduler admits by "
             "deterministic weighted-fair queueing with budget "
             "throttling, identically derived on every rank.",
    )
    serve.add_argument(
        "--slo-ttft-ms", type=float, action=_StoreOverrideAction,
        dest="slo_ttft_ms", default=None,
        help="Time-to-first-token SLO ceiling in ms for --slo-class "
             "requests (HVDTPU_SERVE_SLO_TTFT_MS, unset = no ttft "
             "objective).  Breaches spend the error budget the "
             "two-window burn-rate alerts (obs/slo.py) page on.",
    )
    serve.add_argument(
        "--slo-tpot-ms", type=float, action=_StoreOverrideAction,
        dest="slo_tpot_ms", default=None,
        help="Per-output-token SLO ceiling in ms for --slo-class "
             "requests (HVDTPU_SERVE_SLO_TPOT_MS, unset = no tpot "
             "objective).",
    )
    serve.add_argument(
        "--slo-objective", type=float, action=_StoreOverrideAction,
        dest="slo_objective", default=None,
        help="Fraction of requests that must meet the SLO ceilings "
             "(HVDTPU_SERVE_SLO_OBJECTIVE, default 0.99 — a 1%% error "
             "budget the burn-rate alerts spend against).",
    )
    serve.add_argument(
        "--slo-class", action=_StoreOverrideAction,
        dest="slo_class", default=None,
        help="Which SLO class the ceilings apply to "
             "(HVDTPU_SERVE_SLO_CLASS, default interactive).  Traffic "
             "in classes without a target is digested but never "
             "alerts.",
    )
    serve.add_argument(
        "--serve-autoscale", action=_StoreTrueOverrideAction,
        dest="serve_autoscale", default=None,
        help="Load-driven autoscaling: the launcher watches the "
             "serve.queue_depth/serve.ttft_ms gauges the live plane "
             "aggregates and grows/shrinks the fleet between "
             "--min-workers and --max-workers through deliberately "
             "re-minted rendezvous epochs — in-flight requests replay, "
             "zero are dropped (a scale event is indistinguishable "
             "from a survived failure).  Implies live stats at 0.5s "
             "when --live-stats-secs is unset.",
    )
    serve.add_argument(
        "--scale-up-queue", type=int, action=_StoreOverrideAction,
        dest="scale_up_queue", default=None,
        help="Queue-depth high-water mark: grow one worker when the "
             "queue stays at/above this for the hysteresis window "
             "(default 4).",
    )
    serve.add_argument(
        "--scale-down-idle-secs", type=float, action=_StoreOverrideAction,
        dest="scale_down_idle_secs", default=None,
        help="Release one worker after the fleet has been fully "
             "drained (empty queue, no active slot) this long "
             "(default 10).",
    )
    serve.add_argument(
        "--scale-cooldown-secs", type=float, action=_StoreOverrideAction,
        dest="scale_cooldown_secs", default=None,
        help="Minimum seconds between resizes in EITHER direction "
             "(flap guard, default 15).  Failed grows additionally "
             "back off exponentially.",
    )

    ckpt = parser.add_argument_group("checkpointing")
    ckpt.add_argument(
        "--ckpt-dir", action=_StoreOverrideAction, dest="ckpt_dir",
        default=None,
        help="Sharded-checkpoint directory (HVDTPU_CKPT_DIR): every "
             "rank writes only its own shard; rank 0 commits the "
             "manifest last; elastic State.sync falls back to the "
             "newest valid manifest here when no live peer replica "
             "exists.",
    )
    ckpt.add_argument(
        "--ckpt-replica", action=_StoreTrueOverrideAction,
        dest="ckpt_replica", default=None,
        help="Peer-replica recovery tier: after every State.commit "
             "each rank pushes its committed shard to its ring "
             "neighbor's replica key over the HMAC-signed KV path, so "
             "a respawned rank restores from a live peer in seconds "
             "instead of from disk.",
    )
    ckpt.add_argument(
        "--ckpt-replica-chunk-kb", type=int, action=_StoreOverrideAction,
        dest="ckpt_replica_chunk_kb", default=None,
        help="Replica push chunk size in KiB (default 1024).",
    )
    ckpt.add_argument(
        "--ckpt-commit-timeout-secs", type=float,
        action=_StoreOverrideAction,
        dest="ckpt_commit_timeout_secs", default=None,
        help="Seconds each rank waits for the sharded manifest to "
             "commit (rank 0: for every peer's shard sidecar) before "
             "failing the save on every rank (default 120).",
    )

    timeline = parser.add_argument_group("timeline")
    timeline.add_argument(
        "--timeline-filename", action=_StoreOverrideAction,
        dest="timeline_filename", default=None,
        help="All-rank Chrome trace: each rank writes its own file "
             "derived from this value (template with {rank}, directory, "
             "or plain path getting a rank tag); the launcher merges "
             "them here at job end, one lane per rank.",
    )
    timeline.add_argument(
        "--timeline-mark-cycles", action=_StoreTrueOverrideAction,
        dest="timeline_mark_cycles", default=None,
    )

    obs_group = parser.add_argument_group("observability")
    obs_group.add_argument(
        "--metrics-dump", action=_StoreOverrideAction,
        dest="metrics_dump", default=None,
        help="Per-rank metrics dump target (HVDTPU_METRICS_DUMP): a "
             "directory, a {rank} template, or a plain path that gets a "
             "rank tag inserted.",
    )
    obs_group.add_argument(
        "--flightrec-dump", action=_StoreOverrideAction,
        dest="flightrec_dump", default=None,
        help="Per-rank flight-recorder dump target "
             "(HVDTPU_FLIGHTREC_DUMP): same dir/{rank}/plain-path forms "
             "as --metrics-dump.  Unset, the launcher still arms a "
             "temporary black-box dir so a crashed job gets a "
             "post-mortem; set it to keep the per-rank rings after "
             "clean runs too.",
    )
    obs_group.add_argument(
        "--stats-summary", action="store_true", dest="stats_summary",
        help="After the job ends, aggregate every rank's metrics dump "
             "into one per-rank summary table on stdout (implies a "
             "temporary --metrics-dump when none is given).",
    )
    obs_group.add_argument(
        "--live-stats-secs", type=float, action=_StoreOverrideAction,
        dest="live_stats_secs", default=None,
        help="Stream each rank's metrics to the launcher every N "
             "seconds (default off): one-line console digests, a "
             "crash-safe live_history.jsonl, and a read-only Prometheus "
             "GET /metrics endpoint on the launcher's KV port.",
    )
    obs_group.add_argument(
        "--live-port", type=int, action=_StoreOverrideAction,
        dest="live_port", default=None,
        help="Fixed port for the live telemetry KV/scrape server in "
             "non-elastic jobs (default: ephemeral, announced on "
             "stdout).  Elastic jobs serve /metrics from the existing "
             "rendezvous port.",
    )
    obs_group.add_argument(
        "--live-history-file", action=_StoreOverrideAction,
        dest="live_history_file", default=None,
        help="Where the launcher appends one JSON line per live "
             "aggregation round (default: ./live_history.jsonl while "
             "--live-stats-secs is on).",
    )
    obs_group.add_argument(
        "--alert-skew-ms", type=float, action=_StoreOverrideAction,
        dest="alert_skew_ms", default=None,
        help="Warn (and count engine.straggler.alerts) when a "
             "collective's first-to-last rank arrival skew exceeds this "
             "many milliseconds (default 0 = accumulate silently).",
    )
    obs_group.add_argument(
        "--trace", action=_StoreOverrideAction, dest="trace",
        default=None, metavar="TARGET",
        help="Request-level distributed tracing (HVDTPU_TRACE): each "
             "rank dumps its span ring to a file derived from TARGET "
             "(directory, {rank} template, or plain path getting a "
             "rank tag).  At job end the launcher merges every rank's "
             "spans (its own ingest-side spans included) into a "
             "per-request Chrome-trace waterfall plus a ttft/tpot "
             "latency-decomposition report.",
    )
    obs_group.add_argument(
        "--trace-sample-rate", type=float, action=_StoreOverrideAction,
        dest="trace_sample_rate", default=None,
        help="Fraction of requests traced (HVDTPU_TRACE_SAMPLE_RATE, "
             "default 1.0).  The verdict is a pure function of the "
             "request id, so every rank samples the identical set.",
    )
    obs_group.add_argument(
        "--health", choices=("on", "off"), action=_StoreOverrideAction,
        dest="health", default=None,
        help="Training-health plane (HVDTPU_HEALTH, default off): "
             "in-graph per-step numerics bundle (loss, per-bucket grad "
             "norms, update/param ratio, nonfinite counts) + EWMA "
             "anomaly alerts, and the cross-rank divergence sentinel. "
             "Off leaves the compiled training step byte-identical.",
    )
    obs_group.add_argument(
        "--health-check-steps", type=int, action=_StoreOverrideAction,
        dest="health_check_steps", default=None,
        help="Divergence-sentinel cadence (HVDTPU_HEALTH_CHECK_STEPS, "
             "default 100): every N steps each rank allgathers a tiny "
             "bitwise digest of params/optimizer state/PRNG key and "
             "all ranks compare — the runtime check of the bitwise-"
             "replication invariant.",
    )
    obs_group.add_argument(
        "--divergence-action", choices=("warn", "dump", "halt"),
        action=_StoreOverrideAction, dest="divergence_action",
        default=None,
        help="What a confirmed cross-rank divergence does "
             "(HVDTPU_DIVERGENCE_ACTION, default warn): warn logs and "
             "alerts; dump additionally flushes the flight recorder "
             "and metrics immediately; halt raises on every rank — "
             "stop before the next checkpoint poisons every future "
             "restart.",
    )

    stall = parser.add_argument_group("stall check")
    stall.add_argument(
        "--no-stall-check", action=_StoreTrueOverrideAction,
        dest="no_stall_check", default=None,
    )
    stall.add_argument(
        "--stall-check-warning-time-seconds", type=int,
        action=_StoreOverrideAction,
        dest="stall_check_warning_time_seconds", default=None,
    )
    stall.add_argument(
        "--stall-check-shutdown-time-seconds", type=int,
        action=_StoreOverrideAction,
        dest="stall_check_shutdown_time_seconds", default=None,
    )

    autotune = parser.add_argument_group("autotune")
    autotune.add_argument(
        "--autotune", action=_StoreTrueOverrideAction, dest="autotune",
        default=None,
    )
    autotune.add_argument(
        "--autotune-log-file", action=_StoreOverrideAction,
        dest="autotune_log_file", default=None,
    )
    autotune.add_argument(
        "--autotune-warmup-samples", type=int, action=_StoreOverrideAction,
        dest="autotune_warmup_samples", default=None,
        help="score samples discarded while pipelines warm up",
    )
    autotune.add_argument(
        "--autotune-steps-per-sample", type=int, action=_StoreOverrideAction,
        dest="autotune_steps_per_sample", default=None,
        help="negotiation cycles per score sample",
    )
    autotune.add_argument(
        "--autotune-bayes-opt-max-samples", type=int,
        action=_StoreOverrideAction,
        dest="autotune_bayes_opt_max_samples", default=None,
        help="Bayesian-optimization samples per categorical configuration",
    )
    autotune.add_argument(
        "--autotune-gaussian-process-noise", type=float,
        action=_StoreOverrideAction,
        dest="autotune_gaussian_process_noise", default=None,
        help="GP observation-noise prior for the score surface",
    )
    autotune.add_argument(
        "--autotune-drift-threshold", type=float,
        action=_StoreOverrideAction,
        dest="autotune_drift_threshold", default=None,
        help="fractional throughput regression below the held peak that "
             "counts as drift (default 0.2)",
    )
    autotune.add_argument(
        "--autotune-drift-samples", type=int,
        action=_StoreOverrideAction,
        dest="autotune_drift_samples", default=None,
        help="consecutive drifting score windows before the converged "
             "tuner re-opens its search (default 3)",
    )

    logging_group = parser.add_argument_group("logging")
    logging_group.add_argument(
        "--log-level", action=_StoreOverrideAction, dest="log_level",
        default=None,
        choices=["trace", "debug", "info", "warning", "error", "fatal"],
    )

    parser.add_argument(
        "command", nargs=argparse.REMAINDER,
        help="Command to run on every slot (e.g. python train.py).",
    )
    args = parser.parse_args(argv)
    config_parser.apply_config_file(args, getattr(args, "config_file", None))
    return args


def check_build() -> str:
    """Capability report (reference horovodrun --check-build)."""
    import jax

    from .. import __version__

    lines = [
        f"horovod_tpu v{__version__}:",
        "",
        "Available backends:",
        f"    [X] XLA collectives (jax {jax.__version__})",
        f"    [X] coordination service (jax.distributed)",
        "Available features:",
        "    [X] jit/SPMD collectives (psum/all_gather/ppermute over mesh)",
        "    [X] eager per-op engine (negotiation, fusion, join, timeline)",
        "    [X] hierarchical allreduce (cross x local mesh)",
        "    [X] multi-slice two-fabric collectives (ICI scatter + DCN "
        "exchange, --num-slices / --dcn-compression)",
        "    [X] adasum",
        "    [X] serving plane (continuous-batching inference, --serve)",
    ]
    return "\n".join(lines)


def _pick_free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _resolve_host_slots(
    hosts: Optional[str], hostfile: Optional[str], default: str
):
    """hosts/hostfile/default cascade shared by launch_job and
    discover_nics (reference hostfile/LSF resolution, runner.py:552-627)."""
    if hostfile:
        return parse_hostfile(hostfile)
    if hosts:
        return parse_hosts(hosts)
    return parse_hosts(default)


def _read_port_line(p, deadline: float) -> Optional[int]:
    """Read the HVDTPU_TASK_PORT= line with a real deadline — readline has
    no timeout, so it runs on a reaper thread joined with the remaining
    time (a hung ssh channel must not wedge discovery)."""
    import threading  # noqa: PLC0415
    import time  # noqa: PLC0415

    result: List[Optional[int]] = [None]

    def reader():
        while True:
            line = p.stdout.readline()
            if not line:
                return
            if line.startswith(b"HVDTPU_TASK_PORT="):
                result[0] = int(line.strip().split(b"=", 1)[1])
                return

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    t.join(max(deadline - time.time(), 0.1))
    return result[0]


def discover_nics(
    hosts: Optional[str] = None,
    hostfile: Optional[str] = None,
    *,
    ssh_port: Optional[int] = None,
    timeout: float = 30.0,
) -> List[str]:
    """Start a task server on every job host, ring-probe reachability,
    return the interfaces usable by all (reference _run's NIC discovery,
    runner.py:552-627 + driver/driver_service.py:128-197)."""
    import subprocess  # noqa: PLC0415
    import time  # noqa: PLC0415

    from . import driver_service as ds  # noqa: PLC0415
    from .exec import make_ssh_command  # noqa: PLC0415

    host_slots = _resolve_host_slots(hosts, hostfile, "localhost:1")
    hostnames = [hs.hostname for hs in host_slots]

    key = ds.make_secret()
    server_cmd = [sys.executable, "-m", "horovod_tpu.run.driver_service"]
    procs: List[subprocess.Popen] = []
    tasks: List[tuple] = []
    try:
        for host in hostnames:
            # Binary pipes throughout (like exec.py's ProcessSet.launch):
            # make_ssh_command returns bytes stdin_data, and mixing
            # text=True with bytes writes raises TypeError.
            if is_local_host(host):
                p = subprocess.Popen(
                    server_cmd,
                    env={**os.environ, "HVDTPU_NIC_SECRET": key},
                    stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                )
            else:
                # The secret travels over the ssh channel's stdin
                # (SENSITIVE_ENV), never on the command line.
                cmd, stdin_data = make_ssh_command(
                    host, server_cmd, {"HVDTPU_NIC_SECRET": key}, ssh_port
                )
                p = subprocess.Popen(
                    cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                )
                if stdin_data:
                    p.stdin.write(stdin_data)
                    p.stdin.flush()
            procs.append(p)
        deadline = time.time() + timeout
        for host, p in zip(hostnames, procs):
            port = _read_port_line(p, deadline)
            if port is None:
                raise RuntimeError(f"task server on {host} did not report a port")
            tasks.append((host if not is_local_host(host) else "127.0.0.1",
                          port))
        return ds.discover_common_interfaces(tasks, key)
    finally:
        for p in procs:
            try:
                p.stdin.close()  # task server exits on stdin EOF
            except OSError:
                pass
            try:
                p.terminate()
            except OSError:
                pass
        for p in procs:
            # Reap: without wait() a long-lived caller of the Python API
            # accumulates zombies (the CLI path exits so it never noticed).
            try:
                p.wait(timeout=2.0)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
            except OSError:
                pass


def build_slot_env(
    slot: SlotInfo,
    coordinator: str,
    base_env: Dict[str, str],
) -> Dict[str, str]:
    """Per-slot environment (reference gloo_run.py:143-165,257-269:
    HOROVOD_RANK/SIZE/..., rendezvous addr/port, controller selection)."""
    env = dict(base_env)
    env.update(
        {
            "HVDTPU_RANK": str(slot.rank),
            "HVDTPU_SIZE": str(slot.size),
            "HVDTPU_LOCAL_RANK": str(slot.local_rank),
            "HVDTPU_LOCAL_SIZE": str(slot.local_size),
            "HVDTPU_CROSS_RANK": str(slot.cross_rank),
            "HVDTPU_CROSS_SIZE": str(slot.cross_size),
            "HVDTPU_COORDINATOR": coordinator,
        }
    )
    return env


def _maybe_start_live_plane(
    base_env: Dict[str, str],
    np: int,
    *,
    kv_server=None,
    kv_addr: Optional[str] = None,
    live_stats_secs: Optional[float] = None,
    live_port: Optional[int] = None,
    live_history: Optional[str] = None,
    bind_all: bool = False,
    announce_host: Optional[str] = None,
):
    """Start the launcher half of the live telemetry plane when
    ``--live-stats-secs`` (or the env) enables it; returns
    ``(LivePlane, owned_server)`` or ``(None, None)``.

    The interval resolves from ``base_env`` — the SAME source the
    spawned workers read — never from the launcher's own os.environ: an
    env-dict override must arm both halves or neither (workers
    streaming into a store nobody drains would grow launcher memory
    unboundedly).

    MUTATES ``base_env`` — the KV endpoint, interval and per-job secret
    must be in place before any worker spawns.  Non-elastic jobs get a
    dedicated KV server here (their only launcher-side socket); elastic
    jobs pass their existing rendezvous server + already-routable
    address, and /metrics shares its port.  ``announce_host``: the
    launcher address remote scrapers (and remote workers) should dial;
    default loopback for all-local jobs."""
    try:
        interval = (
            float(live_stats_secs)
            if live_stats_secs is not None
            else float(base_env.get(envmod.LIVE_STATS) or 0.0)
        )
    except ValueError:
        raise ValueError(
            f"{envmod.LIVE_STATS} must be a number of seconds; got "
            f"{base_env.get(envmod.LIVE_STATS)!r}"
        )
    if interval <= 0:
        return None, None
    from ..obs.live import LivePlane  # noqa: PLC0415
    from .rendezvous import KVStoreServer, SECRET_ENV  # noqa: PLC0415

    owned = None
    if kv_server is None:
        owned = kv_server = KVStoreServer(
            port=int(live_port or 0),
            secret=base_env.get(SECRET_ENV) or None,
            bind_all=bind_all,
        )
        kv_server.start()
    host = (announce_host
            or (kv_addr.rsplit(":", 1)[0] if kv_addr else None)
            or "127.0.0.1")
    base_env[SECRET_ENV] = kv_server.secret
    base_env[envmod.LIVE_KV] = kv_addr or f"{host}:{kv_server.port}"
    base_env[envmod.LIVE_STATS] = str(interval)
    plane = LivePlane(
        kv_server,
        interval=interval,
        history_path=live_history or "live_history.jsonl",
        expected_ranks=np,
        announce_host=host,
    )
    plane.start()
    return plane, owned


def _ensure_black_box(base_env: Dict[str, str]):
    """Every job gets a flight-recorder dump target before any rank
    spawns: the black box only pays off if it was armed BEFORE the
    crash.  A user-provided ``--flightrec-dump`` / env value is left
    alone; otherwise the launcher mints a temp dir it owns (removed
    after a clean run, kept — and named in the verdict — after a
    failed one).  Returns ``(dump_spec, launcher_owned)``.

    Also marks THIS process as a launcher: it inherits the job's dump
    env but must not dump its own (empty) artifacts under rank 0's
    filename — a launcher-process ring/metrics dump would clobber
    worker rank 0's evidence."""
    envmod.mark_launcher()
    raw = base_env.get(envmod.FLIGHTREC_DUMP)
    if raw:
        return raw, False
    import tempfile  # noqa: PLC0415

    d = tempfile.mkdtemp(prefix="hvdtpu_blackbox_")
    base_env[envmod.FLIGHTREC_DUMP] = d
    return d, True


def _finish_black_box(
    dump_spec: str,
    owned: bool,
    *,
    failed: bool,
    np: int,
    live_history: Optional[str] = None,
    timeline_path: Optional[str] = None,
) -> None:
    """Job-end half of the flight recorder: on abnormal end, correlate
    every rank's ring dump into ``postmortem.json`` and print the
    verdict; on a clean end, remove a launcher-owned temp dir (the
    clean path writes no post-mortem).  Best-effort throughout — a
    post-mortem failure must never mask the job's real error."""
    if not failed:
        if owned:
            import shutil  # noqa: PLC0415

            shutil.rmtree(dump_spec, ignore_errors=True)
        return
    try:
        from ..obs import postmortem  # noqa: PLC0415

        out_dir = (dump_spec if os.path.isdir(dump_spec)
                   else (os.path.dirname(dump_spec) or "."))
        report = postmortem.generate(
            dump_spec,
            expected_ranks=np,
            live_history=live_history,
            timeline_path=timeline_path,
            output=os.path.join(out_dir, "postmortem.json"),
        )
        if report is None:
            return
        print("\n== post-mortem ==")
        print(report["verdict"])
        if report.get("report_path"):
            print(f"postmortem report: {report['report_path']}")
        print(f"flight-recorder dumps: {dump_spec}")
    except Exception as exc:  # pragma: no cover - defensive
        LOG.warning("post-mortem failed: %s", exc)


def _stop_live_plane(plane, owned_server) -> None:
    """Tear down best-effort: a telemetry failure must never turn a
    finished job into an error."""
    if plane is None:
        return
    try:
        plane.stop()
    except Exception:  # pragma: no cover - defensive
        pass
    if owned_server is not None:
        try:
            owned_server.stop()
        except Exception:  # pragma: no cover - defensive
            pass


def launch_job(
    command: List[str],
    np: int,
    hosts: Optional[str] = None,
    hostfile: Optional[str] = None,
    *,
    env: Optional[Dict[str, str]] = None,
    ssh_port: Optional[int] = None,
    start_timeout: Optional[float] = None,
    job_timeout: Optional[float] = None,
    coordinator_port: Optional[int] = None,
    tag_output: bool = True,
    output_filename: Optional[str] = None,
    live_stats_secs: Optional[float] = None,
    live_port: Optional[int] = None,
    live_history: Optional[str] = None,
) -> Dict[int, int]:
    """Allocate slots, spawn workers, wait for completion (reference
    gloo_run.launch_gloo, gloo_run.py:237-304).

    ``start_timeout`` bounds world formation (exported as
    HVDTPU_START_TIMEOUT, enforced by each rank's jax.distributed init);
    ``job_timeout`` is a whole-job watchdog — unset means run forever.
    ``live_stats_secs`` (or ``HVDTPU_LIVE_STATS_SECS``) turns on the
    live telemetry plane: per-rank metric streaming into a launcher KV
    server, console digests, ``live_history.jsonl``, and a Prometheus
    ``GET /metrics`` scrape endpoint."""
    host_slots = _resolve_host_slots(hosts, hostfile, f"localhost:{np}")
    slots = allocate(host_slots, np)

    first_host = slots[0].hostname
    if is_local_host(first_host):
        coord_host = "127.0.0.1"
        port = coordinator_port or _pick_free_port()
    else:
        # The coordinator binds on the remote first host, where we cannot
        # probe; use the fixed (overridable) port.
        coord_host = first_host
        port = coordinator_port or DEFAULT_COORDINATOR_PORT
    coordinator = f"{coord_host}:{port}"

    base_env = dict(os.environ)
    if env:
        base_env.update(env)
    if start_timeout is not None:
        base_env["HVDTPU_START_TIMEOUT"] = str(int(start_timeout))

    if output_filename:
        os.makedirs(output_filename, exist_ok=True)

    # Live telemetry before any spawn: workers read the KV endpoint and
    # interval from their spawn env.  The dedicated server binds beyond
    # loopback only when some worker is remote, and both the worker env
    # and the announced scrape endpoint then carry the launcher's
    # routable address instead of loopback.
    all_local = all(is_local_host(s.hostname) for s in slots)
    live_announce = None
    if not all_local and (
        live_stats_secs or base_env.get(envmod.LIVE_STATS)
    ):
        from .allocate import routable_ip  # noqa: PLC0415

        probe = next(
            (s.hostname for s in slots if not is_local_host(s.hostname)),
            "127.0.0.1",
        )
        live_announce = routable_ip(probe)
    live_plane, live_server = _maybe_start_live_plane(
        base_env, np,
        live_stats_secs=live_stats_secs, live_port=live_port,
        live_history=live_history, bind_all=not all_local,
        announce_host=live_announce,
    )

    black_box, owns_black_box = _ensure_black_box(base_env)
    procs = ProcessSet()
    procs.install_signal_handlers()
    _clean_stale_obs_files(base_env)
    for slot in slots:
        slot_env = build_slot_env(slot, coordinator, base_env)
        _spawn_worker(
            procs, slot.rank, slot.hostname, command, slot_env, base_env,
            ssh_port=ssh_port, tag_output=tag_output,
            output_dir=output_filename, num_proc=np,
        )
    failed = True
    try:
        result = procs.wait(timeout=job_timeout)
        failed = False
        return result
    finally:
        # Failed jobs merge too — a partial trace of a dead job is the
        # most valuable trace there is.  The live plane drains its final
        # round (workers flush at exit) before the server goes away.
        _stop_live_plane(live_plane, live_server)
        merged = _merge_rank_timelines(base_env)
        _merge_rank_traces(base_env, np)
        # On abnormal end the dead ranks' flight recorders already
        # flushed (signal handlers ran during wait()'s terminate);
        # correlate them into postmortem.json and print the verdict.
        _finish_black_box(
            black_box, owns_black_box, failed=failed, np=np,
            live_history=(
                (live_history or "live_history.jsonl")
                if live_plane is not None else None
            ),
            timeline_path=merged,
        )


def _arm_launcher_trace_env(env: Dict[str, str]) -> None:
    """The launcher is a span producer too (ingest pump, client result
    fetches): flag-derived trace knobs must land in ITS os.environ, not
    just the workers' env dict, or ``--trace`` records no launcher-side
    spans at all — and a flag-given sample rate would diverge from the
    workers', violating the identical-verdict invariant obs/trace.py
    documents."""
    for var in (envmod.TRACE, envmod.TRACE_SAMPLE_RATE):
        if env.get(var):
            os.environ[var] = env[var]


def _clean_stale_obs_files(env: Dict[str, str]) -> None:
    """Remove LEFTOVER per-rank timeline/metrics files from a previous
    job pointed at the same paths — the end-of-job merge and summary
    glob everything matching, and a 2-rank run must not inherit phantom
    lanes/columns from an earlier 4-rank run.  The merged/summary
    outputs themselves never match the per-rank glob."""
    import glob as _glob  # noqa: PLC0415

    from ..obs import pathspec  # noqa: PLC0415

    for var, stem in ((envmod.TIMELINE, "trace"),
                      (envmod.METRICS_DUMP, "metrics"),
                      (envmod.FLIGHTREC_DUMP, "flightrec"),
                      (envmod.TRACE, "spans")):
        raw = env.get(var)
        if not raw:
            continue
        if var == envmod.TRACE and "{rank}" not in raw:
            # A previous run's merged waterfall/report — and the
            # launcher's own span file, whose ``launcher`` tag has no
            # digits for rank_of_path to anchor on — would read as
            # THIS run's; none of them survive the rank-tag loop
            # below, so remove them here.
            from ..obs import trace_merge  # noqa: PLC0415

            doomed = [pathspec.resolve(raw, "spans", "launcher",
                                       epoch="")]
            doomed += list(trace_merge.merged_output_paths(raw))
            for path in doomed:
                try:
                    os.remove(path)
                except OSError:
                    pass
        if var == envmod.FLIGHTREC_DUMP:
            # A previous crashed run's verdict would read as THIS
            # run's — it is ours by name, remove it from wherever
            # _finish_black_box would write it (the dir itself, or the
            # parent of a plain-path/template spec).  Ditto orphaned
            # atomic-write tmp files: a rank killed mid-dump dies
            # inside its signal handler and never unwinds to clean its
            # own tmp.
            out_dir = (raw if os.path.isdir(raw)
                       else (os.path.dirname(raw) or "."))
            try:
                os.remove(os.path.join(out_dir, "postmortem.json"))
            except OSError:
                pass
            for tmp in _glob.glob(
                os.path.join(out_dir, "flightrec.*.tmp.*")
            ):
                try:
                    os.remove(tmp)
                except OSError:
                    pass
        if "{rank}" in raw:
            # A user template has no rank/epoch token to anchor on —
            # its glob would match arbitrary sibling files, and deleting
            # those is worse than a phantom lane.  Template users own
            # their files.
            continue
        try:
            for path in _glob.glob(pathspec.glob_pattern(raw, stem)):
                # Belt and braces: only files that carry our rank tag —
                # never anything a user might have put next to them.
                if pathspec.rank_of_path(path) is not None:
                    os.remove(path)
        except OSError:
            pass


def _merge_rank_traces(env: Dict[str, str], np: int) -> Optional[dict]:
    """Flush the launcher's own spans (ingest pump, client result
    fetches — tagged ``launcher``) and merge every rank's span file
    into the per-request waterfall + latency-decomposition report
    (``--trace``).  Best-effort like the timeline merge: a trace
    failure must never turn a finished job into an error."""
    raw = env.get(envmod.TRACE)
    if not raw:
        return None
    try:
        from ..obs import trace as obs_trace  # noqa: PLC0415
        from ..obs import trace_merge  # noqa: PLC0415

        if obs_trace.get_buffer().recorded:
            # Explicit path: the dump target may live only in the
            # workers' env dict, not this process's os.environ.
            obs_trace.flush(obs_trace.resolve_dump_path(raw))
        out = trace_merge.merge_glob(raw, expected_ranks=np)
        if out is not None:
            doc = out["doc"]
            line = (f"[trace] waterfall {out['waterfall']} "
                    f"({out['events']} spans, "
                    f"{len(doc['requests'])} requests); "
                    f"report {out['report']}")
            if doc["missing_ranks"]:
                line += f"; MISSING ranks {doc['missing_ranks']}"
            print(line, flush=True)
        return out
    except Exception as exc:  # pragma: no cover - defensive
        LOG.warning("trace merge failed: %s", exc)
        return None


def _merge_rank_timelines(env: Dict[str, str]) -> Optional[str]:
    """Merge the job's per-rank Chrome traces (every rank records now;
    HVDTPU_TIMELINE names the template/dir) into one valid trace with a
    lane per rank.  Best-effort: remote ranks' files are not fetched,
    and a merge failure must never turn a finished job into an error."""
    raw = env.get(envmod.TIMELINE)
    if not raw:
        return None
    try:
        from ..obs import timeline_merge  # noqa: PLC0415

        merged = timeline_merge.merge_glob(raw)
        if merged:
            LOG.info("merged all-rank timeline -> %s", merged)
        return merged
    except Exception as exc:  # pragma: no cover - defensive
        LOG.warning("timeline merge failed: %s", exc)
        return None


def _spawn_worker(
    procs, rank: int, host: str, command: List[str],
    worker_env: Dict[str, str], local_env: Dict[str, str], *,
    ssh_port: Optional[int], tag_output: bool,
    output_dir: Optional[str], num_proc: int,
) -> None:
    """Shared local/ssh rank spawn for :func:`launch_job` and the
    elastic monitor.  Local ranks get ``worker_env`` directly; remote
    ranks go over ssh with env inlined (reference gloo_run
    get_remote_command) — only the HVDTPU_/JAX_/XLA_/TPU_ families
    travel, a full env copy would break the remote shell.  ``local_env``
    is what the local ssh client process itself runs under."""
    if is_local_host(host):
        procs.launch(rank, command, worker_env, tag_output=tag_output,
                     output_dir=output_dir, num_proc=num_proc)
        return
    travel = {
        k: v for k, v in worker_env.items()
        if k.startswith(("HVDTPU_", "JAX_", "XLA_", "TPU_"))
    }
    ssh_cmd, stdin_data = make_ssh_command(host, command, travel, ssh_port)
    procs.launch(rank, ssh_cmd, local_env, tag_output=tag_output,
                 stdin_data=stdin_data, output_dir=output_dir,
                 num_proc=num_proc)


class ElasticJobResult:
    """What an elastic run leaves behind: per-rank exit codes of the
    FINAL incarnation of each rank, the last epoch, the world (every
    rank that completed and delivered a result), and the recovery
    trace — a deterministic event list (no timestamps) so two runs with
    the same fault spec compare equal."""

    def __init__(self):
        self.exit_codes: Dict[int, int] = {}
        self.epoch = 0
        self.world: List[int] = []
        self.trace: List[tuple] = []

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"ElasticJobResult(epoch={self.epoch}, "
                f"world={self.world}, trace={self.trace})")


def launch_elastic_job(
    command: List[str],
    np: int,
    hosts: Optional[str] = None,
    hostfile: Optional[str] = None,
    *,
    env: Optional[Dict[str, str]] = None,
    ssh_port: Optional[int] = None,
    min_workers: Optional[int] = None,
    max_workers: Optional[int] = None,
    autoscale: Optional[dict] = None,
    max_retries: int = 3,
    heartbeat_timeout: float = 60.0,
    progress_timeout: float = 300.0,
    progress_grace: float = 0.0,
    blacklist_cooldown: float = 10.0,
    dump_grace_secs: float = 5.0,
    job_timeout: Optional[float] = None,
    kv_server=None,
    tag_output: bool = True,
    output_filename: Optional[str] = None,
    live_stats_secs: Optional[float] = None,
    live_history: Optional[str] = None,
    serve_ingest: bool = False,
    serve_frontends: int = 1,
    front_door=None,
) -> ElasticJobResult:
    """Elastic counterpart of :func:`launch_job`: per-rank failure
    detection (exit code + KV heartbeat + collective-path progress
    beat), host blacklisting with exponential-backoff re-admission, and
    bounded respawn of failed ranks into a re-minted rendezvous epoch.

    Worker contract: each rank runs ``command`` with the
    ``HVDTPU_ELASTIC_*`` env (see elastic/context.py) and coordinates
    through the launcher's KV store; jax.distributed is deliberately NOT
    bootstrapped (its membership cannot survive a rank death).

    ``min_workers``: once the respawn budget is spent, the job may
    continue with a SHRUNKEN world as long as at least this many ranks
    survive (default np — any unrecoverable failure aborts); under
    autoscale it is also the envelope floor.
    ``max_workers``: the envelope ceiling (default np) — ranks
    ``np..max_workers-1`` are standby slots a deliberate grow admits;
    the host list must carry slots for all of them.
    ``autoscale``: :class:`~..serve.autoscale.AutoscaleConfig` override
    dict; when set, the launcher reads the live plane's merged
    ``serve.queue_depth``/``serve.ttft_ms`` gauges and executes the
    policy's grow/shrink decisions through the SAME epoch-mint +
    spawn/drop path failures use (a scale event is a survived failure
    as far as the workers can tell).  Live stats are forced on (0.5s)
    when not otherwise armed — the gauges are the controller's only
    input.
    ``max_retries`` bounds total respawns across the job.
    ``progress_timeout`` / ``progress_grace``: the workload-aware
    progress-beat policy (obs/progress.py ProgressPolicy).  Worker beats
    piggyback the collectives-completed counter and phase; a rank whose
    beat thread lives but whose counter is frozen in steady-state for
    ``progress_timeout`` seconds has a deadlocked training thread and is
    killed/respawned directly — before its peers burn their
    collective-timeout retry budget discovering it.  ``progress_grace``
    is the same window for init/compile phases (0 = never kill there: a
    long XLA compile is legitimate).
    ``dump_grace_secs``: when the monitor declares a rank dead, it is
    sent SIGUSR1+SIGTERM first — the flight recorder's handlers flush
    its black box — and SIGKILLed only after this window (0 restores
    the old immediate SIGKILL, losing the hung rank's evidence).
    ``kv_server``: a caller-started rendezvous server already seeded
    with job payloads (the python API path); created/stopped internally
    when None.
    """
    import pickle  # noqa: PLC0415
    import time  # noqa: PLC0415

    from .rendezvous import (  # noqa: PLC0415
        KVStoreClient, KVStoreServer, SECRET_ENV,
    )

    if min_workers is None:
        min_workers = np
    if not 1 <= min_workers <= np:
        raise ValueError(
            f"min_workers must be in [1, np]; got {min_workers} for np={np}"
        )
    capacity = np if max_workers is None else int(max_workers)
    if capacity < np:
        raise ValueError(
            f"max_workers must be >= np; got {capacity} for np={np}"
        )

    # Slots are allocated for the whole ENVELOPE: standby ranks
    # np..capacity-1 need a host the moment a grow admits them, and a
    # host list that cannot carry them must fail here, pre-spawn.
    host_slots = _resolve_host_slots(hosts, hostfile,
                                     f"localhost:{capacity}")
    slots = allocate(host_slots, capacity)
    host_of: Dict[int, str] = {s.rank: s.hostname for s in slots}
    host_order: List[str] = []
    for hs in host_slots:
        if hs.hostname not in host_order:
            host_order.append(hs.hostname)
    all_local = all(is_local_host(h) for h in host_order)

    owns_server = kv_server is None
    if owns_server:
        kv_server = KVStoreServer(bind_all=not all_local)
        kv_server.start()
    port = kv_server.port
    kv = KVStoreClient(f"127.0.0.1:{port}", kv_server.secret)
    if all_local:
        kv_addr = f"127.0.0.1:{port}"
    else:
        from .allocate import routable_ip  # noqa: PLC0415

        probe = next((h for h in host_order if not is_local_host(h)),
                     "127.0.0.1")
        kv_addr = f"{routable_ip(probe)}:{port}"

    base_env = dict(os.environ)
    if env:
        base_env.update(env)
    base_env[SECRET_ENV] = kv_server.secret
    base_env["HVDTPU_ELASTIC_KV"] = kv_addr
    if output_filename:
        os.makedirs(output_filename, exist_ok=True)

    # Live telemetry rides the rendezvous store: snapshots travel the
    # same signed PUT path as heartbeats, and /metrics shares the port.
    # The autoscale controller's ONLY input is this plane's merged
    # gauges, so autoscale forces it on when nothing else armed it.
    if autoscale is not None and live_stats_secs is None \
            and not base_env.get(envmod.LIVE_STATS):
        live_stats_secs = 0.5
    live_plane, _ = _maybe_start_live_plane(
        base_env, np, kv_server=kv_server, kv_addr=kv_addr,
        live_stats_secs=live_stats_secs, live_history=live_history,
    )

    # Serving mode (--serve): the request front end rides the SAME
    # rendezvous store — the launcher-resident FRONT DOOR (F sharded
    # ingest pumps + a heartbeat supervisor, serve/frontend.py) totally
    # orders client submissions into the per-shard durable logs the
    # serving leaders drain.  ``front_door``: a caller-constructed
    # FrontDoor already wired to this store (ServeJob); the monitor
    # adopts it for takeover handling without owning its lifecycle.
    ingest_pump = front_door
    owns_front_door = False
    if serve_ingest and ingest_pump is None:
        from ..serve.frontend import FrontDoor  # noqa: PLC0415

        ingest_pump = FrontDoor(kv_server,
                                frontends=max(int(serve_frontends), 1))
        ingest_pump.start()
        owns_front_door = True
        print(
            f"[serve] ingest endpoint http://{kv_addr} "
            f"({ingest_pump.frontends} frontend shard(s), signed KV "
            f"protocol, scope serve/ — horovod_tpu.serve.ServeClient)",
            flush=True,
        )
    if ingest_pump is not None and live_plane is not None:
        # serve.frontend.* series are launcher-local (shard ownership,
        # per-shard ingest counters, takeovers): expose them on the
        # same /metrics page the worker gauges land on.
        live_plane.add_render(ingest_pump.prometheus)

    from ..obs import get_registry  # noqa: PLC0415
    from ..obs.progress import ProgressPolicy  # noqa: PLC0415

    metrics = get_registry()
    result = ElasticJobResult()
    trace = result.trace
    blacklist = HostBlacklist(cooldown_base=blacklist_cooldown)

    # Deliberate-resize controller (serving autoscale): the pure policy
    # + metrics glue live in serve/autoscale.py; THIS loop executes its
    # decisions because only it owns epoch minting and process spawn.
    scaler = None
    if autoscale is not None:
        from ..serve.autoscale import (  # noqa: PLC0415
            AutoscaleConfig, AutoscaleController,
        )
        from ..testing.faults import maybe_fail  # noqa: PLC0415

        scaler = AutoscaleController(
            AutoscaleConfig(
                min_workers=min_workers, max_workers=capacity,
                **{k: v for k, v in autoscale.items() if v is not None},
            ),
            registry=metrics,
        )
        if live_plane is not None:
            # autoscale.* series ride the same /metrics exposition the
            # worker gauges do (they live in the launcher's registry,
            # which worker snapshots never carry).
            live_plane.add_render(scaler.prometheus)
    # Slice-aware blacklisting (multislice jobs): a failure is recorded
    # against its rank's slice too, and a quorum of dead hosts within
    # one slice blacklists the whole slice — same contiguous-block
    # rank->slice rule as basics.slice_of_rank.
    try:
        num_slices = int(base_env.get(envmod.NUM_SLICES) or 0)
    except ValueError:
        num_slices = 0
    if num_slices <= 0:
        try:
            ssize = int(base_env.get(envmod.SLICE_SIZE) or 0)
        except ValueError:
            ssize = 0
        num_slices = np // ssize if ssize > 0 and np % ssize == 0 else 0
    slice_of: Dict[int, int] = {}
    if num_slices > 1 and np % num_slices == 0:
        from .allocate import slice_assignment  # noqa: PLC0415

        slice_of = dict(enumerate(slice_assignment(np, num_slices)))

    def record_rank_failure(rank: int, host: str) -> int:
        sid = slice_of.get(rank)
        if sid is None:
            return blacklist.record_failure(host)
        members = sorted(
            {host_of[r] for r, s in slice_of.items()
             if s == sid and r in host_of}
        )
        return blacklist.record_failure(
            host, slice_id=sid, slice_hosts=members
        )
    progress_policy = ProgressPolicy(progress_timeout, progress_grace)
    procs = ProcessSet()
    procs.install_signal_handlers()

    def mint_epoch(epoch: int, world: List[int]) -> None:
        # World before epoch: a worker that sees the new epoch number
        # must find its membership already published.
        kv.put("elastic", f"world_{epoch}", pickle.dumps(sorted(world)))
        kv.put("elastic", "epoch", str(epoch).encode())
        metrics.counter("launcher.epochs_minted").inc()

    # rank -> epoch its CURRENT incarnation was spawned into; beats
    # stamped with an older epoch are a dead predecessor's leftovers.
    spawn_epoch: Dict[int, int] = {}

    def spawn(rank: int, host: str, epoch: int) -> None:
        spawn_epoch[rank] = epoch
        worker_env = dict(base_env)
        worker_env.update({
            "HVDTPU_ELASTIC_RANK": str(rank),
            "HVDTPU_ELASTIC_EPOCH": str(epoch),
            "HVDTPU_ELASTIC_NP": str(np),
        })
        # Epoch-qualified capture dir: a respawn must not truncate the
        # dead incarnation's logs — they are the primary evidence of
        # why it died.
        out_dir = (os.path.join(output_filename, f"epoch.{epoch}")
                   if output_filename else None)
        _spawn_worker(
            procs, rank, host, command, worker_env, base_env,
            ssh_port=ssh_port, tag_output=tag_output,
            output_dir=out_dir, num_proc=np,
        )

    def posted_error(rank: int, up_to_epoch: int) -> Optional[str]:
        """A worker that RAISED (vs crashed) posted its traceback under
        an epoch-qualified key before exiting; that diagnostic both
        aborts the job and wins over the generic exit-code error."""
        import cloudpickle  # noqa: PLC0415

        for e in range(up_to_epoch + 1):
            raw = kv.get("elastic", f"error_{rank}_{e}")
            if raw is not None:
                return cloudpickle.loads(raw)
        return None

    epoch = 0
    world = list(range(np))
    finished: Dict[int, int] = {}
    # Ranks a deliberate scale-down released (they exit 0 and land in
    # `finished`, but the job is NOT draining — the distinction keeps
    # autoscale alive after its own shrinks).
    released: set = set()
    hb_seen: Dict[int, tuple] = {}
    hb_next_scan = 0.0
    scale_next = 0.0
    respawns_used = 0
    deadline = time.monotonic() + job_timeout if job_timeout else None
    black_box, owns_black_box = _ensure_black_box(base_env)
    job_failed = False

    try:
        _clean_stale_obs_files(base_env)
        mint_epoch(epoch, world)
        for rank in world:
            spawn(rank, host_of[rank], epoch)
            trace.append(("spawn", rank, epoch, host_of[rank]))

        while True:
            for rank, rc in procs.poll_exits():
                if rc == 0:
                    finished[rank] = 0
                    continue
                if rank in released:
                    # A released rank that died on its way out (e.g.
                    # terminated for a stale heartbeat after the drop)
                    # owes the job nothing: it must neither be
                    # respawned nor counted as a host failure.
                    trace.append(("released_exit", rank, rc, epoch))
                    continue
                tb = posted_error(rank, epoch)
                if tb is not None:
                    raise RuntimeError(
                        f"elastic rank {rank} raised:\n{tb}"
                    )
                host = host_of[rank]
                count = record_rank_failure(rank, host)
                metrics.counter("launcher.rank_failures").inc()
                metrics.counter("launcher.blacklists").inc()
                trace.append(("failure", rank, rc, epoch))
                trace.append(("blacklist", host, count))
                LOG.warning(
                    "elastic: rank %d on %s exited %d (failure %d on "
                    "this host)", rank, host, rc, count,
                )
                alive = procs.alive_ranks()
                # Released ranks exited 0 but did NOT finish the job's
                # work — counting them as contributors here would let a
                # crash of the last real worker "complete" the job on a
                # released rank's summary, silently dropping in-flight
                # requests.
                contributed = set(finished) - released
                if not alive and contributed:
                    # Every real peer already exited 0: a replacement
                    # would have no survivor to sync state from and
                    # would retrain alone from initial values.  The
                    # committed result is already replicated across the
                    # finished ranks — finish with them instead of
                    # respawning.
                    if len(contributed) < min_workers:
                        raise RuntimeError(
                            f"elastic job lost rank {rank} after only "
                            f"{len(contributed)} workers finished "
                            f"(< min_workers={min_workers})"
                        )
                    epoch += 1
                    world = sorted(contributed)
                    mint_epoch(epoch, world)
                    trace.append(("shrink", epoch, tuple(world)))
                    LOG.warning(
                        "elastic: rank %d died after all peers finished; "
                        "completing with %d/%d workers", rank,
                        len(world), np,
                    )
                    continue
                if respawns_used < max_retries:
                    respawns_used += 1
                    new_host = blacklist.select(host_order, prefer=host)
                    host_of[rank] = new_host
                    epoch += 1
                    world = sorted(set(alive) | {rank})
                    mint_epoch(epoch, world)
                    # The dead incarnation's last observed beat must not
                    # count against the successor's first-beat window.
                    hb_seen.pop(rank, None)
                    progress_policy.forget(rank)
                    spawn(rank, new_host, epoch)
                    metrics.counter("launcher.respawns").inc()
                    trace.append(("respawn", rank, epoch, new_host))
                elif len(set(alive) | contributed) >= min_workers:
                    # Budget spent: continue with the shrunken world
                    # (the dead rank's slot is dropped for good).
                    # min_workers counts CONTRIBUTING ranks — alive ones
                    # plus those that already delivered a result (NOT
                    # released ones) — so an early finisher is not held
                    # against the job.
                    epoch += 1
                    world = sorted(alive)
                    mint_epoch(epoch, world)
                    trace.append(("shrink", epoch, tuple(world)))
                    LOG.warning(
                        "elastic: respawn budget spent; continuing with "
                        "%d/%d workers", len(world), np,
                    )
                else:
                    raise RuntimeError(
                        f"elastic job lost rank {rank} with the respawn "
                        f"budget spent and only "
                        f"{len(set(alive) | contributed)} workers "
                        f"contributing (< min_workers={min_workers})"
                    )
            hb_enabled = bool(heartbeat_timeout and heartbeat_timeout > 0)
            if ((hb_enabled or progress_policy.enabled)
                    and time.monotonic() >= hb_next_scan):
                # Beats only change once per worker heartbeat period, so
                # scanning them on every 50 ms monitor tick is np wasted
                # KV round-trips; exits stay on the fast tick.  The scan
                # runs for EITHER rule: disabling the process-heartbeat
                # rule must not silently disable deadlock detection.
                hb_next_scan = time.monotonic() + min(
                    1.0,
                    heartbeat_timeout / 4 if hb_enabled else 1.0,
                )
                # Staleness is judged entirely on the launcher's clock —
                # the window starts when the launcher OBSERVES a new beat
                # value, never by comparing against the worker's wall
                # clock (cross-host skew > timeout would otherwise kill
                # healthy remote workers in a loop).
                now = time.monotonic()
                from ..obs.progress import beat_epoch  # noqa: PLC0415

                for rank in procs.alive_ranks():
                    raw = kv.get("elastic", f"hb_{rank}")
                    if raw is None:
                        continue  # not beating yet (still importing)
                    be = beat_epoch(raw)
                    if be is not None and be < spawn_epoch.get(rank, 0):
                        # A dead incarnation's leftover beat: the
                        # respawned successor has not beaten yet.
                        # Judging it would kill a healthy successor
                        # that is merely slow to import.
                        continue
                    # Rule 1 — process liveness: the beat body changing
                    # at all proves the beat thread (and process) lives.
                    seen = hb_seen.get(rank)
                    if seen is None or seen[0] != raw:
                        hb_seen[rank] = (raw, now)
                    elif hb_enabled and now - seen[1] > heartbeat_timeout:
                        trace.append(("heartbeat_lost", rank, epoch))
                        metrics.counter("launcher.heartbeat_lost").inc()
                        LOG.warning(
                            "elastic: rank %d heartbeat stale > %.0fs; "
                            "declaring it dead", rank, heartbeat_timeout,
                        )
                        # Restart the window so the successor incarnation
                        # gets a full timeout before its first beat lands.
                        hb_seen.pop(rank, None)
                        progress_policy.forget(rank)
                        # Dump-then-kill: SIGUSR1/SIGTERM first so the
                        # declared-dead rank's flight recorder survives
                        # its own execution; SIGKILL after the grace.
                        procs.terminate_rank(rank, grace=dump_grace_secs)
                        continue
                    # Rule 2 — training-thread liveness: the beat
                    # piggybacks the collective-path progress counter;
                    # a live beat with a frozen counter in steady state
                    # is a deadlocked training thread.  Kill it NOW,
                    # directly, instead of letting every peer discover
                    # it through collective timeouts (retry-budget burn
                    # — the ROADMAP open item this closes).
                    reason = progress_policy.observe(rank, raw, now)
                    if reason is not None:
                        trace.append(("progress_lost", rank, epoch))
                        metrics.counter("launcher.progress_lost").inc()
                        LOG.warning(
                            "elastic: rank %d training thread declared "
                            "dead: %s", rank, reason,
                        )
                        hb_seen.pop(rank, None)
                        progress_policy.forget(rank)
                        procs.terminate_rank(rank, grace=dump_grace_secs)
            if (scaler is not None
                    and live_plane is not None
                    and not (set(finished) - released)
                    and time.monotonic() >= scale_next):
                # Deliberate resize tick.  Guards: never while a real
                # drain is under way (a non-released rank finished),
                # and only against a STABLE world (every member alive —
                # a failure respawn in flight must win the epoch race,
                # not interleave with a resize).
                scale_next = time.monotonic() + 0.25
                if set(world) <= set(procs.alive_ranks()):
                    decision = scaler.tick(
                        time.monotonic(), live_plane.agg.merged(),
                        world,
                    )
                else:
                    decision = None
                if decision is not None and decision.direction == "up":
                    want = decision.target - len(world)
                    standby = [r for r in range(capacity)
                               if r not in world][:want]
                    admitted = []
                    skipped_blacklisted = False
                    for r in standby:
                        # A deliberate grow honors the same host
                        # blacklist the failure-respawn path does: a
                        # cooling-down host must not be handed a
                        # standby just to kill it and burn a respawn.
                        if not blacklist.is_admissible(host_of[r]):
                            trace.append(
                                ("scale_skip_blacklisted", r, epoch))
                            skipped_blacklisted = True
                            continue
                        # Chaos point: a standby host refusing
                        # admission (action=scale_fail) is the
                        # deterministic input the exponential-backoff
                        # policy is tested against.
                        if maybe_fail("scale_admit",
                                      rank=r) == "scale_fail":
                            trace.append(("scale_fail", r, epoch))
                            scaler.grow_failed(time.monotonic(), r)
                            continue
                        admitted.append(r)
                    if not admitted and skipped_blacklisted:
                        # Every standby is cooling down: back off like
                        # a refused admission instead of re-deciding
                        # every tick until a cooldown expires.
                        scaler.grow_failed(time.monotonic(), standby[0])
                    if admitted:
                        epoch += 1
                        for r in admitted:
                            # A previously released rank re-admitted:
                            # its old clean exit is not this
                            # incarnation's result.
                            finished.pop(r, None)
                            released.discard(r)
                            hb_seen.pop(r, None)
                            progress_policy.forget(r)
                        world = sorted(set(world) | set(admitted))
                        mint_epoch(epoch, world)
                        for r in admitted:
                            spawn(r, host_of[r], epoch)
                        trace.append(("scale_up", epoch,
                                      tuple(admitted)))
                        scaler.executed(decision, epoch, len(world))
                elif decision is not None \
                        and decision.direction == "down":
                    drop = len(world) - decision.target
                    victims = sorted(world)[-drop:]
                    released.update(victims)
                    epoch += 1
                    world = [r for r in world if r not in victims]
                    mint_epoch(epoch, world)
                    # The victims notice the epoch bump, find
                    # themselves outside the new world, and exit 0
                    # (RankDroppedError -> clean release); survivors
                    # replay in-flight work in the fresh epoch.
                    trace.append(("scale_down", epoch, tuple(victims)))
                    scaler.executed(decision, epoch, len(world))
            if ingest_pump is not None \
                    and not (set(finished) - released) \
                    and set(world) <= set(procs.alive_ranks()):
                # Frontend takeover -> epoch re-mint: a dead frontend's
                # shards were adopted by a survivor; re-forming the
                # serving world through EXACTLY the resize machinery
                # makes every group replay from the durable per-shard
                # logs — in-flight requests resume bitwise on course.
                # Same stability guards as a resize: the events stay
                # queued in the FrontDoor until the world is whole, so
                # a takeover racing a failure respawn is processed
                # after the respawn's epoch settles.
                takeovers = ingest_pump.poll_takeover()
                if takeovers:
                    epoch += 1
                    mint_epoch(epoch, world)
                    for ev in takeovers:
                        trace.append(("frontend_takeover", ev["fid"],
                                      ev["owner"], epoch))
                    LOG.warning(
                        "elastic: %d frontend takeover(s); re-minted "
                        "epoch %d for the serving world",
                        len(takeovers), epoch,
                    )
            if all(r in finished for r in world):
                result.exit_codes = dict(finished)
                result.epoch = epoch
                # Every rank that delivered a result — not just the last
                # rendezvous world, which drops ranks that finished
                # before a late respawn/shrink re-formed it.
                result.world = sorted(finished)
                return result
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"elastic job timed out after {job_timeout}s "
                    f"(finished={sorted(finished)}, world={world})"
                )
            time.sleep(0.05)
    except BaseException:
        job_failed = True
        # terminate() SIGTERMs the tree and waits up to its graceful
        # window — the survivors' flight recorders flush inside it, so
        # the post-mortem below reads complete rings.
        procs.terminate()
        raise
    finally:
        if ingest_pump is not None and owns_front_door:
            # A caller-passed front door (ServeJob) outlives this
            # launch — its owner stops it after collecting results.
            try:
                ingest_pump.stop()
            except Exception:  # pragma: no cover - defensive
                pass
        # Drain the final live round while the store is still up.
        _stop_live_plane(live_plane, None)
        if owns_server:
            kv_server.stop()
        # All-rank trace merge, dead incarnations included: the
        # streaming writer format keeps a killed rank's file loadable,
        # and its epoch-tagged lane is the story of why it died.
        merged = _merge_rank_timelines(base_env)
        _merge_rank_traces(base_env, np)
        _finish_black_box(
            black_box, owns_black_box, failed=job_failed, np=np,
            live_history=(
                (live_history or "live_history.jsonl")
                if live_plane is not None else None
            ),
            timeline_path=merged,
        )


def main(argv: Optional[List[str]] = None) -> int:
    args = parse_args(argv)
    if args.version:
        from .. import __version__

        print(__version__)
        return 0
    if args.check_build:
        print(check_build())
        return 0
    if args.discover_nics:
        try:
            for iface in discover_nics(
                hosts=args.hosts, hostfile=args.hostfile,
                ssh_port=args.ssh_port,
            ):
                print(iface)
            return 0
        except (RuntimeError, OSError, TimeoutError, ValueError) as exc:
            # ValueError covers forged/corrupt signed responses (_unpack).
            print(f"hvdrun: NIC discovery failed: {exc}", file=sys.stderr)
            return 1
    if not args.np:
        print("error: -np is required", file=sys.stderr)
        return 2
    command = list(args.command)
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        if getattr(args, "serve", False):
            # Serving mode ships its own worker; -np 2 --serve alone is
            # a complete invocation.
            command = [sys.executable, "-m", "horovod_tpu.serve"]
        else:
            print("error: no command given", file=sys.stderr)
            return 2
    if args.verbose and not args.log_level:
        args.log_level = "debug"
    if args.log_level:
        os.environ["HVDTPU_LOG_LEVEL"] = args.log_level
    if getattr(args, "num_slices", None):
        # Refuse a bad partition HERE, before spawning anything — every
        # worker would otherwise discover it independently and downgrade.
        from .allocate import slice_assignment  # noqa: PLC0415

        try:
            slice_assignment(args.np, args.num_slices)
        except ValueError as exc:
            print(f"error: {exc}", file=sys.stderr)
            return 2

    env: Dict[str, str] = {}
    config_parser.set_env_from_args(env, args)
    _arm_launcher_trace_env(env)
    summary_tmp = None
    if getattr(args, "stats_summary", False) and not (
        env.get(envmod.METRICS_DUMP) or os.environ.get(envmod.METRICS_DUMP)
    ):
        # --stats-summary without --metrics-dump: dump into a temp dir
        # that lives exactly as long as the summary needs it.
        import tempfile  # noqa: PLC0415

        summary_tmp = tempfile.mkdtemp(prefix="hvdtpu_metrics_")
        env[envmod.METRICS_DUMP] = summary_tmp
    try:
        LOG.info("launching %d processes: %s", args.np, " ".join(command))
        if getattr(args, "elastic", False) or getattr(args, "serve", False):
            autoscale = None
            if getattr(args, "serve_autoscale", False):
                autoscale = {
                    "scale_up_queue": getattr(args, "scale_up_queue",
                                              None),
                    "scale_down_idle_secs": getattr(
                        args, "scale_down_idle_secs", None),
                }
                cooldown = getattr(args, "scale_cooldown_secs", None)
                if cooldown is not None:
                    autoscale["up_cooldown_secs"] = cooldown
                    autoscale["down_cooldown_secs"] = cooldown
            launch_elastic_job(
                command,
                args.np,
                hosts=args.hosts,
                hostfile=args.hostfile,
                env=env,
                ssh_port=args.ssh_port,
                min_workers=getattr(args, "min_workers", None),
                max_workers=getattr(args, "max_workers", None),
                autoscale=autoscale,
                # `x or default` would coerce an EXPLICIT 0 (zero
                # respawns / zero cooldown) back to the default.
                max_retries=(
                    3 if getattr(args, "max_elastic_retries", None) is None
                    else args.max_elastic_retries
                ),
                blacklist_cooldown=(
                    10.0
                    if getattr(args, "blacklist_cooldown_secs", None) is None
                    else args.blacklist_cooldown_secs
                ),
                progress_timeout=(
                    300.0
                    if getattr(args, "progress_timeout_secs", None) is None
                    else args.progress_timeout_secs
                ),
                progress_grace=(
                    0.0
                    if getattr(args, "progress_grace_secs", None) is None
                    else args.progress_grace_secs
                ),
                dump_grace_secs=(
                    5.0
                    if getattr(args, "dump_grace_secs", None) is None
                    else args.dump_grace_secs
                ),
                output_filename=args.output_filename,
                live_stats_secs=getattr(args, "live_stats_secs", None),
                live_history=getattr(args, "live_history_file", None),
                serve_ingest=getattr(args, "serve", False),
                serve_frontends=int(
                    getattr(args, "serve_frontends", None)
                    or envmod.env_int(envmod.SERVE_FRONTENDS, 1)
                ),
            )
            return 0
        launch_job(
            command,
            args.np,
            hosts=args.hosts,
            hostfile=args.hostfile,
            env=env,
            ssh_port=args.ssh_port,
            start_timeout=args.start_timeout,
            coordinator_port=args.coordinator_port,
            output_filename=args.output_filename,
            live_stats_secs=getattr(args, "live_stats_secs", None),
            live_port=getattr(args, "live_port", None),
            live_history=getattr(args, "live_history_file", None),
        )
        return 0
    except (RuntimeError, ValueError, TimeoutError, OSError) as exc:
        print(f"hvdrun: {exc}", file=sys.stderr)
        return 1
    finally:
        # Failed jobs summarize too — the metrics of a dead run are the
        # ones someone is about to go digging for.
        try:
            _print_stats_summary(args, env)
        finally:
            if summary_tmp is not None:
                import shutil  # noqa: PLC0415

                shutil.rmtree(summary_tmp, ignore_errors=True)


def _print_stats_summary(args, env: Dict[str, str]) -> None:
    """End-of-job per-rank metrics table (--stats-summary)."""
    if not getattr(args, "stats_summary", False):
        return
    raw = env.get(envmod.METRICS_DUMP) or os.environ.get(envmod.METRICS_DUMP)
    if not raw:
        return
    from ..obs import summary as obs_summary  # noqa: PLC0415

    dumps = obs_summary.collect_dumps(raw)
    if not dumps:
        for warn in getattr(dumps, "warnings", []):
            print(f"hvdrun: --stats-summary: {warn}", file=sys.stderr)
        print("hvdrun: --stats-summary: no metrics dumps found "
              f"under {raw!r}", file=sys.stderr)
        return
    print("\n== per-rank metrics summary ==")
    print(obs_summary.format_summary_table(dumps))
    straggler = obs_summary.straggler_section(dumps)
    if straggler is not None:
        print("\n== straggler attribution ==")
        print(straggler)
    fabric = obs_summary.fabric_section(dumps)
    if fabric is not None:
        print("\n== cross-fabric bytes (dcn vs ici) ==")
        print(fabric)
    ckpt = obs_summary.ckpt_section(dumps)
    if ckpt is not None:
        print("\n== checkpoint / recovery ==")
        print(ckpt)
    serve = obs_summary.serve_section(dumps)
    if serve is not None:
        print("\n== serving plane ==")
        print(serve)
    slo = obs_summary.slo_section(dumps)
    if slo is not None:
        print("\n== tenant SLO / burn rate ==")
        print(slo)
    health = obs_summary.health_section(dumps)
    if health is not None:
        print("\n== training health ==")
        print(health)
    goodput = obs_summary.goodput_section(dumps)
    if goodput is not None:
        print("\n== goodput ledger ==")
        print(goodput)
    autoscale = obs_summary.autoscale_section(dumps)
    if autoscale is not None:
        print("\n== autoscale / weight hot-swap ==")
        print(autoscale)
    perf = obs_summary.perf_section(dumps)
    if perf is not None:
        print("\n== mfu / model flops ==")
        print(perf)
    mem = obs_summary.mem_section(dumps)
    if mem is not None:
        print("\n== device memory (memory plane) ==")
        print(mem)
    trend = obs_summary.trend_section(dumps)
    if trend is not None:
        print("\n== perf trend (BENCH trajectory) ==")
        print(trend)
