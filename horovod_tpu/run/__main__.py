"""``python -m horovod_tpu.run`` — the hvdrun CLI entry point
(reference: bin/horovodrun -> run_commandline, runner.py:713)."""

import sys

from .runner import main

if __name__ == "__main__":
    sys.exit(main())
