"""Worker-side stub for the run() API (reference: horovod/run/task_fn.py):
fetch the pickled function from the driver's KV store, execute, publish the
result under this rank."""

from __future__ import annotations

import os
import sys
import traceback

import cloudpickle

from ..testing.faults import maybe_fail
from .rendezvous import KVStoreClient

_SCOPE = "runfunc"


def main() -> int:
    # Black-box the run()-API worker too: signal/excepthook deaths
    # flush the flight-recorder ring and the metrics dump.
    from ..obs import flightrec

    flightrec.install_death_hooks()
    addr = os.environ["HVDTPU_RUN_FUNC_ADDR"]
    rank = int(os.environ.get("HVDTPU_RANK", "0"))
    # Chaos point "task_fn": kill (or fail) a worker before the user
    # function runs — the launcher-side failure-propagation surface
    # (HVDTPU_FAULT_SPEC="task_fn:rank=1").
    maybe_fail("task_fn", rank=rank)
    client = KVStoreClient(addr)
    blob = client.wait(_SCOPE, "func", timeout=60)
    func, args, kwargs = cloudpickle.loads(blob)
    try:
        result = func(*args, **kwargs)
        client.put(_SCOPE, f"result_{rank}", cloudpickle.dumps((True, result)))
        return 0
    except BaseException:
        client.put(
            _SCOPE,
            f"result_{rank}",
            cloudpickle.dumps((False, traceback.format_exc())),
        )
        return 1


if __name__ == "__main__":
    sys.exit(main())
