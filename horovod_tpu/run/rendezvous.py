"""Threaded HTTP key-value store for the launcher.

Reference: horovod/run/http/http_server.py — `RendezvousServer` (gloo ranks
publish/fetch addresses, per-scope completion tracking) and `KVStoreServer`
(pickled function + results for `horovod.run.run`).

The TPU build needs no address full-mesh (jax.distributed's coordinator
covers worker rendezvous), so this server's jobs are: distributing the
pickled function for the python `run()` API, collecting per-rank results,
and serving as a generic KV side-channel for integrations (the Spark-style
driver uses it too)."""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple
from urllib.error import URLError
from urllib.request import Request, urlopen


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # silence per-request stderr noise
        pass

    def _key(self) -> str:
        return self.path.lstrip("/")

    def do_PUT(self):
        length = int(self.headers.get("Content-Length", 0))
        value = self.rfile.read(length)
        with self.server.kv_lock:  # type: ignore[attr-defined]
            self.server.kv[self._key()] = value  # type: ignore[attr-defined]
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_GET(self):
        with self.server.kv_lock:  # type: ignore[attr-defined]
            value = self.server.kv.get(self._key())  # type: ignore[attr-defined]
        if value is None:
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(value)))
        self.end_headers()
        self.wfile.write(value)

    def do_DELETE(self):
        with self.server.kv_lock:  # type: ignore[attr-defined]
            self.server.kv.pop(self._key(), None)  # type: ignore[attr-defined]
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()


class KVStoreServer:
    """reference http_server.py `KVStoreServer` (threaded, start/stop)."""

    def __init__(self, port: int = 0):
        self._httpd = ThreadingHTTPServer(("0.0.0.0", port), _Handler)
        self._httpd.kv = {}  # type: ignore[attr-defined]
        self._httpd.kv_lock = threading.Lock()  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> int:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="hvdtpu_kvstore", daemon=True
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=5)
        self._httpd.server_close()


class KVStoreClient:
    """reference http/http_client.py: put/get against the KV server."""

    def __init__(self, addr: str):
        self._base = f"http://{addr}"

    def put(self, scope: str, key: str, value: bytes) -> None:
        req = Request(
            f"{self._base}/{scope}/{key}", data=value, method="PUT"
        )
        urlopen(req, timeout=30).read()

    def get(self, scope: str, key: str) -> Optional[bytes]:
        try:
            return urlopen(
                f"{self._base}/{scope}/{key}", timeout=30
            ).read()
        except URLError:
            return None
        except Exception:
            return None

    def wait(self, scope: str, key: str, timeout: float = 120.0) -> bytes:
        import time

        deadline = time.time() + timeout
        while time.time() < deadline:
            value = self.get(scope, key)
            if value is not None:
                return value
            time.sleep(0.1)
        raise TimeoutError(f"KV key {scope}/{key} not published in {timeout}s")
