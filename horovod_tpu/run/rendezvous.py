"""Threaded HTTP key-value store for the launcher.

Reference: horovod/run/http/http_server.py — `RendezvousServer` (gloo ranks
publish/fetch addresses, per-scope completion tracking) and `KVStoreServer`
(pickled function + results for `horovod.run.run`); payload integrity via
HMAC-signed messages (horovod/run/common/util/secret.py).

The TPU build needs no address full-mesh (jax.distributed's coordinator
covers worker rendezvous), so this server's jobs are: distributing the
pickled function for the python `run()` API, collecting per-rank results,
and serving as a generic KV side-channel for integrations.

Security model (same as the reference's): every payload is authenticated
with an HMAC over a per-job secret that travels to workers via the
launcher's env, because the values are pickles — an unauthenticated write
would be remote code execution.  Each MAC binds verb + key + body, so a
signature captured for one operation can never be replayed as another
(a PUT body can't mint a DELETE token, a value signed under one key
can't be served under another).  All-local jobs additionally bind
loopback only."""

from __future__ import annotations

import hashlib
import hmac
import os
import secrets as _secrets
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.error import HTTPError, URLError

SECRET_ENV = "HVDTPU_SECRET"
_MAC_HEADER = "X-HVDTPU-MAC"


def make_secret() -> str:
    """Per-job shared secret (reference secret.py make_secret_key)."""
    return _secrets.token_hex(32)


def _mac(secret: str, verb: str, key: str, body: bytes = b"") -> str:
    """Every MAC binds verb + key + body (newline-framed; neither verb
    nor key can contain a newline).  Without the verb/key domain
    separation, a signed PUT whose *user-chosen body* spelled out a
    delete token would hand an observer a valid DELETE for that key —
    cross-verb replay is exactly what the binding closes."""
    msg = f"{verb}\n{key}\n".encode() + body
    return hmac.new(secret.encode(), msg, hashlib.sha256).hexdigest()


def _delete_mac(secret: str, key: str) -> str:
    """DELETE has no body: its MAC covers verb + key alone."""
    return _mac(secret, "DELETE", key)


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # silence per-request stderr noise
        pass

    def _key(self) -> str:
        return self.path.lstrip("/")

    def do_PUT(self):
        length = int(self.headers.get("Content-Length", 0))
        value = self.rfile.read(length)
        mac = self.headers.get(_MAC_HEADER, "")
        if not hmac.compare_digest(
            mac, _mac(self.server.secret, "PUT", self._key(), value)  # type: ignore[attr-defined]
        ):
            self.send_response(403)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        with self.server.kv_lock:  # type: ignore[attr-defined]
            self.server.kv[self._key()] = value  # type: ignore[attr-defined]
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_GET(self):
        if self.path.rstrip("/") == "/healthz":
            # Read-only, unauthenticated liveness probe: operators (and
            # the CI gates) poll this instead of sleeping-and-hoping.
            # Carries only the key count — no values, no pickles, no
            # secret — so it shares /metrics' trust rationale.
            with self.server.kv_lock:  # type: ignore[attr-defined]
                n = len(self.server.kv)  # type: ignore[attr-defined]
            body = (
                '{"status": "ok", "keys": %d}' % n
            ).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if self.path.rstrip("/") == "/metrics":
            # Read-only, UNAUTHENTICATED Prometheus exposition of the
            # live telemetry plane (obs/live.py registers the renderer).
            # Deliberately outside the HMAC envelope: scrapers are
            # commodity tools that cannot sign, and the exposition
            # carries only metric values — never pickles, never the
            # secret.  Every mutating verb stays signed.
            render = getattr(self.server, "metrics_render", None)
            if render is None:
                self.send_response(404)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            try:
                body = render().encode()
            except Exception:
                # A render bug must not kill the server, but it must be
                # VISIBLE to scrapers: a 200 with an empty body would
                # read as a healthy target with every series absent.
                self.send_response(500)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            self.send_response(200)
            self.send_header(
                "Content-Type",
                "text/plain; version=0.0.4; charset=utf-8",
            )
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        with self.server.kv_lock:  # type: ignore[attr-defined]
            value = self.server.kv.get(self._key())  # type: ignore[attr-defined]
        if value is None:
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(value)))
        self.send_header(
            _MAC_HEADER,
            _mac(self.server.secret, "GET", self._key(), value),  # type: ignore[attr-defined]
        )
        self.end_headers()
        self.wfile.write(value)

    def do_DELETE(self):
        # Deletes are mutations: signed like PUT, with the MAC bound to
        # method + key (there is no body) — or an unauthenticated client
        # could erase rendezvous worlds and checkpoint replicas out from
        # under a live job, and a captured delete could be replayed
        # against arbitrary keys.
        mac = self.headers.get(_MAC_HEADER, "")
        if not hmac.compare_digest(
            mac, _delete_mac(self.server.secret, self._key())  # type: ignore[attr-defined]
        ):
            self.send_response(403)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        with self.server.kv_lock:  # type: ignore[attr-defined]
            self.server.kv.pop(self._key(), None)  # type: ignore[attr-defined]
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()


class KVStoreServer:
    """reference http_server.py `KVStoreServer` (threaded, start/stop).

    ``bind_all=False`` (the all-local default) listens on loopback only."""

    def __init__(self, port: int = 0, *, secret: Optional[str] = None,
                 bind_all: bool = False):
        host = "0.0.0.0" if bind_all else "127.0.0.1"
        self.secret = secret or make_secret()
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.kv = {}  # type: ignore[attr-defined]
        self._httpd.kv_lock = threading.Lock()  # type: ignore[attr-defined]
        self._httpd.secret = self.secret  # type: ignore[attr-defined]
        self._httpd.metrics_render = None  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    # -- in-process store access (the live telemetry aggregator) ----------
    # The HTTP surface deliberately has no listing verb; the launcher-
    # resident aggregator reads its own store directly instead.

    def scan(self, prefix: str) -> dict:
        """Snapshot of every key under ``prefix`` -> value."""
        with self._httpd.kv_lock:  # type: ignore[attr-defined]
            return {
                k: v
                for k, v in self._httpd.kv.items()  # type: ignore[attr-defined]
                if k.startswith(prefix)
            }

    def discard(self, keys) -> None:
        """Drop consumed keys (bounded memory for the streaming scopes)."""
        with self._httpd.kv_lock:  # type: ignore[attr-defined]
            for k in keys:
                self._httpd.kv.pop(k, None)  # type: ignore[attr-defined]

    def set_metrics_render(self, fn) -> None:
        """Install (or clear, with None) the ``GET /metrics`` renderer —
        a zero-arg callable returning Prometheus exposition text."""
        self._httpd.metrics_render = fn  # type: ignore[attr-defined]

    def start(self) -> int:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="hvdtpu_kvstore", daemon=True
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=5)
        self._httpd.server_close()


class KVStoreClient:
    """reference http/http_client.py: authenticated put/get.

    404 means "not published yet" (wait() keeps polling); transport errors
    carry the address so misconfiguration fails loudly, not as a generic
    timeout.

    Connections are PERSISTENT (HTTP/1.1 keep-alive, one per calling
    thread): the serving plane drives several KV operations per decode
    step from every group leader, and a fresh TCP connect per call —
    urllib's behavior — costs a connection handshake plus a server-side
    handler-thread spawn each time, which was measured as the
    throughput ceiling of a multi-group fleet (ISSUE 15's np-scaling
    leg) long before the decode math saturated.  A stale or dropped
    connection is re-dialed once per call; every verb here is
    idempotent, so the single retry cannot double-apply anything."""

    def __init__(self, addr: str, secret: Optional[str] = None):
        self._base = f"http://{addr}"
        self._addr = addr
        host, _, port = addr.rpartition(":")
        self._host, self._port = host, int(port)
        self._secret = secret or os.environ.get(SECRET_ENV, "")
        self._local = threading.local()

    def _request(self, method: str, path: str, body: Optional[bytes],
                 headers: dict):
        """One request over the thread's persistent connection; a dead
        connection (server restarted, keep-alive reaped, first use) is
        re-dialed and the request retried ONCE.  Returns (status,
        headers, body).  Connection-refused surfaces as URLError to
        keep wait()'s startup-grace semantics."""
        import http.client  # noqa: PLC0415

        for attempt in (0, 1):
            conn = getattr(self._local, "conn", None)
            try:
                if conn is None:
                    conn = http.client.HTTPConnection(
                        self._host or "127.0.0.1", self._port,
                        timeout=30,
                    )
                    self._local.conn = conn
                conn.request(method, path, body=body, headers=headers)
                resp = conn.getresponse()
                data = resp.read()
                return resp.status, resp.headers, data
            except (http.client.HTTPException, OSError) as e:
                try:
                    conn.close()
                except Exception:
                    pass
                self._local.conn = None
                if attempt or isinstance(e, ConnectionRefusedError):
                    raise URLError(e) from e

    def put(self, scope: str, key: str, value: bytes) -> None:
        status, _, _ = self._request(
            "PUT", f"/{scope}/{key}", value,
            {_MAC_HEADER: _mac(self._secret, "PUT", f"{scope}/{key}",
                               value),
             "Content-Length": str(len(value))},
        )
        if status == 403:
            raise PermissionError(
                f"KV store at {self._addr} rejected the payload signature"
            )
        if status != 200:
            raise HTTPError(f"{self._base}/{scope}/{key}", status,
                            "unexpected status", None, None)

    def delete(self, scope: str, key: str) -> None:
        """Authenticated delete; absent keys are a no-op (the replica
        tier garbage-collects superseded chunks with this)."""
        status, _, _ = self._request(
            "DELETE", f"/{scope}/{key}", None,
            {_MAC_HEADER: _delete_mac(self._secret, f"{scope}/{key}")},
        )
        if status == 403:
            raise PermissionError(
                f"KV store at {self._addr} rejected the delete "
                f"signature"
            )
        if status != 200:
            raise HTTPError(f"{self._base}/{scope}/{key}", status,
                            "unexpected status", None, None)

    def get(self, scope: str, key: str) -> Optional[bytes]:
        """None = key not published yet; raises on transport failure."""
        try:
            status, headers, body = self._request(
                "GET", f"/{scope}/{key}", None, {}
            )
        except URLError as e:
            raise ConnectionError(
                f"cannot reach KV store at {self._addr}: {e.reason}"
            ) from e
        if status == 404:
            return None
        if status != 200:
            raise HTTPError(f"{self._base}/{scope}/{key}", status,
                            "unexpected status", None, None)
        mac = headers.get(_MAC_HEADER, "")
        if not hmac.compare_digest(
            mac, _mac(self._secret, "GET", f"{scope}/{key}", body)
        ):
            raise PermissionError(
                f"KV store at {self._addr} returned a bad payload signature"
            )
        return body

    def wait(self, scope: str, key: str, timeout: float = 120.0) -> bytes:
        """Poll until published.  Transient transport errors are tolerated
        for a short grace window (server may still be starting), then
        surfaced with the address.

        Exponential backoff (50 ms doubling to a 1 s cap): long waits —
        np ranks parked on rendezvous keys, plus the live-stats PUT
        traffic — must not hammer the launcher's single HTTP server with
        fixed-rate polls at np=64."""
        deadline = time.time() + timeout
        grace = time.time() + 5.0
        delay = 0.05
        last_err: Optional[Exception] = None
        while time.time() < deadline:
            try:
                value = self.get(scope, key)
            except ConnectionError as e:
                if time.time() > grace:
                    raise
                last_err = e
                value = None
            if value is not None:
                return value
            time.sleep(min(delay, max(deadline - time.time(), 0.01)))
            delay = min(delay * 2, 1.0)
        raise TimeoutError(
            f"KV key {scope}/{key} not published at {self._addr} within "
            f"{timeout}s" + (f" (last error: {last_err})" if last_err else "")
        )
