"""Host blacklist with exponential-backoff re-admission.

The elastic launcher's memory of which hosts keep killing workers
(upstream analog: the elastic driver's host blacklist in
horovod/runner/elastic/discovery.py, which a fixed cooldown re-admits;
here the cooldown doubles per repeat failure so a flapping host backs
off geometrically instead of thrashing the respawn budget).

Single-host degenerate case: when EVERY candidate is blacklisted the
selector returns the one whose re-admission lands soonest rather than
deadlocking — on a localhost-only job the only host is also the only
place a respawn can go, and failing the job because its one host had one
crash would make the blacklist strictly worse than no blacklist.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Set

from ..utils.logging import get_logger

__all__ = ["HostBlacklist"]

LOG = get_logger("blacklist")

DEFAULT_COOLDOWN_BASE_SECS = 10.0
DEFAULT_COOLDOWN_CAP_SECS = 300.0


@dataclass
class _Entry:
    failures: int = 0
    readmit_at: float = 0.0


class HostBlacklist:
    """Tracks per-host failures; a host is inadmissible until its
    cooldown (base * 2^(failures-1), capped) elapses."""

    def __init__(
        self,
        cooldown_base: float = DEFAULT_COOLDOWN_BASE_SECS,
        cooldown_cap: float = DEFAULT_COOLDOWN_CAP_SECS,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.cooldown_base = cooldown_base
        self.cooldown_cap = cooldown_cap
        self._clock = clock
        self._hosts: Dict[str, _Entry] = {}
        # Slice-level memory (multislice jobs): which hosts of each
        # slice have failed, and which slices have been blacklisted
        # wholesale.  A slice whose DCN link or shared power domain is
        # bad kills its hosts one by one; waiting to blacklist them
        # individually burns one respawn per host on a doomed slice.
        self._slice_failed: Dict[int, Set[str]] = {}
        # slice -> readmit_at of its wholesale hold; once the hold
        # expires the slice gets a CLEAN failure window (stale failures
        # must neither instantly re-blacklist a recovered slice nor —
        # the opposite bug — block a persistently bad one from ever
        # being held out again).
        self._slices_out: Dict[int, float] = {}

    def record_failure(
        self,
        host: str,
        *,
        slice_id: Optional[int] = None,
        slice_hosts: Optional[Sequence[str]] = None,
    ) -> int:
        """Register a worker failure on ``host``; returns the host's
        total failure count.

        With ``slice_id``/``slice_hosts`` (the launcher's view of which
        slice the failed rank belonged to and every host in it), a
        QUORUM of distinct failed hosts — strictly more than half the
        slice — blacklists the WHOLE slice: every member host gets the
        failed hosts' longest cooldown, so the next respawn lands on a
        healthy slice instead of the next victim of the same fabric."""
        entry = self._hosts.setdefault(host, _Entry())
        entry.failures += 1
        cooldown = min(
            self.cooldown_base * (2 ** (entry.failures - 1)),
            self.cooldown_cap,
        )
        entry.readmit_at = self._clock() + cooldown
        if slice_id is not None and slice_hosts:
            members = set(slice_hosts)
            now = self._clock()
            if (
                slice_id in self._slices_out
                and now >= self._slices_out[slice_id]
            ):
                # The previous wholesale hold expired: fresh window —
                # only failures AFTER readmission count toward the next
                # quorum, and a still-bad slice can be held out again.
                del self._slices_out[slice_id]
                self._slice_failed[slice_id] = set()
            failed = self._slice_failed.setdefault(slice_id, set())
            failed.add(host)
            if (
                slice_id not in self._slices_out
                and 2 * len(failed & members) > len(members)
            ):
                worst = max(
                    self._hosts[h].readmit_at
                    for h in failed & members
                    if h in self._hosts
                )
                self._slices_out[slice_id] = worst
                for h in members:
                    e = self._hosts.setdefault(h, _Entry())
                    e.readmit_at = max(e.readmit_at, worst)
                LOG.warning(
                    "slice %d blacklisted: %d/%d of its hosts failed "
                    "(%s); all member hosts held out until the longest "
                    "cooldown elapses",
                    slice_id, len(failed & members), len(members),
                    ",".join(sorted(failed & members)),
                )
        return entry.failures

    def blacklisted_slices(self) -> List[int]:
        """Slices currently held out wholesale by the failure quorum (a
        slice re-admits implicitly when its hold expires — and becomes
        eligible for a fresh quorum if its hosts keep failing)."""
        now = self._clock()
        return sorted(
            s for s, readmit_at in self._slices_out.items()
            if now < readmit_at
        )

    def failures(self, host: str) -> int:
        entry = self._hosts.get(host)
        return entry.failures if entry else 0

    def is_admissible(self, host: str) -> bool:
        """Clean hosts and hosts whose cooldown has elapsed are fair
        game; re-admission is implicit (no state change needed)."""
        entry = self._hosts.get(host)
        return entry is None or self._clock() >= entry.readmit_at

    def readmission_in(self, host: str) -> float:
        """Seconds until ``host`` is admissible again (0 when it already
        is)."""
        entry = self._hosts.get(host)
        if entry is None:
            return 0.0
        return max(entry.readmit_at - self._clock(), 0.0)

    def select(self, hosts: Sequence[str],
               prefer: Optional[str] = None) -> str:
        """Pick a respawn host: ``prefer`` (the failed rank's original
        host) if admissible, else the first admissible candidate in
        order, else the candidate closest to re-admission (degraded
        single-host mode — see module docstring)."""
        if not hosts:
            raise ValueError("no candidate hosts")
        if prefer is not None and prefer in hosts \
                and self.is_admissible(prefer):
            return prefer
        for host in hosts:
            if self.is_admissible(host):
                return host
        return min(hosts, key=lambda h: (self.readmission_in(h),
                                         hosts.index(h)))

    def blacklisted(self) -> List[str]:
        now = self._clock()
        return sorted(
            h for h, e in self._hosts.items() if now < e.readmit_at
        )
