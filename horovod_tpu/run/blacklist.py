"""Host blacklist with exponential-backoff re-admission.

The elastic launcher's memory of which hosts keep killing workers
(upstream analog: the elastic driver's host blacklist in
horovod/runner/elastic/discovery.py, which a fixed cooldown re-admits;
here the cooldown doubles per repeat failure so a flapping host backs
off geometrically instead of thrashing the respawn budget).

Single-host degenerate case: when EVERY candidate is blacklisted the
selector returns the one whose re-admission lands soonest rather than
deadlocking — on a localhost-only job the only host is also the only
place a respawn can go, and failing the job because its one host had one
crash would make the blacklist strictly worse than no blacklist.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence

__all__ = ["HostBlacklist"]

DEFAULT_COOLDOWN_BASE_SECS = 10.0
DEFAULT_COOLDOWN_CAP_SECS = 300.0


@dataclass
class _Entry:
    failures: int = 0
    readmit_at: float = 0.0


class HostBlacklist:
    """Tracks per-host failures; a host is inadmissible until its
    cooldown (base * 2^(failures-1), capped) elapses."""

    def __init__(
        self,
        cooldown_base: float = DEFAULT_COOLDOWN_BASE_SECS,
        cooldown_cap: float = DEFAULT_COOLDOWN_CAP_SECS,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.cooldown_base = cooldown_base
        self.cooldown_cap = cooldown_cap
        self._clock = clock
        self._hosts: Dict[str, _Entry] = {}

    def record_failure(self, host: str) -> int:
        """Register a worker failure on ``host``; returns the host's
        total failure count."""
        entry = self._hosts.setdefault(host, _Entry())
        entry.failures += 1
        cooldown = min(
            self.cooldown_base * (2 ** (entry.failures - 1)),
            self.cooldown_cap,
        )
        entry.readmit_at = self._clock() + cooldown
        return entry.failures

    def failures(self, host: str) -> int:
        entry = self._hosts.get(host)
        return entry.failures if entry else 0

    def is_admissible(self, host: str) -> bool:
        """Clean hosts and hosts whose cooldown has elapsed are fair
        game; re-admission is implicit (no state change needed)."""
        entry = self._hosts.get(host)
        return entry is None or self._clock() >= entry.readmit_at

    def readmission_in(self, host: str) -> float:
        """Seconds until ``host`` is admissible again (0 when it already
        is)."""
        entry = self._hosts.get(host)
        if entry is None:
            return 0.0
        return max(entry.readmit_at - self._clock(), 0.0)

    def select(self, hosts: Sequence[str],
               prefer: Optional[str] = None) -> str:
        """Pick a respawn host: ``prefer`` (the failed rank's original
        host) if admissible, else the first admissible candidate in
        order, else the candidate closest to re-admission (degraded
        single-host mode — see module docstring)."""
        if not hosts:
            raise ValueError("no candidate hosts")
        if prefer is not None and prefer in hosts \
                and self.is_admissible(prefer):
            return prefer
        for host in hosts:
            if self.is_admissible(host):
                return host
        return min(hosts, key=lambda h: (self.readmission_in(h),
                                         hosts.index(h)))

    def blacklisted(self) -> List[str]:
        now = self._clock()
        return sorted(
            h for h, e in self._hosts.items() if now < e.readmit_at
        )
