"""hvdrun — the launcher (reference: horovod/run, `horovodrun` CLI).

Starts N worker processes (local or over ssh), assigns each its
rank/local_rank/cross_rank slot, points them all at a JAX coordination
service, and maps CLI/config knobs onto HVDTPU_* env vars for every rank —
the direct descendant of horovodrun's gloo launch path
(horovod/run/gloo_run.py), with `jax.distributed` playing the role of the
gloo rendezvous.

Entry points:

* ``python -m horovod_tpu.run -np 4 python train.py``  (CLI)
* ``horovod_tpu.run.run(fn, args=(), np=4)``            (python API,
  reference horovod/run/runner.py:719-808)
"""

from .api import run  # noqa: F401
from .runner import main, parse_args  # noqa: F401
