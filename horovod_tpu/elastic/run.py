"""The elastic retry loop: ``elastic.run(fn)``.

Wraps a training function taking a :class:`~.state.State` first argument
in the recover-and-resume loop (upstream ``hvd.elastic.run``):

1. rendezvous into the current epoch's world,
2. ``state.sync()`` — agree on the newest committed snapshot,
3. run ``fn``; on a recoverable failure (:class:`HorovodShutdownError`:
   peer death / engine shutdown / stalled wait;
   :class:`WorkersAvailableException`: the launcher re-minted the
   epoch), roll back to the last commit and loop.

All three steps are inside the recoverable region: a peer dying while
THIS rank is mid-rendezvous or mid-sync (a cascading second failure —
exactly the event elasticity exists for) retries like a failure inside
``fn``.  Non-recoverable exceptions (user bugs, injected ``ckpt_write``
faults, :class:`RankDroppedError` when the launcher shrank past this
rank, ...) propagate unchanged — the elastic loop only absorbs world
breakage this rank can rejoin, never correctness errors.

The retry budget (``HVDTPU_ELASTIC_MAX_RETRIES``, default 10) bounds
*recoveries in this process*; the launcher separately bounds respawns
with its own ``max_retries`` knob.
"""

from __future__ import annotations

import functools
import time

from ..utils.env import env_int
from ..utils.logging import get_logger
from .context import context as _ambient_context
from .exceptions import (
    HorovodShutdownError,
    RankDroppedError,
    WorkersAvailableException,
)

LOG = get_logger("elastic")

MAX_RETRIES_ENV = "HVDTPU_ELASTIC_MAX_RETRIES"
DEFAULT_MAX_RETRIES = 10

__all__ = ["run"]


def run(fn):
    """Decorate ``fn(state, *args, **kwargs)`` with rollback-and-resume
    fault tolerance.  Returns ``fn``'s result once it completes inside a
    stable world."""

    @functools.wraps(fn)
    def wrapper(state, *args, **kwargs):
        ctx = _ambient_context()
        state._ctx = ctx
        max_retries = env_int(MAX_RETRIES_ENV, DEFAULT_MAX_RETRIES)
        recoveries = 0
        while True:
            try:
                ctx.rendezvous()
                before = state.last_restore
                state.sync(ctx)
                prov = state.last_restore
                if prov is not None and prov is not before \
                        and prov["source"] != "none":
                    # The one-line operator answer to "where did this
                    # incarnation's state come from, and how long did
                    # recovery take" (the full story is in the flight
                    # recorder / post-mortem).
                    LOG.info(
                        "rank %s recovered state at commit %d from %s "
                        "in %.0f ms", getattr(ctx, "rank", 0),
                        prov["commits"],
                        "peer replica" if prov.get("replica_adopted")
                        else prov["source"], prov["ms"],
                    )
                return fn(state, *args, **kwargs)
            except RankDroppedError:
                # The launcher shrank the world past this rank; no
                # amount of retrying lets it rejoin.
                raise
            except WorkersAvailableException as exc:
                reason = f"world update: {exc}"
            except HorovodShutdownError as exc:
                reason = f"world failure: {exc}"
            recoveries += 1
            from ..obs import get_registry  # noqa: PLC0415

            get_registry().counter("elastic.recoveries").inc()
            if recoveries > max_retries:
                raise HorovodShutdownError(
                    f"elastic retry budget exhausted after {max_retries} "
                    f"recoveries (last: {reason})"
                )
            LOG.warning(
                "rank %s recovery %d/%d — rolling back to commit %d (%s)",
                getattr(ctx, "rank", 0), recoveries, max_retries,
                state.commits, reason,
            )
            state.restore()
            # The failed epoch's KV scope still holds pre-failure values
            # — the next rendezvous must land in a FRESH epoch or the
            # replayed steps would read stale contributions.
            notify = getattr(ctx, "notify_world_broken", None)
            if notify is not None:
                notify()
            # Give the launcher's monitor a beat to mint the new epoch
            # when we raced it (timeout-path failures); the rendezvous at
            # the top of the loop then blocks until the world re-forms.
            time.sleep(0.05)

    return wrapper
