"""Elastic worker entry: one per rank slot, (re)spawned by the launcher.

Mirrors run/task_fn.py's fetch-execute-publish shape, with the elastic
additions: the ambient :class:`~.context.ElasticContext` is built from
the spawn env before the user function runs, the heartbeat starts
immediately (so the launcher can tell "slow to import" from "hung"), and
failure *results* are published under an epoch-qualified key so the
launcher can tell a user exception (abort the job, surface the
traceback) from a crash (respawn the rank).
"""

from __future__ import annotations

import os
import sys
import traceback

import cloudpickle

from ..testing.faults import maybe_fail
from .context import ElasticContext, context as _set_ambient
from .exceptions import HorovodShutdownError

_SCOPE = "elastic"


def main() -> int:
    # Death-path hooks FIRST (main thread — signal handlers need it):
    # everything after this point leaves a black box if it dies.
    from ..obs import flightrec, goodput

    flightrec.install_death_hooks()
    # The wall-clock goodput ledger rides the flight recorder's event
    # tap from the very first phase event: every second of this
    # incarnation is classified (init/compile/productive/recovery/...)
    # and published as goodput.* gauges in the rank's metrics dump.
    goodput.install(epoch=int(os.environ.get("HVDTPU_ELASTIC_EPOCH",
                                             "0") or 0))
    ctx = _set_ambient()
    if not isinstance(ctx, ElasticContext):  # pragma: no cover - misuse
        raise RuntimeError(
            "horovod_tpu.elastic.worker must be spawned by the elastic "
            "launcher (HVDTPU_ELASTIC_KV unset)"
        )
    ctx.start_heartbeat()
    from ..utils import env as envmod

    if envmod.env_bool(envmod.CKPT_REPLICA):
        # First question the recovery runbook asks of a slow restore:
        # was the replica tier even armed on this incarnation?  Put the
        # answer in the black box, not in launcher-flag archaeology.
        flightrec.record("init", name="ckpt_replica",
                         detail=f"armed rank={ctx.rank} epoch={ctx.epoch}")
    maybe_fail("task_fn", rank=ctx.rank)
    blob = ctx.kv.wait(_SCOPE, "func", timeout=60)
    func, args, kwargs = cloudpickle.loads(blob)
    flush_trigger = "explicit"
    try:
        result = func(*args, **kwargs)
        ctx.kv.put(_SCOPE, f"result_{ctx.rank}",
                   cloudpickle.dumps((True, result)))
        return 0
    except HorovodShutdownError as exc:
        # World breakage that outlived the elastic retry budget (or a
        # rank the launcher dropped) is an infrastructure failure, not a
        # user error: exit like a crash, WITHOUT posting a traceback, so
        # the launcher's monitor respawns/shrinks instead of aborting
        # the whole job on a "user error".
        flightrec.record_exception(exc, where="elastic.worker")
        flush_trigger = "exception"
        return 1
    except BaseException as exc:
        # Epoch-qualified so the launcher attributes the failure to THIS
        # incarnation of the rank, not a successor already respawned
        # into a later epoch.
        flightrec.record_exception(exc, where="elastic.worker")
        flush_trigger = "exception"
        ctx.kv.put(
            _SCOPE,
            f"error_{ctx.rank}_{ctx.epoch}",
            cloudpickle.dumps(traceback.format_exc()),
        )
        return 1
    finally:
        ctx.stop_heartbeat()
        # Explicit flush (atexit also fires on clean exits, but not
        # after an os._exit-style death — dump what we can while we
        # can): ring + metrics + final live delta through the one
        # shared death path.
        try:
            flightrec.flush(flush_trigger)
        except Exception:
            pass


if __name__ == "__main__":
    sys.exit(main())
