"""Elastic exception taxonomy — re-exported from the package-level leaf.

The classes live in :mod:`horovod_tpu.exceptions` so the runtime layer
(engine, checkpoint) can raise them without importing the elastic
package — ``from ..elastic.exceptions import ...`` would execute
``elastic/__init__`` and drag the whole launcher stack (runner,
rendezvous HTTP server, cloudpickle) into every ``import horovod_tpu``.
This module keeps the user-facing spelling
``horovod_tpu.elastic.exceptions`` working.
"""

from __future__ import annotations

from ..exceptions import (  # noqa: F401
    HorovodShutdownError,
    RankDroppedError,
    WorkersAvailableException,
)

__all__ = [
    "HorovodShutdownError",
    "RankDroppedError",
    "WorkersAvailableException",
]
