"""Python launch API for elastic jobs: ``elastic.launch(fn, np=4)``.

The fault-tolerant sibling of ``horovod_tpu.run.run()``: pickles the
function into the launcher's rendezvous store, drives
``run/runner.py:launch_elastic_job`` (failure detection, blacklist,
respawn, epoch minting), and collects per-rank results from the ranks
that survived to the final world.

Returns ``(results, job)`` where ``results`` maps rank -> value for
every rank of the final world (a shrunken job returns fewer entries)
and ``job`` is the :class:`~..run.runner.ElasticJobResult` whose
``trace`` is the deterministic recovery event list chaos tests compare
across runs.
"""

from __future__ import annotations

import sys
from typing import Any, Dict, Optional, Tuple

import cloudpickle

from ..run.rendezvous import KVStoreServer
from ..run.runner import ElasticJobResult, launch_elastic_job

_SCOPE = "elastic"

__all__ = ["launch"]


def launch(
    fn,
    args: tuple = (),
    kwargs: Optional[dict] = None,
    *,
    np: int = 1,
    hosts: Optional[str] = None,
    hostfile: Optional[str] = None,
    env: Optional[Dict[str, str]] = None,
    min_workers: Optional[int] = None,
    max_retries: int = 3,
    heartbeat_timeout: float = 60.0,
    progress_timeout: float = 300.0,
    progress_grace: float = 0.0,
    blacklist_cooldown: float = 10.0,
    dump_grace_secs: float = 5.0,
    timeout: Optional[float] = None,
    live_stats_secs: Optional[float] = None,
    live_history: Optional[str] = None,
) -> Tuple[Dict[int, Any], ElasticJobResult]:
    """Run ``fn(*args, **kwargs)`` on ``np`` elastic workers.

    ``fn`` runs under the ambient elastic context
    (``horovod_tpu.elastic.context()``); wrap its training loop with
    ``elastic.run`` and keep its state in an ``elastic.State`` to get
    rollback-and-resume on worker failure.
    """
    from ..run.api import _parse_host_slots, _pickle_func  # noqa: PLC0415
    from ..run.allocate import is_local_host  # noqa: PLC0415

    host_slots = _parse_host_slots(hosts, hostfile)
    all_local = all(is_local_host(h.hostname) for h in host_slots)
    server = KVStoreServer(bind_all=not all_local)
    server.start()
    from ..run.rendezvous import KVStoreClient  # noqa: PLC0415

    kv = KVStoreClient(f"127.0.0.1:{server.port}", server.secret)
    kv.put(_SCOPE, "func", _pickle_func(fn, args, kwargs or {}))
    try:
        job = launch_elastic_job(
            [sys.executable, "-m", "horovod_tpu.elastic.worker"],
            np,
            hosts=hosts,
            hostfile=hostfile,
            env=env,
            min_workers=min_workers,
            max_retries=max_retries,
            heartbeat_timeout=heartbeat_timeout,
            progress_timeout=progress_timeout,
            progress_grace=progress_grace,
            blacklist_cooldown=blacklist_cooldown,
            dump_grace_secs=dump_grace_secs,
            job_timeout=timeout,
            kv_server=server,
            live_stats_secs=live_stats_secs,
            live_history=live_history,
        )
        results: Dict[int, Any] = {}
        for rank in job.world:
            blob = kv.wait(_SCOPE, f"result_{rank}", timeout=30)
            ok, value = cloudpickle.loads(blob)
            if not ok:  # pragma: no cover - monitor aborts first
                raise RuntimeError(f"rank {rank} raised:\n{value}")
            results[rank] = value
        return results, job
    finally:
        server.stop()
