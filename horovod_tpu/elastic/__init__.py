"""Elastic fault-tolerant training (the TPU-native answer to Elastic
Horovod's commit-and-rollback + re-rendezvous design).

Three layers (see docs/elastic.md for the full state machine):

* **User API** — :class:`State` (commit/restore/sync) and :func:`run`
  (catch recoverable world failures, roll back, re-rendezvous, resume).
* **Launcher** — ``run/runner.py:launch_elastic_job`` /
  :func:`launch`: per-rank failure detection (exit code + heartbeat),
  host blacklisting with exponential backoff (``run/blacklist.py``),
  bounded respawn into re-minted rendezvous epochs.
* **Fault injection** — ``horovod_tpu/testing/faults.py``
  (``HVDTPU_FAULT_SPEC``), so the recovery paths are exercised
  deterministically on CPU in tier-1.

Minimal elastic training loop::

    import horovod_tpu.elastic as elastic

    def train():
        ctx = elastic.context()
        state = elastic.State(w=np.zeros(4), step=0)

        @elastic.run
        def loop(state):
            while state.step < 100:
                grad = compute_grad(state)
                state.w -= 0.1 * ctx.allreduce(grad, name=f"g{state.step}")
                state.step += 1
                state.commit()
            return state.w

        return loop(state)

    results, job = elastic.launch(train, np=4, min_workers=2)
"""

from .context import (  # noqa: F401
    ElasticContext,
    LocalContext,
    context,
    reset_context,
)
from .exceptions import (  # noqa: F401
    HorovodShutdownError,
    RankDroppedError,
    WorkersAvailableException,
)
from .launch import launch  # noqa: F401
from .run import run  # noqa: F401
from .state import State  # noqa: F401

__all__ = [
    "State",
    "run",
    "launch",
    "context",
    "reset_context",
    "ElasticContext",
    "LocalContext",
    "HorovodShutdownError",
    "RankDroppedError",
    "WorkersAvailableException",
]
