"""Commit/restore/sync training state for elastic runs.

The elastic analog of the AsyncSave commit-point discipline in
checkpoint.py: training mutates ``State`` attributes freely; ``commit()``
snapshots them (host copies, like checkpoint.py's ``np.asarray`` of the
pytree) as the rollback point; ``restore()`` rewinds to it after a
recoverable failure; ``sync()`` makes the whole (possibly re-formed)
world agree on the newest committed snapshot — a respawned rank with no
history adopts a survivor's state, the broadcast-from-a-surviving-rank
the ISSUE names.

Upstream mirror: horovod's elastic ``State``/``ObjectState`` with
commit()/restore()/sync() (horovod/common/elastic.py in the post-0.19
line); here sync rides the epoch-scoped KV owner election instead of an
MPI broadcast.
"""

from __future__ import annotations

import copy
import pickle
from typing import Any, Dict

import jax
import numpy as np

from .context import context as _ambient_context
from .exceptions import WorkersAvailableException

__all__ = ["State"]


def _clone(tree):
    """Host-side deep copy of a pytree: arrays land as fresh numpy
    buffers (a jax.Array snapshot is materialized to host, matching the
    checkpoint layer), everything else deep-copies."""

    def leaf(x):
        if isinstance(x, np.ndarray):
            return x.copy()
        if isinstance(x, jax.Array):
            return np.asarray(x)
        return copy.deepcopy(x)

    return jax.tree_util.tree_map(leaf, tree)


class State:
    """A bag of named training objects with commit/rollback semantics.

    >>> state = State(params=params, opt_state=opt_state, step=0)
    >>> state.step += 1          # attribute access hits the live values
    >>> state.commit()           # rollback point
    >>> state.restore()          # rewind to the last commit
    """

    def __init__(self, **values: Any):
        # object.__setattr__ for internals so __setattr__ below can route
        # everything non-underscore into the value dict.
        object.__setattr__(self, "_values", dict(values))
        object.__setattr__(self, "_snapshot", _clone(values))
        object.__setattr__(self, "_commits", 0)
        object.__setattr__(self, "_ctx", None)

    # -- attribute routing ------------------------------------------------

    def __getattr__(self, name: str) -> Any:
        values: Dict[str, Any] = object.__getattribute__(self, "_values")
        if name in values:
            return values[name]
        raise AttributeError(
            f"elastic State has no value {name!r}; registered: "
            f"{sorted(values)}"
        )

    def __setattr__(self, name: str, value: Any) -> None:
        if name.startswith("_"):
            object.__setattr__(self, name, value)
        else:
            self._values[name] = value

    def register(self, **values: Any) -> None:
        """Add more objects to the state (tracked from the next commit)."""
        self._values.update(values)

    @property
    def commits(self) -> int:
        """Number of commits applied (the freshness key sync elects on)."""
        return self._commits

    def values(self) -> Dict[str, Any]:
        return dict(self._values)

    # -- commit discipline ------------------------------------------------

    def commit(self) -> None:
        """Snapshot the live values as the rollback point.

        When the launcher has re-minted the rendezvous epoch since this
        rank last rendezvoused, raises :class:`WorkersAvailableException`
        AFTER taking the snapshot — the commit is durable, and
        ``elastic.run`` re-rendezvouses before the next step touches the
        stale world."""
        object.__setattr__(self, "_snapshot", _clone(self._values))
        object.__setattr__(self, "_commits", self._commits + 1)
        ctx = self._ctx
        if ctx is not None and ctx.world_changed():
            raise WorkersAvailableException(
                f"rendezvous epoch advanced past {ctx.epoch}; "
                f"re-rendezvous before the next step"
            )

    def restore(self) -> None:
        """Rewind the live values to the last commit (initial values when
        nothing has been committed yet)."""
        object.__setattr__(self, "_values", _clone(self._snapshot))

    def sync(self, ctx=None) -> None:
        """Make every rank in the current world hold the newest committed
        snapshot: the rank with the highest commit count (ties: lowest
        rank) broadcasts; everyone adopts it as both snapshot and live
        values."""
        ctx = ctx or self._ctx or _ambient_context()
        blob = ctx.sync_state(
            pickle.dumps((self._snapshot, self._commits)), self._commits
        )
        snapshot, commits = pickle.loads(blob)
        object.__setattr__(self, "_snapshot", snapshot)
        object.__setattr__(self, "_commits", commits)
        object.__setattr__(self, "_values", _clone(snapshot))
