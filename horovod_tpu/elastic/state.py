"""Commit/restore/sync training state for elastic runs.

The elastic analog of the AsyncSave commit-point discipline in
checkpoint.py: training mutates ``State`` attributes freely; ``commit()``
snapshots them (host copies, like checkpoint.py's ``np.asarray`` of the
pytree) as the rollback point; ``restore()`` rewinds to it after a
recoverable failure; ``sync()`` makes the whole (possibly re-formed)
world agree on the newest committed snapshot — a respawned rank with no
history adopts a survivor's state, the broadcast-from-a-surviving-rank
the ISSUE names.

The checkpoint tier (ISSUE 7) routes through here:

* every ``commit()`` also pushes the committed snapshot to this rank's
  replica key over the signed KV path (``HVDTPU_CKPT_REPLICA=1``,
  ckpt/replica.py) — in the data-parallel world the logical state is
  replicated, so a rank's shard of it is the whole snapshot;
* ``sync()`` on a freshly respawned incarnation (commit count 0) first
  adopts its predecessor's live peer replica, then the sharded manifest
  on disk (``HVDTPU_CKPT_DIR``, ckpt/sharded.py), then enters the
  owner election as before — so recovery touches cold storage only
  when no live peer holds a valid copy;
* the restore *provenance* — ``peer`` (live replica or a surviving
  rank's broadcast), ``disk`` (sharded manifest), ``none`` (fresh
  start) — lands in the metrics registry
  (``ckpt.restore_source{source=...}``, ``ckpt.restore_ms``), the
  flight-recorder ring (``ckpt.restore``), and :attr:`State.last_restore`.

Upstream mirror: horovod's elastic ``State``/``ObjectState`` with
commit()/restore()/sync() (horovod/common/elastic.py in the post-0.19
line); here sync rides the epoch-scoped KV owner election instead of an
MPI broadcast.
"""

from __future__ import annotations

import copy
import os
import pickle
import time
from typing import Any, Dict, Optional

import jax
import numpy as np

from ..obs import flightrec as _flightrec
from ..obs import get_registry
from ..utils import env as envmod
from ..utils.logging import get_logger
from .context import context as _ambient_context
from .exceptions import WorkersAvailableException

LOG = get_logger("elastic")

__all__ = ["State"]


def _clone(tree):
    """Host-side deep copy of a pytree: arrays land as fresh numpy
    buffers (a jax.Array snapshot is materialized to host, matching the
    checkpoint layer), everything else deep-copies."""

    def leaf(x):
        if isinstance(x, np.ndarray):
            return x.copy()
        if isinstance(x, jax.Array):
            return np.asarray(x)
        return copy.deepcopy(x)

    return jax.tree_util.tree_map(leaf, tree)


class State:
    """A bag of named training objects with commit/rollback semantics.

    >>> state = State(params=params, opt_state=opt_state, step=0)
    >>> state.step += 1          # attribute access hits the live values
    >>> state.commit()           # rollback point (+ replica push)
    >>> state.restore()          # rewind to the last commit
    """

    def __init__(self, **values: Any):
        # object.__setattr__ for internals so __setattr__ below can route
        # everything non-underscore into the value dict.
        object.__setattr__(self, "_values", dict(values))
        object.__setattr__(self, "_snapshot", _clone(values))
        object.__setattr__(self, "_commits", 0)
        # Commits made by THIS incarnation (vs. adopted through the
        # recovery tier) and the one-shot provenance latch: a first
        # sync() interrupted mid-election and retried must still
        # record where this incarnation's state came from —
        # `_commits == 0` can't tell, because adoption already bumped
        # it.
        object.__setattr__(self, "_own_commits", 0)
        object.__setattr__(self, "_provenance_pending", True)
        object.__setattr__(self, "_ctx", None)
        # Checkpoint tier: None = not probed yet, False = probed and
        # absent (knob off / no KV endpoint), else the ReplicaTier.
        object.__setattr__(self, "_replica_tier", None)
        object.__setattr__(
            self, "_ckpt_dir", os.environ.get(envmod.CKPT_DIR) or None
        )
        object.__setattr__(self, "_last_restore", None)

    # -- attribute routing ------------------------------------------------

    def __getattr__(self, name: str) -> Any:
        values: Dict[str, Any] = object.__getattribute__(self, "_values")
        if name in values:
            return values[name]
        raise AttributeError(
            f"elastic State has no value {name!r}; registered: "
            f"{sorted(values)}"
        )

    def __setattr__(self, name: str, value: Any) -> None:
        if name.startswith("_"):
            object.__setattr__(self, name, value)
        else:
            self._values[name] = value

    def register(self, **values: Any) -> None:
        """Add more objects to the state (tracked from the next commit)."""
        self._values.update(values)

    @property
    def commits(self) -> int:
        """Number of commits applied (the freshness key sync elects on)."""
        return self._commits

    @property
    def last_restore(self) -> Optional[dict]:
        """Provenance of this incarnation's recovery, set by its first
        completed ``sync()``: ``{"source": "peer"|"disk"|"none",
        "commits": N, "ms": float, "replica_adopted": bool}``.  None
        only before that first sync.  An incarnation that recovered
        nothing — a job-start rank, or the surviving side of a failure
        — reports ``source="none"`` (the chaos gates lean on exactly
        that to tell the restored rank from its survivors)."""
        return self._last_restore

    def values(self) -> Dict[str, Any]:
        return dict(self._values)

    # -- checkpoint tier --------------------------------------------------

    def _tier(self, ctx=None):
        """The ambient replica tier, probed once and kept fresh with the
        current world (membership changes move the ring neighbor)."""
        tier = self._replica_tier
        if tier is None:
            from ..ckpt.replica import tier_from_env  # noqa: PLC0415

            ctx = ctx or self._ctx
            tier = tier_from_env(ctx)
            object.__setattr__(self, "_replica_tier",
                               tier if tier is not None else False)
        if tier in (None, False):
            return None
        ctx = ctx or self._ctx
        if ctx is not None and getattr(ctx, "world", None):
            tier.rank = ctx.rank
            tier.world = sorted(ctx.world)
        return tier

    def _push_replica(self) -> None:
        tier = self._tier()
        if tier is None:
            return
        blob = pickle.dumps((self._snapshot, self._commits))
        tier.push(blob, step=self._commits, commits=self._commits)

    def save_sharded(self, directory: Optional[str] = None,
                     step: Optional[int] = None, *, ctx=None):
        """Sharded save of the last committed snapshot (the disk tier):
        this rank writes only its own shard; rank 0 commits the
        manifest (with the commit count in ``extra``) last.  Returns a
        :class:`~..ckpt.sharded.ShardedSave` handle — ``wait()`` is the
        commit point.  ``directory`` defaults to ``HVDTPU_CKPT_DIR``."""
        directory = directory or self._ckpt_dir
        if not directory:
            raise ValueError(
                "no checkpoint directory: pass one or set HVDTPU_CKPT_DIR"
            )
        ctx = ctx or self._ctx or _ambient_context()
        from ..ckpt import sharded as _sharded  # noqa: PLC0415

        # Shard by POSITION in the world, not by raw rank: an elastic
        # shrink can leave gaps (world {0, 2} is 2 writers), and the
        # sharded format wants dense writer indices [0, world_size).
        world = sorted(ctx.world) if getattr(ctx, "world", None) else [0]
        try:
            shard_index = world.index(ctx.rank)
        except ValueError:
            raise RuntimeError(
                f"rank {ctx.rank} is not in the current world {world}; "
                f"re-rendezvous before saving"
            ) from None
        return _sharded.save_sharded_async(
            directory,
            self._snapshot,
            int(self._commits if step is None else step),
            rank=shard_index,
            world_size=len(world),
            extra={"commits": self._commits,
                   "epoch": getattr(ctx, "epoch", 0)},
        )

    # -- commit discipline ------------------------------------------------

    def commit(self) -> None:
        """Snapshot the live values as the rollback point and push the
        replica.

        When the launcher has re-minted the rendezvous epoch since this
        rank last rendezvoused, raises :class:`WorkersAvailableException`
        AFTER taking the snapshot and pushing the replica — the commit
        is durable (and its replica live) either way, and
        ``elastic.run`` re-rendezvouses before the next step touches
        the stale world."""
        object.__setattr__(self, "_snapshot", _clone(self._values))
        object.__setattr__(self, "_commits", self._commits + 1)
        object.__setattr__(self, "_own_commits", self._own_commits + 1)
        self._push_replica()
        ctx = self._ctx
        if ctx is not None and ctx.world_changed():
            raise WorkersAvailableException(
                f"rendezvous epoch advanced past {ctx.epoch}; "
                f"re-rendezvous before the next step"
            )

    def restore(self) -> None:
        """Rewind the live values to the last commit (initial values when
        nothing has been committed yet)."""
        object.__setattr__(self, "_values", _clone(self._snapshot))

    def _adopt(self, snapshot, commits: int) -> None:
        object.__setattr__(self, "_snapshot", snapshot)
        object.__setattr__(self, "_commits", int(commits))

    def _fetch_replica(self, ctx):
        """This rank's predecessor's live replica as ``(snapshot,
        commits)``; None when no peer holds a valid copy (missing,
        torn, checksum-rejected)."""
        tier = self._tier(ctx)
        if tier is None:
            return None
        got = tier.fetch(getattr(ctx, "rank", 0))
        if got is None:
            return None
        payload, meta = got
        try:
            snapshot, commits = pickle.loads(payload)
        except Exception as exc:
            LOG.warning("peer replica unreadable (%s); falling back", exc)
            get_registry().counter("ckpt.replica_invalid").inc()
            return None
        if int(commits) <= 0:
            return None
        return snapshot, int(commits)

    def _peek_disk_commits(self):
        """The newest manifest's commit count from its metadata ALONE —
        no shard reads, no checksums.  The freshness compare against
        the replica must not cost a full checkpoint read when the
        replica (the common case) is going to win anyway."""
        if not self._ckpt_dir:
            return None
        from ..ckpt import sharded as _sharded  # noqa: PLC0415

        step = _sharded.latest_step(self._ckpt_dir)
        if step is None:
            return None
        manifest = _sharded.load_manifest(self._ckpt_dir, step)
        if manifest is None:
            return None
        commits = int((manifest.get("extra") or {}).get("commits", step))
        return commits if commits > 0 else None

    def _fetch_disk(self):
        """The newest restorable sharded manifest on disk as
        ``(snapshot, commits)``; None when the directory is unset,
        empty, or nothing validates."""
        if not self._ckpt_dir:
            return None
        from ..ckpt import sharded as _sharded  # noqa: PLC0415

        try:
            snapshot, manifest = _sharded.restore_sharded(
                self._ckpt_dir, target=self._snapshot, with_manifest=True
            )
        except FileNotFoundError:
            return None
        except Exception as exc:
            LOG.warning("disk checkpoint restore failed (%s); starting "
                        "from initial values", exc)
            return None
        commits = int((manifest.get("extra") or {}).get(
            "commits", manifest["step"]))
        if commits <= 0:
            return None
        return snapshot, commits

    def sync(self, ctx=None) -> None:
        """Make every rank in the current world hold the newest committed
        snapshot.

        A freshly respawned incarnation (commit count 0) recovers
        through the tier first — live peer replica, then sharded disk
        manifest — and only then enters the owner election (highest
        commit count, ties: lowest rank), so whichever source is
        newest wins on every rank.  The recovery provenance is
        recorded; see :attr:`last_restore`."""
        ctx = ctx or self._ctx or _ambient_context()
        t0 = time.monotonic()
        # "Fresh" = this incarnation has never committed AND has not
        # yet recorded its provenance — NOT `_commits == 0`: a first
        # sync that adopted a replica and was then interrupted by a
        # cascading failure retries with adopted commits > 0, and the
        # retry must still probe the tiers and record the provenance.
        fresh = self._provenance_pending and self._own_commits == 0
        adopted = None
        adopted_commits = 0
        if fresh:
            # Probe BOTH local tiers and adopt the freshest — a stale
            # replica (its last push dropped or raced the kill) must
            # never shadow a newer durable manifest.  The disk probe is
            # metadata-only; shards are read (and checksummed) ONLY
            # when disk can actually win, so the common peer-restore
            # path never touches cold storage.  Ties prefer the
            # replica: identical state, and it proves the hot tier.
            replica = self._fetch_replica(ctx)
            disk = None
            disk_hint = self._peek_disk_commits()
            if disk_hint is not None and (replica is None
                                          or disk_hint > replica[1]):
                disk = self._fetch_disk()
            if replica is not None and (disk is None
                                        or replica[1] >= disk[1]):
                adopted, (snapshot, adopted_commits) = "peer", replica
                self._adopt(snapshot, adopted_commits)
            elif disk is not None:
                adopted, (snapshot, adopted_commits) = "disk", disk
                self._adopt(snapshot, adopted_commits)
        blob = ctx.sync_state(
            pickle.dumps((self._snapshot, self._commits)), self._commits
        )
        snapshot, commits = pickle.loads(blob)
        object.__setattr__(self, "_snapshot", snapshot)
        object.__setattr__(self, "_commits", commits)
        object.__setattr__(self, "_values", _clone(snapshot))
        if not fresh:
            return
        if int(commits) <= 0:
            source = "none"
        elif adopted is not None and adopted_commits >= int(commits):
            # The locally adopted tier was at least as fresh as the
            # election winner, so the state this rank holds is (bit for
            # bit) what that tier supplied — even when a tied survivor
            # technically won the broadcast.
            source = adopted
        else:
            # The election overrode local adoption (or there was
            # nothing to adopt): the state came out of a live peer's
            # memory via the broadcast.
            source = "peer"
        ms = (time.monotonic() - t0) * 1e3
        # replica_adopted distinguishes "my predecessor's replica held
        # the state I now run with" from "a surviving peer broadcast to
        # me" — both are source=peer, but only the former proves the
        # replica tier.  A stale replica the election overrode does NOT
        # count, or a broken tier would pass every provenance check.
        replica_ok = (adopted == "peer"
                      and adopted_commits >= int(commits)
                      and int(commits) > 0)
        object.__setattr__(self, "_last_restore", {
            "source": source, "commits": int(commits), "ms": ms,
            "replica_adopted": replica_ok,
        })
        object.__setattr__(self, "_provenance_pending", False)
        # Quiet jobs stay quiet: a fresh start in a job with NO ckpt
        # tier configured is not a recovery event — emitting it would
        # put a "checkpoint / recovery" section (and a post-mortem
        # provenance line) on every elastic job ever run.
        armed = self._tier(ctx) is not None or bool(self._ckpt_dir)
        if source == "none" and not armed:
            return
        metrics = get_registry()
        metrics.counter("ckpt.restore_source", source=source).inc()
        if source != "none":
            metrics.histogram("ckpt.restore_ms").observe(ms)
        _flightrec.record(
            "ckpt.restore", name=f"commit{int(commits)}",
            cycle=int(commits),
            detail=f"source={source} replica={replica_ok} ms={ms:.0f}",
        )
