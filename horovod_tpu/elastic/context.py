"""Worker-side elastic world: epoch rendezvous + KV-backed collectives.

Why not jax.distributed here: its coordination service pins world
membership for the life of the process tree — a dead rank can never be
replaced inside the same service instance, which is exactly the elastic
contract.  The elastic world instead rides the launcher's HMAC-signed
HTTP KV store (run/rendezvous.py — the same store the run() API and the
cluster driver already trust with pickles), with every collective keyed
by the *rendezvous epoch* the launcher mints:

* epoch ``e`` keys are immutable once written, so a re-formed world at
  ``e+1`` can never read a dead world's partial step;
* a survivor blocked on a dead peer's contribution notices the epoch
  bump (the launcher's respawn path) and raises
  :class:`HorovodShutdownError`, which ``elastic.run`` turns into
  rollback + re-rendezvous;
* a respawned rank joins at the new epoch and adopts the newest
  committed state through :meth:`ElasticContext.sync_state`'s
  owner election (highest commit count, lowest rank tiebreak).

The data path is deliberately the rendezvous store, not a ring: elastic
steps are checkpoint-rate, not gradient-rate — the engine's fused eager
path stays the throughput plane, and this is the control/recovery plane
(the same split upstream Elastic Horovod makes between its gloo ring and
its rendezvous server).

Environment contract (set by the elastic launcher, runner.py)::

    HVDTPU_ELASTIC_KV       host:port of the launcher's KV store
    HVDTPU_SECRET           per-job HMAC secret (rendezvous.SECRET_ENV)
    HVDTPU_ELASTIC_RANK     this worker's stable rank
    HVDTPU_ELASTIC_EPOCH    epoch current at spawn time
    HVDTPU_ELASTIC_TIMEOUT  collective/rendezvous wait bound (secs, 120)
    HVDTPU_ELASTIC_HEARTBEAT_SECS   liveness beat period (secs, 1.0)
"""

from __future__ import annotations

import os
import pickle
import threading
import time
from typing import List, Optional, Sequence

import numpy as np

from ..obs import get_registry
from ..obs import flightrec as obs_flightrec
from ..obs import progress as obs_progress
from ..obs import straggler as obs_straggler
from ..run.rendezvous import KVStoreClient
from ..testing.faults import corrupt_grad, maybe_fail
from ..utils.env import env_float
from ..utils.logging import get_logger
from .exceptions import HorovodShutdownError, RankDroppedError

LOG = get_logger("elastic")

_SCOPE = "elastic"
_POLL_SECS = 0.05
DEFAULT_TIMEOUT = 120.0
DEFAULT_HEARTBEAT_SECS = 1.0

__all__ = ["ElasticContext", "LocalContext", "context", "reset_context"]


def _epoch_scope(epoch: int) -> str:
    return f"elastic_e{epoch}"


class ElasticContext:
    """One worker's view of the elastic world (rank, epoch, membership)
    plus the epoch-scoped collectives the training loop runs on."""

    def __init__(
        self,
        rank: int,
        kv: KVStoreClient,
        epoch: int = 0,
        *,
        timeout: float = DEFAULT_TIMEOUT,
        heartbeat_secs: float = DEFAULT_HEARTBEAT_SECS,
    ):
        self.rank = int(rank)
        self.kv = kv
        self.epoch = int(epoch)
        self.world: List[int] = [self.rank]
        self.size = 1
        self.timeout = timeout
        self.heartbeat_secs = heartbeat_secs
        self._seq = 0
        self._min_epoch = 0
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None

    @classmethod
    def from_env(cls) -> "ElasticContext":
        addr = os.environ["HVDTPU_ELASTIC_KV"]
        return cls(
            rank=int(os.environ.get("HVDTPU_ELASTIC_RANK", "0")),
            kv=KVStoreClient(addr),
            epoch=int(os.environ.get("HVDTPU_ELASTIC_EPOCH", "0")),
            timeout=env_float("HVDTPU_ELASTIC_TIMEOUT", DEFAULT_TIMEOUT),
            heartbeat_secs=env_float(
                "HVDTPU_ELASTIC_HEARTBEAT_SECS", DEFAULT_HEARTBEAT_SECS
            ),
        )

    # -- liveness ---------------------------------------------------------

    def start_heartbeat(self) -> None:
        """Beat ``hb_<rank>`` from a dedicated thread so the launcher
        can spot a *frozen process* — SIGSTOP, OOM-thrash, a wedged
        host (a crashed one is caught by its exit code first).

        The beat body piggybacks the collective-path progress counter
        and phase (obs/progress.py): the wall-clock field keeps proving
        the *process* lives (the beat thread survives a training-thread
        deadlock, so its mere arrival proves nothing more), while the
        launcher's workload-aware progress policy watches the counter to
        catch the deadlocked *training thread* directly — instead of
        leaving the hang to peers' collective timeouts and their retry
        budget."""
        if self._hb_thread is not None:
            return
        # The live telemetry publisher rides the heartbeat lifecycle:
        # same launcher KV endpoint, same signed PUT path, armed by the
        # same spawn env (HVDTPU_LIVE_STATS_SECS).
        from ..obs import stream as obs_stream  # noqa: PLC0415

        obs_stream.maybe_start_from_env()

        def _beat():
            while True:
                try:
                    # Epoch-stamped: the launcher must not attribute a
                    # dead incarnation's last beat to the respawned
                    # successor (hb_<rank> is not epoch-scoped).
                    self.kv.put(
                        _SCOPE, f"hb_{self.rank}",
                        obs_progress.beat_payload(epoch=self.epoch),
                    )
                except Exception:
                    pass  # launcher going down; the exit path handles it
                if self._hb_stop.wait(self.heartbeat_secs):
                    return

        self._hb_thread = threading.Thread(
            target=_beat, name="hvdtpu_elastic_hb", daemon=True
        )
        self._hb_thread.start()

    def stop_heartbeat(self) -> None:
        self._hb_stop.set()

    # -- epoch / membership ----------------------------------------------

    def current_epoch(self) -> int:
        raw = self.kv.get(_SCOPE, "epoch")
        return int(raw) if raw is not None else self.epoch

    def world_changed(self) -> bool:
        """True when the launcher minted a newer epoch than the one this
        context last rendezvoused into."""
        return self.current_epoch() > self.epoch

    def notify_world_broken(self) -> None:
        """Record that a collective/sync failed in the current epoch.
        The next rendezvous then refuses to rejoin it: epoch ``e``'s
        keys still hold pre-failure values (collective contributions,
        the epoch-start sync blob), so replaying rolled-back steps
        against them silently diverges from peers.  Recovery only
        proceeds once the launcher mints a fresh epoch; a rank that
        never sees one times out, exits, and is respawned into one."""
        obs_flightrec.record("world_broken", cycle=self.epoch)
        self._min_epoch = self.epoch + 1

    def rendezvous(self, timeout: Optional[float] = None) -> int:
        """Join the current epoch's world: fetch membership, check in,
        wait for every member.  Restarts transparently if the epoch
        advances mid-wait; raises :class:`HorovodShutdownError` when the
        deadline passes with members still missing."""
        # The whole join is a launcher/peer wait: the progress beat
        # reports `waiting`, so the staleness policy never shoots a rank
        # that is merely parked for a respawned peer to come up.
        with obs_progress.waiting():
            return self._rendezvous(timeout)

    def _rendezvous(self, timeout: Optional[float] = None) -> int:
        deadline = time.monotonic() + (timeout or self.timeout)
        while True:
            e = self.current_epoch()
            if e < self._min_epoch:
                if time.monotonic() > deadline:
                    raise HorovodShutdownError(
                        f"rendezvous timed out waiting for a fresh epoch "
                        f"(> {self._min_epoch - 1}) after a world failure "
                        f"— the launcher never re-formed the world"
                    )
                time.sleep(_POLL_SECS)
                continue
            raw = self._fetch(_SCOPE, f"world_{e}", deadline,
                              what=f"world for epoch {e}", epoch=None)
            world = sorted(pickle.loads(raw))
            if self.rank not in world:
                # The launcher shrank the world past this rank (it was
                # presumed dead); there is nothing left to compute here.
                raise RankDroppedError(
                    f"rank {self.rank} is not a member of epoch {e}'s "
                    f"world {world}; the launcher dropped it"
                )
            self.kv.put(_SCOPE, f"ready_{e}_{self.rank}", b"1")
            restart = False
            for r in world:
                while self.kv.get(_SCOPE, f"ready_{e}_{r}") is None:
                    if self.current_epoch() > e:
                        restart = True
                        break
                    if time.monotonic() > deadline:
                        raise HorovodShutdownError(
                            f"rendezvous for epoch {e} timed out waiting "
                            f"for rank {r} (world {world})"
                        )
                    time.sleep(_POLL_SECS)
                if restart:
                    break
            if restart:
                continue
            self.epoch, self.world, self.size = e, world, len(world)
            # Collective numbering is per-epoch: survivors (mid-run
            # _seq) and a respawned rank (fresh process, _seq 0) must
            # agree on auto-minted names like "op3" after recovery.
            self._seq = 0
            # Straggler attribution starts clean per incarnation: the
            # old world's blame (often the very rank that just died or
            # was respawned) must not leak into the new epoch's verdict.
            obs_straggler.reset()
            get_registry().counter("elastic.rendezvous").inc()
            obs_flightrec.record(
                "rendezvous", name=f"epoch{e}", cycle=e,
                detail=f"world={world}",
            )
            LOG.info("rank %d joined epoch %d world %s",
                     self.rank, e, world)
            return e

    # -- collectives ------------------------------------------------------

    def allreduce(self, value, name: Optional[str] = None, *,
                  average: bool = True) -> np.ndarray:
        """Epoch-scoped allreduce: every member publishes, everyone
        gathers.  A missing peer surfaces as HorovodShutdownError —
        either via the epoch bump (launcher noticed first) or the
        timeout (it didn't)."""
        self._seq += 1
        name = name or f"op{self._seq}"
        # Deterministic chaos: the worker_exit injection point sits at
        # the step boundary BEFORE this rank contributes, so when it
        # fires no peer can have completed the step (ISSUE acceptance:
        # recovery resumes from the last commit on every rank).
        maybe_fail("worker_exit", step=self._seq, rank=self.rank)
        # Flight recorder, KV-collective flavor: the per-epoch sequence
        # number is this path's "cycle" — identical on every member, so
        # the post-mortem aligns elastic rings the same way it aligns
        # engine rings.
        obs_flightrec.record(
            "enqueue", name=name, cycle=self._seq, detail="kv_allreduce",
        )
        arr = np.asarray(value)
        scope = _epoch_scope(self.epoch)
        self.kv.put(scope, f"ar_{name}_{self.rank}", pickle.dumps(arr))
        deadline = time.monotonic() + self.timeout
        parts = []
        waits = {}
        # Contribution is in: from here this rank is blocked on PEERS,
        # and the beat's waiting flag says so — a hung peer freezes this
        # counter too, and the policy must kill the peer, not us.
        with obs_progress.waiting():
            for r in self.world:
                t0 = time.monotonic()
                raw = self._fetch(scope, f"ar_{name}_{r}", deadline,
                                  what=f"allreduce {name!r} from rank {r}")
                waits[r] = time.monotonic() - t0
                parts.append(pickle.loads(raw))
        # Straggler attribution, KV-collective flavor: blame the peer
        # this rank actually sat polling for (a delayed rank waits on
        # nobody, so it never smears blame; see obs/straggler.py).
        obs_straggler.record_waits(
            waits, self.rank, tensor=name,
            alert_ms=env_float("HVDTPU_ALERT_SKEW_MS", 0.0),
        )
        total = parts[0].astype(np.float64) if average else parts[0]
        for p in parts[1:]:
            total = total + p
        if average:
            total = (total / len(parts)).astype(arr.dtype)
        # Chaos hook for the divergence sentinel: grad_ready fires
        # AFTER the reduction, on this rank's copy of the agreed total
        # — the SDC shape where exactly one rank walks away with a
        # different result (a pre-reduce flip would spread identically
        # to every rank and diverge nothing).
        action = maybe_fail("grad_ready", step=self._seq, rank=self.rank,
                            name=name)
        if action in ("flip_bits", "nan_inject"):
            total = corrupt_grad(total, action, rank=self.rank,
                                 step=self._seq, name=name)
        # Progress beat source for the elastic path: the collective
        # completed with every member's contribution in hand.
        obs_flightrec.record(
            "complete", name=name, cycle=self._seq, detail="kv_allreduce",
        )
        obs_progress.tick()
        get_registry().counter("elastic.kv_collectives").inc()
        return total

    def sync_state(self, blob: bytes, commit_count: int) -> bytes:
        """Elect the state owner for this epoch — highest commit count,
        lowest rank on ties — and broadcast its serialized snapshot.
        A freshly respawned rank (commit count 0) therefore always
        adopts a survivor's state, and a full fresh start converges on
        rank 0's initial values."""
        obs_flightrec.record(
            "sync_state", name=f"epoch{self.epoch}", cycle=self.epoch,
            detail=f"commits={int(commit_count)}",
        )
        scope = _epoch_scope(self.epoch)
        self.kv.put(scope, f"have_{self.rank}",
                    pickle.dumps(int(commit_count)))
        deadline = time.monotonic() + self.timeout
        counts = {}
        with obs_progress.waiting():  # checked in; blocked on peers
            for r in self.world:
                raw = self._fetch(scope, f"have_{r}", deadline,
                                  what=f"commit count from rank {r}")
                counts[r] = pickle.loads(raw)
            owner = max(self.world, key=lambda r: (counts[r], -r))
            if owner == self.rank:
                self.kv.put(scope, "state", blob)
            out = self._fetch(scope, "state", deadline,
                              what=f"state from owner rank {owner}")
        # Epoch-start sync is a completed collective (liveness), but NOT
        # steady state: the user's first step — and its possibly very
        # long jit compile — has not started yet, and snapping to steady
        # here would hand the steady budget to that compile.
        obs_progress.tick(to_steady=False)
        return out

    # -- plumbing ---------------------------------------------------------

    def _fetch(self, scope: str, key: str, deadline: float, *,
               what: str, epoch: Optional[int] = -1) -> bytes:
        """Poll one key; fail with HorovodShutdownError on epoch bump
        (unless ``epoch=None`` disables the check — the rendezvous loop
        handles bumps itself) or deadline."""
        watch_epoch = self.epoch if epoch == -1 else epoch
        while True:
            raw = self.kv.get(scope, key)
            if raw is not None:
                return raw
            if watch_epoch is not None:
                current = self.current_epoch()
                if current > watch_epoch:
                    raise HorovodShutdownError(
                        f"world re-formed (epoch {watch_epoch} -> "
                        f"{current}) while waiting for {what}"
                    )
            if time.monotonic() > deadline:
                raise HorovodShutdownError(
                    f"timed out waiting for {what} — a peer likely died "
                    f"without the launcher re-forming the world yet"
                )
            time.sleep(_POLL_SECS)


class LocalContext:
    """Degenerate single-process world so ``elastic.run`` / ``State``
    work (and unit-test) without a launcher: collectives are identity,
    rendezvous is a no-op, the fault-injection points still fire."""

    def __init__(self):
        self.rank = 0
        self.size = 1
        self.epoch = 0
        self.world: Sequence[int] = (0,)
        self._seq = 0

    def start_heartbeat(self) -> None:
        pass

    def stop_heartbeat(self) -> None:
        pass

    def current_epoch(self) -> int:
        return self.epoch

    def world_changed(self) -> bool:
        return False

    def rendezvous(self, timeout: Optional[float] = None) -> int:
        return self.epoch

    def notify_world_broken(self) -> None:
        pass

    def allreduce(self, value, name: Optional[str] = None, *,
                  average: bool = True) -> np.ndarray:
        self._seq += 1
        maybe_fail("worker_exit", step=self._seq, rank=self.rank)
        obs_progress.tick()
        return np.asarray(value)

    def sync_state(self, blob: bytes, commit_count: int) -> bytes:
        return blob


_current = None
_current_lock = threading.Lock()


def context():
    """The ambient elastic context: built from the launcher env when
    present, a LocalContext otherwise.  Cached per process."""
    global _current
    with _current_lock:
        if _current is None:
            if os.environ.get("HVDTPU_ELASTIC_KV"):
                _current = ElasticContext.from_env()
            else:
                _current = LocalContext()
        return _current


def reset_context() -> None:
    """Drop the cached ambient context (tests, or re-launch in-process)."""
    global _current
    # Detach under the lock, tear down outside it: stop_stream() joins
    # the publisher thread and performs a final network publish, and a
    # concurrent context() call would sit behind that for the whole
    # join (hvdtpu-lint HVDC102).
    with _current_lock:
        ctx, _current = _current, None
    if ctx is not None:
        ctx.stop_heartbeat()
        from ..obs import stream as obs_stream  # noqa: PLC0415

        obs_stream.stop_stream()
