"""Exception taxonomy for elastic (fault-tolerant) training.

Mirrors upstream Elastic Horovod's split (horovod/common/exceptions.py in
the post-0.19 line):

* :class:`HorovodShutdownError` — a collective failed because the world
  broke underneath it: a peer died mid-negotiation, the engine was torn
  down by the coordinated-shutdown flag, or a rendezvous wait timed out.
  ``elastic.run`` treats it as *recoverable*: roll state back to the last
  commit, re-rendezvous, resume (upstream: HorovodInternalError).
* :class:`WorkersAvailableException` — the launcher re-minted the
  rendezvous epoch (failed rank respawned, or the world shrank/grew)
  while this rank was between collectives.  Also recoverable; raised at
  commit boundaries so ranks notice membership changes promptly
  (upstream: HostsUpdatedInterrupt).
* :class:`RankDroppedError` — the launcher shrank the world past this
  rank (it was presumed dead and its slot was dropped for good).  NOT
  recoverable: there is no world for this rank to rejoin, so
  ``elastic.run`` lets it propagate instead of burning the retry budget.

All subclass ``RuntimeError`` so pre-elastic call sites that assert on
``RuntimeError`` keep working unchanged.

This module is a true leaf ON PURPOSE: the engine (runtime layer), the
checkpoint layer, and the elastic user API all import from it, and any
heavier import here would both create cycles and drag the launcher
stack into every ``import horovod_tpu``.  ``elastic.exceptions``
re-exports these names for API symmetry, but runtime-layer code should
import from here so it never executes ``elastic/__init__``.
"""

from __future__ import annotations

__all__ = [
    "HorovodShutdownError",
    "RankDroppedError",
    "WorkersAvailableException",
]


class HorovodShutdownError(RuntimeError):
    """A collective or rendezvous failed because the world broke: peer
    death, coordinated engine shutdown, or a stalled wait.  Recoverable
    under ``elastic.run`` (rollback to last commit + re-rendezvous)."""


class RankDroppedError(HorovodShutdownError):
    """This rank is no longer a member of the current world — the
    launcher shrank past it.  Not recoverable: ``elastic.run`` re-raises
    instead of retrying a rendezvous that can never succeed."""


class WorkersAvailableException(RuntimeError):
    """The launcher advanced the rendezvous epoch (a failed rank was
    respawned or the world was re-formed); the current world is stale.
    Recoverable under ``elastic.run`` (re-rendezvous + state sync)."""
