"""Test-support machinery shipped with the package (not under tests/):
the deterministic fault-injection registry lives here because its
injection points are compiled into production code paths (checkpoint
writes, engine enqueue, worker entry) and must be importable wherever
those run."""

from . import faults  # noqa: F401
