"""Deterministic, env-driven fault injection.

Chaos testing the elastic subsystem needs real failures — a rank dying
mid-step, a checkpoint write erroring on rank 0 — that are *reproducible*
under ``JAX_PLATFORMS=cpu`` in tier-1.  This registry provides them: the
launcher (or a test) sets ``HVDTPU_FAULT_SPEC`` and the injection points
threaded through production code fire exactly where the spec says, every
run, no timing dependence.

Grammar::

    HVDTPU_FAULT_SPEC := fault ("," fault)*
    fault             := point (":" key "=" value)*
    key               := rank | step | epoch | count | action | code | name

    HVDTPU_FAULT_SPEC="ckpt_write:step=3:rank=0,worker_exit:step=5:rank=2"

* ``point`` — the injection-site name.  Sites wired so far:
  ``ckpt_write`` (checkpoint.py rank-0 write), ``enqueue`` (eager-engine
  enqueue path), ``worker_exit`` (elastic context, once per collective;
  also run/task_fn.py at function start), ``task_fn`` (run/task_fn.py
  before the user function runs), ``shard_write`` (ckpt/sharded.py
  per-rank shard write), ``replica_push`` (ckpt/replica.py peer-replica
  push after each commit), ``trace_flush`` (obs/trace.py span-dump
  path), ``mem_alloc`` (obs/memplane.py alloc_guard on the serve
  decode/prefill paths), ``grad_ready`` (the reduced-gradient landing
  sites — ops/eager.py's blocking allreduce after synchronize, and
  elastic/context.py's KV allreduce after the total is computed; fired
  AFTER the reduction so a corruption lands on one rank's copy of the
  *agreed* result, the silent-data-corruption shape the divergence
  sentinel exists to catch — corrupting before the reduce would spread
  identically to every rank and diverge nothing), ``campaign_point``
  (bench/campaign.py, between one sweep point's journal commit and the
  next point's launch).
* ``rank`` — only fire on this rank (resolved from the ``rank=`` call
  argument, else ``HVDTPU_RANK``, else ``HVDTPU_ELASTIC_RANK``).  Absent
  means any rank.
* ``step`` — fire when the observed step equals N.  Call sites with a
  natural step (checkpoint saves) pass it explicitly; sites without one
  (enqueue) use the per-point 1-based invocation counter.  Absent means
  the first eligible call.
* ``epoch`` — the rendezvous epoch to fire in, default 0 (``any`` to
  disable the filter).  The default is what keeps chaos runs convergent:
  a respawned worker re-executes the same steps at epoch >= 1 and must
  NOT re-trigger the fault that killed its predecessor.
* ``count`` — times to fire (default 1).
* ``action`` — ``raise`` (default) raises :class:`InjectedFault`;
  ``raise:<ExcName>`` raises that builtin exception instead (e.g.
  ``raise:ValueError``) — the deterministic driver for the excepthook
  dump path; ``exit`` calls ``os._exit(code)``; ``abort`` delivers
  SIGABRT to this process via ``signal.raise_signal`` (no Python
  cleanup, no atexit — but the flight recorder's fatal-signal handler
  still runs, which is exactly the death the signal-dump path is
  chaos-tested against); ``hang`` blocks the calling thread
  forever (daemon threads — heartbeats — keep running: the exact
  signature of a deadlocked training thread, which is what the
  progress-beat staleness policy exists to catch);
  ``delay:<ms>`` sleeps the calling thread for that many milliseconds
  and then CONTINUES (default 1000) — a deterministic straggler, the
  chaos input the live telemetry plane's attribution is tested against;
  ``corrupt_write`` instructs the call site to flip bytes in the data it
  is about to write (the site receives the action name back from
  :func:`maybe_fail` and applies :func:`corrupt_bytes` — a deterministic
  torn/corrupted shard, the chaos input checksum validation is tested
  against); ``drop_replica`` instructs the call site to suppress the
  write entirely (the peer-replica push path — a deterministically
  stale replica); ``trace_drop`` instructs the span-flush path
  (obs/trace.py, point ``trace_flush``) to suppress the next span dump
  on a rank — the deterministic missing-rank input trace-merge's
  degraded handling is chaos-tested against; ``swap_abort`` instructs
  the weight hot-swap path (serve/service.py, point ``swap_commit`` —
  fired after shard prefetch succeeded, before the version flip is
  applied) to ``os._exit`` the rank — the deterministic mid-swap death
  the single-version convergence gate is chaos-tested against;
  ``scale_fail`` instructs the launcher's autoscale grow path (point
  ``scale_admit``) to treat the standby host as refusing admission —
  the deterministic failed-grow input the exponential-backoff policy
  is chaos-tested against; ``oom`` instructs an allocation-heavy call
  site (point ``mem_alloc``, consumed through
  ``obs.memplane.alloc_guard``) to raise a backend-shaped
  RESOURCE_EXHAUSTED — the deterministic out-of-device-memory input
  the OOM black box (``mem.oom`` flight-recorder event + post-mortem
  memory verdict) is chaos-tested against; ``frontend_exit`` instructs
  a front-door ingest pump (serve/frontend.py, point ``frontend_beat``
  — fired at the top of each pump round, with the pump's frontend id
  as the rank and its beat counter as the step) to die abruptly
  mid-stream without draining — the deterministic frontend death the
  heartbeat-takeover chaos gate is tested against; ``flip_bits``
  instructs a ``grad_ready`` site to XOR one exponent bit of one
  element of the reduced gradient it is about to hand back (element
  chosen by ``crc32(rank:step:name)`` — deterministic per rank, step
  and tensor, finite-in/finite-out, the canonical SDC bit flip);
  ``nan_inject`` instructs the same site to overwrite that element
  with NaN (the nonfinite-provenance chaos input).  Both are applied
  by the site via :func:`corrupt_grad`.  ``degrade`` instructs the
  campaign driver (bench/campaign.py, point ``campaign_point`` — fired
  between the previous point's journal commit and the next point's
  launch, with the 1-based point index as the step) to force that
  point down the degraded-record path without running it — the
  deterministic mid-sweep failure the resume/retry machinery is
  chaos-tested against; the generic ``abort`` at the same point is
  the "campaign dies between points" input the CI resume gate seeds.
  ``worker_exit``/``task_fn`` points default to ``exit``.
* ``code`` — exit code for ``action=exit`` (default 43, distinguishable
  from real crashes in launcher traces).
* ``name`` — only fire when the call site passes a matching ``name=``
  (e.g. a tensor name on the enqueue path).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional

__all__ = ["InjectedFault", "maybe_fail", "corrupt_bytes", "corrupt_grad",
           "parse_spec", "reset", "active", "point_count"]

SPEC_ENV = "HVDTPU_FAULT_SPEC"
DEFAULT_EXIT_CODE = 43
_EXIT_POINTS = ("worker_exit", "task_fn")
# Advisory actions only take effect at call sites that consume
# maybe_fail's return value; parse-time validation keeps a spec like
# "ckpt_write:action=corrupt_write" from "firing" as a silent no-op —
# a chaos test built on it would pass vacuously.
_ADVISORY_POINTS = {
    "corrupt_write": ("shard_write",),
    "drop_replica": ("replica_push",),
    "trace_drop": ("trace_flush",),
    "swap_abort": ("swap_commit",),
    "scale_fail": ("scale_admit",),
    "oom": ("mem_alloc",),
    "frontend_exit": ("frontend_beat",),
    "flip_bits": ("grad_ready",),
    "nan_inject": ("grad_ready",),
    "degrade": ("campaign_point",),
}


class InjectedFault(RuntimeError):
    """Raised by a fired ``action=raise`` fault; carries the site name."""

    def __init__(self, point: str, detail: str):
        super().__init__(
            f"injected fault at {point!r} ({detail}) — HVDTPU_FAULT_SPEC"
        )
        self.point = point


@dataclass
class FaultSpec:
    point: str
    rank: Optional[int] = None
    step: Optional[int] = None
    epoch: Optional[int] = 0
    count: int = 1
    action: str = "raise"
    code: int = DEFAULT_EXIT_CODE
    delay_ms: int = 1000
    exc_name: Optional[str] = None
    name: Optional[str] = None
    fired: int = field(default=0, compare=False)

    def describe(self) -> str:
        parts = [self.point]
        for k in ("rank", "step", "epoch", "count", "name"):
            v = getattr(self, k)
            if v is not None:
                parts.append(f"{k}={v}")
        return ":".join(parts)


def parse_spec(raw: str) -> List[FaultSpec]:
    """Parse a spec string; raises ``ValueError`` on malformed entries so
    a typo'd spec fails the run loudly instead of silently never firing."""
    specs: List[FaultSpec] = []
    for chunk in raw.split(","):
        chunk = chunk.strip()
        if not chunk:
            continue
        fields = chunk.split(":")
        point = fields[0].strip()
        if not point:
            raise ValueError(f"fault spec entry has no point name: {chunk!r}")
        spec = FaultSpec(point=point)
        if point in _EXIT_POINTS:
            spec.action = "exit"
        for kv in fields[1:]:
            if "=" not in kv:
                # ``action=delay:<ms>`` / ``action=raise:<ExcName>``:
                # the parameter rides as a bare field right after the
                # action (the grammar's separator is ":", so it can't
                # live in the value).
                if spec.action == "delay" and kv.strip().isdigit():
                    spec.delay_ms = int(kv.strip())
                    continue
                if spec.action == "raise" and kv.strip().isidentifier():
                    exc_name = kv.strip()
                    cls = getattr(__import__("builtins"), exc_name, None)
                    if not (isinstance(cls, type)
                            and issubclass(cls, BaseException)):
                        raise ValueError(
                            f"action=raise:{exc_name}: {exc_name!r} is "
                            f"not a builtin exception"
                        )
                    spec.exc_name = exc_name
                    continue
                raise ValueError(
                    f"fault spec field {kv!r} in {chunk!r} is not key=value"
                )
            key, value = (s.strip() for s in kv.split("=", 1))
            if key in ("rank", "step", "count", "code", "delay_ms"):
                setattr(spec, key, int(value))
            elif key == "epoch":
                spec.epoch = None if value in ("any", "*") else int(value)
            elif key == "action":
                if value not in ("raise", "exit", "abort", "hang", "delay",
                                 "corrupt_write", "drop_replica",
                                 "trace_drop", "swap_abort",
                                 "scale_fail", "oom", "frontend_exit",
                                 "flip_bits", "nan_inject", "degrade"):
                    raise ValueError(f"unknown fault action {value!r}")
                spec.action = value
            elif key == "name":
                spec.name = value
            else:
                raise ValueError(
                    f"unknown fault spec key {key!r} in {chunk!r}"
                )
        allowed = _ADVISORY_POINTS.get(spec.action)
        if allowed is not None and spec.point not in allowed:
            raise ValueError(
                f"action={spec.action} is only implemented at "
                f"{'/'.join(allowed)}, not at point {spec.point!r} — "
                f"it would fire as a silent no-op there"
            )
        specs.append(spec)
    return specs


# Parsed cache, keyed by the raw env string so a test that monkeypatches
# the env (or calls reset()) gets a fresh registry.
_cache_raw: Optional[str] = None
_specs: Dict[str, List[FaultSpec]] = {}
_counters: Dict[str, int] = {}


def reset() -> None:
    """Drop the parsed registry and per-point counters (tests)."""
    global _cache_raw
    _cache_raw = None
    _specs.clear()
    _counters.clear()


def _load() -> Dict[str, List[FaultSpec]]:
    global _cache_raw
    raw = os.environ.get(SPEC_ENV, "")
    if raw != _cache_raw:
        _specs.clear()
        _counters.clear()
        for spec in parse_spec(raw):
            _specs.setdefault(spec.point, []).append(spec)
        _cache_raw = raw
    return _specs


def active() -> bool:
    """True when any fault spec is loaded (cheap hot-path gate)."""
    return bool(_load())


def point_count(point: str) -> int:
    """Current value of a point's 1-based invocation counter (0 before
    the first visit) — lets an advisory site key deterministic payload
    corruption (:func:`corrupt_grad`) on the same step number
    :func:`maybe_fail` just matched."""
    return _counters.get(point, 0)


def _resolve_rank(rank: Optional[int]) -> Optional[int]:
    if rank is not None:
        return rank
    from ..utils.env import resolve_rank  # noqa: PLC0415

    return resolve_rank(None)


def _resolve_epoch() -> int:
    value = os.environ.get("HVDTPU_ELASTIC_EPOCH")
    return int(value) if value not in (None, "") else 0


def corrupt_bytes(data: bytes) -> bytes:
    """Deterministically damage ``data`` (first/middle/last byte flipped)
    — the payload an ``action=corrupt_write`` call site writes instead of
    the real one, so checksum validation has something real to catch."""
    if not data:
        return data
    buf = bytearray(data)
    for i in (0, len(buf) // 2, len(buf) - 1):
        buf[i] ^= 0xFF
    return bytes(buf)


def corrupt_grad(arr, action: str, *, rank: int = 0, step: int = 0,
                 name: Optional[str] = None):
    """Apply a fired ``grad_ready`` advisory action to a reduced
    gradient: damage exactly ONE element, chosen deterministically by
    ``crc32(rank:step:name)`` so a chaos assertion can name the exact
    bucket/tensor it expects to see diverge.

    ``flip_bits`` XORs 0x40 into the element's most-significant byte —
    for floats that is a single exponent-bit flip (the canonical SDC:
    a large, *finite* magnitude change that value-level sanity checks
    miss but a bitwise digest cannot); ``nan_inject`` overwrites the
    element with NaN (integer dtypes fall back to the bit flip).
    Returns a same-dtype copy; the input is never mutated.
    """
    import zlib  # noqa: PLC0415

    import numpy as np  # noqa: PLC0415

    a = np.array(arr, copy=True)
    if a.size == 0:
        return a
    key = f"{rank}:{step}:{name or ''}".encode()
    # CRC32 is linear over GF(2): a one-character key change (e.g. the
    # rank digit) XORs a fixed delta whose low bits can be all-zero, so
    # ``crc % power_of_two_size`` would hit the same slot for every
    # rank.  Avalanche the high bits down before reducing.
    h = zlib.crc32(key)
    h ^= h >> 16
    h = (h * 0x45D9F3B) & 0xFFFFFFFF
    h ^= h >> 16
    pos = h % a.size
    if action == "nan_inject" and np.issubdtype(a.dtype, np.floating):
        a.reshape(-1)[pos] = np.nan
        return a
    if action not in ("flip_bits", "nan_inject"):
        raise ValueError(f"corrupt_grad does not implement {action!r}")
    raw = a.view(np.uint8).reshape(a.size, a.dtype.itemsize)
    # Little-endian: the last byte of each element is the most
    # significant — sign + high exponent bits for IEEE floats.
    raw[pos, -1] ^= 0x40
    return a


def maybe_fail(
    point: str,
    *,
    step: Optional[int] = None,
    rank: Optional[int] = None,
    name: Optional[str] = None,
) -> Optional[str]:
    """Fire any matching fault for ``point``; no-op when none match.

    ``step=None`` uses the per-point invocation counter (1-based) — the
    counter advances on every call whether or not a fault fires, so
    ``step=N`` deterministically means "the Nth visit to this point".

    Returns the fired action name for the *advisory* actions the call
    site must apply itself (``corrupt_write``, ``drop_replica``,
    ``trace_drop``, ``swap_abort``, ``scale_fail``, ``oom``) and
    ``None`` otherwise — existing callers that ignore the return value
    keep their exact semantics.
    """
    specs = _load().get(point)
    counter = None
    if specs is not None or point in _counters:
        counter = _counters[point] = _counters.get(point, 0) + 1
    if not specs:
        return None
    observed_step = step if step is not None else counter
    observed_rank = _resolve_rank(rank)
    observed_epoch = _resolve_epoch()
    for spec in specs:
        if spec.fired >= spec.count:
            continue
        if spec.rank is not None and spec.rank != observed_rank:
            continue
        if spec.step is not None and spec.step != observed_step:
            continue
        if spec.epoch is not None and spec.epoch != observed_epoch:
            continue
        if spec.name is not None and spec.name != name:
            continue
        spec.fired += 1
        # Black-box the injection itself: a chaos run's post-mortem must
        # show the fault firing as an event, not leave the analyzer to
        # infer it from the wreckage.
        from ..obs import flightrec  # noqa: PLC0415

        flightrec.record(
            "fault", name=point,
            detail=f"{spec.action}:{spec.describe()}",
        )
        if spec.action in ("corrupt_write", "drop_replica", "trace_drop",
                           "swap_abort", "scale_fail", "oom",
                           "frontend_exit", "flip_bits", "nan_inject",
                           "degrade"):
            # Advisory actions: the call site owns the I/O, so the
            # registry can only instruct it — corrupt the payload it is
            # about to write, or skip the push entirely.
            return spec.action
        if spec.action == "delay":
            # A deterministic straggler: stall the calling thread, then
            # proceed normally — the collective completes late, which is
            # exactly the skew signature straggler attribution must name.
            import time  # noqa: PLC0415

            time.sleep(spec.delay_ms / 1000.0)
            return None
        if spec.action == "exit":
            # os._exit, not sys.exit: the injected death must look like a
            # hard crash (no atexit, no finally blocks posting results).
            os._exit(spec.code)
        if spec.action == "abort":
            # raise_signal (not os.abort): os.abort bypasses Python
            # signal handlers, which would defeat the very dump path
            # this action exists to chaos-test.  With the flight
            # recorder's handler installed the rank dumps its ring,
            # then dies by real SIGABRT (no atexit, no finally blocks);
            # without it, it is a plain abort.
            import signal  # noqa: PLC0415

            signal.raise_signal(signal.SIGABRT)
        if spec.action == "hang":
            # Deadlock the CALLING thread only: daemon threads (the KV
            # heartbeat) keep beating, so the process looks alive while
            # its training thread is wedged — reproducing the failure
            # mode the collective-path progress beat detects.  The
            # process dies by external SIGTERM/SIGKILL.
            import threading  # noqa: PLC0415

            while True:
                threading.Event().wait(3600)
        if spec.exc_name is not None:
            cls = getattr(__import__("builtins"), spec.exc_name)
            raise cls(
                f"injected fault at {point!r} ({spec.describe()}) — "
                f"HVDTPU_FAULT_SPEC"
            )
        raise InjectedFault(point, spec.describe())
