"""ctypes binding to the native eager engine (cpp/hvdtpu -> libhvdtpu.so).

Reference: horovod/common/basics.py loads the built shared library and wraps
its ``extern "C"`` surface (operations.cc:661-799); async handles follow
horovod/torch/handle_manager.cc.  This binding presents the *same Python
interface* as the pure-Python :class:`~horovod_tpu.runtime.engine.EagerEngine`
(``enqueue``/``join``/``barrier``/``shutdown`` returning futures), so
``ops/eager.py`` is engine-agnostic; selection happens in
``_engine_registry`` via ``HVDTPU_EAGER_ENGINE`` ∈ {auto, native, python}.

Division of labor: Python performs the address rendezvous (a fixed-width
allgather over the already-initialized coordination service — the analog of
the reference's HTTP-KV gloo rendezvous, gloo_context.cc:113-157) and hands
the C++ engine full ownership of the eager path: TCP mesh, rank-0
negotiation, response cache, fusion, ring/VHDD collectives, timeline, stall
inspection.
"""

from __future__ import annotations

import concurrent.futures
import ctypes
import os
import threading
import time
from pathlib import Path
from typing import Dict, Optional

import numpy as np

from ..basics import global_topology
from ..obs import get_registry
from ..obs import flightrec as obs_flightrec
from ..obs import progress as obs_progress
from ..obs import trace as obs_trace
from ..testing.faults import maybe_fail
from ..utils import env as envmod
from ..utils.logging import get_logger
from . import timeline as timeline_mod
from .messages import RequestType

LOG = get_logger("native")

LIB_PATH = Path(__file__).resolve().parent.parent / "lib" / "libhvdtpu.so"

# DataType enum of cpp/hvdtpu/common.h.
_DTYPES = {
    "uint8": 0,
    "int8": 1,
    "int32": 2,
    "int64": 3,
    "float16": 4,
    "bfloat16": 5,
    "float32": 6,
    "float64": 7,
    "bool": 8,
}


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # noqa: PLC0415

        return np.dtype(getattr(ml_dtypes, name))


def native_available() -> bool:
    return LIB_PATH.exists()


def _load() -> ctypes.CDLL:
    lib = ctypes.CDLL(str(LIB_PATH))
    lib.hvdtpu_listen.restype = ctypes.c_int
    lib.hvdtpu_connect.restype = ctypes.c_int
    lib.hvdtpu_connect.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_char_p, ctypes.c_longlong,
        ctypes.c_double, ctypes.c_int, ctypes.c_double, ctypes.c_double,
        ctypes.c_char_p, ctypes.c_int,
    ]
    lib.hvdtpu_enqueue.restype = ctypes.c_longlong
    lib.hvdtpu_enqueue.argtypes = [
        ctypes.c_int, ctypes.c_char_p, ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_longlong), ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_double, ctypes.c_double,
    ]
    lib.hvdtpu_join.restype = ctypes.c_longlong
    lib.hvdtpu_poll.restype = ctypes.c_int
    lib.hvdtpu_poll.argtypes = [ctypes.c_longlong]
    lib.hvdtpu_wait.restype = ctypes.c_int
    lib.hvdtpu_wait.argtypes = [ctypes.c_longlong]
    lib.hvdtpu_error.restype = ctypes.c_char_p
    lib.hvdtpu_error.argtypes = [ctypes.c_longlong]
    lib.hvdtpu_result_nbytes.restype = ctypes.c_longlong
    lib.hvdtpu_result_nbytes.argtypes = [ctypes.c_longlong]
    lib.hvdtpu_result_ndim.restype = ctypes.c_int
    lib.hvdtpu_result_ndim.argtypes = [ctypes.c_longlong]
    lib.hvdtpu_result_shape.argtypes = [
        ctypes.c_longlong, ctypes.POINTER(ctypes.c_longlong)
    ]
    lib.hvdtpu_result_copy.restype = ctypes.c_int
    lib.hvdtpu_result_copy.argtypes = [ctypes.c_longlong, ctypes.c_void_p]
    lib.hvdtpu_release.argtypes = [ctypes.c_longlong]
    lib.hvdtpu_is_shutdown.restype = ctypes.c_int
    lib.hvdtpu_set_params.argtypes = [
        ctypes.c_longlong, ctypes.c_double, ctypes.c_int
    ]
    lib.hvdtpu_perf_bytes.restype = ctypes.c_longlong
    lib.hvdtpu_get_fusion_bytes.restype = ctypes.c_longlong
    lib.hvdtpu_get_cycle_ms.restype = ctypes.c_double
    # fault injection (tests): rank-local cache gate flip — see engine.cc
    lib.hvdtpu_inject_local_cache_enabled.argtypes = [ctypes.c_int]
    return lib


def _my_ip() -> str:
    """Routable address of this host (the TCP mesh spans hosts)."""
    host = os.environ.get("HVDTPU_MESH_ADDR")
    if host:
        return host
    from ..run.allocate import routable_ip  # noqa: PLC0415

    coordinator = os.environ.get("HVDTPU_COORDINATOR", "")
    probe = coordinator.rsplit(":", 1)[0] if coordinator else "127.0.0.1"
    return routable_ip(probe)


class NativeEngine:
    """Eager engine backed by libhvdtpu.so (drop-in for EagerEngine)."""

    # The TCP data plane moves host bytes; jax.Arrays are ingested as
    # zero-copy dlpack views (ops/eager.py _ingest) and results committed
    # back to the caller's device by synchronize().
    accepts_device_arrays = False

    def __init__(self):
        topo = global_topology()
        self.rank = topo.process_rank
        self.world = topo.process_count
        self.lib = _load()
        # Observability plane: the engine-cycle internals live in C++,
        # but completed collectives are resolved here — counting them
        # here keeps the metrics dump and the progress beat engine-
        # agnostic (and first registry use arms the exit dump).
        self._m_completed = get_registry().counter(
            "engine.collectives_completed"
        )

        # The hierarchical knob has no consumer in the native TCP data
        # plane; say so instead of silently ignoring it (the python
        # engine's XLA plane is the one that can run the two-fabric
        # schedule — HVDTPU_EAGER_ENGINE=python).
        if envmod.env_bool(envmod.HIERARCHICAL_ALLREDUCE):
            LOG.warning(
                "hierarchical allreduce requested but the native TCP "
                "data plane has no two-fabric schedule; downgrading to "
                "flat (use HVDTPU_EAGER_ENGINE=python for the slice-aware "
                "XLA path)"
            )

        port = self.lib.hvdtpu_listen()
        if port < 0:
            raise RuntimeError("native engine: listen failed")

        addrs = self._exchange_addrs(f"{_my_ip()}:{port}")

        fusion = envmod.env_int(
            envmod.FUSION_THRESHOLD, envmod.DEFAULT_FUSION_BYTES
        )
        cycle_ms = envmod.env_float(envmod.CYCLE_TIME, 5.0)
        cache_cap = envmod.env_int(envmod.CACHE_CAPACITY, 1024)
        stall_warn = envmod.env_float(envmod.STALL_CHECK_TIME, 60.0)
        stall_shutdown = envmod.env_float(envmod.STALL_SHUTDOWN_TIME, 0.0)
        if envmod.env_bool(envmod.STALL_CHECK_DISABLE):
            stall_warn = 1e18
        # Every rank records its own per-rank file (the C++ writer stamps
        # pid=rank); the launcher merges them at job end into one trace
        # with a lane per rank (obs/timeline_merge.py).
        raw_timeline = os.environ.get(envmod.TIMELINE, "")
        timeline_path = (
            timeline_mod.resolve_path(raw_timeline, self.rank)
            if raw_timeline else ""
        )
        mark_cycles = 1 if envmod.env_bool(envmod.TIMELINE_MARK_CYCLES) else 0

        rc = self.lib.hvdtpu_connect(
            self.rank, self.world, ",".join(addrs).encode(), fusion,
            cycle_ms, cache_cap, stall_warn, stall_shutdown,
            timeline_path.encode(), mark_cycles,
        )
        if rc != 0:
            raise RuntimeError(f"native engine: mesh connect failed (rc={rc})")

        self._lock = threading.Lock()
        self._outstanding: Dict[int, tuple] = {}  # handle -> (future, dtype, name)
        self._pump_wake = threading.Event()
        self._stop = False
        self._barrier_seq = 0
        self._pump = threading.Thread(
            target=self._pump_loop, name="hvdtpu_native_pump", daemon=True
        )
        self._pump.start()

        # Autotune (reference parameter_manager.cc): rank 0 runs the GP
        # tuner against the engine's bytes/sec counter; proposals go down
        # through hvdtpu_set_params and ride the negotiation to every rank.
        self._tuner: Optional[threading.Thread] = None
        if self.rank == 0 and envmod.env_bool(envmod.AUTOTUNE):
            from .autotune import (  # noqa: PLC0415
                ParameterManager,
                TunedParams,
                build_categories,
            )

            self._pm = ParameterManager(
                enabled=True,
                initial=TunedParams(
                    fusion_bytes=fusion, cycle_s=cycle_ms / 1000.0
                ),
                log_path=os.environ.get(envmod.AUTOTUNE_LOG) or None,
                # Shared topology-derived chain (autotune.build_categories):
                # the native engine consumes fusion/cycle (continuous) and
                # the response-cache toggle (categorical); its TCP data
                # plane has no two-fabric schedule, so hierarchical is
                # never explored regardless of topology
                # (hierarchical_capable=False), and it has no schedule
                # replay, so the cache-off category stays.
                categories=build_categories(
                    multislice=topo.num_slices > 1,
                    replay_enabled=False,
                    hierarchical_capable=False,
                ),
            )
            self._tuner = threading.Thread(
                target=self._tuner_loop, name="hvdtpu_autotune", daemon=True
            )
            self._tuner.start()

    # --------------------------------------------------------- rendezvous

    def _exchange_addrs(self, mine: str) -> list:
        """Fixed-width allgather of "ip:port" over the coordination service
        (the native analog of gloo's HTTP-KV rendezvous)."""
        from jax.experimental import multihost_utils  # noqa: PLC0415

        buf = np.zeros(64, np.uint8)
        raw = mine.encode()
        if len(raw) > 64:
            raise ValueError(f"address too long: {mine}")
        buf[: len(raw)] = np.frombuffer(raw, np.uint8)
        gathered = np.asarray(
            multihost_utils.process_allgather(buf)
        ).reshape(self.world, 64)
        return [
            bytes(gathered[r]).rstrip(b"\x00").decode()
            for r in range(self.world)
        ]

    # --------------------------------------------------------------- API

    def enqueue(
        self,
        op: RequestType,
        name: str,
        tensor: Optional[np.ndarray],
        *,
        reduce_op: int = 0,
        root_rank: int = -1,
        prescale: float = 1.0,
        postscale: float = 1.0,
    ) -> concurrent.futures.Future:
        # Same chaos point and black-box event as the python engine's
        # enqueue — fault specs and post-mortems must not care which
        # engine a job ran on.
        maybe_fail("enqueue", name=name)
        obs_flightrec.record("enqueue", name=name, detail=op.name)
        if tensor is not None:
            # np.ascontiguousarray silently promotes 0-d scalars to shape
            # (1,), which would bypass the controller's scalar validation;
            # np.asarray preserves 0-d (and is already contiguous then).
            arr = np.asarray(tensor)
            if not arr.flags["C_CONTIGUOUS"]:
                arr = np.ascontiguousarray(arr)
            dtype_name = str(arr.dtype)
            shape = arr.shape
            data_ptr = arr.ctypes.data_as(ctypes.c_void_p)
        else:
            arr = None
            dtype_name = "float32"
            shape = ()
            data_ptr = None
        code = _DTYPES.get(dtype_name)
        fut: concurrent.futures.Future = concurrent.futures.Future()
        if code is None:
            fut.set_exception(
                TypeError(f"unsupported dtype for eager collectives: {dtype_name}")
            )
            return fut
        shape_arr = (ctypes.c_longlong * max(len(shape), 1))(*shape)
        handle = self.lib.hvdtpu_enqueue(
            int(op), name.encode(), data_ptr, shape_arr, len(shape), code,
            int(reduce_op), int(root_rank), float(prescale), float(postscale),
        )
        with self._lock:
            # Enqueue wall stamp for the trace plane: the C++ engine
            # negotiates internally, so per-op enqueue->completion is
            # the finest span Python can honestly record here (the
            # python engine's negotiate/execute split does not exist at
            # this boundary — same granularity gap PR-3 documented for
            # straggler attribution).
            t0 = time.time() if obs_trace.enabled() else None
            self._outstanding[handle] = (fut, dtype_name, name, t0)
        self._pump_wake.set()
        return fut

    def join(self) -> concurrent.futures.Future:
        fut: concurrent.futures.Future = concurrent.futures.Future()
        handle = self.lib.hvdtpu_join()
        with self._lock:
            self._outstanding[handle] = (fut, None, "join", None)
        self._pump_wake.set()
        return fut

    def barrier(self) -> concurrent.futures.Future:
        # Sequence-numbered: overlapping barriers queue instead of hitting
        # the duplicate-name guard; the Nth call on every rank pairs up.
        with self._lock:
            self._barrier_seq += 1
            seq = self._barrier_seq
        return self.enqueue(RequestType.BARRIER, f"hvdtpu.barrier.{seq}", None)

    # ----------------------------------------------------------- autotune

    def _tuner_loop(self) -> None:
        """Rank-0 scoring loop: one tick per engine cycle's worth of wall
        clock; scores the perf-bytes delta and pushes tuner moves into the
        engine (reference parameter_manager.cc Update/Tune cadence)."""
        last_bytes = 0
        while not self._stop and not self.lib.hvdtpu_is_shutdown():
            time.sleep(max(self.lib.hvdtpu_get_cycle_ms() / 1000.0, 0.001))
            now_bytes = self.lib.hvdtpu_perf_bytes()
            self._pm.record_bytes(now_bytes - last_bytes)
            last_bytes = now_bytes
            proposal = self._pm.cycle()
            if proposal is not None:
                self.lib.hvdtpu_set_params(
                    proposal.fusion_bytes,
                    proposal.cycle_s * 1000.0,
                    1 if proposal.cache_enabled else 0,
                )
            if self._pm.converged:
                return

    def shutdown(self) -> None:
        self._stop = True
        self._pump_wake.set()
        self.lib.hvdtpu_shutdown()
        if self._pump.is_alive() and threading.current_thread() is not self._pump:
            self._pump.join(timeout=10)

    # --------------------------------------------------------------- pump

    def _pump_loop(self) -> None:
        """Resolve futures as native handles complete.  One waiter thread
        for all handles (the reference resolves through per-op callbacks;
        ctypes callbacks from a C++ thread are brittle under interpreter
        shutdown, polling from a Python-owned thread is not)."""
        while True:
            with self._lock:
                items = list(self._outstanding.items())
            if not items:
                if self._stop:
                    return
                self._pump_wake.wait(timeout=0.05)
                self._pump_wake.clear()
                continue
            progressed = False
            for handle, (fut, dtype_name, name, t_enq) in items:
                st = self.lib.hvdtpu_poll(handle)
                if st == 0:
                    continue
                progressed = True
                with self._lock:
                    self._outstanding.pop(handle, None)
                if st == 1:
                    if dtype_name is None:  # join
                        fut.set_result(self.world - 1)
                    else:
                        fut.set_result(self._fetch_result(handle, dtype_name))
                        # Progress-beat + metrics + black-box source,
                        # same semantics as the python engine's
                        # _perform_operation.
                        obs_flightrec.record("complete", name=name)
                        self._m_completed.inc()
                        obs_progress.tick()
                        if t_enq is not None:
                            obs_trace.add_span("engine", "collective",
                                               t_enq, time.time(),
                                               op=name)
                else:
                    msg = self.lib.hvdtpu_error(handle).decode()
                    obs_flightrec.record("error", name=name,
                                         detail=msg[:200])
                    exc: Exception
                    if "same name as another tensor" in msg:
                        exc = ValueError(msg)
                    else:
                        exc = RuntimeError(msg)
                    fut.set_exception(exc)
                self.lib.hvdtpu_release(handle)
            if not progressed:
                time.sleep(0.001)

    def _fetch_result(self, handle: int, dtype_name: str):
        nbytes = self.lib.hvdtpu_result_nbytes(handle)
        ndim = self.lib.hvdtpu_result_ndim(handle)
        shape_arr = (ctypes.c_longlong * max(ndim, 1))()
        self.lib.hvdtpu_result_shape(handle, shape_arr)
        shape = tuple(shape_arr[i] for i in range(ndim))
        if nbytes == 0 and not shape:
            return None  # barrier
        out = np.empty(shape, _np_dtype(dtype_name))
        assert out.nbytes == nbytes, (out.nbytes, nbytes, shape, dtype_name)
        self.lib.hvdtpu_result_copy(handle, out.ctypes.data_as(ctypes.c_void_p))
        return out
