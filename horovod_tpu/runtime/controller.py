"""Negotiation logic for the eager path.

Reference: horovod/common/controller.cc — the rank-0 coordinator receives
every rank's ready-tensor Requests, counts per-name readiness
(IncrementTensorCount, controller.cc:789-812), validates consistency and
builds Responses (ConstructResponse, controller.cc:378-611), fuses them
(FuseResponses, controller.cc:640-761), and broadcasts the ResponseList.

TPU redesign: the transport is a symmetric allgather (every rank sees every
rank's RequestList), so **every rank runs the identical, deterministic
controller function** below and arrives at the same ResponseList without a
coordinator broadcast leg.  This halves the control-plane round-trips
(gather+bcast -> one allgather) and removes the rank-0 special case; the
reference already relies on response construction being deterministic, we
just exploit it symmetrically.

The controller state (message table, joined set) persists across cycles in
ControllerState; readiness spans cycles exactly as in the reference (a
tensor submitted by rank 0 in cycle k and rank 1 in cycle k+3 completes in
cycle k+3).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from ..obs import flightrec as obs_flightrec
from ..utils.logging import get_logger
from .messages import Request, RequestList, RequestType, Response, ResponseType

LOG = get_logger("controller")


@dataclass
class _TableEntry:
    """Per-name readiness record (reference MessageTable, controller.h:33)."""

    requests: Dict[int, Request] = field(default_factory=dict)
    first_seen: float = field(default_factory=time.monotonic)
    arrival_order: int = 0
    # rank -> (negotiation cycle index, monotonic time) of its FIRST
    # request for this entry — the raw data straggler attribution reads.
    arrivals: Dict[int, Tuple[int, float]] = field(default_factory=dict)


@dataclass
class ControllerState:
    world_size: int
    message_table: Dict[Tuple, _TableEntry] = field(default_factory=dict)
    joined_ranks: Set[int] = field(default_factory=set)
    shutdown_ranks: Set[int] = field(default_factory=set)
    arrival_counter: int = 0
    # Monotonic negotiation-cycle counter: arrival skew is attributed in
    # cycles (identical on every rank — wall clocks are not), and only
    # ops spanning >1 cycle blame anyone.
    cycle_index: int = 0
    # stall bookkeeping (reference stall_inspector.cc)
    last_stall_check: float = field(default_factory=time.monotonic)


_counter_cache: Optional[Tuple] = None


def _negotiation_counters() -> Tuple:
    """Cached (negotiation_cycles, requests_absorbed) counter handles.
    Re-resolved when the process-global registry is swapped (tests call
    reset_registry); otherwise one dict hit per process lifetime."""
    global _counter_cache
    from ..obs import get_registry  # noqa: PLC0415

    reg = get_registry()
    if _counter_cache is None or _counter_cache[0] is not reg:
        _counter_cache = (
            reg,
            reg.counter("controller.negotiation_cycles"),
            reg.counter("controller.requests_absorbed"),
        )
    return _counter_cache[1], _counter_cache[2]


def _validate(requests: Dict[int, Request]) -> Optional[str]:
    """Consistency checks the reference performs in ConstructResponse
    (controller.cc:378-611): matching dtype, op params, shapes (allreduce:
    identical; allgather: identical all-but-dim0; broadcast: identical +
    same root)."""
    reqs = list(requests.values())
    first = reqs[0]
    if first.request_type == RequestType.ALLGATHER and len(first.shape) == 0:
        return (
            f"Allgather of {first.tensor_name} requires at least a "
            f"1-dimensional tensor (got a scalar)."
        )
    if (first.request_type == RequestType.REDUCESCATTER
            and len(first.shape) == 0):
        return (
            f"Reducescatter of {first.tensor_name} requires at least a "
            f"1-dimensional tensor (got a scalar)."
        )
    for r in reqs[1:]:
        if r.dtype != first.dtype:
            return (
                f"Mismatched data types for {first.tensor_name}: "
                f"rank {first.request_rank} sent {first.dtype}, "
                f"rank {r.request_rank} sent {r.dtype}."
            )
        if r.request_type != first.request_type:
            return (
                f"Mismatched collective operations for {first.tensor_name}."
            )
        if (
            r.reduce_op != first.reduce_op
            or r.prescale_factor != first.prescale_factor
            or r.postscale_factor != first.postscale_factor
        ):
            return f"Mismatched reduce options for {first.tensor_name}."
        if first.request_type in (RequestType.ALLREDUCE, RequestType.ADASUM,
                                  RequestType.BROADCAST, RequestType.ALLTOALL,
                                  RequestType.REDUCESCATTER):
            if tuple(r.shape) != tuple(first.shape):
                return (
                    f"Mismatched shapes for {first.tensor_name}: "
                    f"{tuple(first.shape)} vs {tuple(r.shape)}."
                )
        elif first.request_type == RequestType.ALLGATHER:
            if len(r.shape) == 0:
                return (
                    f"Allgather of {first.tensor_name} requires at least a "
                    f"1-dimensional tensor (got a scalar)."
                )
            if tuple(r.shape[1:]) != tuple(first.shape[1:]):
                return (
                    f"Mismatched allgather shapes beyond dim 0 for "
                    f"{first.tensor_name}."
                )
        if first.request_type == RequestType.BROADCAST:
            if r.root_rank != first.root_rank:
                return (
                    f"Mismatched root ranks for broadcast {first.tensor_name}:"
                    f" {first.root_rank} vs {r.root_rank}."
                )
    return None


def compute_responses(
    state: ControllerState,
    all_lists: List[RequestList],
    *,
    fusion_threshold_bytes: int,
    stall_warning_secs: float = 60.0,
    stall_shutdown_secs: float = 0.0,
    alert_skew_ms: float = 0.0,
    timeline=None,
    cache=None,
) -> Tuple[List[Response], bool]:
    """One negotiation cycle: merge every rank's RequestList into the
    message table, emit ready Responses (fused), handle join/shutdown.

    Returns (responses, should_shutdown).  Deterministic: all ranks call
    with identical inputs and must produce identical outputs — this is the
    invariant the whole eager path rests on (the reference gets it by
    construction from the rank-0 broadcast; we get it from determinism).
    """
    state.cycle_index += 1
    cycle_now = time.monotonic()
    # Launcher-visible negotiation counters: the per-rank metrics dump
    # (and the live /metrics plane) carries how many cycles actually ran
    # the deterministic controller and how many requests it absorbed —
    # the denominator half of the replay fast path's skip-rate story
    # (engine.stats.negotiated_cycles is the engine-side mirror; this
    # one survives even when the engine object is torn down early).
    # Handles resolved once (engine.py's "resolved once, updates are
    # lock-free" convention): this runs on every negotiated cycle.
    m_cycles, m_absorbed = _negotiation_counters()
    m_cycles.inc()
    absorbed = sum(len(rlist.requests) for rlist in all_lists)
    if absorbed:
        m_absorbed.inc(absorbed)
    # Absorb joins & shutdowns first (reference controller.cc:219-221,256-259).
    for rank, rlist in enumerate(all_lists):
        if rlist.shutdown:
            state.shutdown_ranks.add(rank)
        if rlist.joined:
            state.joined_ranks.add(rank)

    for rlist in all_lists:
        for req in rlist.requests:
            if req.request_type == RequestType.JOIN:
                continue  # join is carried by the flag; request is a marker
            entry = state.message_table.get(req.key())
            if entry is None:
                entry = _TableEntry(arrival_order=state.arrival_counter)
                state.arrival_counter += 1
                state.message_table[req.key()] = entry
                if timeline is not None:
                    timeline.negotiate_start(
                        req.tensor_name, req.request_type.name
                    )
            if timeline is not None:
                timeline.negotiate_rank_ready(req.tensor_name, req.request_rank)
            entry.arrivals.setdefault(
                req.request_rank, (state.cycle_index, cycle_now)
            )
            entry.requests[req.request_rank] = req

    needed = state.world_size - len(state.joined_ranks)
    ready: List[Tuple[Tuple, _TableEntry]] = [
        (key, e)
        for key, e in state.message_table.items()
        if len(e.requests) >= needed
    ]
    # Deterministic order: completion order isn't globally defined, so order
    # by first-arrival counter (identical on all ranks since inputs are).
    ready.sort(key=lambda kv: kv[1].arrival_order)

    responses: List[Response] = []
    for key, entry in ready:
        del state.message_table[key]
        name, rtype = key
        # Flight recorder: negotiation completed for this op on this
        # cycle — (cycle, op) is the alignment key the cross-rank
        # post-mortem uses (deterministic controller: identical streams
        # on every rank up to the failure point).
        obs_flightrec.record(
            "negotiate", name=name, cycle=state.cycle_index,
            detail=rtype.name,
        )
        _attribute_straggler(entry, name, alert_skew_ms, timeline)
        err = _validate(entry.requests)
        if timeline is not None:
            timeline.negotiate_end(name, rtype.name)
        if err is not None:
            if cache is not None:
                # a failed renegotiation must not leave a stale entry
                cache.evict_name(name)
            responses.append(
                Response(ResponseType.ERROR, [name], error_message=err)
            )
            continue
        first = next(iter(entry.requests.values()))
        # Device-plane vote (reference Request::device): the response runs
        # as an XLA device collective only when EVERY participating rank's
        # payload is device-resident — any host buffer demotes the op.
        # Deterministic (a pure function of the gathered requests), so all
        # ranks pick the same plane, which is what keeps the collectives
        # matched.
        on_device = all(r.device for r in entry.requests.values())
        if rtype == RequestType.ALLGATHER:
            sizes = [
                entry.requests[r].shape[0] if r in entry.requests else 0
                for r in range(state.world_size)
            ]
            resp = Response(ResponseType.ALLGATHER, [name], tensor_sizes=sizes)
            resp._shapes = [tuple(first.shape)]  # type: ignore[attr-defined]
            resp._dtype = first.dtype  # type: ignore[attr-defined]
            resp._device = on_device  # type: ignore[attr-defined]
            responses.append(resp)
        else:
            resp = Response(ResponseType(int(rtype)), [name])
            # Negotiated shape/dtype so joined ranks can contribute zeros
            # of the right geometry (reference tensor_queue.h:39-41).
            resp._shapes = [tuple(first.shape)]  # type: ignore[attr-defined]
            resp._dtype = first.dtype  # type: ignore[attr-defined]
            resp._root_rank = first.root_rank  # type: ignore[attr-defined]
            resp._device = on_device  # type: ignore[attr-defined]
            if rtype in (RequestType.ALLREDUCE, RequestType.ADASUM,
                         RequestType.REDUCESCATTER):
                # Fusion identity + byte size (reference keeps dtype
                # homogeneous per fusion, controller.cc:676-689).  The
                # execute path also reads this meta for wire dtype/op.
                # Note ADASUM responses still never FUSE — both engines'
                # fuse loops gate on ResponseType.ALLREDUCE — which is
                # deliberate: the reference's fused Adasum computes
                # per-tensor projection coefficients (adasum.h
                # tensor_counts, one "layer" per tensor); a whole-buffer
                # projection over concatenated tensors would change the
                # math, so each Adasum tensor keeps its own exchange here.
                resp._fuse_meta = (  # type: ignore[attr-defined]
                    first.dtype,
                    first.reduce_op,
                    first.prescale_factor,
                    first.postscale_factor,
                )
                try:
                    itemsize = np.dtype(first.dtype).itemsize
                except TypeError:
                    itemsize = 4  # bfloat16 etc. — not a numpy dtype name
                resp._nbytes = (  # type: ignore[attr-defined]
                    int(np.prod(first.shape)) * itemsize if first.shape else itemsize
                )
            if cache is not None:
                # Insert pre-fusion, in construction order — the identical
                # order on every rank is what keeps slot indices coherent
                # (reference response_cache.cc put() from ComputeResponseList).
                cache.insert(first, resp)
            responses.append(resp)

    responses = _fuse(responses, state, fusion_threshold_bytes)

    # Join completion: every rank joined -> JOIN response resets the state
    # (reference controller.cc:300-307).
    if len(state.joined_ranks) == state.world_size and state.world_size > 0:
        responses.append(Response(ResponseType.JOIN, ["join"]))
        state.joined_ranks.clear()

    _check_stalls(state, stall_warning_secs, stall_shutdown_secs)

    should_shutdown = len(state.shutdown_ranks) > 0
    return responses, should_shutdown


def _attribute_straggler(
    entry: _TableEntry, name: str, alert_skew_ms: float, timeline
) -> None:
    """Straggler attribution for one completed negotiation: the rank
    whose request arrived LAST, and the first-to-last arrival skew.

    Attribution fires only when the arrivals spanned more than one
    negotiation cycle — within a single cycle, "last" is an artifact of
    request-list ordering and every op would smear blame randomly.  The
    inputs (cycle indices, absorption order) are identical on every
    rank, so all ranks accumulate the identical attribution — the
    ``--stats-summary`` straggler section and the live digest agree no
    matter whose snapshot they read.  Wall-clock skew is this rank's
    local measurement of the same cycles (sub-cycle noise, cross-rank
    consistent to within a cycle time)."""
    if len(entry.arrivals) < 2:
        return
    items = sorted(
        enumerate(entry.arrivals.items()),
        key=lambda pair: (pair[1][1][0], pair[0]),
    )
    _, (first_rank, (first_cycle, first_t)) = items[0]
    _, (last_rank, (last_cycle, last_t)) = items[-1]
    if last_cycle <= first_cycle:
        return  # same-cycle completion: nobody kept anybody waiting
    from ..obs import straggler as obs_straggler  # noqa: PLC0415

    obs_straggler.record(
        last_rank,
        (last_t - first_t) * 1e3,
        tensor=name,
        timeline=timeline,
        alert_ms=alert_skew_ms,
    )


def _fuse(
    responses: List[Response],
    state: ControllerState,
    threshold: int,
) -> List[Response]:
    """Fuse adjacent same-type ALLREDUCE responses (reference FuseResponses,
    controller.cc:640-761, incl. the same-dtype constraint :676-689).
    Fusion metadata (dtype/size) rides on the per-rank entries at execution
    time, so here we only group names; the engine concats buffers."""
    del state
    fused: List[Response] = []
    pending: Optional[Response] = None
    pending_meta: Optional[Tuple] = None
    pending_bytes = 0

    def flush():
        nonlocal pending, pending_bytes, pending_meta
        if pending is not None:
            fused.append(pending)
        pending, pending_bytes, pending_meta = None, 0, None

    for resp in responses:
        if resp.response_type != ResponseType.ALLREDUCE:
            flush()
            fused.append(resp)
            continue
        # Fusion identity includes the data plane: a device-resident fused
        # buffer can't absorb a host-plane response (and vice versa).
        meta = (
            getattr(resp, "_fuse_meta", None),
            getattr(resp, "_device", False),
        )
        nbytes = getattr(resp, "_nbytes", 0)
        if (
            pending is None
            or pending_meta != meta
            or pending_bytes + nbytes > threshold
        ):
            flush()
            pending = resp
            pending_meta = meta
            pending_bytes = nbytes
        else:
            pending.tensor_names.extend(resp.tensor_names)
            pending._shapes.extend(  # type: ignore[attr-defined]
                resp._shapes  # type: ignore[attr-defined]
            )
            pending_bytes += nbytes
    flush()
    return fused


def _check_stalls(
    state: ControllerState, warn_secs: float, shutdown_secs: float
) -> None:
    """Reference stall_inspector.cc: warn when some ranks have submitted a
    tensor and others haven't for > warn_secs; optionally escalate."""
    now = time.monotonic()
    if now - state.last_stall_check < min(warn_secs, 10.0):
        return
    state.last_stall_check = now
    for (name, _), entry in state.message_table.items():
        age = now - entry.first_seen
        if age > warn_secs:
            missing = sorted(
                set(range(state.world_size))
                - set(entry.requests)
                - state.joined_ranks
            )
            # Aggregatable counterpart of the log line (the reference's
            # stall inspector only logs): the per-tensor counter and the
            # lagging-rank list survive the job via the metrics dump, so
            # "which rank kept everyone waiting" is answerable after the
            # fact instead of by grepping np log streams.
            from ..obs import get_registry  # noqa: PLC0415

            metrics = get_registry()
            metrics.counter("controller.stall_warnings",
                            tensor=name).inc()
            metrics.gauge("controller.stall_lagging_ranks",
                          tensor=name).set(len(missing))
            LOG.warning(
                "One or more tensors were submitted to be reduced/gathered "
                "but some ranks have not yet done so after %.0f s: tensor "
                "%s is waiting on ranks %s",
                age,
                name,
                missing,
            )
            if shutdown_secs > 0 and age > shutdown_secs:
                raise RuntimeError(
                    f"Stalled tensor {name} exceeded shutdown threshold "
                    f"({shutdown_secs}s); aborting (reference "
                    f"HOROVOD_STALL_SHUTDOWN_TIME_SECONDS behavior)."
                )
