"""Response cache for the Python eager engine's steady-state fast path.

Reference: horovod/common/response_cache.cc — an LRU cache of previously
negotiated Responses keyed by tensor name + parameters; once every rank's
queued messages hit the cache, negotiation collapses from exchanging full
serialized RequestLists to a fixed-size **bit-vector vote**
(CacheCoordinator::sync, response_cache.h:107-167).

Coherence model (the whole design hangs on this): cache mutations happen
only from data every rank observes identically — insertions in response-
construction order during slow-path negotiation, LRU touches in cached-
response execution order, evictions on conflicting re-submissions that
every rank sees in the gathered payloads.  All ranks therefore hold
bitwise-identical caches and a slot index means the same tensor
everywhere, which is what makes the armed-bit vote sufficient.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from .messages import Request, RequestType, Response, ResponseType

# lookup() outcomes
MISS = 0    # name unknown -> negotiate, then insert
HIT = 1     # signature matches -> vote the slot bit
CONFLICT = 2  # name cached with DIFFERENT params -> evict + renegotiate


def request_signature(req: Request) -> tuple:
    """Everything that must match for a cached response to be reusable
    (reference response_cache.cc keyed on name + the full request params)."""
    return (
        req.tensor_name,
        int(req.request_type),
        req.dtype,
        tuple(req.shape),
        req.reduce_op,
        req.root_rank,
        req.prescale_factor,
        req.postscale_factor,
        # req.device deliberately EXCLUDED: residency may legitimately
        # differ across ranks (host buffer on one, jax.Array on another),
        # and a rank-varying field in the signature would make mixed
        # submissions permanently thrash HIT/CONFLICT.  The executed plane
        # is the NEGOTIATED one stored on the slot (_Slot.device), identical
        # everywhere.
    )


def cacheable(rtype: RequestType) -> bool:
    """ALLGATHER is excluded: its response depends on per-submission ragged
    dim-0 sizes (Response::tensor_sizes), so a cached copy would be stale
    by construction.  BARRIER/JOIN are control events, not data ops."""
    return rtype in (
        RequestType.ALLREDUCE,
        RequestType.ADASUM,
        RequestType.BROADCAST,
        RequestType.ALLTOALL,
        RequestType.REDUCESCATTER,
    )


@dataclass
class _Slot:
    signature: tuple
    response_type: ResponseType
    tensor_name: str
    shape: Tuple[int, ...]
    dtype: str
    root_rank: int
    fuse_meta: Optional[tuple]
    nbytes: int
    device: bool = False
    lru_tick: int = 0


class ResponseCache:
    """Fixed-capacity slot table; slot index == bit position in the vote."""

    def __init__(self, capacity: int):
        self.capacity = max(int(capacity), 0)
        self._slots: Dict[int, _Slot] = {}
        self._by_name: Dict[str, int] = {}
        self._tick = 0
        # Content-mutation counter: bumped on every insert/evict (NOT on
        # LRU touches, which don't change what a slot means).  All
        # mutations derive from data every rank observes identically, so
        # the counter is bitwise-identical everywhere — which is what
        # lets (mutations, slot list) serve as an exact fingerprint of
        # an executed schedule for the replay fast path.
        self._mutations = 0
        # Slots shielded from LRU eviction this cycle (slots some rank is
        # actively voting on — set by the engine from the gathered bit
        # matrix, which is identical on every rank, keeping eviction
        # decisions coherent).
        self.protected: frozenset = frozenset()

    def __len__(self) -> int:
        return len(self._slots)

    @property
    def num_bits(self) -> int:
        return (self.capacity + 7) // 8

    def lookup(self, req: Request) -> Tuple[int, int]:
        """-> (status, slot).  slot is -1 unless status is HIT/CONFLICT."""
        if self.capacity == 0 or not cacheable(req.request_type):
            return MISS, -1
        slot = self._by_name.get(req.tensor_name)
        if slot is None:
            return MISS, -1
        if self._slots[slot].signature == request_signature(req):
            return HIT, slot
        return CONFLICT, slot

    def touch(self, slot: int) -> None:
        """LRU touch — call in deterministic (execution) order only."""
        self._tick += 1
        self._slots[slot].lru_tick = self._tick

    def evict_name(self, name: str) -> None:
        slot = self._by_name.pop(name, None)
        if slot is not None:
            del self._slots[slot]
            self._mutations += 1

    def schedule_key(self, slots) -> tuple:
        """Exact fingerprint of the cached schedule ``slots`` (a sorted
        slot-index iterable): identical across cycles iff the executed
        schedule is bitwise-identical.  The mutation counter folds in
        slot *content*: a conflict-evict-reinsert that reuses the same
        index still changes the key.  Coherent across ranks because
        every mutation is (see the module docstring)."""
        return (self._mutations, tuple(slots))

    def insert(self, req: Request, resp: Response) -> None:
        """Insert a freshly negotiated (pre-fusion) response.  Called in
        response-construction order — identical on every rank."""
        if self.capacity == 0 or not cacheable(req.request_type):
            return
        self.evict_name(req.tensor_name)
        if len(self._slots) >= self.capacity:
            victims = [
                s for s in self._slots if s not in self.protected
            ]
            if not victims:
                # every slot is being voted on: skip the insertion rather
                # than strand a voter (deterministic — protected set and
                # occupancy are identical on every rank)
                return
            victim = min(victims, key=lambda s: self._slots[s].lru_tick)
            del self._by_name[self._slots[victim].tensor_name]
            del self._slots[victim]
            self._mutations += 1
        # lowest free slot: deterministic allocation
        slot = next(i for i in range(self.capacity) if i not in self._slots)
        self._tick += 1
        self._mutations += 1
        self._slots[slot] = _Slot(
            signature=request_signature(req),
            response_type=resp.response_type,
            tensor_name=req.tensor_name,
            shape=tuple(req.shape),
            dtype=req.dtype,
            root_rank=req.root_rank,
            fuse_meta=getattr(resp, "_fuse_meta", None),
            nbytes=getattr(resp, "_nbytes", 0),
            device=getattr(resp, "_device", False),
            lru_tick=self._tick,
        )
        self._by_name[req.tensor_name] = slot

    def response_for(self, slot: int) -> Response:
        """Reconstruct the negotiated response from the cache (reference
        executes the stored Response object; we store its template)."""
        s = self._slots[slot]
        resp = Response(s.response_type, [s.tensor_name])
        resp._shapes = [tuple(s.shape)]  # type: ignore[attr-defined]
        resp._dtype = s.dtype  # type: ignore[attr-defined]
        resp._root_rank = s.root_rank  # type: ignore[attr-defined]
        if s.fuse_meta is not None:
            resp._fuse_meta = s.fuse_meta  # type: ignore[attr-defined]
        resp._nbytes = s.nbytes  # type: ignore[attr-defined]
        resp._device = s.device  # type: ignore[attr-defined]
        return resp

    def name_for(self, slot: int) -> str:
        return self._slots[slot].tensor_name
