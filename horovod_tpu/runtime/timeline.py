"""Chrome-tracing timeline for the eager path.

Reference: horovod/common/timeline.cc (311 LoC) — rank 0 writes a Chrome
trace-event JSON; a dedicated writer thread drains a lock-free queue so the
hot loop never blocks on file IO; per-tensor state machine NEGOTIATING ->
TOP_LEVEL -> ACTIVITY (timeline.h:77).

Same design here, with two deliberate departures:

* **Every rank records** (the reference gates on rank 0).  Events are
  stamped ``pid = rank``, each rank writes its own file — the
  ``HVDTPU_TIMELINE`` value is a template (``{rank}``), a directory, or
  a plain path that gets a rank tag inserted (:func:`resolve_path`) —
  and the launcher merges them at job end into one trace with a lane
  per rank (obs/timeline_merge.py).  Negotiation skew across ranks is
  invisible in a rank-0-only trace; it is the whole point of this one.
* **Crash-safe streaming format**: one comma-terminated event per line,
  flushed per drained batch, no required ``]`` terminator (Chrome's
  trace format explicitly allows the unclosed-array form for streaming).
  A rank killed mid-job — the normal case under elastic respawn — leaves
  a trace that still loads; clean shutdown appends a ``trace_complete``
  metadata event plus the terminator so the file is also plain valid
  JSON.

Device-level timing belongs to the XLA profiler (jax.profiler.trace) and
is deliberately not duplicated — this timeline covers the host-side
negotiation/queue phases the XLA profiler can't see (SURVEY.md §5.1).

Enable with HVDTPU_TIMELINE=/path/trace.json (reference: HOROVOD_TIMELINE,
operations.cc:403-411); cycle markers via HVDTPU_TIMELINE_MARK_CYCLES.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from typing import Optional

# Activity names mirror reference common.h:31-59.
NEGOTIATE = "NEGOTIATE"
QUEUE = "QUEUE"
EXECUTE = "EXECUTE"
CYCLE = "CYCLE"


def resolve_path(raw: str, rank: int) -> str:
    """Map the ``HVDTPU_TIMELINE`` value to this rank's file — shared
    template/dir/plain-path + epoch-tag rules in obs/pathspec.py, so
    ``--timeline-filename t.json`` yields ``t.rank.<k>.json`` per rank
    (``t.e<E>.rank.<k>.json`` under elastic) and the launcher's merge
    — which globs with the same module — writes the original ``t.json``.
    """
    from ..obs import pathspec  # noqa: PLC0415

    return pathspec.resolve(raw, "trace", rank)


class Timeline:
    """Facade; no-ops unless enabled (so the engine can call it
    unconditionally, as the reference does via Initialized() checks)."""

    def __init__(self, path: Optional[str], rank: int, mark_cycles: bool = False):
        self._enabled = bool(path)
        self._rank = rank
        self._mark_cycles = mark_cycles
        self._queue: "queue.SimpleQueue" = queue.SimpleQueue()
        self._writer: Optional[threading.Thread] = None
        self._start = time.perf_counter()
        if self._enabled:
            self._path = path
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            self._writer = threading.Thread(
                target=self._write_loop, name="hvdtpu_timeline", daemon=True
            )
            self._writer.start()

    @property
    def enabled(self) -> bool:
        return self._enabled

    def _ts(self) -> float:
        return (time.perf_counter() - self._start) * 1e6  # us

    def _emit(self, ph: str, name: str, cat: str, tid: str = "ops", **extra):
        if self._enabled:
            self._queue.put(
                {"ph": ph, "name": name, "cat": cat, "pid": self._rank,
                 "tid": tid, "ts": self._ts(), **extra}
            )

    # -- per-tensor state machine (reference timeline.h:77-126) ------------
    def negotiate_start(self, tensor_name: str, op: str):
        self._emit("B", f"{NEGOTIATE}_{op}", "negotiate", tid=tensor_name)

    def negotiate_rank_ready(self, tensor_name: str, rank: int):
        self._emit(
            "i", f"rank_{rank}_ready", "negotiate", tid=tensor_name, s="t"
        )

    def negotiate_end(self, tensor_name: str, op: str):
        self._emit("E", f"{NEGOTIATE}_{op}", "negotiate", tid=tensor_name)

    def start(self, tensor_name: str, op: str):
        self._emit("B", op, "op", tid=tensor_name)

    def activity_start(self, tensor_name: str, activity: str):
        self._emit("B", activity, "activity", tid=tensor_name)

    def activity_end(self, tensor_name: str):
        self._emit("E", "", "activity", tid=tensor_name)

    def end(self, tensor_name: str, op: str):
        self._emit("E", op, "op", tid=tensor_name)

    def mark_cycle(self):
        if self._mark_cycles:
            self._emit("i", "CYCLE_START", "cycle", s="g")

    def counter(self, name: str, values: dict):
        """Chrome-trace counter event ("ph": "C"): a numeric series over
        time — used by straggler attribution to plot arrival skew per
        collective alongside the op lanes."""
        self._emit("C", name, "counter", tid="counters", args=dict(values))

    # -- writer ------------------------------------------------------------
    def _write_loop(self):
        """Streaming-tolerant writer: every event line ends with a comma
        and the batch is flushed, so the on-disk trace is loadable at any
        kill point (obs/timeline_merge.load_events repairs the tail; the
        Chrome trace format accepts the unclosed array as-is)."""
        with open(self._path, "w") as f:
            f.write("[\n")
            while True:
                try:
                    ev = self._queue.get(timeout=0.5)
                except queue.Empty:
                    f.flush()
                    continue
                if ev is None:
                    break
                f.write(json.dumps(ev))
                f.write(",\n")
                # drain whatever else is queued before flushing once
                while True:
                    try:
                        ev = self._queue.get_nowait()
                    except queue.Empty:
                        break
                    if ev is None:
                        f.flush()
                        self._terminate(f)
                        return
                    f.write(json.dumps(ev))
                    f.write(",\n")
                f.flush()
            self._terminate(f)

    def _terminate(self, f) -> None:
        # Clean shutdown: the terminator event keeps the trailing comma
        # legal, so a completed trace is ALSO plain valid JSON.
        f.write(json.dumps(
            {"ph": "M", "name": "trace_complete", "pid": self._rank,
             "tid": "meta", "ts": self._ts()}
        ))
        f.write("\n]\n")

    def shutdown(self):
        if self._enabled:
            self._queue.put(None)
            self._writer.join(timeout=5)
            self._enabled = False


def from_env(rank: int) -> Timeline:
    raw = os.environ.get("HVDTPU_TIMELINE")
    return Timeline(
        resolve_path(raw, rank) if raw else None,
        rank,
        mark_cycles=os.environ.get("HVDTPU_TIMELINE_MARK_CYCLES", "0")
        in ("1", "true"),
    )
