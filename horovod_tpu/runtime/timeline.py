"""Chrome-tracing timeline for the eager path.

Reference: horovod/common/timeline.cc (311 LoC) — rank 0 writes a Chrome
trace-event JSON; a dedicated writer thread drains a lock-free queue so the
hot loop never blocks on file IO; per-tensor state machine NEGOTIATING ->
TOP_LEVEL -> ACTIVITY (timeline.h:77).

Same design here: events go into a queue.SimpleQueue (single producer =
engine thread, single consumer = writer thread), the writer streams JSON
incrementally.  Device-level timing belongs to the XLA profiler
(jax.profiler.trace) and is deliberately not duplicated — this timeline
covers the host-side negotiation/queue phases the XLA profiler can't see
(SURVEY.md §5.1).

Enable with HVDTPU_TIMELINE=/path/trace.json (reference: HOROVOD_TIMELINE,
operations.cc:403-411); cycle markers via HVDTPU_TIMELINE_MARK_CYCLES.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from typing import Optional

# Activity names mirror reference common.h:31-59.
NEGOTIATE = "NEGOTIATE"
QUEUE = "QUEUE"
EXECUTE = "EXECUTE"
CYCLE = "CYCLE"


class Timeline:
    """Facade; no-ops unless enabled (so the engine can call it
    unconditionally, as the reference does via Initialized() checks)."""

    def __init__(self, path: Optional[str], rank: int, mark_cycles: bool = False):
        self._enabled = bool(path) and rank == 0
        self._mark_cycles = mark_cycles
        self._queue: "queue.SimpleQueue" = queue.SimpleQueue()
        self._writer: Optional[threading.Thread] = None
        self._start = time.perf_counter()
        if self._enabled:
            self._path = path
            self._writer = threading.Thread(
                target=self._write_loop, name="hvdtpu_timeline", daemon=True
            )
            self._writer.start()

    @property
    def enabled(self) -> bool:
        return self._enabled

    def _ts(self) -> float:
        return (time.perf_counter() - self._start) * 1e6  # us

    def _emit(self, ph: str, name: str, cat: str, tid: str = "ops", **extra):
        if self._enabled:
            self._queue.put(
                {"ph": ph, "name": name, "cat": cat, "pid": 0, "tid": tid,
                 "ts": self._ts(), **extra}
            )

    # -- per-tensor state machine (reference timeline.h:77-126) ------------
    def negotiate_start(self, tensor_name: str, op: str):
        self._emit("B", f"{NEGOTIATE}_{op}", "negotiate", tid=tensor_name)

    def negotiate_rank_ready(self, tensor_name: str, rank: int):
        self._emit(
            "i", f"rank_{rank}_ready", "negotiate", tid=tensor_name, s="t"
        )

    def negotiate_end(self, tensor_name: str, op: str):
        self._emit("E", f"{NEGOTIATE}_{op}", "negotiate", tid=tensor_name)

    def start(self, tensor_name: str, op: str):
        self._emit("B", op, "op", tid=tensor_name)

    def activity_start(self, tensor_name: str, activity: str):
        self._emit("B", activity, "activity", tid=tensor_name)

    def activity_end(self, tensor_name: str):
        self._emit("E", "", "activity", tid=tensor_name)

    def end(self, tensor_name: str, op: str):
        self._emit("E", op, "op", tid=tensor_name)

    def mark_cycle(self):
        if self._mark_cycles:
            self._emit("i", "CYCLE_START", "cycle", s="g")

    # -- writer ------------------------------------------------------------
    def _write_loop(self):
        with open(self._path, "w") as f:
            f.write("[\n")
            first = True
            while True:
                try:
                    ev = self._queue.get(timeout=0.5)
                except queue.Empty:
                    f.flush()
                    continue
                if ev is None:
                    break
                if not first:
                    f.write(",\n")
                f.write(json.dumps(ev))
                first = False
            f.write("\n]\n")

    def shutdown(self):
        if self._enabled:
            self._queue.put(None)
            self._writer.join(timeout=5)
            self._enabled = False


def from_env(rank: int) -> Timeline:
    return Timeline(
        os.environ.get("HVDTPU_TIMELINE"),
        rank,
        mark_cycles=os.environ.get("HVDTPU_TIMELINE_MARK_CYCLES", "0")
        in ("1", "true"),
    )
