"""Device-resident data plane for the eager engine.

The reference keeps eager collectives on the accelerator with NCCL plus a
ready-event/finalizer machine (horovod/common/operations.cc:266-291 busy-wait
on ReadyEvents, torch/ready_event.cc:1-116 cudaEvent readiness, persistent
device fusion buffers fusion_buffer_manager.cc:20-53).  The TPU-native
answer needs none of that plumbing: XLA *is* the device collective runtime.
This module executes each negotiated (fused) eager payload as a compiled
``shard_map`` collective over a process-spanning mesh — ``psum`` /
``all_gather`` / ``psum_scatter`` / ``all_to_all`` over ICI/DCN — so a
``jax.Array`` enqueued on one chip is reduced chip-to-chip and the result
is committed back to the caller's device with no host round-trip.

Readiness: a ``jax.Array`` handed to the engine may still be being produced
by an earlier async dispatch; enqueueing it into another XLA computation
makes the runtime sequence the two on the device stream — the ReadyEvent
busy-wait of the reference is replaced by XLA's own dataflow order.

Donation: the staging buffer (the ``(world, n)`` stacked array built from
the fused payload) is always freshly constructed here — eager ``jnp``
reshapes/concats allocate new buffers — so every jitted collective donates
it (``donate_argnums=0``): the collective consumes its input allocation
instead of holding payload memory twice, which is the reference's in-place
fusion-buffer behavior.

Ordering: the engine calls this plane only for responses that completed
negotiation, in the deterministic response order every rank computes — so
all processes issue identical collectives in identical order, which is the
correctness contract for multi-controller XLA.  (It is the same contract the
engine's control-plane ``process_allgather`` already relies on, and the
reason the Python engine documents that user code must not run concurrent
main-thread collectives while eager ops are in flight.)
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..basics import global_topology
# the jax-version shard_map shim lives with the other collective
# compat helpers; aliased here because every plane fn builds on it
from ..ops.collectives import shard_map_compat as _shard_map
from ..utils.logging import get_logger

LOG = get_logger("device_plane")

PROC_AXIS = "hvdtpu_proc"
LOCAL_AXIS = "hvdtpu_local"
# Two-fabric axes of the slice mesh (multislice jobs): ICI_AXIS spans
# the processes WITHIN one slice (fast fabric), DCN_AXIS spans the
# slices (slow fabric).  The hierarchical allreduce reduce-scatters over
# ICI, allreduces only the 1/slice_procs shard over DCN, and gathers
# back over ICI — NCCLHierarchicalAllreduce's schedule
# (nccl_operations.cc:162-300) with the fabrics renamed.
DCN_AXIS = "hvdtpu_dcn"
ICI_AXIS = "hvdtpu_ici"

# DCN wire compressors (--dcn-compression): the cross-slice shard is
# cast to this dtype before the DCN psum and widened after.  Only float
# wires compress; integer payloads always cross exact.
DCN_WIRES = {"none": None, "bf16": "bfloat16", "fp16": "float16"}


class DevicePlane:
    """Compiled XLA collectives over ALL addressable devices.

    The plane's mesh row order is process order, which ``basics.init`` pins
    to the engine's rank order (jax.distributed process_id == HVDTPU_RANK),
    so "row r" and "engine rank r" coincide by construction.

    A process owning k>1 chips (the standard TPU-VM host topology: one
    process, 4 chips) gets a 2-D ``(world, k)`` mesh: every fused/eager
    payload is split into k chunks fanned across the local chips so each
    chip's ICI links carry 1/k of the cross-host bytes — the reference's
    LOCAL communicator tier (common.h:111-115, mpi/mpi_context.cc,
    MPIHierarchicalAllgather in mpi_operations.cc) expressed as mesh axes
    instead of nested communicators.  Allreduce: per-chunk cross psum +
    local all_gather.  Allgather: per-chunk cross all_gather + local
    reassembly.  Broadcast: per-chunk masked psum + local reassembly.
    Reducescatter: per-rank-block sub-chunks psum_scattered cross-host +
    local reassembly.  Alltoall: per-sub-chunk cross all_to_all + local
    reassembly.  The local reassembly all_gathers ride intra-host ICI,
    which is the cheap direction.  Results commit back to the caller's
    device either way.
    """

    def __init__(self) -> None:
        topo = global_topology()
        self.world = topo.process_count
        self.rank = topo.process_rank
        by_proc: dict = {}
        for d in topo.devices:
            by_proc.setdefault(d.process_index, []).append(d)
        if sorted(by_proc) != list(range(self.world)):
            raise RuntimeError(
                f"device/process mismatch: process indices {sorted(by_proc)} "
                f"vs world {self.world} (is jax.distributed initialized?)"
            )
        for p in by_proc:
            by_proc[p] = sorted(by_proc[p], key=lambda d: d.id)
        self.local_devices = list(by_proc[self.rank])
        self.device = self.local_devices[0]
        missing = [d for d in self.local_devices
                   if d not in jax.local_devices()]
        if missing:
            raise RuntimeError(
                f"plane devices {missing} for rank {self.rank} are not "
                "addressable from this process"
            )
        devs = [by_proc[p][0] for p in range(self.world)]
        self.mesh = Mesh(np.asarray(devs, dtype=object), (PROC_AXIS,))
        # Slice mesh (multislice topologies only): the anchor-device row
        # reshaped (num_slices, procs_per_slice).  Built whenever the
        # topology's slice partition divides the world evenly; whether a
        # given cycle USES it is the engine's negotiated decision.
        self.num_slices = max(int(topo.num_slices), 1)
        self.slice_procs = 1
        self.mesh_slices = None
        if (
            self.num_slices > 1
            and self.world > 1
            and self.world % self.num_slices == 0
        ):
            self.slice_procs = self.world // self.num_slices
            grid = np.asarray(devs, dtype=object).reshape(
                self.num_slices, self.slice_procs
            )
            self.mesh_slices = Mesh(grid, (DCN_AXIS, ICI_AXIS))
        counts = {len(v) for v in by_proc.values()}
        self.n_local = counts.pop() if len(counts) == 1 else 1
        if self.n_local > 1:
            grid = np.empty((self.world, self.n_local), dtype=object)
            for p in range(self.world):
                grid[p, :] = by_proc[p]
            self.mesh2d = Mesh(grid, (PROC_AXIS, LOCAL_AXIS))
        else:
            self.mesh2d = None
            if len(counts) > 0:
                LOG.warning(
                    "heterogeneous local device counts %s: allreduce runs "
                    "on the one-device-per-process row mesh",
                    sorted(len(v) for v in by_proc.values()),
                )
        # Memory-plane program names already registered: the first
        # fused shape per schedule stands as the representative
        # breakdown (one extra small AOT compile per name, once per
        # process — subsequent cycles pay nothing).
        self._mem_registered: set = set()

    # ----------------------------------------------------- memory plane

    def _register_memory(self, name: str, fn, *args) -> None:
        """Publish one compiled collective program's memory breakdown
        (obs/memplane.py) the first time that schedule runs.  The jit
        dispatch cache and the AOT lowering are separate caches, so
        this costs ONE extra compile of a small psum program per
        schedule name per process — bounded, and the per-program
        ``mem.compiled.*`` gauges are what makes the engine's wire
        plane visible to the budget gate.  Never fatal."""
        if name in self._mem_registered:
            return
        try:
            from ..obs import memplane  # noqa: PLC0415

            # Only when the plane is armed (census installed /
            # HVDTPU_MEM_CENSUS): this registration is the one compile
            # site where reading the artifact costs a REAL extra
            # compile, and a job that never asked for memory
            # accounting must not pay it on every engine spin-up.
            if not memplane.accounting_armed():
                return
            self._mem_registered.add(name)
            memplane.register_program(name, fn.lower(*args).compile())
        except Exception:  # pragma: no cover - defensive
            self._mem_registered.add(name)

    # ------------------------------------------------------------- staging

    def stage(self, local: jax.Array) -> jax.Array:
        """Build the (world, ...) global array whose row r is rank r's
        buffer — the device analog of the host plane's gathered matrix.
        The returned array's buffer is fresh (the [None] reshape allocates),
        so downstream jits may donate it."""
        if next(iter(local.devices())) != self.device:
            local = jax.device_put(local, self.device)
        row = local[None]
        shape = (self.world,) + tuple(local.shape)
        sharding = NamedSharding(self.mesh, P(PROC_AXIS))
        return jax.make_array_from_single_device_arrays(shape, sharding, [row])

    @staticmethod
    def _local(out: jax.Array) -> jax.Array:
        """Extract this process's (replicated or shard) copy as a committed
        single-device array."""
        return out.addressable_shards[0].data

    # ---------------------------------------------------------- collectives

    @functools.lru_cache(maxsize=256)
    def _allreduce_fn(self, reduce_op: int, pre: float, post: float,
                      wire: str, acc: str, exact_int_avg: bool):
        from ..ops.collectives import ReduceOp  # noqa: PLC0415

        def f(x):  # x: (1, n) local shard in wire dtype
            v = x[0].astype(acc)
            if pre != 1.0:
                v = (v * pre).astype(wire).astype(acc)
            if reduce_op == int(ReduceOp.MIN):
                total = lax.pmin(v, PROC_AXIS)
            elif reduce_op == int(ReduceOp.MAX):
                total = lax.pmax(v, PROC_AXIS)
            else:
                total = lax.psum(v, PROC_AXIS)
                if reduce_op == int(ReduceOp.AVERAGE):
                    if exact_int_avg:
                        total = total // self.world
                    else:
                        total = total / self.world
            if post != 1.0:
                total = total * post
            return total.astype(wire)

        return jax.jit(
            _shard_map(
                f, mesh=self.mesh, in_specs=P(PROC_AXIS), out_specs=P(),
            ),
            donate_argnums=(0,),
        )

    # ---------------------------------------- hierarchical (two-fabric) path

    @property
    def hierarchical_ok(self) -> bool:
        """Whether this plane can run the slice-aware schedule: a
        multi-slice topology whose slice partition divides the world."""
        return self.mesh_slices is not None

    def _stage_slices(self, flat: jax.Array) -> jax.Array:
        """Stage a 1-D buffer (padded to a multiple of slice_procs) onto
        the slice mesh: global shape (num_slices, slice_procs, n), this
        process's row at (slice_id, intra-slice index)."""
        if next(iter(flat.devices())) != self.device:
            flat = jax.device_put(flat, self.device)
        row = flat[None, None]
        shape = (self.num_slices, self.slice_procs) + tuple(flat.shape)
        sharding = NamedSharding(self.mesh_slices, P(DCN_AXIS, ICI_AXIS))
        return jax.make_array_from_single_device_arrays(shape, sharding, [row])

    @functools.lru_cache(maxsize=256)
    def _allreduce_hier_fn(self, reduce_op: int, pre: float, post: float,
                           wire: str, acc: str, exact_int_avg: bool,
                           dcn_wire: Optional[str]):
        """The 3-phase two-fabric schedule (parallel/hierarchical.py's
        jit op applied to the engine's staged fused buffer):
        psum_scatter(ICI) -> psum(DCN) on 1/slice_procs of the bytes,
        optionally on a compressed wire -> all_gather(ICI).  SUM/AVERAGE
        only — scatter-based reduction does not compose with MIN/MAX."""
        from ..ops.collectives import ReduceOp  # noqa: PLC0415

        def f(x):  # x: (1, 1, n) — this rank's padded fused buffer
            v = x[0, 0].astype(acc)
            if pre != 1.0:
                v = (v * pre).astype(wire).astype(acc)
            # Phase 1 (ICI): reduce-scatter so each intra-slice rank owns
            # the slice-partial sum of its 1/slice_procs segment.
            shard = lax.psum_scatter(
                v, ICI_AXIS, scatter_dimension=0, tiled=True
            )
            # Phase 2 (DCN): allreduce only the shard across slices; the
            # compressed wire casts the slice-partial sums down before
            # the slow fabric and widens right after.
            if dcn_wire is not None:
                shard = lax.psum(shard.astype(dcn_wire), DCN_AXIS).astype(acc)
            else:
                shard = lax.psum(shard, DCN_AXIS)
            if reduce_op == int(ReduceOp.AVERAGE):
                if exact_int_avg:
                    shard = shard // self.world
                else:
                    shard = shard / self.world
            if post != 1.0:
                shard = shard * post
            # Phase 3 (ICI): gather the fully-reduced shards back.
            return lax.all_gather(shard.astype(wire), ICI_AXIS, tiled=True)

        return jax.jit(
            _shard_map(
                f, mesh=self.mesh_slices,
                in_specs=P(DCN_AXIS, ICI_AXIS), out_specs=P(),
            ),
            donate_argnums=(0,),
        )

    def allreduce_hier(self, flat: jax.Array, reduce_op: int, pre: float,
                       post: float, acc_dtype: str, exact_int_avg: bool,
                       dcn_wire: Optional[str] = None) -> jax.Array:
        """Hierarchical reduce of a 1-D fused buffer; caller guarantees
        ``hierarchical_ok`` and a SUM/AVERAGE reduce_op (both negotiated,
        so every rank takes this path on the same op)."""
        n = int(flat.shape[0])
        pad = (-n) % self.slice_procs
        if pad:
            flat = jnp.pad(flat, (0, pad))
        fn = self._allreduce_hier_fn(
            reduce_op, pre, post, str(flat.dtype), acc_dtype,
            exact_int_avg, dcn_wire,
        )
        staged = self._stage_slices(flat)
        self._register_memory("engine.allreduce_hier", fn, staged)
        out = self._local(fn(staged))
        return out[:n]

    # ------------------------------------------- sharded (multi-chip) path

    def _commit_chunks(self, per_chip, shape: Tuple[int, ...]) -> jax.Array:
        """Commit chunk j to local chip j and assemble the global array on
        the 2-D mesh.  All movement is chip-to-chip device_put — no host."""
        rows = [
            jax.device_put(per_chip[j][None, None], self.local_devices[j])
            for j in range(self.n_local)
        ]
        sharding = NamedSharding(self.mesh2d, P(PROC_AXIS, LOCAL_AXIS))
        return jax.make_array_from_single_device_arrays(shape, sharding, rows)

    def _stage_sharded(self, flat: jax.Array) -> jax.Array:
        """Split a 1-D buffer into n_local chunks, chunk j committed to
        local chip j; returns the (world, k, m) global array sharded over
        the 2-D mesh."""
        k = self.n_local
        n = int(flat.shape[0])
        m = -(-n // k)
        if m * k != n:
            flat = jnp.pad(flat, (0, m * k - n))
        resh = flat.reshape(k, m)
        return self._commit_chunks(resh, (self.world, k, m))

    @functools.lru_cache(maxsize=256)
    def _allreduce_sharded_fn(self, reduce_op: int, pre: float, post: float,
                              wire: str, acc: str, exact_int_avg: bool):
        from ..ops.collectives import ReduceOp  # noqa: PLC0415

        def f(x):  # x: (1, 1, m) — this chip's chunk of this rank's buffer
            v = x[0, 0].astype(acc)
            if pre != 1.0:
                v = (v * pre).astype(wire).astype(acc)
            if reduce_op == int(ReduceOp.MIN):
                total = lax.pmin(v, PROC_AXIS)
            elif reduce_op == int(ReduceOp.MAX):
                total = lax.pmax(v, PROC_AXIS)
            else:
                total = lax.psum(v, PROC_AXIS)
                if reduce_op == int(ReduceOp.AVERAGE):
                    if exact_int_avg:
                        total = total // self.world
                    else:
                        total = total / self.world
            if post != 1.0:
                total = total * post
            # re-assemble: every local chip ends with the full reduced
            # buffer, so the result can commit back to the caller's chip
            full = lax.all_gather(total.astype(wire), LOCAL_AXIS)
            return full[None]  # (1, k, m)

        return jax.jit(
            _shard_map(
                f, mesh=self.mesh2d,
                in_specs=P(PROC_AXIS, LOCAL_AXIS), out_specs=P(PROC_AXIS),
            ),
            donate_argnums=(0,),
        )

    def _stage_sharded_blocks(self, flat: jax.Array, blocks: int) -> jax.Array:
        """Like ``_stage_sharded`` but the 1-D buffer is ``blocks`` equal
        rank-blocks whose boundaries must be preserved: each block is split
        into n_local sub-chunks, sub-chunk j of every block committed to
        local chip j.  Returns the (world, k, blocks, mb) global array."""
        k = self.n_local
        b = int(flat.shape[0]) // blocks
        mb = -(-b // k)
        resh = flat.reshape(blocks, b)
        if mb * k != b:
            resh = jnp.pad(resh, ((0, 0), (0, mb * k - b)))
        resh = jnp.transpose(resh.reshape(blocks, k, mb), (1, 0, 2))
        return self._commit_chunks(resh, (self.world, k, blocks, mb))

    def allreduce(self, flat: jax.Array, reduce_op: int, pre: float,
                  post: float, acc_dtype: str, exact_int_avg: bool) -> jax.Array:
        """Reduce a 1-D fused buffer across processes; returns the reduced
        buffer (wire dtype) on the caller's device (multi-chip path) or the
        plane's anchor device."""
        if self.mesh2d is not None:
            n = int(flat.shape[0])
            try:
                caller_dev = next(iter(flat.devices()))
            except Exception:
                caller_dev = self.device
            fn = self._allreduce_sharded_fn(
                reduce_op, pre, post, str(flat.dtype), acc_dtype,
                exact_int_avg,
            )
            staged = self._stage_sharded(flat)
            self._register_memory("engine.fused_allreduce", fn, staged)
            out = fn(staged)
            shards = out.addressable_shards
            pick = next(
                (s for s in shards if s.data.devices() == {caller_dev}),
                shards[0],
            )
            return pick.data[0].reshape(-1)[:n]
        fn = self._allreduce_fn(
            reduce_op, pre, post, str(flat.dtype), acc_dtype, exact_int_avg
        )
        staged = self.stage(flat)
        self._register_memory("engine.fused_allreduce", fn, staged)
        return self._local(fn(staged))

    @functools.lru_cache(maxsize=64)
    def _allgather_fn(self):
        def f(x):  # x: (1, ...) local shard
            return lax.all_gather(x[0], PROC_AXIS)

        return jax.jit(
            _shard_map(
                f, mesh=self.mesh, in_specs=P(PROC_AXIS), out_specs=P(),
            ),
            donate_argnums=(0,),
        )

    @functools.lru_cache(maxsize=64)
    def _allgather_sharded_fn(self):
        """Hierarchical allgather (ref MPIHierarchicalAllgather,
        mpi_operations.cc): each chip cross-gathers its 1/k element-chunk
        of every rank's buffer, then the k chunks reassemble over the
        local axis — every chip's cross-host ICI carries world*n/k bytes
        instead of one chip carrying world*n."""
        def f(x):  # x: (1, 1, m) — this chip's element-chunk of this rank
            rows = lax.all_gather(x[0, 0], PROC_AXIS)        # (world, m)
            full = lax.all_gather(rows, LOCAL_AXIS, axis=1)  # (world, k, m)
            return full.reshape(full.shape[0], -1)           # (world, k*m)

        return jax.jit(
            _shard_map(
                f, mesh=self.mesh2d,
                in_specs=P(PROC_AXIS, LOCAL_AXIS), out_specs=P(),
            ),
            donate_argnums=(0,),
        )

    def allgather(self, local: jax.Array) -> jax.Array:
        """(world, *local.shape) on this plane's device (rows = ranks)."""
        n = int(local.size)
        if self.mesh2d is not None and n > 0:
            out = self._local(
                self._allgather_sharded_fn()(
                    self._stage_sharded(jnp.ravel(local))
                )
            )
            return out[:, :n].reshape((self.world,) + tuple(local.shape))
        return self._local(self._allgather_fn()(self.stage(local)))

    @functools.lru_cache(maxsize=64)
    def _broadcast_fn(self, root: int, wire: str):
        # One psum of a masked contribution — O(bytes) on the ICI ring,
        # same trick as the jit path's _broadcast (ops/collectives.py).
        def f(x):
            v = x[0]
            mask = (lax.axis_index(PROC_AXIS) == root)
            contrib = jnp.where(mask, v, jnp.zeros_like(v))
            return lax.psum(contrib, PROC_AXIS).astype(wire)

        return jax.jit(
            _shard_map(
                f, mesh=self.mesh, in_specs=P(PROC_AXIS), out_specs=P(),
            ),
            donate_argnums=(0,),
        )

    @functools.lru_cache(maxsize=64)
    def _broadcast_sharded_fn(self, root: int, wire: str):
        """Hierarchical broadcast: each chip psums its masked 1/k chunk
        cross-host, then the chunks reassemble over the local axis."""
        def f(x):  # x: (1, 1, m)
            v = x[0, 0]
            mask = (lax.axis_index(PROC_AXIS) == root)
            contrib = jnp.where(mask, v, jnp.zeros_like(v))
            chunk = lax.psum(contrib, PROC_AXIS).astype(wire)    # (m,)
            return lax.all_gather(chunk, LOCAL_AXIS).reshape(-1)  # (k*m,)

        return jax.jit(
            _shard_map(
                f, mesh=self.mesh2d,
                in_specs=P(PROC_AXIS, LOCAL_AXIS), out_specs=P(),
            ),
            donate_argnums=(0,),
        )

    def broadcast(self, local: jax.Array, root: int) -> jax.Array:
        if local.dtype == jnp.bool_:
            # psum over bool is invalid; ride uint8
            out = self.broadcast(local.astype(jnp.uint8), root)
            return self._cast(out, jnp.bool_)
        n = int(local.size)
        if self.mesh2d is not None and n > 0:
            out = self._local(
                self._broadcast_sharded_fn(int(root), str(local.dtype))(
                    self._stage_sharded(jnp.ravel(local))
                )
            )
            return out[:n].reshape(tuple(local.shape))
        return self._local(
            self._broadcast_fn(root, str(local.dtype))(self.stage(local))
        )

    @staticmethod
    def _cast(x: jax.Array, dtype) -> jax.Array:
        return x.astype(dtype)

    @functools.lru_cache(maxsize=64)
    def _reducescatter_fn(self, average: bool, pre: float, post: float,
                          wire: str, acc: str):
        def f(x):  # x: (1, n0, ...) — n0 divisible by world
            v = x[0].astype(acc)
            if pre != 1.0:
                v = (v * pre).astype(wire).astype(acc)
            chunk = lax.psum_scatter(v, PROC_AXIS, scatter_dimension=0,
                                     tiled=True)
            if average:
                chunk = chunk / self.world
            if post != 1.0:
                chunk = chunk * post
            return chunk.astype(wire)[None]

        return jax.jit(
            _shard_map(
                f, mesh=self.mesh, in_specs=P(PROC_AXIS),
                out_specs=P(PROC_AXIS),
            ),
            donate_argnums=(0,),
        )

    @functools.lru_cache(maxsize=64)
    def _reducescatter_sharded_fn(self, average: bool, pre: float,
                                  post: float, wire: str, acc: str):
        """Hierarchical reduce-scatter: each chip psum_scatters its 1/k
        sub-chunk of every rank-block cross-host (so each chip ends with
        sub-chunk j of THIS rank's reduced block), then the k sub-chunks
        reassemble over the local axis."""
        def f(x):  # x: (1, 1, world, mb) — sub-chunk j of every rank-block
            v = x[0, 0].astype(acc)
            if pre != 1.0:
                v = (v * pre).astype(wire).astype(acc)
            chunk = lax.psum_scatter(v, PROC_AXIS, scatter_dimension=0)
            if average:
                chunk = chunk / self.world
            if post != 1.0:
                chunk = chunk * post
            full = lax.all_gather(chunk.astype(wire), LOCAL_AXIS)  # (k, mb)
            return full.reshape(-1)[None]  # (1, k*mb)

        return jax.jit(
            _shard_map(
                f, mesh=self.mesh2d,
                in_specs=P(PROC_AXIS, LOCAL_AXIS),
                out_specs=P(PROC_AXIS),
            ),
            donate_argnums=(0,),
        )

    def reducescatter(self, local: jax.Array, average: bool, pre: float,
                      post: float, acc_dtype: str) -> jax.Array:
        """Even-dim0 reduce-scatter; returns this rank's chunk."""
        if self.mesh2d is not None and int(local.size) > 0:
            b = int(local.size) // self.world
            out = self._local(
                self._reducescatter_sharded_fn(
                    average, pre, post, str(local.dtype), acc_dtype
                )(self._stage_sharded_blocks(jnp.ravel(local), self.world))
            )[0]
            return out[:b].reshape(
                (int(local.shape[0]) // self.world,)
                + tuple(local.shape[1:])
            )
        fn = self._reducescatter_fn(
            average, pre, post, str(local.dtype), acc_dtype
        )
        out = fn(self.stage(local))
        return self._local(out)[0]

    @functools.lru_cache(maxsize=64)
    def _alltoall_fn(self):
        def f(x):  # x: (1, n0, ...) — n0 divisible by world
            v = x[0]
            n = self.world
            chunks = v.reshape((n, v.shape[0] // n) + v.shape[1:])
            out = lax.all_to_all(chunks, PROC_AXIS, split_axis=0,
                                 concat_axis=0, tiled=False)
            return out.reshape(v.shape)[None]

        return jax.jit(
            _shard_map(
                f, mesh=self.mesh, in_specs=P(PROC_AXIS),
                out_specs=P(PROC_AXIS),
            ),
            donate_argnums=(0,),
        )

    @functools.lru_cache(maxsize=64)
    def _alltoall_sharded_fn(self):
        """Hierarchical alltoall: each chip all_to_alls its 1/k sub-chunk
        of every destination block cross-host, then the k sub-chunks
        reassemble over the local axis."""
        def f(x):  # x: (1, 1, world, mb)
            v = x[0, 0]
            out = lax.all_to_all(v, PROC_AXIS, split_axis=0,
                                 concat_axis=0, tiled=False)  # (world, mb)
            full = lax.all_gather(out, LOCAL_AXIS, axis=1)  # (world, k, mb)
            return full.reshape(full.shape[0], -1)[None]  # (1, world, k*mb)

        return jax.jit(
            _shard_map(
                f, mesh=self.mesh2d,
                in_specs=P(PROC_AXIS, LOCAL_AXIS),
                out_specs=P(PROC_AXIS),
            ),
            donate_argnums=(0,),
        )

    def alltoall(self, local: jax.Array) -> jax.Array:
        if self.mesh2d is not None and int(local.size) > 0:
            b = int(local.size) // self.world
            rows = self._local(
                self._alltoall_sharded_fn()(
                    self._stage_sharded_blocks(jnp.ravel(local), self.world)
                )
            )[0]  # (world, k*mb): row i = rank i's block for this rank
            return rows[:, :b].reshape(tuple(local.shape))
        out = self._alltoall_fn()(self.stage(local))
        return self._local(out)[0]


def build_plane() -> Optional[DevicePlane]:
    """Construct the plane, or None (with one log line) when the topology
    can't support it — the engine then stays on its host data plane."""
    try:
        return DevicePlane()
    except Exception as exc:  # device/process mismatch, no distributed init
        LOG.warning("device data plane unavailable: %s", exc)
        return None
