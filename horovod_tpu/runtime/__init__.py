"""The eager-path runtime: background engine, negotiation protocol,
messages, timeline, stall inspection.

This is the TPU re-design of the reference's core runtime
(horovod/common/: operations.cc background loop, controller.cc negotiation,
tensor_queue.cc, fusion_buffer_manager.cc).  See runtime/engine.py for the
architecture notes."""
