"""Wire messages for the eager-path negotiation protocol.

Reference: horovod/common/message.h:47-194 (Request/RequestList/Response/
ResponseList, serialized with FlatBuffers, wire/message.fbs).  The TPU
build's control plane moves little data and already has a reliable ordered
transport (the coordination-service allgather), so the wire format is a
compact self-describing tuple encoding via pickle of primitive types —
the schema lives here, in one place, like message.fbs did.
"""

from __future__ import annotations

import enum
import pickle
from dataclasses import dataclass, field
from typing import List, Optional, Tuple


class RequestType(enum.IntEnum):
    """reference message.h:52-58."""

    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2
    JOIN = 3
    ADASUM = 4
    ALLTOALL = 5
    BARRIER = 6
    # 7 reserved (ResponseType.ERROR) — request->response maps by value
    REDUCESCATTER = 8


class ResponseType(enum.IntEnum):
    """reference message.h:137-144."""

    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2
    JOIN = 3
    ADASUM = 4
    ALLTOALL = 5
    BARRIER = 6
    ERROR = 7
    REDUCESCATTER = 8


@dataclass(frozen=True)
class Request:
    """One rank's declaration that a named tensor is ready
    (reference message.h:47-100)."""

    request_rank: int
    request_type: RequestType
    tensor_name: str
    dtype: str
    shape: Tuple[int, ...]
    reduce_op: int = 0  # ReduceOp value for ALLREDUCE/ADASUM
    root_rank: int = -1  # BROADCAST only
    prescale_factor: float = 1.0
    postscale_factor: float = 1.0
    # Payload lives on an accelerator (reference Request::device,
    # message.h:47-100): when every rank's request is device-resident the
    # response executes on the XLA device data plane; any host payload
    # demotes the whole op to the host plane.  Part of the negotiated
    # signature so the plane choice is identical on all ranks.
    device: bool = False

    def key(self) -> tuple:
        """Identity under negotiation (name + everything that must agree)."""
        return (self.tensor_name, self.request_type)


@dataclass
class RequestList:
    """reference message.h:103-129: requests + shutdown flag.

    `tuned_params` is the TPU build's parameter-sync channel: rank 0's
    autotuner attaches its current TunedParams wire tuple here and every
    rank applies it after negotiation — the descendant of the reference's
    rank-0 parameter Bcast (controller.cc:33-47 SynchronizeParameters)."""

    requests: List[Request] = field(default_factory=list)
    shutdown: bool = False
    joined: bool = False
    tuned_params: Optional[tuple] = None

    def serialize(self) -> bytes:
        payload = (
            [
                (
                    r.request_rank,
                    int(r.request_type),
                    r.tensor_name,
                    r.dtype,
                    tuple(r.shape),
                    r.reduce_op,
                    r.root_rank,
                    r.prescale_factor,
                    r.postscale_factor,
                    r.device,
                )
                for r in self.requests
            ],
            self.shutdown,
            self.joined,
            self.tuned_params,
        )
        return pickle.dumps(payload, protocol=4)

    @staticmethod
    def deserialize(data: bytes) -> "RequestList":
        reqs, shutdown, joined, tuned = pickle.loads(data)
        return RequestList(
            tuned_params=tuned,
            requests=[
                Request(
                    request_rank=a,
                    request_type=RequestType(b),
                    tensor_name=c,
                    dtype=d,
                    shape=tuple(e),
                    reduce_op=f,
                    root_rank=g,
                    prescale_factor=h,
                    postscale_factor=i,
                    device=j,
                )
                for (a, b, c, d, e, f, g, h, i, j) in reqs
            ],
            shutdown=shutdown,
            joined=joined,
        )


@dataclass
class Response:
    """Coordinator's instruction to execute one (possibly fused) op
    (reference message.h:132-194)."""

    response_type: ResponseType
    tensor_names: List[str]
    error_message: str = ""
    # Per-rank dim-0 sizes for ragged allgather (reference
    # Response::tensor_sizes, controller.cc:453-518).
    tensor_sizes: List[int] = field(default_factory=list)
