"""Runtime parameter autotuning for the eager engine.

Reference: horovod/common/parameter_manager.cc (528 LoC) +
optim/bayesian_optimization.cc + optim/gaussian_process.cc — the reference
tunes {tensor-fusion threshold, cycle time, response-cache enabled,
hierarchical allreduce/allgather} by scoring throughput (bytes/sec) per
sample window and driving Bayesian optimization over a Gaussian process;
rank 0 tunes and broadcasts the winning parameters to all ranks
(controller.cc:33-47 SynchronizeParameters).

TPU redesign: same tunables and the same GP/EI math, but in NumPy instead
of Eigen+lbfgs (hyperparameters are picked by a small marginal-likelihood
grid rather than L-BFGS — the search space is 2-D and tiny).  The
categorical axes (cache on/off, hierarchical on/off) are explored as a
deterministic chain, with the continuous (fusion, cycle) surface tuned by
the GP within each category — mirroring the reference's
CategoricalParameter / BayesianParameter split (parameter_manager.h:59-78).
Parameter sync rides the negotiation: rank 0 attaches tuned params to its
RequestList and every rank applies them on receipt (the descendant of the
reference's param Bcast).

Where this DEPARTS from the reference: the reference calls
``SetAutoTuning(false)`` after one sweep and never moves again; this
tuner is a *continuous controller*.  After the categorical sweep
converges it holds the incumbent but keeps scoring every sample window —
the objective is read from the engine's telemetry plane
(``engine.fusion_bytes``/``engine.cycle_time_ms`` registry instruments:
bytes moved per second of *busy* cycle time, so host idle between steps
cannot convict a good parameter point) — and a drift detector re-opens
the GP search when throughput shows sustained regression (elastic world
change, workload phase change).  Tuner state is published as
``autotune.*`` registry gauges, so ``/metrics`` and the live digest show
what the tuner is doing at any moment.
"""

from __future__ import annotations

import csv
import os
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..utils import env as envmod

# Continuous search space (log-ish ranges chosen around the reference
# defaults: fusion 64 MB, cycle 5 ms — operations.cc:419,427).
FUSION_BOUNDS_MB = (1.0, 128.0)
CYCLE_BOUNDS_MS = (1.0, 50.0)

# Gradient-bucket size for the jit path's backward-overlap plane
# (optim/overlap.py) — the in-backward analog of fusion_mb.  It is a
# tuning CATEGORY, not a live GP dimension: the bucket boundaries are
# baked into the compiled XLA program, so every move costs a full
# recompile (minutes on TPU) where a fusion_mb move costs one
# negotiation cycle.  The candidate chain below is the offline sweep
# (bench.py --grad-bucket-mb) a deployment walks once per model shape;
# too small → per-collective launch latency dominates, too large → the
# last bucket's wire time has no backward compute left to hide behind
# (docs/performance.md "overlap").
GRAD_BUCKET_BOUNDS_MB = (2.0, 64.0)
DEFAULT_GRAD_BUCKET_MB = envmod.DEFAULT_GRAD_BUCKET_MB


def grad_bucket_candidates() -> List[float]:
    """The geometric bucket-size chain (MB) an offline sweep explores —
    one octave apart inside GRAD_BUCKET_BOUNDS_MB, like the categorical
    chains build_categories() emits for the engine knobs."""
    out, mb = [], GRAD_BUCKET_BOUNDS_MB[0]
    while mb <= GRAD_BUCKET_BOUNDS_MB[1]:
        out.append(mb)
        mb *= 2
    return out


def resolve_grad_bucket_bytes(cli_mb: Optional[float] = None) -> int:
    """The ONE resolution path for the bucket-size knob (CLI flag over
    HVDTPU_GRAD_BUCKET_MB over the 16 MB default), shared by bench.py
    and optim/overlap.py so the two can never disagree about what a
    given run used."""
    mb = (
        float(cli_mb)
        if cli_mb is not None
        else envmod.env_float(envmod.GRAD_BUCKET_MB,
                              DEFAULT_GRAD_BUCKET_MB)
    )
    if mb <= 0:
        raise ValueError(f"grad bucket size must be positive, got {mb} MB")
    return int(mb * 1024 * 1024)

def build_categories(
    *,
    multislice: bool = False,
    replay_enabled: bool = False,
    hierarchical_capable: bool = True,
) -> List[Dict[str, bool]]:
    """The ONE categorical exploration chain both engines tune over
    (reference explores hierarchical/cache combinations as
    CategoricalParameter values, parameter_manager.h:59-78).

    Topology-derived: each entry costs a full Bayesian sweep, so a knob
    with no consumer on this topology must not appear —

    * ``hierarchical_allreduce: True`` is explored ONLY on multi-slice
      topologies whose data plane can run the two-fabric schedule
      (``multislice and hierarchical_capable``).  On a single slice the
      flat XLA psum is already torus-optimal and the hierarchical path
      would be pure overhead; before this builder each engine hand-rolled
      its own list and a dead always-on entry drifted into the default.
    * ``cache_enabled: False`` is excluded while schedule replay is on:
      disabling the cache forfeits the negotiation-free steady state by
      construction, so a noisy sample window must not be able to freeze
      out the fast path.
    """
    cats: List[Dict[str, bool]] = [
        {"cache_enabled": True, "hierarchical_allreduce": False},
    ]
    if multislice and hierarchical_capable:
        cats.append(
            {"cache_enabled": True, "hierarchical_allreduce": True}
        )
    if not replay_enabled:
        cats.append(
            {"cache_enabled": False, "hierarchical_allreduce": False}
        )
    return cats

DEFAULT_WARMUP_SAMPLES = 3  # discarded while pipelines fill (reference WARMUPS)
DEFAULT_STEPS_PER_SAMPLE = 10  # negotiation cycles per score sample
DEFAULT_BAYES_SAMPLES_PER_CATEGORY = 12
GP_NOISE = 1e-6

# Drift detector defaults: re-open the search when the held incumbent's
# score runs DRIFT_THRESHOLD (fraction) below the post-convergence peak
# for DRIFT_SAMPLES consecutive sample windows.  20% x 3 windows ignores
# ordinary jitter (shared-tunnel variance is ±3%, docs/performance.md)
# while catching a real regime change within ~3 windows.
DEFAULT_DRIFT_THRESHOLD = 0.2
DEFAULT_DRIFT_SAMPLES = 3
_HOLD_EWMA_ALPHA = 0.3
_HOLD_LOG_EVERY = 50  # CSV decimation while holding (drift rows always log)

# Tuner lifecycle states, published as the autotune.state gauge.
STATE_WARMUP = 0
STATE_SEARCHING = 1
STATE_CONVERGED = 2
STATE_RETUNING = 3
STATE_NAMES = {
    STATE_WARMUP: "warmup",
    STATE_SEARCHING: "searching",
    STATE_CONVERGED: "converged",
    STATE_RETUNING: "retuning",
}


class GaussianProcess:
    """GP regression with an RBF kernel (reference gaussian_process.cc).

    Inputs are expected normalized to [0, 1]^d.  Hyperparameters
    (signal variance, length scale) are selected by maximizing the log
    marginal likelihood over a small grid — the reference fits them with
    L-BFGS (vendored lbfgs); a grid is adequate for a 2-D tuner and keeps
    this dependency-free.
    """

    def __init__(self, length_scale: float = 0.2, signal_var: float = 1.0,
                 noise: float = 1e-4):
        self.length_scale = length_scale
        self.signal_var = signal_var
        self.noise = noise
        self._x: Optional[np.ndarray] = None
        self._alpha: Optional[np.ndarray] = None
        self._chol: Optional[np.ndarray] = None
        self._y_mean = 0.0
        self._y_std = 1.0

    def _kernel(self, a: np.ndarray, b: np.ndarray,
                length_scale: float, signal_var: float) -> np.ndarray:
        d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return signal_var * np.exp(-0.5 * d2 / (length_scale ** 2))

    def _log_marginal(self, x: np.ndarray, y: np.ndarray,
                      length_scale: float, signal_var: float) -> float:
        k = self._kernel(x, x, length_scale, signal_var)
        k[np.diag_indices_from(k)] += self.noise
        try:
            chol = np.linalg.cholesky(k)
        except np.linalg.LinAlgError:
            return -np.inf
        alpha = np.linalg.solve(chol.T, np.linalg.solve(chol, y))
        return float(
            -0.5 * y @ alpha
            - np.log(np.diag(chol)).sum()
            - 0.5 * len(y) * np.log(2 * np.pi)
        )

    def fit(self, x: np.ndarray, y: np.ndarray) -> None:
        x = np.atleast_2d(np.asarray(x, float))
        y = np.asarray(y, float).reshape(-1)
        self._y_mean = float(y.mean())
        self._y_std = float(y.std()) or 1.0
        yn = (y - self._y_mean) / self._y_std
        if len(y) >= 4:
            best = (-np.inf, self.length_scale, self.signal_var)
            for ls in (0.05, 0.1, 0.2, 0.4, 0.8):
                for sv in (0.5, 1.0, 2.0):
                    lm = self._log_marginal(x, yn, ls, sv)
                    if lm > best[0]:
                        best = (lm, ls, sv)
            _, self.length_scale, self.signal_var = best
        k = self._kernel(x, x, self.length_scale, self.signal_var)
        k[np.diag_indices_from(k)] += self.noise
        self._chol = np.linalg.cholesky(k)
        self._alpha = np.linalg.solve(
            self._chol.T, np.linalg.solve(self._chol, yn)
        )
        self._x = x

    def predict(self, x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Posterior mean and stddev at x (denormalized to y's scale)."""
        x = np.atleast_2d(np.asarray(x, float))
        if self._x is None:
            return (np.zeros(len(x)) + self._y_mean,
                    np.ones(len(x)) * self._y_std)
        ks = self._kernel(x, self._x, self.length_scale, self.signal_var)
        mean = ks @ self._alpha
        v = np.linalg.solve(self._chol, ks.T)
        var = np.maximum(
            self.signal_var - (v ** 2).sum(0), GP_NOISE
        )
        return (mean * self._y_std + self._y_mean,
                np.sqrt(var) * self._y_std)


class BayesianOptimization:
    """Expected-improvement Bayesian optimization over [0,1]^d
    (reference bayesian_optimization.cc: NextPoint via EI maximization)."""

    def __init__(self, dims: int, seed: int = 0, xi: float = 0.01,
                 noise: float = 1e-4):
        self.dims = dims
        self.xi = xi
        self._rng = np.random.RandomState(seed)
        self._x: List[np.ndarray] = []
        self._y: List[float] = []
        self.gp = GaussianProcess(noise=noise)

    def add_sample(self, x: np.ndarray, y: float) -> None:
        self._x.append(np.asarray(x, float))
        self._y.append(float(y))
        self.gp.fit(np.stack(self._x), np.asarray(self._y))

    def best(self) -> Tuple[np.ndarray, float]:
        i = int(np.argmax(self._y))
        return self._x[i], self._y[i]

    def next_point(self) -> np.ndarray:
        if len(self._y) < 2:
            return self._rng.uniform(size=self.dims)
        candidates = self._rng.uniform(size=(256, self.dims))
        # seed the candidate pool near the incumbent too
        bx, _ = self.best()
        local = np.clip(
            bx + self._rng.normal(scale=0.08, size=(64, self.dims)), 0, 1
        )
        candidates = np.concatenate([candidates, local])
        mean, std = self.gp.predict(candidates)
        y_best = max(self._y)
        z = (mean - y_best - self.xi) / std
        # EI = (mu - y* - xi) * Phi(z) + sigma * phi(z)
        phi = np.exp(-0.5 * z ** 2) / np.sqrt(2 * np.pi)
        cdf = 0.5 * (1 + _erf(z / np.sqrt(2)))
        ei = (mean - y_best - self.xi) * cdf + std * phi
        return candidates[int(np.argmax(ei))]


def _erf(x: np.ndarray) -> np.ndarray:
    # Abramowitz & Stegun 7.1.26; |err| < 1.5e-7 — plenty for EI ranking.
    sign = np.sign(x)
    x = np.abs(x)
    t = 1.0 / (1.0 + 0.3275911 * x)
    y = 1.0 - (((((1.061405429 * t - 1.453152027) * t) + 1.421413741)
                * t - 0.284496736) * t + 0.254829592) * t * np.exp(-x * x)
    return sign * y


@dataclass
class TunedParams:
    """The parameter struct rank 0 ships to every rank each time the tuner
    moves (reference Params struct, controller.cc:33-47)."""

    fusion_bytes: int
    cycle_s: float
    cache_enabled: bool = True
    hierarchical_allreduce: bool = False

    def as_wire(self) -> tuple:
        return (self.fusion_bytes, self.cycle_s, self.cache_enabled,
                self.hierarchical_allreduce)

    @staticmethod
    def from_wire(t: tuple) -> "TunedParams":
        return TunedParams(int(t[0]), float(t[1]), bool(t[2]), bool(t[3]))


class ParameterManager:
    """Owns the engine tunables and drives the continuous score→tune loop
    (reference parameter_manager.h:59-78,178-220, minus its one-shot
    freeze).

    Usage (engine, rank 0 only):
        pm = ParameterManager(enabled=..., initial=TunedParams(...),
                              metrics_source=...)
        pm.record_bytes(n)                 # legacy scoring feed (no-op
                                           # when metrics_source is set)
        new = pm.cycle()                   # per negotiation cycle;
                                           # returns TunedParams when moved

    ``metrics_source`` is a zero-arg callable returning cumulative
    ``(bytes_moved, busy_seconds)`` — the engine wires it to its
    ``engine.fusion_bytes`` / ``engine.cycle_time_ms`` registry
    instruments, making the telemetry plane the objective function.
    Scoring on *busy* time (sum of measured cycle durations, no
    inter-cycle sleep, no host idle between steps) is what keeps an
    input-bound phase from convicting a good parameter point.  Without a
    source the manager falls back to record_bytes() over wall-clock
    spans (unit tests and the reference behavior).
    """

    def __init__(
        self,
        enabled: bool,
        initial: TunedParams,
        log_path: Optional[str] = None,
        warmup_samples: Optional[int] = None,
        steps_per_sample: Optional[int] = None,
        samples_per_category: Optional[int] = None,
        categories: Optional[List[Dict[str, bool]]] = None,
        metrics_source: Optional[Callable[[], Tuple[float, float]]] = None,
        drift_threshold: Optional[float] = None,
        drift_samples: Optional[int] = None,
    ):
        # Sampling-window knobs resolve through the reference's env names
        # (common.h:67-69 HOROVOD_AUTOTUNE_{WARMUP_SAMPLES,STEPS_PER_SAMPLE,
        # BAYES_OPT_MAX_SAMPLES}) so tests and deployments can trade tuning
        # latency for sample quality deterministically.
        if warmup_samples is None:
            warmup_samples = envmod.env_int(
                envmod.AUTOTUNE_WARMUP_SAMPLES, DEFAULT_WARMUP_SAMPLES
            )
        if steps_per_sample is None:
            steps_per_sample = envmod.env_int(
                envmod.AUTOTUNE_STEPS_PER_SAMPLE, DEFAULT_STEPS_PER_SAMPLE
            )
        if samples_per_category is None:
            samples_per_category = envmod.env_int(
                envmod.AUTOTUNE_BAYES_OPT_MAX_SAMPLES,
                DEFAULT_BAYES_SAMPLES_PER_CATEGORY,
            )
        # GP observation-noise prior (reference common.h:70
        # HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE): raise on noisy shared
        # machines so the tuner discounts sample-to-sample jitter.
        self._gp_noise = envmod.env_float(envmod.AUTOTUNE_GP_NOISE, 1e-4)
        if drift_threshold is None:
            drift_threshold = envmod.env_float(
                envmod.AUTOTUNE_DRIFT_THRESHOLD, DEFAULT_DRIFT_THRESHOLD
            )
        if drift_samples is None:
            drift_samples = envmod.env_int(
                envmod.AUTOTUNE_DRIFT_SAMPLES, DEFAULT_DRIFT_SAMPLES
            )
        # `categories` must list only configurations the owning engine
        # actually consumes — every category costs a full Bayesian sweep,
        # so exploring knobs with no consumer wastes 1/len(categories) of
        # the tuning budget per phantom entry.  Engines pass the
        # topology-derived build_categories() result; the no-argument
        # default is the conservative single-slice chain.
        self.categories = (
            build_categories() if categories is None else categories
        )
        self.enabled = enabled
        self.current = initial
        self.warmup_samples = warmup_samples
        self.steps_per_sample = steps_per_sample
        self.samples_per_category = samples_per_category
        self._bytes = 0
        self._steps = 0
        self._sample_start = time.monotonic()
        self._samples_seen = 0
        self._category_i = 0
        self._bayes = BayesianOptimization(dims=2, seed=0, noise=self._gp_noise)
        self._per_category_samples = 0
        self._best: Tuple[float, TunedParams] = (-1.0, initial)

        # Continuous-controller state.
        self._state = STATE_WARMUP
        self._source = metrics_source
        self._src_bytes0 = 0.0
        self._src_busy0 = 0.0
        if metrics_source is not None:
            self._src_bytes0, self._src_busy0 = metrics_source()
        self.drift_threshold = float(drift_threshold)
        self.drift_samples = int(drift_samples)
        self._hold_ewma: Optional[float] = None
        self._hold_peak = 0.0
        self._drift_count = 0
        self._hold_log_i = 0
        self.reopens = 0
        self._last_score = 0.0

        # Gauges: the tuner's externally visible state (/metrics and the
        # live digest read these; resolved once, updates are lock-free).
        from ..obs import get_registry  # noqa: PLC0415

        metrics = get_registry()
        self._g_state = metrics.gauge("autotune.state")
        self._g_last = metrics.gauge("autotune.last_score")
        self._g_best = metrics.gauge("autotune.best_score")
        self._g_fusion = metrics.gauge("autotune.fusion_mb")
        self._g_cycle = metrics.gauge("autotune.cycle_ms")
        self._g_cache = metrics.gauge("autotune.cache_enabled")
        self._g_category = metrics.gauge("autotune.category")
        self._g_samples = metrics.gauge("autotune.samples")
        self._g_reopens = metrics.gauge("autotune.reopens")
        self._publish()

        # Tuning-history CSV: APPEND, with the header only on a fresh
        # file, and epoch-tagged under the elastic launcher — an elastic
        # respawn re-creates the engine (and this manager), and mode "w"
        # here used to clobber the very tuning history that explains what
        # the dead incarnation had learned.
        self._log_path = None
        if log_path:
            from ..obs import pathspec  # noqa: PLC0415

            log_path = pathspec.epoch_tag(log_path)
            self._log_path = log_path
            if (not os.path.exists(log_path)
                    or os.path.getsize(log_path) == 0):
                with open(log_path, "a", newline="") as f:
                    csv.writer(f).writerow(
                        ["sample", "score_bytes_per_sec", "fusion_mb",
                         "cycle_ms", "cache_enabled",
                         "hierarchical_allreduce", "state"]
                    )

    # -------------------------------------------------------------- scoring

    def record_bytes(self, n: int) -> None:
        self._bytes += n

    def _window_score(self) -> Tuple[float, float]:
        """Close the current sample window; returns (score, bytes_moved).
        Score is bytes per second of busy cycle time when a metrics
        source is wired; bytes per wall-clock second otherwise."""
        if self._source is not None:
            bytes_now, busy_now = self._source()
            d_bytes = bytes_now - self._src_bytes0
            d_busy = busy_now - self._src_busy0
            self._src_bytes0, self._src_busy0 = bytes_now, busy_now
            self._bytes = 0
            return (d_bytes / d_busy if d_busy > 0 else 0.0, d_bytes)
        elapsed = time.monotonic() - self._sample_start
        moved = self._bytes
        score = self._bytes / elapsed if elapsed > 0 else 0.0
        self._bytes = 0
        return score, moved

    def cycle(self) -> Optional[TunedParams]:
        """Advance one negotiation cycle; maybe emit new params to try.

        Unlike the reference (SetAutoTuning(false) after one sweep),
        this keeps running after convergence: held samples feed the
        drift detector, which re-opens the search on sustained
        regression."""
        if not self.enabled:
            return None
        self._steps += 1
        if self._steps < self.steps_per_sample:
            return None
        score, moved = self._window_score()
        self._steps = 0
        self._sample_start = time.monotonic()
        if moved <= 0:
            # Idle window (training paused: eval, checkpoint, input
            # stall) — evidence of NOTHING.  Scoring it as 0 would feed
            # garbage into the GP and, worse, convict a held incumbent
            # of drift after any pause spanning drift_samples windows.
            return None
        self._samples_seen += 1
        self._last_score = score
        if self._samples_seen <= self.warmup_samples:
            return None
        if self._state == STATE_WARMUP:
            self._state = STATE_SEARCHING
        try:
            if self._state == STATE_CONVERGED:
                return self._hold(score)
            return self._tune(score)
        finally:
            self._publish()

    # --------------------------------------------------------------- tuning

    def _norm(self, p: TunedParams) -> np.ndarray:
        # Clamp into bounds before the log: params can start outside the
        # search box (e.g. HVDTPU_FUSION_THRESHOLD=0 disables fusion, and
        # log2(0) would poison the GP kernel with NaNs).
        fmb = float(np.clip(p.fusion_bytes / (1024 * 1024), *FUSION_BOUNDS_MB))
        cms = float(np.clip(p.cycle_s * 1000, *CYCLE_BOUNDS_MS))
        return np.asarray([
            (np.log2(fmb) - np.log2(FUSION_BOUNDS_MB[0]))
            / (np.log2(FUSION_BOUNDS_MB[1]) - np.log2(FUSION_BOUNDS_MB[0])),
            (np.log2(cms) - np.log2(CYCLE_BOUNDS_MS[0]))
            / (np.log2(CYCLE_BOUNDS_MS[1]) - np.log2(CYCLE_BOUNDS_MS[0])),
        ])

    def _denorm(self, x: np.ndarray) -> Tuple[int, float]:
        lf0, lf1 = np.log2(FUSION_BOUNDS_MB)
        lc0, lc1 = np.log2(CYCLE_BOUNDS_MS)
        fmb = 2.0 ** (lf0 + float(np.clip(x[0], 0, 1)) * (lf1 - lf0))
        cms = 2.0 ** (lc0 + float(np.clip(x[1], 0, 1)) * (lc1 - lc0))
        return int(fmb * 1024 * 1024), cms / 1000.0

    def _tune(self, score: float) -> Optional[TunedParams]:
        """One SEARCHING/RETUNING sample: feed the GP, maybe move."""
        if score > self._best[0]:
            self._best = (score, self.current)
        self._log(score)
        self._bayes.add_sample(self._norm(self.current), score)
        self._per_category_samples += 1
        if self._per_category_samples >= self.samples_per_category:
            self._per_category_samples = 0
            if self._state == STATE_RETUNING:
                # a re-opened search stays in the incumbent's category:
                # one GP budget, then settle again
                return self._converge()
            # advance the categorical chain; reset the continuous surface
            self._category_i += 1
            if self._category_i >= len(self.categories):
                return self._converge()
            self._bayes = BayesianOptimization(
                dims=2, seed=self._category_i, noise=self._gp_noise
            )
        fusion_bytes, cycle_s = self._denorm(self._bayes.next_point())
        cat = self._probe_category()
        self.current = TunedParams(
            fusion_bytes=fusion_bytes, cycle_s=cycle_s, **cat
        )
        return self.current

    def _probe_category(self) -> Dict[str, bool]:
        """The categorical config the next continuous probe rides on:
        the chain position while SEARCHING, the INCUMBENT's own config
        while RETUNING — after a full sweep _category_i points past the
        chain's end, and indexing the last entry would silently retune
        in whatever category happened to be swept last (e.g. cache-off)
        rather than the one the incumbent won with."""
        if self._state == STATE_RETUNING:
            return {
                "cache_enabled": self._best[1].cache_enabled,
                "hierarchical_allreduce":
                    self._best[1].hierarchical_allreduce,
            }
        return self.categories[min(self._category_i,
                                   len(self.categories) - 1)]

    def _converge(self) -> Optional[TunedParams]:
        """Settle on the best configuration scored and enter the hold
        state (the reference stops here for good; we keep watching)."""
        self._state = STATE_CONVERGED
        # Seed the smoothed hold signal with the winning search score:
        # it is evidence of the healthy level, but as an EWMA seed its
        # weight decays 0.7^k per window, so a single lucky sample
        # cannot permanently inflate the bar real windows are judged
        # against (the perpetual-retune failure mode).
        self._hold_ewma = self._best[0]
        self._hold_peak = 0.0
        self._drift_count = 0
        # Emit the incumbent even if it equals the last point tried —
        # peers apply params idempotently; returning None here would
        # leave them on the final *probe* point forever.
        self.current = self._best[1]
        return self.current

    def _hold(self, score: float) -> Optional[TunedParams]:
        """One CONVERGED sample: hold the incumbent, watch for drift.
        Drift is judged on the SMOOTHED signal (EWMA vs the peak the
        EWMA itself reached), never on a raw window — one noisy window
        in either direction moves the EWMA by at most alpha."""
        if self._hold_ewma is None:
            self._hold_ewma = score
        else:
            self._hold_ewma = (
                _HOLD_EWMA_ALPHA * score
                + (1 - _HOLD_EWMA_ALPHA) * self._hold_ewma
            )
        self._hold_peak = max(self._hold_peak, self._hold_ewma)
        if self._hold_ewma < self._hold_peak * (1.0 - self.drift_threshold):
            self._drift_count += 1
        else:
            self._drift_count = 0
        # Hold-state logging is decimated: drifting windows are always
        # interesting, otherwise one row per _HOLD_LOG_EVERY windows —
        # the removed one-shot tuner stopped logging at convergence, and
        # an unbounded per-window append would grow the CSV forever on
        # long jobs.
        self._hold_log_i += 1
        if self._drift_count or self._hold_log_i % _HOLD_LOG_EVERY == 0:
            self._log(score)
        if self._drift_count < self.drift_samples:
            return None
        return self._reopen(score)

    def _reopen(self, score: float) -> Optional[TunedParams]:
        """Sustained regression: the world changed under the incumbent.
        Restart the GP in the incumbent's category, seeded with the
        incumbent at its CURRENT (regressed) score — the stale
        pre-drift best would otherwise be unbeatable and the search
        could never move."""
        self._state = STATE_RETUNING
        self.reopens += 1
        self._drift_count = 0
        self._per_category_samples = 0
        self._best = (score, self.current)
        self._bayes = BayesianOptimization(
            dims=2, seed=100 + self.reopens, noise=self._gp_noise
        )
        self._bayes.add_sample(self._norm(self.current), score)
        fusion_bytes, cycle_s = self._denorm(self._bayes.next_point())
        cat = self._probe_category()
        self.current = TunedParams(
            fusion_bytes=fusion_bytes, cycle_s=cycle_s, **cat
        )
        return self.current

    @property
    def converged(self) -> bool:
        return self._state == STATE_CONVERGED

    @property
    def state(self) -> int:
        return self._state

    def best_score(self) -> float:
        return self._best[0]

    def _publish(self) -> None:
        self._g_state.set(self._state)
        self._g_last.set(self._last_score)
        self._g_best.set(self._best[0])
        self._g_fusion.set(self.current.fusion_bytes / 1048576)
        self._g_cycle.set(self.current.cycle_s * 1000)
        self._g_cache.set(int(self.current.cache_enabled))
        self._g_category.set(min(self._category_i,
                                 len(self.categories) - 1))
        self._g_samples.set(self._samples_seen)
        self._g_reopens.set(self.reopens)

    def _log(self, score: float) -> None:
        if not self._log_path:
            return
        p = self.current
        with open(self._log_path, "a", newline="") as f:
            csv.writer(f).writerow([
                self._samples_seen, round(score, 1),
                round(p.fusion_bytes / 1048576, 2),
                round(p.cycle_s * 1000, 3),
                int(p.cache_enabled), int(p.hierarchical_allreduce),
                STATE_NAMES[self._state],
            ])
