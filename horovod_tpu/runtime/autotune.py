"""Runtime parameter autotuning for the eager engine.

Reference: horovod/common/parameter_manager.cc (528 LoC) +
optim/bayesian_optimization.cc + optim/gaussian_process.cc — the reference
tunes {tensor-fusion threshold, cycle time, response-cache enabled,
hierarchical allreduce/allgather} by scoring throughput (bytes/sec) per
sample window and driving Bayesian optimization over a Gaussian process;
rank 0 tunes and broadcasts the winning parameters to all ranks
(controller.cc:33-47 SynchronizeParameters).

TPU redesign: same tunables and the same GP/EI math, but in NumPy instead
of Eigen+lbfgs (hyperparameters are picked by a small marginal-likelihood
grid rather than L-BFGS — the search space is 2-D and tiny).  The
categorical axes (cache on/off, hierarchical on/off) are explored as a
deterministic chain, with the continuous (fusion, cycle) surface tuned by
the GP within each category — mirroring the reference's
CategoricalParameter / BayesianParameter split (parameter_manager.h:59-78).
Parameter sync rides the negotiation: rank 0 attaches tuned params to its
RequestList and every rank applies them on receipt (the descendant of the
reference's param Bcast).
"""

from __future__ import annotations

import csv
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils import env as envmod

# Continuous search space (log-ish ranges chosen around the reference
# defaults: fusion 64 MB, cycle 5 ms — operations.cc:419,427).
FUSION_BOUNDS_MB = (1.0, 128.0)
CYCLE_BOUNDS_MS = (1.0, 50.0)

# Categorical exploration chain (reference explores hierarchical/cache
# combinations; on TPU "hierarchical" selects the 2-level cross×local
# reduction in the data plane).
CATEGORIES: List[Dict[str, bool]] = [
    {"cache_enabled": True, "hierarchical_allreduce": False},
    {"cache_enabled": True, "hierarchical_allreduce": True},
    {"cache_enabled": False, "hierarchical_allreduce": False},
]

DEFAULT_WARMUP_SAMPLES = 3  # discarded while pipelines fill (reference WARMUPS)
DEFAULT_STEPS_PER_SAMPLE = 10  # negotiation cycles per score sample
DEFAULT_BAYES_SAMPLES_PER_CATEGORY = 12
GP_NOISE = 1e-6


class GaussianProcess:
    """GP regression with an RBF kernel (reference gaussian_process.cc).

    Inputs are expected normalized to [0, 1]^d.  Hyperparameters
    (signal variance, length scale) are selected by maximizing the log
    marginal likelihood over a small grid — the reference fits them with
    L-BFGS (vendored lbfgs); a grid is adequate for a 2-D tuner and keeps
    this dependency-free.
    """

    def __init__(self, length_scale: float = 0.2, signal_var: float = 1.0,
                 noise: float = 1e-4):
        self.length_scale = length_scale
        self.signal_var = signal_var
        self.noise = noise
        self._x: Optional[np.ndarray] = None
        self._alpha: Optional[np.ndarray] = None
        self._chol: Optional[np.ndarray] = None
        self._y_mean = 0.0
        self._y_std = 1.0

    def _kernel(self, a: np.ndarray, b: np.ndarray,
                length_scale: float, signal_var: float) -> np.ndarray:
        d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return signal_var * np.exp(-0.5 * d2 / (length_scale ** 2))

    def _log_marginal(self, x: np.ndarray, y: np.ndarray,
                      length_scale: float, signal_var: float) -> float:
        k = self._kernel(x, x, length_scale, signal_var)
        k[np.diag_indices_from(k)] += self.noise
        try:
            chol = np.linalg.cholesky(k)
        except np.linalg.LinAlgError:
            return -np.inf
        alpha = np.linalg.solve(chol.T, np.linalg.solve(chol, y))
        return float(
            -0.5 * y @ alpha
            - np.log(np.diag(chol)).sum()
            - 0.5 * len(y) * np.log(2 * np.pi)
        )

    def fit(self, x: np.ndarray, y: np.ndarray) -> None:
        x = np.atleast_2d(np.asarray(x, float))
        y = np.asarray(y, float).reshape(-1)
        self._y_mean = float(y.mean())
        self._y_std = float(y.std()) or 1.0
        yn = (y - self._y_mean) / self._y_std
        if len(y) >= 4:
            best = (-np.inf, self.length_scale, self.signal_var)
            for ls in (0.05, 0.1, 0.2, 0.4, 0.8):
                for sv in (0.5, 1.0, 2.0):
                    lm = self._log_marginal(x, yn, ls, sv)
                    if lm > best[0]:
                        best = (lm, ls, sv)
            _, self.length_scale, self.signal_var = best
        k = self._kernel(x, x, self.length_scale, self.signal_var)
        k[np.diag_indices_from(k)] += self.noise
        self._chol = np.linalg.cholesky(k)
        self._alpha = np.linalg.solve(
            self._chol.T, np.linalg.solve(self._chol, yn)
        )
        self._x = x

    def predict(self, x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Posterior mean and stddev at x (denormalized to y's scale)."""
        x = np.atleast_2d(np.asarray(x, float))
        if self._x is None:
            return (np.zeros(len(x)) + self._y_mean,
                    np.ones(len(x)) * self._y_std)
        ks = self._kernel(x, self._x, self.length_scale, self.signal_var)
        mean = ks @ self._alpha
        v = np.linalg.solve(self._chol, ks.T)
        var = np.maximum(
            self.signal_var - (v ** 2).sum(0), GP_NOISE
        )
        return (mean * self._y_std + self._y_mean,
                np.sqrt(var) * self._y_std)


class BayesianOptimization:
    """Expected-improvement Bayesian optimization over [0,1]^d
    (reference bayesian_optimization.cc: NextPoint via EI maximization)."""

    def __init__(self, dims: int, seed: int = 0, xi: float = 0.01,
                 noise: float = 1e-4):
        self.dims = dims
        self.xi = xi
        self._rng = np.random.RandomState(seed)
        self._x: List[np.ndarray] = []
        self._y: List[float] = []
        self.gp = GaussianProcess(noise=noise)

    def add_sample(self, x: np.ndarray, y: float) -> None:
        self._x.append(np.asarray(x, float))
        self._y.append(float(y))
        self.gp.fit(np.stack(self._x), np.asarray(self._y))

    def best(self) -> Tuple[np.ndarray, float]:
        i = int(np.argmax(self._y))
        return self._x[i], self._y[i]

    def next_point(self) -> np.ndarray:
        if len(self._y) < 2:
            return self._rng.uniform(size=self.dims)
        candidates = self._rng.uniform(size=(256, self.dims))
        # seed the candidate pool near the incumbent too
        bx, _ = self.best()
        local = np.clip(
            bx + self._rng.normal(scale=0.08, size=(64, self.dims)), 0, 1
        )
        candidates = np.concatenate([candidates, local])
        mean, std = self.gp.predict(candidates)
        y_best = max(self._y)
        z = (mean - y_best - self.xi) / std
        # EI = (mu - y* - xi) * Phi(z) + sigma * phi(z)
        phi = np.exp(-0.5 * z ** 2) / np.sqrt(2 * np.pi)
        cdf = 0.5 * (1 + _erf(z / np.sqrt(2)))
        ei = (mean - y_best - self.xi) * cdf + std * phi
        return candidates[int(np.argmax(ei))]


def _erf(x: np.ndarray) -> np.ndarray:
    # Abramowitz & Stegun 7.1.26; |err| < 1.5e-7 — plenty for EI ranking.
    sign = np.sign(x)
    x = np.abs(x)
    t = 1.0 / (1.0 + 0.3275911 * x)
    y = 1.0 - (((((1.061405429 * t - 1.453152027) * t) + 1.421413741)
                * t - 0.284496736) * t + 0.254829592) * t * np.exp(-x * x)
    return sign * y


@dataclass
class TunedParams:
    """The parameter struct rank 0 ships to every rank each time the tuner
    moves (reference Params struct, controller.cc:33-47)."""

    fusion_bytes: int
    cycle_s: float
    cache_enabled: bool = True
    hierarchical_allreduce: bool = False

    def as_wire(self) -> tuple:
        return (self.fusion_bytes, self.cycle_s, self.cache_enabled,
                self.hierarchical_allreduce)

    @staticmethod
    def from_wire(t: tuple) -> "TunedParams":
        return TunedParams(int(t[0]), float(t[1]), bool(t[2]), bool(t[3]))


class ParameterManager:
    """Owns the engine tunables and drives the score→tune loop
    (reference parameter_manager.h:59-78,178-220).

    Usage (engine, rank 0 only):
        pm = ParameterManager(enabled=..., initial=TunedParams(...))
        pm.record_bytes(n)                 # per executed response
        new = pm.cycle()                   # per negotiation cycle;
                                           # returns TunedParams when moved
    """

    def __init__(
        self,
        enabled: bool,
        initial: TunedParams,
        log_path: Optional[str] = None,
        warmup_samples: Optional[int] = None,
        steps_per_sample: Optional[int] = None,
        samples_per_category: Optional[int] = None,
        categories: Optional[List[Dict[str, bool]]] = None,
    ):
        # Sampling-window knobs resolve through the reference's env names
        # (common.h:67-69 HOROVOD_AUTOTUNE_{WARMUP_SAMPLES,STEPS_PER_SAMPLE,
        # BAYES_OPT_MAX_SAMPLES}) so tests and deployments can trade tuning
        # latency for sample quality deterministically.
        if warmup_samples is None:
            warmup_samples = envmod.env_int(
                envmod.AUTOTUNE_WARMUP_SAMPLES, DEFAULT_WARMUP_SAMPLES
            )
        if steps_per_sample is None:
            steps_per_sample = envmod.env_int(
                envmod.AUTOTUNE_STEPS_PER_SAMPLE, DEFAULT_STEPS_PER_SAMPLE
            )
        if samples_per_category is None:
            samples_per_category = envmod.env_int(
                envmod.AUTOTUNE_BAYES_OPT_MAX_SAMPLES,
                DEFAULT_BAYES_SAMPLES_PER_CATEGORY,
            )
        # GP observation-noise prior (reference common.h:70
        # HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE): raise on noisy shared
        # machines so the tuner discounts sample-to-sample jitter.
        self._gp_noise = envmod.env_float(envmod.AUTOTUNE_GP_NOISE, 1e-4)
        # `categories` must list only configurations the owning engine
        # actually consumes — every category costs a full Bayesian sweep,
        # so exploring knobs with no consumer wastes 1/len(categories) of
        # the tuning budget per phantom entry.
        self.categories = CATEGORIES if categories is None else categories
        self.enabled = enabled
        self.current = initial
        self.warmup_samples = warmup_samples
        self.steps_per_sample = steps_per_sample
        self.samples_per_category = samples_per_category
        self._bytes = 0
        self._steps = 0
        self._sample_start = time.monotonic()
        self._samples_seen = 0
        self._category_i = 0
        self._bayes = BayesianOptimization(dims=2, seed=0, noise=self._gp_noise)
        self._per_category_samples = 0
        self._done = False
        self._best: Tuple[float, TunedParams] = (-1.0, initial)
        self._log_path = log_path
        if log_path:
            with open(log_path, "w", newline="") as f:
                csv.writer(f).writerow(
                    ["sample", "score_bytes_per_sec", "fusion_mb",
                     "cycle_ms", "cache_enabled", "hierarchical_allreduce"]
                )

    # -------------------------------------------------------------- scoring

    def record_bytes(self, n: int) -> None:
        self._bytes += n

    def cycle(self) -> Optional[TunedParams]:
        """Advance one negotiation cycle; maybe emit new params to try."""
        if not self.enabled or self._done:
            return None
        self._steps += 1
        if self._steps < self.steps_per_sample:
            return None
        elapsed = time.monotonic() - self._sample_start
        score = self._bytes / elapsed if elapsed > 0 else 0.0
        self._bytes = 0
        self._steps = 0
        self._sample_start = time.monotonic()
        self._samples_seen += 1
        if self._samples_seen <= self.warmup_samples:
            return None
        return self._tune(score)

    # --------------------------------------------------------------- tuning

    def _norm(self, p: TunedParams) -> np.ndarray:
        # Clamp into bounds before the log: params can start outside the
        # search box (e.g. HVDTPU_FUSION_THRESHOLD=0 disables fusion, and
        # log2(0) would poison the GP kernel with NaNs).
        fmb = float(np.clip(p.fusion_bytes / (1024 * 1024), *FUSION_BOUNDS_MB))
        cms = float(np.clip(p.cycle_s * 1000, *CYCLE_BOUNDS_MS))
        return np.asarray([
            (np.log2(fmb) - np.log2(FUSION_BOUNDS_MB[0]))
            / (np.log2(FUSION_BOUNDS_MB[1]) - np.log2(FUSION_BOUNDS_MB[0])),
            (np.log2(cms) - np.log2(CYCLE_BOUNDS_MS[0]))
            / (np.log2(CYCLE_BOUNDS_MS[1]) - np.log2(CYCLE_BOUNDS_MS[0])),
        ])

    def _denorm(self, x: np.ndarray) -> Tuple[int, float]:
        lf0, lf1 = np.log2(FUSION_BOUNDS_MB)
        lc0, lc1 = np.log2(CYCLE_BOUNDS_MS)
        fmb = 2.0 ** (lf0 + float(np.clip(x[0], 0, 1)) * (lf1 - lf0))
        cms = 2.0 ** (lc0 + float(np.clip(x[1], 0, 1)) * (lc1 - lc0))
        return int(fmb * 1024 * 1024), cms / 1000.0

    def _tune(self, score: float) -> Optional[TunedParams]:
        if score > self._best[0]:
            self._best = (score, self.current)
        self._log(score)
        self._bayes.add_sample(self._norm(self.current), score)
        self._per_category_samples += 1
        if self._per_category_samples >= self.samples_per_category:
            # advance the categorical chain; reset the continuous surface
            self._category_i += 1
            self._per_category_samples = 0
            if self._category_i >= len(self.categories):
                # converged: settle on the best configuration ever scored
                self._done = True
                self.current = self._best[1]
                return self.current
            self._bayes = BayesianOptimization(
                dims=2, seed=self._category_i, noise=self._gp_noise
            )
        fusion_bytes, cycle_s = self._denorm(self._bayes.next_point())
        cat = self.categories[min(self._category_i, len(self.categories) - 1)]
        self.current = TunedParams(
            fusion_bytes=fusion_bytes, cycle_s=cycle_s, **cat
        )
        return self.current

    @property
    def converged(self) -> bool:
        return self._done

    def best_score(self) -> float:
        return self._best[0]

    def _log(self, score: float) -> None:
        if not self._log_path:
            return
        p = self.current
        with open(self._log_path, "a", newline="") as f:
            csv.writer(f).writerow([
                self._samples_seen, round(score, 1),
                round(p.fusion_bytes / 1048576, 2),
                round(p.cycle_s * 1000, 3),
                int(p.cache_enabled), int(p.hierarchical_allreduce),
            ])
