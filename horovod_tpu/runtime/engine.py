"""The eager-path background engine.

Reference architecture (horovod/common/operations.cc): one background
thread per process owns all communication; framework threads enqueue
named TensorTableEntries and get async handles; the thread runs a ~5 ms
cycle of [negotiate -> execute fused responses -> fire callbacks]
(RunLoopOnce, operations.cc:550; PerformOperation, operations.cc:232).

TPU redesign decisions:

* **Single-process worlds skip the thread entirely** — collectives over a
  world of one are identity transforms (the reference executes them
  through the full machinery; we resolve the future at enqueue, which makes
  the eager API free in the common single-host case).
* **Negotiation transport** is an allgather of serialized RequestLists over
  the JAX coordination service (two-phase: fixed-size length gather, padded
  payload gather) — the descendant of MPIController's
  MPI_Gatherv/MPI_Bcast legs (mpi_controller.cc:107-199), but symmetric:
  every rank runs the deterministic controller (see controller.py).
* **Data transport** executes each fused response as a device computation
  over a process-spanning mesh (allgather-based v1; the engine is the seam
  where a native/C++ transport slots in).
* Shutdown is coordinated through the negotiation itself (any rank's flag
  ends the job for everyone, reference controller.cc:256-259,309): cycles
  are collective, so a rank that stopped cycling unilaterally would
  deadlock its peers — the flag makes every loop exit on the same cycle.
"""

from __future__ import annotations

import atexit
import concurrent.futures
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..basics import global_topology
from ..exceptions import HorovodShutdownError
from ..obs import get_registry
from ..obs import flightrec as obs_flightrec
from ..obs import progress as obs_progress
from ..obs import trace as obs_trace
from ..testing.faults import maybe_fail
from ..utils import env as envmod
from ..utils.logging import get_logger
from . import response_cache as rcache
from . import timeline as timeline_mod
from .autotune import ParameterManager, TunedParams, build_categories
from .controller import ControllerState, _fuse, compute_responses
from .messages import Request, RequestList, RequestType, Response, ResponseType

LOG = get_logger("engine")

# Reference defaults: fusion 64 MB (operations.cc:419 — canonical constant
# in utils/env.py), cycle 5 ms (operations.cc:427).  The python control
# plane pays ~1 ms per coordination allgather, so the multi-process default
# cycle is a touch longer.
DEFAULT_FUSION_BYTES = envmod.DEFAULT_FUSION_BYTES
DEFAULT_CYCLE_MS_SINGLE = 1.0
DEFAULT_CYCLE_MS_MULTI = 10.0

SHUT_DOWN_ERROR = (
    "horovod_tpu has been shut down. This was caused by an exception on one "
    "of the ranks or an asymmetric shutdown; check the logs of other ranks."
    "  (reference: common.h:154-159)"
)
DUPLICATE_NAME_ERROR = (
    "Requested to {op} a tensor with the same name as another tensor that is "
    "currently being processed.  (reference: common.h:161-164)"
)


def _response_bytes(resp: Response) -> int:
    """Payload size of one (possibly fused) response, for autotune scoring
    (reference scores bytes/sec per sample, parameter_manager.h:178-220)."""
    shapes = getattr(resp, "_shapes", [])
    itemsize = _np_dtype(getattr(resp, "_dtype", "float32")).itemsize
    return sum(
        (int(np.prod(s)) if s else 1) * itemsize for s in shapes
    )


def _np_dtype(name: str) -> np.dtype:
    """dtype-string -> numpy dtype, tolerating ml_dtypes names (bfloat16)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # noqa: PLC0415

        return np.dtype(getattr(ml_dtypes, name))


# Dtypes the staged-XLA host path can carry exactly.  64-bit types are
# excluded (jax without x64 truncates them; they ride the raw-bytes gather),
# bool rides the gather too (psum over bool is undefined).
_STAGEABLE_DTYPES = frozenset(
    {"float32", "float16", "bfloat16", "int32", "int8", "uint8"}
)


def _replay_plan_ok(plan: List[Response], world: int) -> bool:
    """Whether a memorized schedule can carry the replay epoch-check
    lane.  The flag rides the FIRST fused buffer as one extra scalar, so
    that buffer's reduction must preserve "any rank set a nonzero flag"
    as a nonzero output: SUM/AVERAGE over a non-bool dtype with nonzero
    pre/post scales (int AVERAGE floor-divides and could round a lone
    flag to zero).  Every field tested is negotiated — identical on all
    ranks — so the qualification decision is too.  Gradient-training
    schedules (allreduce-SUM/AVERAGE first) qualify; exotic schedules
    simply never enter replay and keep the bit-vote fast path."""
    if not plan:
        return False
    first = plan[0]
    if first.response_type != ResponseType.ALLREDUCE:
        return False
    meta = getattr(first, "_fuse_meta", None)
    if meta is None:
        return False
    dtype_name, reduce_op, pre, post = meta
    from ..ops.collectives import ReduceOp as _R  # noqa: PLC0415

    if reduce_op not in (int(_R.SUM), int(_R.AVERAGE)):
        return False
    if pre == 0.0 or post == 0.0:
        return False
    try:
        wire = _np_dtype(dtype_name)
    except Exception:
        return False
    if wire.kind == "b":
        return False
    if wire.kind in ("i", "u") and reduce_op == int(_R.AVERAGE):
        return False
    # float16's narrow exponent range can underflow a flag scaled by
    # tiny pre/post factors on the plane paths (min subnormal ~6e-8),
    # and AVERAGE divides by the world on top; bf16/f32 have f32-sized
    # exponents and are safe for any realistic scale.  The raw-gather
    # path appends the flag AFTER prescale, so only the plane-scaled
    # paths need this.
    if dtype_name == "float16":
        scale = abs(pre * post)
        if reduce_op == int(_R.AVERAGE):
            scale /= max(world, 1)
        if scale < 1e-6:
            return False
    return True


def _is_device_tensor(tensor) -> bool:
    """Single-device jax.Array: the payload kind the device data plane can
    carry without a host round-trip.  Sharded arrays and host buffers take
    the host plane."""
    if not isinstance(tensor, jax.Array):
        return False
    try:
        return len(tensor.devices()) == 1
    except Exception:  # deleted/donated array
        return False


@dataclass
class TensorTableEntry:
    """reference common.h:233-250."""

    request: Request
    tensor: Optional[np.ndarray]
    future: concurrent.futures.Future = field(
        default_factory=concurrent.futures.Future
    )


class EagerEngine:
    """Owns the background thread, tensor table, controller state."""

    # jax.Array payloads stay device-resident end to end (device_plane.py);
    # the native engine's TCP wire needs host bytes instead.
    accepts_device_arrays = True

    def __init__(self):
        topo = global_topology()
        self.rank = topo.process_rank
        self.world = topo.process_count
        self.fusion_bytes = envmod.env_int(
            envmod.FUSION_THRESHOLD, DEFAULT_FUSION_BYTES
        )
        default_cycle = (
            DEFAULT_CYCLE_MS_SINGLE if self.world == 1 else DEFAULT_CYCLE_MS_MULTI
        )
        self.cycle_s = (
            envmod.env_float(envmod.CYCLE_TIME, default_cycle) / 1000.0
        )
        self.stall_warn = envmod.env_float(envmod.STALL_CHECK_TIME, 60.0)
        self.stall_shutdown = envmod.env_float(envmod.STALL_SHUTDOWN_TIME, 0.0)
        if envmod.env_bool(envmod.STALL_CHECK_DISABLE):
            self.stall_warn = float("inf")
        # Straggler-attribution warning threshold (--alert-skew-ms);
        # 0 accumulates engine.straggler.* silently.
        self.alert_skew_ms = envmod.env_float(envmod.ALERT_SKEW, 0.0)
        self.timeline = timeline_mod.from_env(self.rank)

        self._lock = threading.Lock()
        self._table: Dict[str, TensorTableEntry] = {}
        self._pending: List[Request] = []
        self._joined = False
        self._join_future: Optional[concurrent.futures.Future] = None
        self._shutdown_requested = False
        self._done = False
        self._controller = ControllerState(world_size=self.world)
        self._thread: Optional[threading.Thread] = None
        self._barrier_seq = 0

        # Response cache + steady-state fast path (reference
        # response_cache.cc / CacheCoordinator): repeated tensor sets vote
        # fixed-size armed-bit vectors instead of re-exchanging serialized
        # RequestLists every cycle.
        self._cache = rcache.ResponseCache(
            envmod.env_int(envmod.CACHE_CAPACITY, 1024)
        )
        # Live cache toggle (reference parameter_manager.h cache_enabled):
        # flipped by tuned params, which apply on the same cycle boundary on
        # every rank, so arming stays coherent.
        self.cache_enabled = True
        self._armed: Dict[int, Request] = {}
        self._armed_since: Dict[int, float] = {}
        self._last_armed_stall_check = time.monotonic()
        self.stats = {
            "cycles": 0,
            "fast_cycles": 0,  # cycles with no payload exchange anywhere
            "negotiated_cycles": 0,  # cycles that ran a control exchange
            "replay_cycles": 0,  # zero-control-plane replay executions
            "replay_idle_cycles": 0,  # replay cycles with nothing enqueued
            "replay_epochs": 0,  # times the engine entered replay
            "replay_breaks": 0,  # times a deviation broke an epoch
            "payload_cycles": 0,
            "control_bytes": 0,
            "payload_bytes": 0,
            "cache_hits": 0,
            "cache_misses": 0,
            "cached_responses": 0,  # ops executed straight from cache votes
            "negotiated_responses": 0,  # ops through full negotiation
            "host_data_ops": 0,  # responses executed on the host data plane
            "host_wire_bytes": 0,  # local payload bytes shipped per gather
            "host_recv_bytes": 0,  # bytes received: O(world x bytes) for
            # raw gathers, O(bytes) for staged XLA reduces
            "host_staged_ops": 0,  # host payloads reduced via staged psum
            "device_data_ops": 0,  # responses executed as XLA collectives
            "device_payload_bytes": 0,  # bytes that stayed device-resident
        }

        # Observability plane (obs/registry.py): cycle-loop instruments
        # resolved once here — updates on the handles are lock-free, so
        # the per-cycle cost is a few float ops.  The stats dict above is
        # published via a snapshot-time collector instead of mirrored
        # increments on the hot path.
        metrics = get_registry()
        self._metrics = metrics
        self._m_cycle_ms = metrics.histogram("engine.cycle_time_ms")
        self._m_negotiate_ms = metrics.histogram("engine.negotiation_ms")
        self._m_fusion_bytes = metrics.histogram("engine.fusion_bytes")
        self._m_queue_depth = metrics.gauge("engine.tensor_queue_depth")
        self._m_completed = metrics.counter("engine.collectives_completed")
        self._m_cached_stalls = metrics.counter(
            "engine.cached_stall_warnings"
        )
        # Per-fabric byte counters (multislice observability): bytes the
        # XLA data plane moved over the fast intra-slice fabric (ICI) vs
        # the slow cross-slice fabric (DCN).  On the hierarchical path
        # dcn_bytes ≈ ici_bytes / slice_procs — the bandwidth argument
        # the schedule exists for; on the flat path of a multislice job
        # every payload byte is charged to DCN, which is exactly the
        # full-tensor cost the tuner should see and move away from.
        self._m_dcn_bytes = metrics.counter("engine.dcn_bytes")
        self._m_ici_bytes = metrics.counter("engine.ici_bytes")
        self._m_dcn_ratio = metrics.gauge("engine.dcn_compression_ratio")
        # WeakMethod so the registry never pins a dead engine alive, and
        # the closure signals CollectorRetired once the engine is gone
        # so the registry prunes it (single deref — no GC race between
        # the liveness check and the call).
        import weakref  # noqa: PLC0415

        _wm = weakref.WeakMethod(self._publish_stats)

        def _collect(reg, _wm=_wm):
            publish = _wm()
            if publish is None:
                from ..obs.registry import CollectorRetired  # noqa: PLC0415

                raise CollectorRetired
            publish(reg)

        metrics.register_collector(_collect)

        # Device data plane (runtime/device_plane.py): fused payloads whose
        # tensors are jax.Arrays execute as compiled XLA collectives over a
        # process mesh — no host round-trip (the analog of the reference's
        # NCCL device path, operations.cc:266-291).  The kill switch gates
        # *enqueue* (Request.device=False), so disabling it on any rank
        # demotes the op globally through negotiation instead of desyncing
        # the planes.  Built at engine start; every cycle's control vector
        # carries a "no plane" bit, so plane selection for BOTH the
        # negotiated-device path and the staged host path is a function of
        # data all ranks share — a rank whose plane failed to build demotes
        # the whole job to the host gather instead of desyncing collectives.
        self._device_enabled = envmod.env_bool(envmod.EAGER_DEVICE, default=True)
        self._device_plane = None
        if self.world > 1 and self._device_enabled:
            from . import device_plane  # noqa: PLC0415

            self._device_plane = device_plane.build_plane()
        self._plane_ok_all = self._device_plane is not None

        # Two-fabric (multislice) data path: when the topology has >1
        # slice and the plane built its slice mesh, SUM/AVERAGE fused
        # allreduces can run the hierarchical schedule — selected
        # per-cycle from the tuner's hierarchical_allreduce param (or
        # pinned by --hierarchical-allreduce).  The flag only ever flips
        # through negotiated tuned params or the launcher-uniform env,
        # so every rank picks the same schedule for the same op.
        from .device_plane import DCN_WIRES  # noqa: PLC0415

        self._hier_capable = bool(
            self._device_plane is not None
            and self._device_plane.hierarchical_ok
        )
        self.hierarchical = False
        hier_req = envmod.env_bool(envmod.HIERARCHICAL_ALLREDUCE)
        # --hierarchical-allreduce PINS the schedule: tuned params keep
        # moving fusion/cycle/cache but must not un-pin it (identical on
        # every rank — the env is launcher-uniform, capability is
        # topology-derived).
        self._hier_pinned = bool(hier_req and self._hier_capable)
        if hier_req:
            if self._hier_capable:
                self.hierarchical = True
            else:
                # The single-slice half of this downgrade warns at
                # init() (basics.py) so jit-only jobs see it too; this
                # covers a multi-slice topology whose plane can't run
                # the schedule (plane disabled/failed, uneven slices).
                LOG.warning(
                    "hierarchical allreduce requested but the device "
                    "plane cannot run the two-fabric schedule on this "
                    "topology (%d slices over %d ranks, plane=%s); "
                    "downgrading to flat allreduce",
                    getattr(topo, "num_slices", 1), self.world,
                    "ok" if self._device_plane is not None else "absent",
                )
        dcn_choice = (
            os.environ.get(envmod.DCN_COMPRESSION) or "none"
        ).strip().lower()
        if dcn_choice not in DCN_WIRES:
            LOG.warning(
                "unknown %s=%r (choices: %s); DCN wire stays uncompressed",
                envmod.DCN_COMPRESSION, dcn_choice,
                "/".join(sorted(DCN_WIRES)),
            )
            dcn_choice = "none"
        self._dcn_wire = DCN_WIRES[dcn_choice]

        # Stable-schedule replay fast path (ROADMAP item 1b; GSPMD's
        # static-schedule guarantee recreated dynamically): after
        # `replay_after` consecutive cycles whose executed schedule is
        # bitwise-identical on every rank — a pure function of data all
        # ranks share, so every rank flips in the same cycle — the engine
        # stops exchanging control vectors entirely and replays the
        # memorized fused schedule, re-validated per cycle by a one-scalar
        # epoch-check lane on the first fused buffer (the same
        # ride-the-data trick as the shutdown-flag propagation).  Any
        # deviation (cache MISS/CONFLICT, new tensor, shutdown, join,
        # tuner move, sustained stall) raises the lane and every rank
        # falls back to full negotiation on the same cycle.
        self.replay_enabled = (
            self.world > 1
            and envmod.env_bool(envmod.SCHEDULE_REPLAY, default=True)
        )
        self.replay_after = max(
            2,
            envmod.env_int(
                envmod.SCHEDULE_REPLAY_CYCLES, envmod.DEFAULT_REPLAY_CYCLES
            ),
        )
        self._replaying = False
        self._replay_plan: Optional[List[Response]] = None
        self._replay_names: frozenset = frozenset()
        self._replay_idle_since: Optional[float] = None
        self._stable_cycles = 0
        self._last_sched_key: Optional[tuple] = None
        # Epoch-check lane plumbing (_execute_allreduce): set for the
        # first fused buffer of a replay cycle only.
        self._replay_flag_lane: Optional[float] = None
        self._replay_flag_total = 0.0

        # Autotuner (reference parameter_manager.cc, reworked into a
        # continuous controller): rank 0 scores bytes per second of BUSY
        # cycle time — read straight off this engine's registry
        # instruments, so the telemetry plane is the objective function —
        # and proposes new params; peers apply whatever rides rank 0's
        # RequestList.  After convergence it holds but keeps watching;
        # a drift-detector reopen ships new params, which deterministically
        # breaks any replay epoch (a tuner move is a deviation).
        self._pm: Optional[ParameterManager] = None
        self._pending_params: Optional[tuple] = None
        if self.rank == 0 and envmod.env_bool(envmod.AUTOTUNE):
            # Topology-derived category chain (autotune.build_categories,
            # shared with the native engine): continuous knobs (fusion,
            # cycle) plus the response-cache toggle, plus — ONLY on
            # multi-slice topologies whose plane can run the two-fabric
            # schedule — hierarchical_allreduce, so the online controller
            # picks flat vs hierarchical from measured bytes/sec.
            categories = build_categories(
                multislice=self._hier_capable,
                replay_enabled=self.replay_enabled,
            )
            if self._hier_pinned:
                # The pin removes the hierarchical axis from the search:
                # every category keeps the schedule on (deduped), so a
                # noisy sample window can never score the job back to
                # flat against the user's explicit flag.
                seen: set = set()
                pinned = []
                for c in categories:
                    c = {**c, "hierarchical_allreduce": True}
                    k = tuple(sorted(c.items()))
                    if k not in seen:
                        seen.add(k)
                        pinned.append(c)
                categories = pinned
            self._pm = ParameterManager(
                enabled=True,
                initial=TunedParams(
                    fusion_bytes=self.fusion_bytes, cycle_s=self.cycle_s,
                    hierarchical_allreduce=self.hierarchical,
                ),
                log_path=os.environ.get(envmod.AUTOTUNE_LOG) or None,
                categories=categories,
                metrics_source=(
                    lambda fb=self._m_fusion_bytes, cy=self._m_cycle_ms:
                    (fb.sum, cy.sum / 1e3)
                ),
            )

    # ------------------------------------------------------------------ API

    @classmethod
    def start(cls) -> "EagerEngine":
        eng = cls()
        if eng.world > 1:
            eng._thread = threading.Thread(
                target=eng._loop, name="hvdtpu_background", daemon=True
            )
            eng._thread.start()
            atexit.register(eng.shutdown)
        return eng

    def enqueue(
        self,
        op: RequestType,
        name: str,
        tensor: Optional[np.ndarray],
        *,
        reduce_op: int = 0,
        root_rank: int = -1,
        prescale: float = 1.0,
        postscale: float = 1.0,
    ) -> concurrent.futures.Future:
        """reference EnqueueTensorAllreduce/... operations.cc:803-954."""
        # Deterministic chaos (HVDTPU_FAULT_SPEC "enqueue:..."): fail the
        # submission before it reaches negotiation, the same surface an
        # OOM snapshotting the payload or a dead transport would present.
        maybe_fail("enqueue", name=name)
        # Flight recorder: the submission is the first fact the
        # post-mortem aligns on — a rank that enqueued an op its peers
        # never did is the classic desync, and this event is how the
        # analyzer proves it.  O(1), in-place slot write.
        obs_flightrec.record(
            "enqueue", name=name, cycle=self.stats["cycles"],
            detail=op.name,
        )
        shape = tuple(tensor.shape) if tensor is not None else ()
        dtype = str(tensor.dtype) if tensor is not None else "float32"
        req = Request(
            request_rank=self.rank,
            request_type=op,
            tensor_name=name,
            dtype=dtype,
            shape=shape,
            reduce_op=reduce_op,
            root_rank=root_rank,
            prescale_factor=prescale,
            postscale_factor=postscale,
            device=self._device_enabled and _is_device_tensor(tensor),
        )
        if self.world > 1 and isinstance(tensor, jax.Array):
            # Snapshot the payload at enqueue (an async device-to-device
            # copy — still zero host round-trips).  The engine's reference
            # to the caller's array does not survive jit donation: without
            # the snapshot a buffer donated between enqueue and the
            # background cycle would fail materialization on this rank
            # after peers already negotiated the collective — a distributed
            # hang.  The reference's enqueue likewise memcpys into its own
            # buffer (fusion_buffer_manager.cc).
            tensor = jnp.copy(tensor)
        entry = TensorTableEntry(request=req, tensor=tensor)
        if self.world == 1:
            self._execute_local(entry)
            return entry.future
        with self._lock:
            if self._done:
                entry.future.set_exception(
                    HorovodShutdownError(SHUT_DOWN_ERROR)
                )
                return entry.future
            if name in self._table:
                entry.future.set_exception(
                    ValueError(DUPLICATE_NAME_ERROR.format(op=op.name.lower()))
                )
                return entry.future
            self._table[name] = entry
            self._pending.append(req)
        return entry.future

    def join(self) -> concurrent.futures.Future:
        """reference EnqueueJoin (operations.cc:930) + §3.5 semantics:
        mark this rank joined; pending peers' collectives proceed with this
        rank contributing zeros; resolves when every rank has joined."""
        fut: concurrent.futures.Future = concurrent.futures.Future()
        if self.world == 1:
            fut.set_result(0)
            return fut
        with self._lock:
            self._joined = True
            self._join_future = fut
        return fut

    def barrier(self) -> concurrent.futures.Future:
        # Sequence-numbered so overlapping barriers queue instead of
        # colliding with DUPLICATE_NAME; the Nth barrier call on every
        # rank pairs up (same convention as unnamed-tensor sequence names).
        with self._lock:
            self._barrier_seq += 1
            seq = self._barrier_seq
        return self.enqueue(RequestType.BARRIER, f"hvdtpu.barrier.{seq}", None)

    def shutdown(self) -> None:
        """Coordinated shutdown, reference semantics: ANY rank's shutdown
        flag propagates through the negotiation and tears the whole job
        down; peers' outstanding entries fail with SHUT_DOWN_ERROR
        (reference controller.cc:256-259,309 + operations.cc:526-532).
        The flag rides the next cycle so every rank exits its loop in the
        same cycle — no rank stops cycling unilaterally."""
        if self.world == 1:
            self._done = True
            return
        with self._lock:
            if self._done:
                return
            self._shutdown_requested = True
        if self._thread is not None and self._thread is not threading.current_thread():
            self._thread.join(timeout=30)
        self.timeline.shutdown()

    def _publish_stats(self, metrics) -> None:
        """Snapshot-time collector: publish the stats dict (and derived
        rates) as gauges.  Runs at dump/summary time, not per cycle."""
        for key, value in self.stats.items():
            metrics.gauge(f"engine.stats.{key}").set(value)
        lookups = self.stats["cache_hits"] + self.stats["cache_misses"]
        if lookups:
            metrics.gauge("engine.cache_hit_rate").set(
                self.stats["cache_hits"] / lookups
            )
        metrics.gauge("engine.fusion_threshold_bytes").set(self.fusion_bytes)
        # The headline steady-state number: fraction of executed cycles
        # that paid NO control-plane exchange (the CI fastpath gate and
        # the bench record both read this).
        if self.stats["cycles"]:
            metrics.gauge("engine.negotiation_skip_rate").set(
                1.0 - self.stats["negotiated_cycles"] / self.stats["cycles"]
            )
        metrics.gauge("engine.replay_active").set(
            1.0 if self._replaying else 0.0
        )

    # ------------------------------------------------------ background loop

    def _loop(self) -> None:
        while True:
            start = time.monotonic()
            try:
                again = self._run_loop_once()
            except Exception as exc:  # transport/controller failure
                LOG.error("background loop error: %s", exc)
                # The loop swallows this (peers' futures get it), so the
                # excepthook will never see it — record it here or the
                # black box ends with an unexplained last cycle.
                obs_flightrec.record_exception(exc, where="engine.loop")
                self._fail_all(exc)
                return
            elapsed = time.monotonic() - start
            self._m_cycle_ms.observe(elapsed * 1e3)
            if not again:
                break
            if elapsed < self.cycle_s:
                time.sleep(self.cycle_s - elapsed)
        # Typed so elastic.run can classify engine teardown as recoverable
        # (HorovodShutdownError subclasses RuntimeError — pre-elastic call
        # sites keep working).
        self._fail_all(HorovodShutdownError(SHUT_DOWN_ERROR))
        self._done = True

    def _run_loop_once(self) -> bool:
        """One cycle: the replay fast path when an epoch is open, the
        negotiated path otherwise."""
        if self._replaying:
            return self._run_replay_once()
        return self._run_negotiated_once()

    def _run_negotiated_once(self) -> bool:
        """One negotiated cycle (reference RunLoopOnce, operations.cc:550).

        Steady-state fast path, tier 1 (reference ComputeResponseList
        controller.cc:174-202 + CacheCoordinator::sync): requests that hit
        the response cache only arm a slot bit; the cycle exchanges ONE
        fixed-size control vector, and full serialized RequestLists ride a
        second exchange only when some rank actually has uncached work.

        Tier 2 — schedule replay — is armed HERE: every cycle's stability
        is judged from the gathered control data (identical on all
        ranks), and `replay_after` consecutive identical schedules flip
        every rank into `_run_replay_once` on the same cycle."""
        self.timeline.mark_cycle()
        with self._lock:
            requests = list(self._pending)
            self._pending.clear()
            shutdown = self._shutdown_requested
            joined = self._joined
            params = self._pending_params
            self._pending_params = None

        now = time.monotonic()
        misses: List[Request] = []
        for req in requests:
            status, slot = (
                self._cache.lookup(req)
                if self.cache_enabled
                else (rcache.MISS, -1)
            )
            if (
                status == rcache.HIT
                and req.key() in self._controller.message_table
            ):
                # Divergence repair, part 1: a peer already negotiated this
                # name through the slow path (a tuner cache toggle can land
                # on opposite sides of a straggler enqueue, so ranks may
                # classify the same tensor differently).  Arming would
                # deadlock — the slot vote waits on the peer while the
                # peer's table entry waits on us — so fall through to the
                # slow path with everyone else.
                status = rcache.MISS
            if status == rcache.HIT:
                self._armed[slot] = req
                self._armed_since[slot] = now
                self.stats["cache_hits"] += 1
            else:
                misses.append(req)
                self.stats["cache_misses"] += 1

        payload = b""
        if misses or params is not None:
            payload = RequestList(
                requests=misses, tuned_params=params
            ).serialize()

        trace_on = obs_trace.enabled()
        t_negw = time.time() if trace_on else 0.0
        t_neg = time.monotonic()
        shutdown_ranks, joined_ranks, bits, all_lists = self._exchange(
            payload, shutdown, joined
        )
        self._m_negotiate_ms.observe((time.monotonic() - t_neg) * 1e3)
        self._m_queue_depth.set(len(self._table))
        self.stats["cycles"] += 1
        self.stats["negotiated_cycles"] += 1
        if trace_on:
            # Training-side tracing, step ≙ engine cycle: the same
            # merged view that decomposes a serve request decomposes a
            # training step into negotiation vs wire time.
            obs_trace.add_span("engine", "negotiate", t_negw,
                               time.time(), step=self.stats["cycles"])

        state = self._controller
        state.shutdown_ranks.update(shutdown_ranks)
        state.joined_ranks.update(joined_ranks)

        # Cache votes: a slot executes when every non-joined rank armed it
        # (bitvector AND ≙ response_cache.h:133-137 status bits).  Computed
        # from the GLOBAL bit matrix, not the local _armed dict: a joined
        # rank armed nothing but must still participate (with zeros) in the
        # cached collectives its peers execute — same invariant as the
        # slow path's zero-contribution entries.
        voted: set = set()
        union = np.bitwise_or.reduce(bits, axis=0) if len(bits) else bits
        for byte_i, byte in enumerate(union):
            b = int(byte)
            while b:
                bit = b & -b
                voted.add(byte_i * 8 + bit.bit_length() - 1)
                b ^= bit
        ready: List[int] = []
        for slot in sorted(voted):
            if all(
                ((bits[r, slot >> 3] >> (slot & 7)) & 1)
                or (r in state.joined_ranks)
                for r in range(self.world)
            ):
                ready.append(slot)
        cached_responses: List[Response] = []
        for slot in ready:
            self._cache.touch(slot)  # LRU in deterministic slot order
            cached_responses.append(self._cache.response_for(slot))
            self._armed.pop(slot, None)
            self._armed_since.pop(slot, None)
        cached_responses = _fuse(cached_responses, state, self.fusion_bytes)
        self.stats["cached_responses"] += len(ready)
        self._check_armed_stalls(now)
        # Slots any rank is voting on this cycle must survive LRU eviction
        # during this cycle's insertions: evicting a slot a peer is armed
        # on would leave it voting for a dead/reassigned slot.  The union
        # is identical on every rank, so eviction stays coherent.
        protected = voted

        fast = all_lists is None
        if all_lists is None:
            self.stats["fast_cycles"] += 1
            all_lists = [RequestList() for _ in range(self.world)]
        else:
            self.stats["payload_cycles"] += 1
            # Conflict resolution: a re-submission under a cached name with
            # different params invalidates the cache entry on EVERY rank
            # (all see the same payloads); if we were voting on the stale
            # slot, fall back to renegotiating our own request next cycle.
            for rlist in all_lists:
                for req in rlist.requests:
                    st, slot = self._cache.lookup(req)
                    if st == rcache.CONFLICT:
                        stale = self._armed.pop(slot, None)
                        self._armed_since.pop(slot, None)
                        self._cache.evict_name(req.tensor_name)
                        if stale is not None:
                            with self._lock:
                                self._pending.append(stale)
                    elif st == rcache.HIT and slot in self._armed:
                        # Divergence repair, part 2 (see the MISS
                        # reclassification above): a peer negotiated this
                        # name slow-path while we already hold it armed.
                        # The slot vote can never complete (the peer's bit
                        # will not arrive), so move our armed request back
                        # through negotiation; the peer's table entry then
                        # completes on our next payload.
                        stale = self._armed.pop(slot)
                        self._armed_since.pop(slot, None)
                        with self._lock:
                            self._pending.append(stale)
            # Parameter sync: every rank (rank 0 included — it may have
            # tuned last cycle) applies the params riding rank 0's list.
            if all_lists[0].tuned_params is not None:
                self._apply_params(
                    TunedParams.from_wire(all_lists[0].tuned_params)
                )

        self._cache.protected = protected
        responses, should_shutdown = compute_responses(
            state,
            all_lists,
            fusion_threshold_bytes=self.fusion_bytes,
            stall_warning_secs=self.stall_warn,
            stall_shutdown_secs=self.stall_shutdown,
            alert_skew_ms=self.alert_skew_ms,
            timeline=self.timeline,
            cache=self._cache,
        )
        self._cache.protected = frozenset()
        self.stats["negotiated_responses"] += sum(
            len(r.tensor_names)
            for r in responses
            if r.response_type != ResponseType.JOIN
        )
        # Cached responses execute first, then freshly negotiated ones —
        # the same deterministic order on every rank.
        t_execw = time.time() if trace_on else 0.0
        for resp in cached_responses:
            self._perform_operation(resp)
        for resp in responses:
            self._perform_operation(resp)
        if trace_on and (cached_responses or responses):
            obs_trace.add_span(
                "engine", "execute", t_execw, time.time(),
                step=self.stats["cycles"],
                responses=len(cached_responses) + len(responses),
            )
        if self._pm is not None:
            for resp in cached_responses + responses:
                self._pm.record_bytes(_response_bytes(resp))
            proposal = self._pm.cycle()
            if proposal is not None:
                # Same write the replay path makes under the lock:
                # _pending_params is drained under self._lock at cycle
                # start, so the publish side must hold it too.
                with self._lock:
                    self._pending_params = proposal.as_wire()

        # ---- replay arming: judge this cycle's stability --------------
        # Every input below is shared data (gathered control vector,
        # deterministic cache/controller state) — deliberately NOT local
        # facts like _pending_params, so the stability counters stay
        # bitwise-identical on every rank and all ranks enter the replay
        # epoch on the same cycle.  A rank-local fact (rank 0's fresh
        # tuner proposal) surfaces as a deviation INSIDE the epoch
        # instead, where the flag lane makes it global.
        key = None
        neutral = False
        if (
            self.replay_enabled
            and self.cache_enabled
            and fast                      # no payload exchanged anywhere
            and not state.shutdown_ranks
            and not state.joined_ranks
            and not state.message_table   # no negotiation mid-flight
        ):
            if ready and set(ready) == voted:  # every armed slot completed
                key = self._cache.schedule_key(ready)
            elif not ready:
                # Nothing EXECUTED this cycle (between steps, or an arm
                # that straddled a cycle boundary and hasn't completed
                # its vote yet): evidence of neither stability nor
                # change — the same idle gap a replay epoch tolerates.
                # Neutral: leave the counter and the last key alone.
                # (Judged from the gathered bit matrix, so identical
                # everywhere.)
                neutral = True
        if not neutral:
            if key is not None and key == self._last_sched_key:
                self._stable_cycles += 1
            else:
                self._stable_cycles = 1 if key is not None else 0
            self._last_sched_key = key
        if (
            key is not None
            and self._stable_cycles >= self.replay_after
            and _replay_plan_ok(cached_responses, self.world)
        ):
            self._enter_replay(cached_responses)
        return not should_shutdown

    # ------------------------------------------------------ schedule replay

    def _enter_replay(self, plan: List[Response]) -> None:
        """Open a replay epoch: memorize the fused schedule every rank
        just executed identically `replay_after` times.  Called from the
        negotiated path with arguments that are identical on every rank,
        so every rank opens the epoch on the same cycle."""
        self._replaying = True
        self._replay_plan = list(plan)
        self._replay_names = frozenset(
            n for resp in plan for n in resp.tensor_names
        )
        self._replay_idle_since = None
        self.stats["replay_epochs"] += 1
        obs_flightrec.record(
            "replay_enter", name=",".join(sorted(self._replay_names)),
            cycle=self.stats["cycles"],
            detail=f"{len(plan)} fused responses",
        )
        LOG.info(
            "entering schedule-replay epoch after %d stable cycles "
            "(%d fused responses, %d tensors)",
            self._stable_cycles, len(plan), len(self._replay_names),
        )

    def _exit_replay(self, reason: str) -> None:
        self._replaying = False
        self._replay_plan = None
        self._replay_names = frozenset()
        self._replay_idle_since = None
        self._stable_cycles = 0
        self._last_sched_key = None
        self.stats["replay_breaks"] += 1
        self._metrics.counter("engine.replay_break", reason=reason).inc()
        obs_flightrec.record(
            "replay_break", name="", cycle=self.stats["cycles"],
            detail=reason,
        )
        LOG.info("schedule-replay epoch broken: %s", reason)

    def _run_replay_once(self) -> bool:
        """One replay cycle: zero control-plane exchange.

        Safety argument (docs/performance.md has the long form): the
        epoch was entered by every rank on the same cycle from shared
        data; inside it, every rank executes the same memorized fused
        collectives in the same order, so the SPMD schedule stays
        matched by construction.  Re-validation rides the FIRST fused
        buffer: one extra scalar lane carries this rank's deviation
        flag, the reduction makes the flag sum visible to everyone who
        participates, and a nonzero sum means every rank discards the
        cycle's data (a deviating rank contributed zeros), restores its
        entries, and falls back to full negotiation — which is built
        for skew, conflicts and shutdown.  A deviating or stalled rank
        always still joins that first collective (flags up, zeros
        down), so no peer is left blocked."""
        self.timeline.mark_cycle()
        now = time.monotonic()
        plan = self._replay_plan
        with self._lock:
            requests = list(self._pending)
            self._pending.clear()
            shutdown = self._shutdown_requested
            joined = self._joined
            params_pending = self._pending_params is not None

        deviation = None
        leftovers: List[Request] = []
        for req in requests:
            status, _slot = (
                self._cache.lookup(req)
                if self.cache_enabled
                else (rcache.MISS, -1)
            )
            if status == rcache.HIT and req.tensor_name in self._replay_names:
                continue  # steady-state re-arm; its entry is in the table
            leftovers.append(req)
            deviation = "conflict" if status == rcache.CONFLICT else "miss"
        if leftovers:
            with self._lock:
                # keep arrival order for the renegotiation that follows
                self._pending[:0] = leftovers
        if params_pending:
            deviation = "tuner-move"
        if joined:
            deviation = "join"
        if shutdown:
            deviation = "shutdown"

        if deviation is None:
            with self._lock:
                is_ready = all(
                    n in self._table for n in self._replay_names
                )
            if not is_ready:
                # Nothing (or not everything) enqueued yet.  Peers that
                # are ready wait inside the first fused collective — the
                # same wait slow-path negotiation would impose on them.
                # Sustained idleness past the stall-warning budget breaks
                # the epoch instead: long skew belongs to the
                # skew-tolerant negotiated path.
                if self._replay_idle_since is None:
                    self._replay_idle_since = now
                # Bounded even under --no-stall-check (stall_warn=inf):
                # this deadline is replay's ONLY liveness escape — a
                # ready or deviating peer is blocked inside the first
                # fused collective until this rank joins it, and a flag
                # that never comes would hang the world.  The negotiated
                # path has no such wait (idle ranks still exchange
                # control vectors), so disabling stall WARNINGS must not
                # disable this.
                if now - self._replay_idle_since > min(self.stall_warn, 60.0):
                    deviation = "stall"
                    LOG.warning(
                        "replay epoch stalled for %.0f s waiting for "
                        "local enqueues; breaking back to negotiation",
                        now - self._replay_idle_since,
                    )
                else:
                    self.stats["replay_idle_cycles"] += 1
                    return True
        self._replay_idle_since = None

        first = plan[0]
        my_flag = 1.0 if deviation else 0.0
        if deviation:
            # Participate with zeros: the nonzero flag makes everyone
            # discard this cycle's data, so the lanes only need to be
            # shaped right, not meaningful.
            entries1: List[Optional[TensorTableEntry]] = (
                [None] * len(first.tensor_names)
            )
        else:
            with self._lock:
                entries1 = [
                    self._table.pop(n, None) for n in first.tensor_names
                ]
        self._replay_flag_lane = my_flag
        self._replay_flag_total = 0.0
        try:
            try:
                self._execute_allreduce(first, entries1)
            finally:
                self._replay_flag_lane = None
        except BaseException:
            # Transport failure mid-replay: put the popped entries back
            # so the loop's _fail_all can fail their futures too.
            with self._lock:
                for e in entries1:
                    if e is not None:
                        self._table[e.request.tensor_name] = e
            raise

        self.stats["cycles"] += 1
        if my_flag != 0.0 or self._replay_flag_total != 0.0:
            # Epoch broken (locally or by a peer): the flag sum is the
            # same for every participant, so every rank takes this
            # branch on the same cycle.  _execute_allreduce skipped the
            # scatter, so no future saw the discarded data.
            with self._lock:
                for e in entries1:
                    if e is not None:
                        self._table[e.request.tensor_name] = e
                pending_names = {r.tensor_name for r in self._pending}
                # Every planned tensor already enqueued locally goes back
                # through negotiation (its request was consumed as a
                # re-arm in some earlier replay cycle).
                for name in sorted(self._replay_names):
                    e = self._table.get(name)
                    if e is not None and name not in pending_names:
                        self._pending.append(e.request)
            self._exit_replay(deviation or "peer-flag")
            return True

        # Clean replay cycle: deliver the rest of the memorized schedule.
        self.stats["replay_cycles"] += 1
        names = ",".join(first.tensor_names)
        obs_flightrec.record(
            "replay", name=names, cycle=self.stats["cycles"],
            detail=first.response_type.name,
        )
        done = len(first.tensor_names)
        self.stats["cached_responses"] += done
        self._m_completed.inc(done)
        self._m_fusion_bytes.observe(_response_bytes(first))
        obs_progress.tick(done)
        t_execw = time.time()
        for resp in plan[1:]:
            self._perform_operation(resp)
            self.stats["cached_responses"] += len(resp.tensor_names)
        if obs_trace.enabled():
            # Replay cycles have no negotiate span by construction —
            # in the merged view a replaying engine's lane is wire
            # time with the negotiation bars gone.
            obs_trace.add_span("engine", "replay_execute", t_execw,
                               time.time(), step=self.stats["cycles"],
                               responses=len(plan))
        if self._pm is not None:
            for resp in plan:
                self._pm.record_bytes(_response_bytes(resp))
            proposal = self._pm.cycle()
            if proposal is not None:
                with self._lock:
                    self._pending_params = proposal.as_wire()
        return True

    def _check_armed_stalls(self, now: float) -> None:
        """Armed-but-unready slots live outside the controller's message
        table, so the stall inspector can't see them; warn here (reference
        stall_inspector.cc InvalidateStalledCachedTensors)."""
        if now - self._last_armed_stall_check < min(self.stall_warn, 10.0):
            return
        self._last_armed_stall_check = now
        for slot, since in self._armed_since.items():
            age = now - since
            if age > self.stall_warn:
                self._m_cached_stalls.inc()
                LOG.warning(
                    "Cached tensor %s has been waiting on peer ranks for "
                    "%.0f s",
                    self._cache.name_for(slot),
                    age,
                )
                if self.stall_shutdown > 0 and age > self.stall_shutdown:
                    raise RuntimeError(
                        f"Stalled cached tensor {self._cache.name_for(slot)} "
                        f"exceeded shutdown threshold ({self.stall_shutdown}s)"
                    )

    def _apply_params(self, p: TunedParams) -> None:
        """Apply rank-0-tuned params (reference SynchronizeParameters,
        controller.cc:33-47).  The hierarchical toggle applies on the
        same cycle boundary on every rank (it rides the negotiation), so
        schedule selection stays coherent; the capability gate is
        topology-derived and identical everywhere."""
        self.fusion_bytes = p.fusion_bytes
        self.cycle_s = p.cycle_s
        self.cache_enabled = p.cache_enabled
        self.hierarchical = (
            bool(p.hierarchical_allreduce) or self._hier_pinned
        ) and self._hier_capable

    # ---------------------------------------------------------- negotiation

    def _exchange(self, payload: bytes, shutdown: bool, joined: bool):
        """One negotiation round: allgather a fixed-size control vector
        [flags | payload length | armed cache bits]; gather the serialized
        RequestList payloads in a second round ONLY if some rank has one
        (the reference's slow path, mpi_controller.cc:107-199 Gatherv +
        Bcast; the fast path is the control vector alone, ≙ the bitvector
        AND/OR allreduce of controller.cc:174-202).

        Returns (shutdown_ranks, joined_ranks, bits, all_lists) where bits
        is a (world, num_bits) uint8 matrix of armed votes and all_lists is
        None on a fast (control-only) cycle."""
        from jax.experimental import multihost_utils  # noqa: PLC0415

        nbits = self._cache.num_bits
        vec = np.zeros(5 + nbits, np.uint8)
        vec[0] = (
            (1 if shutdown else 0)
            | (2 if joined else 0)
            | (4 if payload else 0)
            | (8 if self._device_plane is None else 0)  # "no device plane"
        )
        vec[1:5] = np.frombuffer(
            np.uint32(len(payload)).tobytes(), np.uint8
        )
        for slot in self._armed:
            vec[5 + (slot >> 3)] |= 1 << (slot & 7)
        gathered = np.asarray(
            multihost_utils.process_allgather(vec)
        ).reshape(self.world, -1)
        self.stats["control_bytes"] += int(vec.size) * self.world

        flags = gathered[:, 0]
        shutdown_ranks = {r for r in range(self.world) if flags[r] & 1}
        joined_ranks = {r for r in range(self.world) if flags[r] & 2}
        # Plane coherence: the device/staged data planes are used only when
        # EVERY rank has one — evaluated from this same gathered vector, so
        # the decision is identical everywhere this cycle.
        self._plane_ok_all = not bool((flags & 8).any())
        bits = gathered[:, 5:]
        if not bool((flags & 4).any()):
            return shutdown_ranks, joined_ranks, bits, None

        lengths = gathered[:, 1:5].copy().view(np.uint32).reshape(-1)
        max_len = int(lengths.max())
        buf = np.zeros(max_len, np.uint8)
        buf[: len(payload)] = np.frombuffer(payload, np.uint8)
        pg = np.asarray(
            multihost_utils.process_allgather(buf)
        ).reshape(self.world, max_len)
        self.stats["payload_bytes"] += max_len * self.world
        all_lists = [
            RequestList.deserialize(pg[r, : int(lengths[r])].tobytes())
            if lengths[r]
            else RequestList()
            for r in range(self.world)
        ]
        return shutdown_ranks, joined_ranks, bits, all_lists

    # ------------------------------------------------------------ execution

    def _perform_operation(self, resp: Response) -> None:
        """reference PerformOperation (operations.cc:232-309)."""
        if resp.response_type == ResponseType.JOIN:
            with self._lock:
                fut, self._join_future = self._join_future, None
                self._joined = False
            if fut is not None:
                fut.set_result(self.world - 1)
            return

        entries: List[Optional[TensorTableEntry]] = []
        with self._lock:
            for name in resp.tensor_names:
                entries.append(self._table.pop(name, None))

        if resp.response_type == ResponseType.ERROR:
            obs_flightrec.record(
                "error", name=",".join(resp.tensor_names),
                cycle=self._controller.cycle_index,
                detail=(resp.error_message or "")[:200],
            )
            for e in entries:
                if e is not None:
                    e.future.set_exception(RuntimeError(resp.error_message))
            return

        try:
            names = ",".join(resp.tensor_names)
            obs_flightrec.record(
                "execute", name=names,
                cycle=self._controller.cycle_index,
                detail=resp.response_type.name,
            )
            self.timeline.start(names, resp.response_type.name)
            if resp.response_type in (
                ResponseType.ALLREDUCE,
                ResponseType.ADASUM,
            ):
                self._execute_allreduce(resp, entries)
            elif resp.response_type == ResponseType.ALLGATHER:
                self._execute_allgather(resp, entries)
            elif resp.response_type == ResponseType.BROADCAST:
                self._execute_broadcast(resp, entries)
            elif resp.response_type == ResponseType.ALLTOALL:
                self._execute_alltoall(resp, entries)
            elif resp.response_type == ResponseType.REDUCESCATTER:
                self._execute_reducescatter(resp, entries)
            elif resp.response_type == ResponseType.BARRIER:
                e = entries[0]
                if e is not None:
                    e.future.set_result(None)
            self.timeline.end(names, resp.response_type.name)
            obs_flightrec.record(
                "complete", name=names,
                cycle=self._controller.cycle_index,
                detail=resp.response_type.name,
            )
            # Progress beat source: a performed response proves the
            # collective path is moving (obs/progress.py); the count is
            # per user-level collective, so fused responses tick once
            # per member tensor.
            done = len(resp.tensor_names)
            self._m_completed.inc(done)
            self._m_fusion_bytes.observe(_response_bytes(resp))
            obs_progress.tick(done)
        except Exception as exc:
            for e in entries:
                if e is not None and not e.future.done():
                    e.future.set_exception(exc)

    # A joined rank has no entry for a tensor its peers are reducing: it
    # participates with zeros of the negotiated shape (reference
    # tensor_queue.h:39-41 zero-tensor substitution).

    # ------------------------------------------------------ device data plane

    def _plane(self):
        return self._device_plane

    def _use_device(self, resp: Response) -> bool:
        """Negotiated plane for this response — identical on all ranks:
        the controller sets _device = AND of every rank's Request.device,
        and _plane_ok_all is computed from the SAME cycle's gathered
        control flags, so no rank can demote to the host plane while a
        peer runs the device collective."""
        return bool(getattr(resp, "_device", False)) and self._plane_ok_all

    def _use_staged(self) -> bool:
        """Whether host payloads may reduce via the staged XLA plane —
        like _use_device, a function of data every rank shares."""
        return self._plane_ok_all

    def _data_allgather(self, local: np.ndarray) -> np.ndarray:
        """Data-plane allgather over processes -> (world, *local.shape).

        Transports RAW BYTES (uint8 view): jax without x64 silently casts
        float64/int64 payloads to 32-bit, so gathering the typed array
        would corrupt 64-bit tensors; bytes are lossless for every dtype.
        """
        from jax.experimental import multihost_utils  # noqa: PLC0415

        self.stats["host_data_ops"] += 1
        local = np.ascontiguousarray(local)
        self.stats["host_wire_bytes"] += int(local.nbytes)
        self.stats["host_recv_bytes"] += int(local.nbytes) * self.world
        raw = local.reshape(-1).view(np.uint8)
        out = multihost_utils.process_allgather(raw)
        flat = np.asarray(out).reshape(self.world, raw.size)
        return (
            flat.view(local.dtype).reshape((self.world,) + tuple(local.shape))
        )

    @staticmethod
    def _scatter_results(entries, shapes, total) -> None:
        """Slice the reduced fused buffer back to per-entry futures
        (MemcpyOutFusionBuffer analog); works on numpy and jax totals."""
        offset = 0
        for e, shape in zip(entries, shapes):
            n = int(np.prod(shape)) if shape else 1
            if e is not None:
                out = total[offset : offset + n].reshape(shape)
                e.future.set_result(out.astype(e.tensor.dtype))
            offset += n

    def _plane_allreduce(self, buf, dtype_name, reduce_op, pre, post,
                         is_int):
        """One XLA-plane reduce of a fused buffer — shared by the device
        path (jax buf in, jax total out) and the staged host path.

        Routes to the hierarchical (two-fabric) schedule when the tuned
        ``hierarchical`` flag is up, the plane has a slice mesh, and the
        negotiated reduce op composes with scatter-based reduction
        (SUM/AVERAGE) — every input to this decision is shared data, so
        all ranks issue the same collective.  Per-fabric byte counters
        are charged here: the hierarchical path's DCN leg carries
        1/slice_procs of the bytes (optionally on the compressed wire);
        a flat reduce on a multislice topology charges the full payload
        to DCN, which is the cost the schedule exists to avoid."""
        from ..ops.collectives import ReduceOp as _R  # noqa: PLC0415

        plane = self._plane()
        acc_dtype = (
            "float32" if dtype_name in ("bfloat16", "float16") else dtype_name
        )
        exact_int_avg = bool(is_int and reduce_op == int(_R.AVERAGE))
        wire_item = _np_dtype(dtype_name).itemsize
        if (
            self.hierarchical
            and plane.hierarchical_ok
            and reduce_op in (int(_R.SUM), int(_R.AVERAGE))
        ):
            # Integer payloads always cross DCN exact: a float-cast wire
            # would corrupt them.
            dcn_wire = self._dcn_wire if not is_int else None
            total = plane.allreduce_hier(
                buf, reduce_op, pre, post, acc_dtype, exact_int_avg,
                dcn_wire,
            )
            dcn_item = (
                _np_dtype(dcn_wire).itemsize if dcn_wire else wire_item
            )
            # Both fabrics charged at the PADDED size the schedule
            # actually moved: the dcn == ici / slice_procs identity must
            # hold exactly even when the buffer (e.g. with the replay
            # flag lane appended) is not divisible by slice_procs.
            shard_elems = -(-int(buf.size) // plane.slice_procs)
            self._m_ici_bytes.inc(
                shard_elems * plane.slice_procs * wire_item
            )
            self._m_dcn_bytes.inc(shard_elems * dcn_item)
            self._m_dcn_ratio.set(wire_item / dcn_item)
            return total
        if plane.num_slices > 1:
            # Flat reduce on a multislice world: the full payload
            # crosses the slow fabric — the cost the schedule avoids.
            # Single-slice jobs deliberately touch NEITHER counter, so
            # the fabric digest/summary sections stay absent there (the
            # documented contract).
            self._m_dcn_bytes.inc(int(buf.size) * wire_item)
        return plane.allreduce(
            buf,
            reduce_op,
            pre,
            post,
            acc_dtype=acc_dtype,
            exact_int_avg=exact_int_avg,
        )

    def _execute_allreduce(self, resp: Response, entries) -> None:
        # Replay epoch-check lane: when set (first fused buffer of a
        # replay cycle only), ONE extra scalar rides the buffer; after
        # the reduction the flag sum is published to _replay_flag_total
        # and a nonzero sum suppresses the scatter — the cycle's data is
        # being discarded because some rank deviated and contributed
        # zeros.  _replay_plan_ok guarantees the reduction preserves
        # nonzero flags, and _scatter_results slices by negotiated
        # offsets so the trailing lane never reaches a future.
        flag_lane = self._replay_flag_lane
        meta = getattr(resp, "_fuse_meta", None)
        shapes = getattr(resp, "_shapes", [()] * len(resp.tensor_names))
        dtype_name, reduce_op, pre, post = (
            meta if meta else ("float32", 1, 1.0, 1.0)
        )
        # Dtype-native wire: the buffer travels in the NEGOTIATED dtype
        # (bf16 gradients cost 2 bytes/elt on the wire, int64 sums are
        # exact — the reference likewise reduces dtype-native, half.cc /
        # mpi_operations.cc).  16-bit floats accumulate in f32, like the
        # reference's vectorized half kernels accumulate wide.
        wire_dtype = _np_dtype(dtype_name)
        is_int = wire_dtype.kind in ("i", "u")
        acc_dtype = (
            np.dtype(np.float32)
            if dtype_name in ("bfloat16", "float16")
            else wire_dtype
        )
        scaled = pre != 1.0 or post != 1.0
        if scaled and is_int:
            # pre/post scaling of integer tensors computes in f64 (the
            # reference's PrescaleFactor path also goes through double);
            # exactness beyond 2^53 is only guaranteed for scale == 1.
            acc_dtype = np.dtype(np.float64)
        from ..ops.collectives import ReduceOp as _R  # noqa: PLC0415

        # The XLA plane serves everything except ADASUM (numpy VHDD
        # reference math) and scaled ints (need f64) — conditions derived
        # from NEGOTIATED fields, so every rank picks the same plane.
        plane_ok = reduce_op != int(_R.ADASUM) and not (scaled and is_int)

        # Device-resident path: jax.Array payloads reduce as one compiled
        # XLA collective — no host round-trip (device_plane.py).
        if plane_ok and wire_dtype.kind != "b" and self._use_device(resp):
            wire_j = jnp.dtype(wire_dtype)
            flats = []
            for e, shape in zip(entries, shapes):
                if e is not None and e.tensor is not None:
                    flats.append(jnp.ravel(e.tensor).astype(wire_j))
                else:
                    n = int(np.prod(shape)) if shape else 1
                    flats.append(jnp.zeros(n, wire_j))
            if flag_lane is not None:
                flats.append(jnp.full(1, flag_lane, wire_j))
            if len(flats) > 1:
                try:
                    buf = jnp.concatenate(flats)
                except Exception:
                    # Entries committed to different local chips cannot
                    # be concatenated in place; the failure surfaces as
                    # ValueError or XlaRuntimeError depending on JAX
                    # version, so any concat failure falls back to fusing
                    # on the plane's anchor (chip-to-chip moves, no host
                    # round-trip).  A non-device failure fails the
                    # re-stage too and propagates from there.
                    anchor = self._plane().device
                    buf = jnp.concatenate(
                        [jax.device_put(f, anchor) for f in flats]
                    )
            else:
                buf = flats[0]
            total = self._plane_allreduce(
                buf, dtype_name, reduce_op, pre, post, is_int
            )
            self.stats["device_data_ops"] += 1
            self.stats["device_payload_bytes"] += (
                int(total.size) * wire_dtype.itemsize
            )
            if flag_lane is not None:
                self._replay_flag_total = abs(float(np.asarray(total[-1])))
                if self._replay_flag_total != 0.0:
                    return  # epoch broken: discard
            self._scatter_results(entries, shapes, total)
            return
        # Fused buffer: concat all entries (MemcpyInFusionBuffer analog,
        # collective_operations.cc:159-210).  A joined rank has no entry for
        # a tensor its peers are reducing and contributes zeros of the
        # negotiated shape (reference tensor_queue.h:39-41).
        flats = []
        for e, shape in zip(entries, shapes):
            if e is not None and e.tensor is not None:
                flats.append(np.ravel(np.asarray(e.tensor, wire_dtype)))
            else:
                n = int(np.prod(shape)) if shape else 1
                flats.append(np.zeros(n, wire_dtype))
        buf = np.concatenate(flats) if len(flats) > 1 else flats[0]
        # Host payloads of device-native dtypes reduce as a STAGED XLA
        # collective: one H2D, a real O(bytes) reduce over the plane's
        # gloo/ICI ring, one D2H — instead of the O(world x bytes)
        # gather-everything fallback (reference's GlooAllreduce ring,
        # gloo_operations.cc:107-142).  64-bit dtypes stay on the exact
        # raw-bytes gather (jax without x64 would truncate them).
        if plane_ok and dtype_name in _STAGEABLE_DTYPES and self._use_staged():
            if flag_lane is not None:
                # The plane scales pre/post in a float accumulator;
                # scaled ints never reach this path, so the flag
                # survives any qualifying scale (see _replay_plan_ok).
                buf = np.concatenate(
                    [buf, np.full(1, flag_lane, wire_dtype)]
                )
            total = np.asarray(
                self._plane_allreduce(
                    jnp.asarray(buf), dtype_name, reduce_op, pre, post,
                    is_int,
                )
            )
            self.stats["host_staged_ops"] += 1
            self.stats["host_wire_bytes"] += int(buf.nbytes)
            self.stats["host_recv_bytes"] += int(buf.nbytes)
            if flag_lane is not None:
                self._replay_flag_total = abs(float(total[-1]))
                if self._replay_flag_total != 0.0:
                    return  # epoch broken: discard
            self._scatter_results(entries, shapes, total)
            return
        if pre != 1.0:
            buf = (buf.astype(acc_dtype) * pre).astype(wire_dtype)
        if flag_lane is not None:
            # Appended AFTER the manual prescale: an int wire with a
            # fractional pre would otherwise truncate a lone flag to 0
            # and peers would silently scatter a deviating rank's zeros.
            buf = np.concatenate([buf, np.full(1, flag_lane, wire_dtype)])
        gathered = self._data_allgather(buf)
        if flag_lane is not None:
            # Raw gather delivers per-rank rows pre-reduction: read every
            # rank's flag exactly, then strip the lane before reducing.
            self._replay_flag_total = float(
                np.abs(gathered[:, -1].astype(np.float64)).sum()
            )
            gathered = gathered[:, :-1]
            if self._replay_flag_total != 0.0:
                return  # epoch broken: discard
        if reduce_op == int(_R.ADASUM):
            from ..ops.adasum import _numpy_adasum_rows  # noqa: PLC0415

            total = _numpy_adasum_rows(
                gathered.astype(np.float64)
            ).astype(wire_dtype)
        elif reduce_op == int(_R.MIN):
            total = gathered.astype(acc_dtype).min(axis=0)
        elif reduce_op == int(_R.MAX):
            total = gathered.astype(acc_dtype).max(axis=0)
        else:
            total = gathered.astype(acc_dtype).sum(axis=0)
            if reduce_op == int(_R.AVERAGE):
                if is_int and not scaled:
                    total = total // self.world  # exact int semantics
                else:
                    total = total / self.world
        if post != 1.0:
            total = total.astype(acc_dtype) * post
        self._scatter_results(entries, shapes, np.asarray(total))

    def _execute_allgather(self, resp: Response, entries) -> None:
        e = entries[0]
        sizes = resp.tensor_sizes
        max_d0 = max(sizes) if sizes else 0
        if self._use_device(resp):
            plane = self._plane()
            tail = tuple(getattr(resp, "_shapes", [(0,)])[0][1:])
            wire_j = jnp.dtype(_np_dtype(getattr(resp, "_dtype", "float32")))
            if e is None or e.tensor is None:
                local = jnp.zeros((0,) + tail, wire_j)
            else:
                local = jnp.asarray(e.tensor)
            pad = max_d0 - local.shape[0]
            if pad:
                local = jnp.concatenate(
                    [local, jnp.zeros((pad,) + tuple(local.shape[1:]),
                                      local.dtype)]
                )
            gathered = plane.allgather(local)
            self.stats["device_data_ops"] += 1
            self.stats["device_payload_bytes"] += int(gathered.nbytes)
            if e is None:
                return
            pieces = [gathered[r, : sizes[r]] for r in range(self.world)]
            e.future.set_result(jnp.concatenate(pieces, axis=0))
            return
        if e is None or e.tensor is None:
            # joined rank: participate with an all-pad buffer (its size
            # was negotiated as 0, so no rows of it survive the slicing)
            tail = tuple(getattr(resp, "_shapes", [(0,)])[0][1:])
            local = np.zeros((0,) + tail, _np_dtype(getattr(resp, "_dtype", "float32")))
        else:
            local = np.asarray(e.tensor)
        # Ragged: pad dim0 to the negotiated max (reference negotiates
        # per-rank sizes in Response::tensor_sizes, controller.cc:453-518;
        # XLA wants static shapes, so pad-and-slice).
        pad = max_d0 - local.shape[0]
        if pad:
            local = np.concatenate(
                [local, np.zeros((pad,) + local.shape[1:], local.dtype)]
            )
        gathered = self._data_allgather(local)
        if e is None:
            return
        pieces = [gathered[r, : sizes[r]] for r in range(self.world)]
        e.future.set_result(np.concatenate(pieces, axis=0))

    def _execute_broadcast(self, resp: Response, entries) -> None:
        e = entries[0]
        shape = tuple(getattr(resp, "_shapes", [()])[0])
        wire_name = getattr(resp, "_dtype", "float32")
        if self._use_device(resp):
            plane = self._plane()
            root = (
                e.request.root_rank
                if e is not None
                else getattr(resp, "_root_rank", 0)
            )
            if e is None or e.tensor is None:
                local = jnp.zeros(shape, jnp.dtype(_np_dtype(wire_name)))
            else:
                local = jnp.asarray(e.tensor)
            out = plane.broadcast(local, int(root))
            self.stats["device_data_ops"] += 1
            self.stats["device_payload_bytes"] += int(out.nbytes)
            if e is not None:
                e.future.set_result(out)
            return
        # Staged host broadcast: O(bytes) masked psum instead of gathering
        # every rank's buffer to deliver one root's tensor.
        if wire_name in _STAGEABLE_DTYPES and self._use_staged():
            plane = self._plane()
            root = (
                e.request.root_rank
                if e is not None
                else getattr(resp, "_root_rank", 0)
            )
            if e is None or e.tensor is None:
                local = np.zeros(shape, _np_dtype(wire_name))
            else:
                local = np.asarray(e.tensor)
            out = np.asarray(plane.broadcast(jnp.asarray(local), int(root)))
            self.stats["host_staged_ops"] += 1
            self.stats["host_wire_bytes"] += int(local.nbytes)
            self.stats["host_recv_bytes"] += int(local.nbytes)
            if e is not None:
                e.future.set_result(out.astype(local.dtype))
            return
        if e is None or e.tensor is None:
            local = np.zeros(shape, _np_dtype(wire_name))
            self._data_allgather(local)  # participate; result unused
            return
        gathered = self._data_allgather(np.asarray(e.tensor))
        e.future.set_result(gathered[e.request.root_rank])

    def _execute_alltoall(self, resp: Response, entries) -> None:
        e = entries[0]
        shape = tuple(getattr(resp, "_shapes", [()])[0])
        # Even-split device path; the shape is negotiated-identical, so the
        # divisibility test picks the same plane on every rank.
        if (
            shape
            and shape[0] % self.world == 0
            and self._use_device(resp)
        ):
            plane = self._plane()
            if e is None or e.tensor is None:
                local = jnp.zeros(
                    shape,
                    jnp.dtype(_np_dtype(getattr(resp, "_dtype", "float32"))),
                )
            else:
                local = jnp.asarray(e.tensor)
            out = plane.alltoall(local)
            self.stats["device_data_ops"] += 1
            self.stats["device_payload_bytes"] += int(out.nbytes)
            if e is not None:
                e.future.set_result(out)
            return
        if e is None or e.tensor is None:
            local = np.zeros(shape, _np_dtype(getattr(resp, "_dtype", "float32")))
            self._data_allgather(local)
            return
        local = np.asarray(e.tensor)
        if local.shape[0] % self.world:
            raise ValueError(
                f"alltoall dim0 ({local.shape[0]}) must divide world size "
                f"({self.world})"
            )
        gathered = self._data_allgather(local)
        k = local.shape[0] // self.world
        mine = np.concatenate(
            [gathered[r, self.rank * k : (self.rank + 1) * k] for r in range(self.world)],
            axis=0,
        )
        e.future.set_result(mine)

    def _execute_reducescatter(self, resp: Response, entries) -> None:
        """Sum across ranks, keep this rank's dim-0 rows; uneven dim0 gives
        the first (dim0 % world) ranks one extra row (the convention later
        Horovod versions adopted for hvd.reducescatter)."""
        e = entries[0]
        meta = getattr(resp, "_fuse_meta", None)
        dtype_name, reduce_op, pre, post = (
            meta if meta else ("float32", 1, 1.0, 1.0)
        )
        wire_dtype = _np_dtype(dtype_name)
        shape = tuple(getattr(resp, "_shapes", [(0,)])[0])
        from ..ops.collectives import ReduceOp as _R  # noqa: PLC0415

        # Even-split device path (psum_scatter); uneven dim0 falls back to
        # the host plane's extra-row convention.  16-bit floats accumulate
        # f32, ints are excluded (uneven exactness) — all negotiated fields.
        is_float = wire_dtype.kind == "f" or dtype_name in (
            "bfloat16", "float16"
        )
        if (
            bool(shape)
            and shape[0] % self.world == 0
            and is_float
            and self._use_device(resp)
        ):
            plane = self._plane()
            wire_j = jnp.dtype(wire_dtype)
            if e is None or e.tensor is None:
                local = jnp.zeros(shape, wire_j)
            else:
                local = jnp.asarray(e.tensor).astype(wire_j)
            out = plane.reducescatter(
                local,
                average=reduce_op == int(_R.AVERAGE),
                pre=pre,
                post=post,
                acc_dtype="float32"
                if dtype_name in ("bfloat16", "float16")
                else dtype_name,
            )
            self.stats["device_data_ops"] += 1
            self.stats["device_payload_bytes"] += int(local.nbytes)
            if e is not None:
                e.future.set_result(out.astype(e.tensor.dtype))
            return
        if e is None or e.tensor is None:
            local = np.zeros(shape, wire_dtype)
        else:
            local = np.asarray(e.tensor, wire_dtype)
        acc_dtype = (
            np.dtype(np.float32)
            if dtype_name in ("bfloat16", "float16")
            else wire_dtype
        )
        if pre != 1.0:
            local = (local.astype(acc_dtype) * pre).astype(wire_dtype)
        gathered = self._data_allgather(local)
        total = gathered.astype(acc_dtype).sum(axis=0)
        from ..ops.collectives import ReduceOp  # noqa: PLC0415

        if reduce_op == int(ReduceOp.AVERAGE):
            total = total / self.world
        if post != 1.0:
            total = total * post
        if e is None:
            return
        dim0 = shape[0]
        base, rem = divmod(dim0, self.world)
        start = self.rank * base + min(self.rank, rem)
        rows = base + (1 if self.rank < rem else 0)
        e.future.set_result(
            np.asarray(total[start : start + rows]).astype(e.tensor.dtype)
        )

    # -------------------------------------------------------- single process

    def _execute_local(self, entry: TensorTableEntry) -> None:
        """world==1: collectives are identities (with scaling applied).
        Device arrays pass through untouched — the ultimate zero-copy."""
        req = entry.request
        t = entry.tensor
        on_device = isinstance(t, jax.Array)
        _as = (lambda x: x) if on_device else np.asarray
        if req.request_type in (RequestType.ALLREDUCE, RequestType.ADASUM):
            out = _as(t)
            scale = req.prescale_factor * req.postscale_factor
            if scale != 1.0:
                out = out * scale
            entry.future.set_result(out)
        elif req.request_type in (
            RequestType.ALLGATHER,
            RequestType.ALLTOALL,
            RequestType.REDUCESCATTER,
        ):
            entry.future.set_result(_as(t))
        elif req.request_type == RequestType.BROADCAST:
            if req.root_rank not in (0, -1):
                entry.future.set_exception(
                    ValueError(
                        f"broadcast root_rank {req.root_rank} out of range "
                        f"for world size 1"
                    )
                )
            else:
                entry.future.set_result(_as(t))
        elif req.request_type == RequestType.BARRIER:
            entry.future.set_result(None)
        else:
            entry.future.set_result(None)
        # Count only actual completions (same placement discipline as
        # _perform_operation: after success, never before).
        if entry.future.done() and entry.future.exception() is None:
            obs_flightrec.record(
                "complete", name=req.tensor_name,
                detail=req.request_type.name,
            )
            self._m_completed.inc()
            obs_progress.tick()

    def _fail_all(self, exc: Exception) -> None:
        with self._lock:
            entries = list(self._table.values())
            self._table.clear()
            self._armed.clear()
            self._armed_since.clear()
            self._done = True
            jf, self._join_future = self._join_future, None
        for e in entries:
            if not e.future.done():
                e.future.set_exception(exc)
        if jf is not None and not jf.done():
            jf.set_exception(exc)
