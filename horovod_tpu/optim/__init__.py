"""Distributed optimizer layer.

TPU-native re-design of the reference's ``hvd.DistributedOptimizer``
(horovod/torch/__init__.py:67-222, horovod/tensorflow/__init__.py:266-311):
where the reference intercepts per-parameter gradient hooks and fires
``allreduce_async_`` as each grad materializes, the TPU build expresses the
same contract — "grads are globally reduced before the update" — as an
**optax gradient transformation** that runs inside the jitted SPMD step.

Scheduling caveat: because the transform runs inside ``tx.update``, its
psums sit *after* the whole backward pass in the compiled graph — XLA
will not hoist them into the backward on its own, so the wire time of
one end-of-step exchange is fully exposed.  The backward-overlap plane
(:mod:`horovod_tpu.optim.overlap`) restores the reference's
as-gradients-materialize overlap on the jit path: it plants one fused
collective per size-bounded gradient bucket in the cotangent graph
(``sync_gradients`` / ``OverlapPlan``), where the scheduler can hide it
behind remaining backward compute, and optionally reduce-scatter-shards
the optimizer update (ZeRO-1 shape).  Prefer it for throughput-critical
training; this transform remains the simple, composable default.
"""

from __future__ import annotations

import pickle
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..basics import DP_AXIS, global_topology, mesh as build_mesh
from ..ops.collectives import (
    Adasum,
    Average,
    ReduceOp,
    Sum,
    allreduce,
    grouped_allreduce,
)
from ..ops.compression import Compression

__all__ = [
    "DistributedOptimizer",
    "DistributedGradientTransform",
    "distribute",
    "broadcast_parameters",
    "broadcast_optimizer_state",
    "broadcast_object",
    "overlap",
    "sync_gradients",
    "OverlapPlan",
]

from . import overlap  # noqa: E402  (backward-overlap gradient plane)
from .overlap import OverlapPlan, sync_gradients  # noqa: E402


def DistributedGradientTransform(
    op: ReduceOp = Average,
    *,
    axis_name: str = DP_AXIS,
    compression=Compression.none,
    gradient_predivide_factor: float = 1.0,
    groups: Optional[int] = None,
    sparse_as_dense: bool = True,
    hierarchical_axes: Optional[tuple] = None,
    dcn_compression=None,
) -> optax.GradientTransformation:
    """An optax transform that allreduces grads across the mesh axis.

    Chain it in front of any optimizer::

        tx = optax.chain(hvd.DistributedGradientTransform(), optax.adam(1e-3))

    ``compression`` casts to a wire dtype around the reduce (reference
    compression.py).  ``gradient_predivide_factor`` splits the averaging
    into a pre-scale (1/f) and post-scale (f/N), the numerically-safer
    ordering for large worlds the reference exposes on its torch optimizer.
    ``groups``: number of fusion groups for grouped_allreduce (None = one
    fused reduce per dtype across the whole pytree, the analog of the 64 MB
    fusion buffer, fusion_buffer_manager.cc).
    ``sparse_as_dense``: IndexedSlices gradient leaves are scatter-added to
    dense before the reduce (reference DistributedOptimizer's
    sparse_as_dense option); with False they take the allgather path
    (horovod/tensorflow/__init__.py:74-89) and stay sparse in the output —
    only meaningful when the downstream optimizer knows how to apply them.
    ``hierarchical_axes``: ``(local_axis, cross_axis)`` of a two-fabric
    mesh (``hvd.mesh('hierarchical')`` or the slice mesh) — the reduce
    runs the 3-phase slice-aware schedule instead of the flat psum:
    reduce-scatter on ICI, cross-fabric exchange on 1/local_size of the
    bytes, gather back on ICI.  With ``op=Adasum`` the cross-fabric
    combiner is the Adasum projection (``hierarchical_adasum`` — the
    reference's AdasumGpuAllreduceOp hierarchy), which is
    order-insensitive, so slices can combine as they arrive.
    ``dcn_compression`` (``"bf16"``/``"fp16"``/None) additionally casts
    only the cross-fabric shard for Sum/Average hierarchical reduces.
    """
    if op not in (Average, Sum, Adasum):
        raise ValueError(f"DistributedGradientTransform supports Average/Sum/Adasum, got {op!r}")
    if hierarchical_axes is not None and len(hierarchical_axes) != 2:
        raise ValueError(
            "hierarchical_axes must be (local_axis, cross_axis), got "
            f"{hierarchical_axes!r}"
        )
    # NOTE: the gradient_predivide_factor x hierarchical incompatibility
    # is validated at the first update_fn call (below), not here: a
    # transform is often constructed generically (CLI-driven configs set
    # both knobs) and never actually run on the hierarchical schedule —
    # erroring at construction punished configurations that would never
    # hit the incompatible path.  update_fn is where the schedule
    # actually used is known.

    pre = 1.0
    post = 1.0
    eff_op = op
    if op == Average and gradient_predivide_factor != 1.0:
        # average = (1/f) before the wire, (f/N) after (reference torch
        # __init__.py gradient_predivide_factor plumbing).
        eff_op = Sum
        pre = 1.0 / gradient_predivide_factor

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params
        from ..ops.sparse import (  # noqa: PLC0415
            IndexedSlices,
            allreduce_sparse,
            to_dense,
        )

        leaves, treedef = jax.tree_util.tree_flatten(
            updates, is_leaf=lambda x: isinstance(x, IndexedSlices)
        )
        sparse_out = {}
        dense_idx = []
        dense_leaves = []
        for i, leaf in enumerate(leaves):
            if isinstance(leaf, IndexedSlices):
                if sparse_as_dense:
                    dense_idx.append(i)
                    dense_leaves.append(to_dense(leaf))
                else:
                    if op == Adasum:
                        # Reference parity: Adasum rejects sparse tensors
                        # (horovod/torch/mpi_ops.py Adasum+sparse raises).
                        raise ValueError(
                            "Adasum does not support sparse (IndexedSlices) "
                            "gradients; use sparse_as_dense=True or "
                            "op=Average/Sum."
                        )
                    sparse_out[i] = allreduce_sparse(
                        leaf, op, axis_name=axis_name
                    )
            else:
                dense_idx.append(i)
                dense_leaves.append(leaf)
        leaves = dense_leaves
        wire, ctxs = [], []
        for leaf in leaves:
            w, c = compression.compress(leaf)
            wire.append(w)
            ctxs.append(c)

        if hierarchical_axes is not None:
            if gradient_predivide_factor != 1.0:
                raise ValueError(
                    "gradient_predivide_factor is a flat-psum knob; the "
                    "hierarchical schedule applies its averaging once "
                    "after the cross-fabric phase"
                )
            from ..parallel.hierarchical import (  # noqa: PLC0415
                hierarchical_adasum,
                hierarchical_allreduce,
            )

            local_ax, cross_ax = hierarchical_axes
            if eff_op == Adasum:
                reduced = [
                    hierarchical_adasum(
                        w, local_axis=local_ax, cross_axis=cross_ax
                    )
                    for w in wire
                ]
            else:
                reduced = [
                    hierarchical_allreduce(
                        w, eff_op, local_axis=local_ax,
                        cross_axis=cross_ax, compression=dcn_compression,
                    )
                    for w in wire
                ]
        elif eff_op == Adasum:
            from ..ops.adasum import adasum_allreduce  # noqa: PLC0415

            reduced = [adasum_allreduce(w, axis_name=axis_name) for w in wire]
        else:
            post_local = post
            if op == Average and gradient_predivide_factor != 1.0:
                post_local = gradient_predivide_factor / jax.lax.axis_size(axis_name)
            reduced = grouped_allreduce(
                wire,
                eff_op,
                axis_name=axis_name,
                prescale_factor=pre,
                postscale_factor=post_local,
            )
        reduced_dense = [
            compression.decompress(r, c) for r, c in zip(reduced, ctxs)
        ]
        out = [None] * (len(reduced_dense) + len(sparse_out))
        for i, r in zip(dense_idx, reduced_dense):
            out[i] = r
        for i, s in sparse_out.items():
            out[i] = s
        return jax.tree_util.tree_unflatten(treedef, out), state

    return optax.GradientTransformation(init_fn, update_fn)


def DistributedOptimizer(
    optimizer: optax.GradientTransformation,
    *,
    op: ReduceOp = Average,
    axis_name: str = DP_AXIS,
    compression=Compression.none,
    backward_passes_per_step: int = 1,
    gradient_predivide_factor: float = 1.0,
) -> optax.GradientTransformation:
    """Wrap an optax optimizer so updates see globally-reduced gradients
    (reference: hvd.DistributedOptimizer, torch/__init__.py:396-449).

    ``backward_passes_per_step`` accumulates that many microbatch grads
    locally before one fused reduce + update — the reference's gradient
    accumulation knob (torch/__init__.py:101-126), realized with
    ``optax.MultiSteps`` so accumulation happens *before* the wire and each
    network round carries the accumulated sum.
    """
    tx = optax.chain(
        DistributedGradientTransform(
            op,
            axis_name=axis_name,
            compression=compression,
            gradient_predivide_factor=gradient_predivide_factor,
        ),
        optimizer,
    )
    if backward_passes_per_step > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=backward_passes_per_step)
    return tx


def distribute(
    step_fn,
    *,
    mesh_shape: str = "flat",
    axis_name: str = DP_AXIS,
    in_specs=None,
    out_specs=None,
    donate_argnums=(),
):
    """Turn a per-device train step into a jitted SPMD program over the job
    mesh — the TPU replacement for "launch N copies of the script"
    (SURVEY.md §7: the jit path needs no runtime controller; XLA schedules
    the fused psums).

    Convention when specs are omitted: every argument is replicated except
    the LAST, which is sharded along dim 0 (the batch); outputs are
    replicated.  Pass explicit ``jax.sharding.PartitionSpec`` trees to
    override.
    """
    # shard_map via the shared version shim: older jax only ships
    # jax.experimental.shard_map (check_rep), newer jax.shard_map
    # (check_vma) — the bare `from jax import shard_map` died on the
    # older interpreter and took the whole CPU bench path with it.
    from jax.sharding import PartitionSpec as P  # noqa: PLC0415

    from ..ops.collectives import shard_map_compat  # noqa: PLC0415

    m = build_mesh(mesh_shape)
    # Build the shard_map/jit pipeline once per argument count (the default
    # in_specs depend on arity); rebuilding per call would defeat the jit
    # cache and recompile the step every iteration.
    compiled: dict = {}

    def wrapper(*args):
        key = len(args)
        fn = compiled.get(key)
        if fn is None:
            specs = (
                in_specs
                if in_specs is not None
                else tuple([P()] * (len(args) - 1) + [P(axis_name)])
            )
            mapped = shard_map_compat(
                step_fn,
                mesh=m,
                in_specs=specs,
                out_specs=out_specs if out_specs is not None else P(),
            )
            fn = jax.jit(mapped, donate_argnums=donate_argnums)
            compiled[key] = fn
        return fn(*args)

    return wrapper


# ---------------------------------------------------------------------------
# State replication (reference: broadcast_parameters /
# broadcast_optimizer_state / broadcast_object, torch/__init__.py:452-648)
# ---------------------------------------------------------------------------


def _engine_active() -> bool:
    """True when the eager engine's background thread is running.

    While it runs, ALL cross-process traffic must flow through it — issuing
    a multihost_utils collective from another thread races the engine's own
    negotiation collectives and deadlocks (the exact hazard the reference's
    one-communication-thread rule exists for, operations.cc:311-330).
    """
    from .._engine_registry import peek_engine  # noqa: PLC0415

    return peek_engine() is not None


def broadcast_parameters(params, root_rank: int = 0):
    """Replicate a parameter pytree from ``root_rank``'s process to all
    (reference: torch/__init__.py:452-508; used at train start so every
    worker begins from identical state).

    Cross-process transport is the eager engine's broadcast when the engine
    is running (single communication owner), otherwise the JAX coordination
    service (multihost broadcast) — the descendants of the reference's
    MPI_Bcast-based parameter broadcast.  Single-process jobs return the
    tree unchanged.
    """
    topo = global_topology()
    if topo.process_count == 1:
        return params
    if _engine_active():
        from ..ops import eager  # noqa: PLC0415

        # Enqueue every leaf first so the engine can fuse them into a few
        # negotiation cycles (the reference enqueues all parameter
        # broadcasts before synchronizing, torch/__init__.py:452-508).
        # Leaves pass through as-is: jax.Array leaves ride the device data
        # plane (no host round-trip); scalars/lists are normalized here.
        leaves, treedef = jax.tree_util.tree_flatten(params)
        # Explicit names: pairing by name (not the auto _seq counter)
        # keeps the exchange robust if a caller wraps this in any
        # conditional — flatten order is identical on every rank, so
        # the index is a rank-stable key.
        handles = [
            eager.broadcast_async(
                l if isinstance(l, (jax.Array, np.ndarray)) else np.asarray(l),
                root_rank=root_rank,
                name=f"hvd.bcast_param.{i}",
            )
            for i, l in enumerate(leaves)
        ]
        outs = [eager.synchronize(h) for h in handles]
        return jax.tree_util.tree_unflatten(treedef, outs)
    from jax.experimental import multihost_utils  # noqa: PLC0415

    return multihost_utils.broadcast_one_to_all(
        params, is_source=topo.process_rank == root_rank
    )


def broadcast_optimizer_state(opt_state, root_rank: int = 0):
    """Replicate optimizer state (reference torch/__init__.py:511-605).

    The reference walks torch state dicts, wraps scalars as tensors, and
    re-casts after the wire; optax state is already a pytree of arrays, so
    it rides the same path as parameters.  Non-array leaves (step schedules
    etc.) travel via :func:`broadcast_object`.
    """
    # Split array leaves from aux python values.
    leaves, treedef = jax.tree_util.tree_flatten(opt_state)
    is_arr = [isinstance(l, (jnp.ndarray, np.ndarray)) or jnp.isscalar(l) for l in leaves]
    arr_leaves = [l for l, a in zip(leaves, is_arr) if a]
    aux_leaves = [l for l, a in zip(leaves, is_arr) if not a]
    arr_leaves = broadcast_parameters(arr_leaves, root_rank)
    aux_leaves = broadcast_object(aux_leaves, root_rank)
    merged, ai, xi = [], 0, 0
    for a in is_arr:
        if a:
            merged.append(arr_leaves[ai])
            ai += 1
        else:
            merged.append(aux_leaves[xi])
            xi += 1
    return jax.tree_util.tree_unflatten(treedef, merged)


def broadcast_object(obj: Any, root_rank: int = 0) -> Any:
    """Pickle-broadcast an arbitrary python object from ``root_rank``
    (reference: broadcast_object via cloudpickle, torch/__init__.py:608-648).
    """
    topo = global_topology()
    if topo.process_count == 1:
        return obj
    is_source = topo.process_rank == root_rank
    payload = pickle.dumps(obj) if is_source else b""
    if _engine_active():
        from ..ops import eager  # noqa: PLC0415

        # Two-phase: broadcast length, then the byte buffer (the reference
        # broadcasts a size tensor then the bytes, torch/__init__.py:627-641).
        # Named so the two-phase exchange pairs by key on every rank
        # even when a caller guards broadcast_object in a conditional.
        length = int(
            eager.broadcast(
                np.asarray([len(payload)], np.int64), root_rank=root_rank,
                name="hvd.bcast_obj.len",
            )[0]
        )
        buf = np.zeros(length, np.uint8)
        if is_source:
            buf[:] = np.frombuffer(payload, np.uint8)
        buf = eager.broadcast(buf, root_rank=root_rank,
                              name="hvd.bcast_obj.buf")
        return pickle.loads(np.asarray(buf).tobytes()) if length else None
    from jax.experimental import multihost_utils  # noqa: PLC0415

    length = multihost_utils.broadcast_one_to_all(
        np.asarray(len(payload), np.int64), is_source=is_source
    )
    buf = np.zeros(int(length), np.uint8)
    if is_source:
        buf[:] = np.frombuffer(payload, np.uint8)
    buf = multihost_utils.broadcast_one_to_all(buf, is_source=is_source)
    return pickle.loads(np.asarray(buf).tobytes()) if int(length) else None
