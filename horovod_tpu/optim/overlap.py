"""Backward-overlap gradient plane for the jit path.

Horovod's core contribution is firing allreduce *as each gradient
materializes during backward* (Sergeev & Del Balso 2018, §3: the
background thread + fusion buffer overlap communication with the rest
of the backward pass).  The jit path's ``DistributedGradientTransform``
does the opposite: every psum runs inside ``tx.update`` *after* the
whole backward completes, serializing one giant end-of-step exchange
behind all compute.  This module restores the reference's overlap — as
graph structure instead of a runtime thread, which is exactly GSPMD's
static-schedule model (PAPERS.md):

* :func:`sync_gradients` — ``value_and_grad`` whose cotangent path
  carries one fused collective per size-bounded gradient *bucket*
  (``--grad-bucket-mb``, default 16), planted with ``jax.custom_vjp``
  identity taps so each bucket's psum is emitted the moment its last
  gradient is produced.  XLA's scheduler then interleaves the wire with
  the remaining backward compute (tests assert this from the compiled
  HLO, not from hope).

* :class:`OverlapPlan` — the full step builder.  Mode ``"bucket"`` is
  the tap plane above plus a plain optax update; mode
  ``"bucket+zero1"`` additionally shards the optimizer over the data
  axis (ZeRO-1 shape): parameters are *carried as 1/world flat shards*,
  all-gathered per bucket in the forward (so the VJP plants a per-bucket
  reduce-scatter in the backward), updated on the shard (optimizer
  memory and update flops ÷ world), and re-enter the next step still
  sharded — per-step wire cost identical to one allreduce.  On a
  two-fabric mesh the cross-slice legs ride DCN on 1/local_size of the
  bytes (optionally compressed), composing with the PR-8 hierarchical
  plane.

* :func:`inspect_schedule` — compiled-HLO proof.  Parses
  ``.lower(...).compile().as_text()`` (the *scheduled* module), locates
  every gradient collective and every compute op, and reports how many
  collectives land strictly inside the backward.  CI gates on this, so
  "the buckets overlap" is a checked property of the artifact, not a
  claim about the compiler.

* :func:`donated_params` / :func:`audit_donation` — donation audit:
  params/opt_state must stay donated end-to-end through the wrapper
  (an undonated step doubles peak parameter memory and, on the ZeRO
  path, silently forfeits the memory the sharding just bought).

Equivalence contract: ``off``, ``bucket`` and ``bucket+zero1`` produce
bitwise-identical losses/params on the same mesh — a psum is element-
wise, so re-bucketing only regroups independent reductions, and a
reduce-scatter shard is bitwise-equal to the matching slice of the full
psum (tests/test_overlap.py pins this, including odd-sized leaves that
straddle bucket boundaries and an N→M bucket-count change).  The ZeRO
path additionally requires an *element-wise* optimizer (sgd/momentum/
adam...); transforms that couple elements across leaves (global-norm
clipping) would need their norms reduced across shards and are
rejected by documentation, not detection — see docs/performance.md.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

from ..basics import DP_AXIS
from ..ops.collectives import (
    Average,
    ReduceOp,
    Sum,
    all_gather_flat,
    axis_size,
    shard_map_compat,
)

__all__ = [
    "MODES",
    "Bucket",
    "BucketLayout",
    "build_layout",
    "sync_gradients",
    "OverlapPlan",
    "ScheduleReport",
    "inspect_schedule",
    "donated_params",
    "audit_donation",
]

MODES = ("off", "bucket", "bucket+zero1")

_MODE_IDS = {m: i for i, m in enumerate(MODES)}


# ---------------------------------------------------------------------------
# bucket layout
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Bucket:
    """One fused gradient bucket: a contiguous run of parameter leaves
    in reverse-topological order, single dtype, concatenated flat."""

    index: int
    leaf_indices: Tuple[int, ...]   # positions in the params flatten order
    shapes: Tuple[Tuple[int, ...], ...]
    sizes: Tuple[int, ...]
    dtype: Any
    pad: int                        # zeros appended so shard_ways divides

    @property
    def size(self) -> int:
        return sum(self.sizes)

    @property
    def padded_size(self) -> int:
        return self.size + self.pad

    @property
    def nbytes(self) -> int:
        return self.size * jnp.dtype(self.dtype).itemsize


@dataclass(frozen=True)
class BucketLayout:
    """Static bucket assignment for a parameter pytree.  Pure data —
    everything here is derivable from shapes/dtypes, so every rank
    computes the identical layout (the SPMD analog of the reference's
    negotiated fusion bins, controller.cc:640-761)."""

    buckets: Tuple[Bucket, ...]
    treedef: Any
    num_leaves: int
    bucket_bytes: int
    shard_ways: int

    @property
    def total_bytes(self) -> int:
        return sum(b.nbytes for b in self.buckets)


def build_layout(params, bucket_bytes: int, *,
                 shard_ways: int = 1) -> BucketLayout:
    """Assign parameter leaves to size-bounded buckets in
    reverse-topological order.

    "Reverse-topological" is approximated by the reverse of the pytree
    flatten order: frameworks register layers input→output, so reversed
    leaves are produced-first in the backward pass — the same heuristic
    PyTorch DDP buckets by (reversed ``model.parameters()``).  A bucket
    closes when adding the next leaf would exceed ``bucket_bytes`` or
    change dtype (flat buffers cannot mix dtypes without a cast); a
    single leaf larger than the cap gets its own bucket — like the
    reference's fusion bins, one tensor is never split across buckets.

    ``shard_ways`` > 1 (the ZeRO path) pads each bucket with zeros to a
    multiple of the shard count so tiled scatter/gather divide evenly.
    """
    leaves, treedef = jax.tree_util.tree_flatten(params)
    if not leaves:
        raise ValueError("cannot build a bucket layout over an empty pytree")
    if bucket_bytes <= 0:
        raise ValueError(f"bucket_bytes must be positive, got {bucket_bytes}")
    shapes, dtypes = [], []
    for i, leaf in enumerate(leaves):
        dt = jnp.result_type(leaf)
        if not jnp.issubdtype(dt, jnp.inexact):
            raise ValueError(
                f"parameter leaf {i} has non-float dtype {dt}; the overlap "
                f"plane differentiates the loss w.r.t. every leaf, so "
                f"params must be all-float (move counters/ints out of the "
                f"params pytree)"
            )
        shapes.append(tuple(jnp.shape(leaf)))
        dtypes.append(dt)

    buckets: List[Bucket] = []
    run: List[int] = []
    run_bytes = 0

    def close(run: List[int]) -> None:
        if not run:
            return
        sizes = tuple(int(np.prod(shapes[i], dtype=np.int64)) if shapes[i]
                      else 1 for i in run)
        total = sum(sizes)
        pad = (-total) % shard_ways
        buckets.append(Bucket(
            index=len(buckets),
            leaf_indices=tuple(run),
            shapes=tuple(shapes[i] for i in run),
            sizes=sizes,
            dtype=dtypes[run[0]],
            pad=pad,
        ))

    for i in reversed(range(len(leaves))):
        nbytes = (int(np.prod(shapes[i], dtype=np.int64)) if shapes[i]
                  else 1) * jnp.dtype(dtypes[i]).itemsize
        if run and (dtypes[i] != dtypes[run[0]]
                    or run_bytes + nbytes > bucket_bytes):
            close(run)
            run, run_bytes = [], 0
        run.append(i)
        run_bytes += nbytes
    close(run)
    return BucketLayout(
        buckets=tuple(buckets),
        treedef=treedef,
        num_leaves=len(leaves),
        bucket_bytes=int(bucket_bytes),
        shard_ways=int(shard_ways),
    )


def _bucket_concat(pieces: Sequence, bucket: Bucket):
    """Ravel+concat a bucket's leaves (bucket order), zero-padded."""
    flat = (jnp.ravel(pieces[0]) if len(pieces) == 1
            else jnp.concatenate([jnp.ravel(p) for p in pieces]))
    if bucket.pad:
        flat = jnp.pad(flat, (0, bucket.pad))
    return flat


def _bucket_split(flat, bucket: Bucket) -> List:
    """Inverse of :func:`_bucket_concat`: strip pad, slice, reshape."""
    out, off = [], 0
    for shape, size in zip(bucket.shapes, bucket.sizes):
        out.append(lax.dynamic_slice_in_dim(flat, off, size).reshape(shape))
        off += size
    return out


# ---------------------------------------------------------------------------
# reduction schedules (flat and two-fabric)
# ---------------------------------------------------------------------------


def _reduce_flat(flat, op, axis_name, hierarchical_axes, dcn_compression):
    """One bucket's full reduce: flat psum, or the 3-phase two-fabric
    schedule (scatter ICI → exchange DCN → gather ICI) when a
    hierarchical mesh is given."""
    if hierarchical_axes is not None:
        from ..parallel.hierarchical import (  # noqa: PLC0415
            hierarchical_allreduce,
        )

        local_ax, cross_ax = hierarchical_axes
        return hierarchical_allreduce(
            flat, op, local_axis=local_ax, cross_axis=cross_ax,
            compression=dcn_compression,
        )
    y = lax.psum(flat, axis_name)
    if op == Average:
        y = y / axis_size(axis_name)
    return y


def _scatter_flat(flat, op, axis_name, hierarchical_axes, dcn_compression):
    """One bucket's reduce-scatter: this rank's 1/shard_ways chunk of
    the fully-reduced buffer.  Bitwise-equal to slicing
    :func:`_reduce_flat`'s result (the ZeRO equivalence argument)."""
    if hierarchical_axes is not None:
        from ..parallel.hierarchical import (  # noqa: PLC0415
            hierarchical_reduce_scatter,
        )

        local_ax, cross_ax = hierarchical_axes
        return hierarchical_reduce_scatter(
            flat, op, local_axis=local_ax, cross_axis=cross_ax,
            compression=dcn_compression,
        )
    shard = lax.psum_scatter(flat, axis_name, scatter_dimension=0,
                             tiled=True)
    if op == Average:
        shard = shard / axis_size(axis_name)
    return shard


def _gather_flat(shard, axis_name, hierarchical_axes):
    if hierarchical_axes is not None:
        from ..parallel.hierarchical import (  # noqa: PLC0415
            hierarchical_all_gather,
        )

        local_ax, cross_ax = hierarchical_axes
        return hierarchical_all_gather(
            shard, local_axis=local_ax, cross_axis=cross_ax
        )
    return all_gather_flat(shard, axis_name=axis_name)


# ---------------------------------------------------------------------------
# in-backward bucketed sync (mode "bucket")
# ---------------------------------------------------------------------------


def _make_bucket_tap(bucket: Bucket, reduce_fn):
    """Identity on the bucket's leaves whose VJP reduces the fused
    cotangent buffer.  Reverse-mode AD runs this rule once, at the point
    in the cotangent graph where the *last* of the bucket's gradients
    has been produced — which is exactly where the reference's hook
    fires ``allreduce_async_`` — so the scheduler sees the collective
    with the remaining backward compute still ahead of it."""

    @jax.custom_vjp
    def tap(*xs):
        return xs

    def fwd(*xs):
        return xs, None

    def bwd(_, cts):
        flat = _bucket_concat(cts, bucket)
        red = reduce_fn(flat)
        return tuple(_bucket_split(red, bucket))

    tap.defvjp(fwd, bwd)
    return tap


def _tap_params(params, layout: BucketLayout, reduce_fn):
    """Thread every parameter leaf through its bucket's tap."""
    leaves = jax.tree_util.tree_flatten(params)[0]
    out = list(leaves)
    for b in layout.buckets:
        tapped = _make_bucket_tap(b, reduce_fn)(
            *[leaves[i] for i in b.leaf_indices]
        )
        for i, t in zip(b.leaf_indices, tapped):
            out[i] = t
    return jax.tree_util.tree_unflatten(layout.treedef, out)


def sync_gradients(
    loss_fn: Callable,
    params,
    *args,
    op: ReduceOp = Average,
    axis_name: str = DP_AXIS,
    bucket_mb: Optional[float] = None,
    layout: Optional[BucketLayout] = None,
    has_aux: bool = False,
    hierarchical_axes: Optional[tuple] = None,
    dcn_compression=None,
):
    """``value_and_grad(loss_fn)(params, *args)`` with in-backward
    bucketed gradient sync — call inside ``shard_map`` over
    ``axis_name`` (or the two-fabric mesh).  Returns ``(loss, grads)``
    (``((loss, aux), grads)`` with ``has_aux``) where ``grads`` is
    already globally reduced, one fused collective per bucket emitted
    inside the backward graph.

    ``bucket_mb`` caps each bucket (default: ``--grad-bucket-mb`` /
    HVDTPU_GRAD_BUCKET_MB / 16 MB); pass a prebuilt ``layout`` to skip
    re-planning (and to share one layout with an :class:`OverlapPlan`).
    """
    if op not in (Average, Sum):
        raise ValueError(f"sync_gradients supports Average/Sum, got {op!r}")
    if layout is None:
        from ..runtime.autotune import (  # noqa: PLC0415
            resolve_grad_bucket_bytes,
        )

        layout = build_layout(params, resolve_grad_bucket_bytes(bucket_mb))

    def reduce_fn(flat):
        return _reduce_flat(flat, op, axis_name, hierarchical_axes,
                            dcn_compression)

    def tapped_loss(p, *a):
        return loss_fn(_tap_params(p, layout, reduce_fn), *a)

    return jax.value_and_grad(tapped_loss, has_aux=has_aux)(params, *args)


# ---------------------------------------------------------------------------
# the full step builder
# ---------------------------------------------------------------------------


class OverlapPlan:
    """One planned configuration of the overlap plane for a given
    parameter pytree: bucket layout + mode + reduce schedule + optax
    transform.  Build once per model, then wrap :meth:`local_step` in
    ``shard_map``/``jit`` with :meth:`state_spec` (donating the state —
    see :func:`audit_donation`).

    State layout by mode (``state = (model, opt_state)``):

    * ``off`` / ``bucket`` — ``model`` is the replicated params pytree,
      ``opt_state = tx.init(params)``; spec ``P()``.
    * ``bucket+zero1`` — ``model`` is the list of flat per-bucket
      parameter buffers, globally sharded over the data axis (each
      device holds 1/world), and ``opt_state = tx.init(<own shard>)``;
      spec from :meth:`state_spec`.  :meth:`materialize` reassembles
      the params pytree outside the step.
    """

    def __init__(
        self,
        params,
        tx: optax.GradientTransformation,
        *,
        mode: str = "bucket",
        op: ReduceOp = Average,
        axis_name: str = DP_AXIS,
        bucket_mb: Optional[float] = None,
        hierarchical_axes: Optional[tuple] = None,
        dcn_compression=None,
        mesh=None,
        publish_metrics: bool = True,
    ):
        if mode not in MODES:
            raise ValueError(f"mode must be one of {MODES}, got {mode!r}")
        if op not in (Average, Sum):
            raise ValueError(f"OverlapPlan supports Average/Sum, got {op!r}")
        if hierarchical_axes is not None and len(hierarchical_axes) != 2:
            raise ValueError(
                "hierarchical_axes must be (local_axis, cross_axis), got "
                f"{hierarchical_axes!r}"
            )
        self.mode = mode
        self.op = op
        self.tx = tx
        self.axis_name = axis_name
        self.hierarchical_axes = (tuple(hierarchical_axes)
                                  if hierarchical_axes else None)
        self.dcn_compression = dcn_compression
        self._mesh = mesh

        from ..runtime.autotune import (  # noqa: PLC0415
            resolve_grad_bucket_bytes,
        )

        bucket_bytes = resolve_grad_bucket_bytes(bucket_mb)
        shard_ways = self._shard_ways() if mode == "bucket+zero1" else 1
        self.layout = build_layout(params, bucket_bytes,
                                   shard_ways=shard_ways)
        if publish_metrics:
            self._publish_metrics()

    # ------------------------------------------------------------ topology

    def _shard_axes(self) -> Tuple[str, ...]:
        """Mesh axes the ZeRO shards split dim 0 over, scatter-major:
        the local (ICI) axis varies slowest — matching
        hierarchical_reduce_scatter's local-then-cross slicing."""
        if self.hierarchical_axes is not None:
            local_ax, cross_ax = self.hierarchical_axes
            return (local_ax, cross_ax)
        return (self.axis_name,)

    def _require_mesh(self):
        if self._mesh is None:
            from ..basics import mesh as build_mesh  # noqa: PLC0415

            self._mesh = build_mesh("flat")
        return self._mesh

    def _shard_ways(self) -> int:
        mesh = self._require_mesh()
        ways = 1
        for ax in self._shard_axes():
            ways *= mesh.shape[ax]
        return ways

    # ------------------------------------------------------------- metrics

    def _publish_metrics(self) -> None:
        try:
            from ..obs import get_registry  # noqa: PLC0415

            reg = get_registry()
            reg.gauge("overlap.mode").set(_MODE_IDS[self.mode])
            reg.gauge("overlap.buckets").set(len(self.layout.buckets))
            reg.gauge("overlap.grad_bucket_mb").set(
                self.layout.bucket_bytes / 1048576
            )
            reg.gauge("overlap.total_grad_bytes").set(
                self.layout.total_bytes
            )
            for b in self.layout.buckets:
                reg.gauge("overlap.bucket_bytes",
                          bucket=str(b.index)).set(b.nbytes)
            import time  # noqa: PLC0415

            from ..obs import trace as obs_trace  # noqa: PLC0415

            if obs_trace.enabled():
                # Bucket-layout annotation on the trace plane: the
                # per-bucket wire time itself lives inside the compiled
                # program (inspect_schedule proves the overlap from the
                # HLO), but the merged waterfall still needs the layout
                # — one instant span per bucket keyed by index — so an
                # engine/step lane can be read against the bucket
                # shapes that produced it.
                t = time.time()
                for b in self.layout.buckets:
                    obs_trace.add_span(
                        "overlap", f"bucket{b.index}", t, t,
                        bucket=b.index, bytes=b.nbytes,
                        leaves=len(b.sizes), mode=self.mode,
                    )
        except Exception:
            # Metrics are observability, not correctness: a plan built in
            # a stripped environment (no obs plane) must still train.
            pass

    def register_memory(self, compiled, program: Optional[str] = None
                        ) -> dict:
        """Publish the compiled train step's memory breakdown as
        ``mem.compiled.*{program=overlap.<mode>}`` gauges (memory
        plane, obs/memplane.py) — call at the compile site with the
        executable (``step.lower(...).compile()``), the same artifact
        :func:`inspect_schedule` proves the overlap from.  This is the
        registration that makes the ZeRO-1 claim checkable: the
        ``bucket`` vs ``bucket+zero1`` argument bytes differ by
        exactly the sharded state (scripts/mem_gate.py gates the
        ratio).  Returns the breakdown; version-tolerant (``source:
        unavailable`` on interpreters without ``memory_analysis``)."""
        from ..obs import memplane  # noqa: PLC0415

        return memplane.register_program(
            program or f"overlap.{self.mode}", compiled
        )

    # -------------------------------------------------------------- state

    def init(self, params):
        """Initial ``(model, opt_state)`` for :meth:`local_step`.
        Call with concrete (host) params, outside jit.  The state holds
        COPIES of the caller's leaves: the step is meant to be jitted
        with the state donated, and donating aliased buffers would
        delete the caller's params out from under a later re-init
        (same hazard class as ckpt's copy-on-flatten)."""
        if self.mode != "bucket+zero1":
            params = jax.tree_util.tree_map(jnp.array, params)
            return (params, self.tx.init(params))
        leaves = jax.tree_util.tree_flatten(params)[0]
        buffers = [
            _bucket_concat([leaves[i] for i in b.leaf_indices], b)
            for b in self.layout.buckets
        ]
        opt_state = self._init_sharded_opt(buffers)
        return (buffers, opt_state)

    def _shard_structs(self) -> List[jax.ShapeDtypeStruct]:
        ways = self.layout.shard_ways
        return [
            jax.ShapeDtypeStruct((b.padded_size // ways,), b.dtype)
            for b in self.layout.buckets
        ]

    def _opt_state_spec(self):
        """PartitionSpec tree for the sharded optimizer state: shard-
        shaped leaves split over the shard axes, scalars (step counts)
        replicated.  Derived from ``eval_shape`` of ``tx.init`` on the
        shard shapes, so it is correct for any element-wise optimizer,
        not just the ones we tested."""
        from jax.sharding import PartitionSpec as P  # noqa: PLC0415

        shapes = jax.eval_shape(self.tx.init, self._shard_structs())
        axes = self._shard_axes()
        return jax.tree_util.tree_map(
            lambda s: P(axes) if getattr(s, "ndim", 0) >= 1 else P(),
            shapes,
        )

    def _init_sharded_opt(self, buffers):
        """``tx.init`` of each rank's own shard, assembled into the
        globally-sharded state — run through a one-time shard_map so the
        per-rank slice is exactly what ``local_step`` will update (any
        optimizer init, not just zeros-like, lands on the right rank).
        """
        from jax.sharding import PartitionSpec as P  # noqa: PLC0415

        mesh = self._require_mesh()
        axes = self._shard_axes()
        init = shard_map_compat(
            lambda bufs: self.tx.init(list(bufs)),
            mesh=mesh,
            in_specs=(tuple(P(axes) for _ in buffers),),
            out_specs=self._opt_state_spec(),
        )
        return jax.jit(init)(tuple(buffers))

    def state_spec(self):
        """PartitionSpec pytree for the ``(model, opt_state)`` state —
        hand it to shard_map's in/out specs for the state argument."""
        from jax.sharding import PartitionSpec as P  # noqa: PLC0415

        if self.mode != "bucket+zero1":
            return P()
        axes = self._shard_axes()
        return ([P(axes) for _ in self.layout.buckets],
                self._opt_state_spec())

    def materialize(self, state):
        """Full params pytree from a step state (host-side; no
        collectives — ZeRO buffers are globally addressable arrays)."""
        model, _ = state
        if self.mode != "bucket+zero1":
            return model
        leaves: List[Any] = [None] * self.layout.num_leaves
        for b, buf in zip(self.layout.buckets, model):
            for i, piece in zip(b.leaf_indices,
                                _bucket_split(jnp.asarray(buf), b)):
                leaves[i] = piece
        return jax.tree_util.tree_unflatten(self.layout.treedef, leaves)

    def rebucket(self, state, new_plan: "OverlapPlan"):
        """Carry a ZeRO state across an N→M bucket-layout change
        (re-tuned ``--grad-bucket-mb``, elastic world resize): params
        re-shard exactly; optimizer-state leaves are re-grouped by
        matching each run of per-bucket arrays against the old layout's
        buffer shapes.  Works for any optax state whose array leaves
        parallel the bucket list (sgd/momentum/adam/adamw...); anything
        stranger raises rather than guessing."""
        if self.mode != "bucket+zero1" or new_plan.mode != "bucket+zero1":
            raise ValueError("rebucket is only meaningful between "
                             "bucket+zero1 plans")
        if new_plan.layout.num_leaves != self.layout.num_leaves:
            raise ValueError("rebucket requires the same parameter tree")
        _, opt_state = state
        # Re-shard the params directly (what _regroup does for state
        # fields): going through new_plan.init would also build — and
        # immediately discard — a full sharded optimizer state.
        leaves = jax.tree_util.tree_flatten(self.materialize(state))[0]
        new_buffers = [
            _bucket_concat([leaves[i] for i in b.leaf_indices], b)
            for b in new_plan.layout.buckets
        ]

        old_shapes = [((b.padded_size,), jnp.dtype(b.dtype))
                      for b in self.layout.buckets]
        n_old = len(self.layout.buckets)
        # The state's treedef changes with the bucket count (its inner
        # lists are per-bucket); the new structure is what tx.init on
        # the NEW layout's shards would produce.
        new_treedef = jax.tree_util.tree_structure(
            jax.eval_shape(new_plan.tx.init, new_plan._shard_structs())
        )
        leaves, _ = jax.tree_util.tree_flatten(opt_state)
        out: List[Any] = []
        i = 0
        while i < len(leaves):
            leaf = leaves[i]
            if getattr(leaf, "ndim", 0) == 0:
                out.append(leaf)
                i += 1
                continue
            run = leaves[i:i + n_old]
            if [(jnp.shape(l), jnp.result_type(l)) for l in run] \
                    != old_shapes:
                raise ValueError(
                    "optimizer state does not parallel the bucket list; "
                    "re-initialize it for the new layout instead"
                )
            out.extend(self._regroup(run, new_plan))
            i += n_old
        return (new_buffers,
                jax.tree_util.tree_unflatten(new_treedef, out))

    def _regroup(self, per_bucket: Sequence, new_plan: "OverlapPlan"):
        """Reassemble one state field from old buckets, split per new."""
        leaves: List[Any] = [None] * self.layout.num_leaves
        for b, buf in zip(self.layout.buckets, per_bucket):
            for i, piece in zip(b.leaf_indices,
                                _bucket_split(jnp.asarray(buf), b)):
                leaves[i] = piece
        return [
            _bucket_concat([leaves[i] for i in b.leaf_indices], b)
            for b in new_plan.layout.buckets
        ]

    # ---------------------------------------------------------------- step

    def local_step(self, loss_fn: Callable, *, has_aux: bool = False,
                   health: bool = False):
        """The per-device train-step body: ``fn(state, *batch) ->
        (state, loss[, aux])`` where ``loss_fn(params, *batch)`` returns
        the local scalar loss (or ``(loss, aux)``).  Wrap the result in
        shard_map over the plan's mesh/axes and jit it with the state
        donated.

        ``health=True`` appends one more output: the fused float32
        health-bundle vector (obs/health.py ``bundle_names`` order —
        loss, global grad norm, max update/param ratio, nonfinite
        count, per-bucket grad norms), computed from values the step
        already holds so it rides the existing device→host sync.  With
        ``health=False`` (the default) the traced computation is
        exactly today's — the compiled HLO is byte-identical, which CI
        asserts."""
        if self.mode == "bucket+zero1":
            return self._zero1_step(loss_fn, has_aux, health)
        return self._replicated_step(loss_fn, has_aux, health)

    def _grads_off(self, loss_fn, params, args, has_aux):
        """End-of-backward fused reduce (the status quo this plane is
        measured against): full value_and_grad, then one concat psum per
        dtype — the single giant exchange XLA cannot start until the
        whole backward has finished."""
        val, grads = jax.value_and_grad(loss_fn, has_aux=has_aux)(
            params, *args
        )
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        by_dtype: dict = {}
        for i, leaf in enumerate(leaves):
            by_dtype.setdefault(jnp.result_type(leaf), []).append(i)
        out = list(leaves)
        for idxs in by_dtype.values():
            flat = (jnp.ravel(leaves[idxs[0]]) if len(idxs) == 1
                    else jnp.concatenate(
                        [jnp.ravel(leaves[i]) for i in idxs]))
            red = _reduce_flat(flat, self.op, self.axis_name,
                               self.hierarchical_axes,
                               self.dcn_compression)
            off = 0
            for i in idxs:
                n = leaves[i].size
                out[i] = lax.dynamic_slice_in_dim(red, off, n).reshape(
                    jnp.shape(leaves[i])
                )
                off += n
        return val, jax.tree_util.tree_unflatten(treedef, out)

    def _replicated_step(self, loss_fn, has_aux, health=False):
        def step(state, *args):
            params, opt_state = state
            if self.mode == "bucket":
                val, grads = sync_gradients(
                    loss_fn, params, *args,
                    op=self.op, axis_name=self.axis_name,
                    layout=self.layout, has_aux=has_aux,
                    hierarchical_axes=self.hierarchical_axes,
                    dcn_compression=self.dcn_compression,
                )
            else:
                val, grads = self._grads_off(loss_fn, params, args,
                                             has_aux)
            if health:
                # Captured BEFORE the update so the ratio compares the
                # step's update against the params it applied to.
                old_params = params
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            loss, aux = (val if has_aux else (val, None))
            out = ((params, opt_state), loss)
            if has_aux:
                out = out + (aux,)
            if health:
                from ..obs.health import health_bundle  # noqa: PLC0415

                bundle = health_bundle(
                    loss,
                    jax.tree_util.tree_flatten(grads)[0],
                    self.layout,
                    jax.tree_util.tree_flatten(updates)[0],
                    jax.tree_util.tree_flatten(old_params)[0],
                )
                out = out + (bundle,)
            return out

        return step

    def _zero1_step(self, loss_fn, has_aux, health=False):
        hier = self.hierarchical_axes

        def gather_with_scatter_vjp(shard):
            """Forward: reassemble the bucket's full flat buffer from
            the shards.  VJP: the bucket's gradient reduce-scatter —
            emitted inside the backward at this bucket's position, with
            the cross-slice leg on DCN when the mesh is two-fabric."""

            @jax.custom_vjp
            def gather(s):
                return _gather_flat(s, self.axis_name, hier)

            def fwd(s):
                return gather(s), None

            def bwd(_, g):
                return (_scatter_flat(g, self.op, self.axis_name, hier,
                                      self.dcn_compression),)

            gather.defvjp(fwd, bwd)
            return gather(shard)

        def shard_loss(shards, *args):
            leaves: List[Any] = [None] * self.layout.num_leaves
            for b, s in zip(self.layout.buckets, shards):
                full = gather_with_scatter_vjp(s)
                for i, piece in zip(b.leaf_indices, _bucket_split(full, b)):
                    leaves[i] = piece
            params = jax.tree_util.tree_unflatten(self.layout.treedef,
                                                  leaves)
            return loss_fn(params, *args)

        def step(state, *args):
            shards, opt_state = state
            shards = list(shards)
            val, gshards = jax.value_and_grad(
                shard_loss, has_aux=has_aux
            )(shards, *args)
            old_shards = shards
            updates, opt_state = self.tx.update(gshards, opt_state, shards)
            shards = optax.apply_updates(shards, updates)
            loss, aux = (val if has_aux else (val, None))
            out = ((shards, opt_state), loss)
            if has_aux:
                out = out + (aux,)
            if health:
                out = out + (self._zero1_bundle(loss, gshards, updates,
                                                old_shards),)
            return out

        return step

    def _zero1_bundle(self, loss, gshards, updates, shards):
        """The zero1 health bundle: each rank holds only its flat shard
        of every bucket, so the per-bucket sum-of-squares, nonfinite
        count and max-ratio are psum/pmax'd over the shard axes — one
        tiny ``(n_buckets + 2,)`` cross-replica vector, not a second
        gradient exchange."""
        f32 = jnp.float32
        axes = (self.hierarchical_axes if self.hierarchical_axes
                else (self.axis_name,))
        sq = []
        nonfinite = jnp.zeros((), f32)
        for g in gshards:
            g32 = g.astype(f32)
            sq.append(jnp.sum(g32 * g32))
            nonfinite = nonfinite + jnp.sum(
                (~jnp.isfinite(g32)).astype(f32))
        ratio = jnp.zeros((), f32)
        eps = f32(1e-12)
        for u, p in zip(updates, shards):
            r = (jnp.max(jnp.abs(u.astype(f32)))
                 / (jnp.max(jnp.abs(p.astype(f32))) + eps))
            ratio = jnp.maximum(ratio, r)
        summed = jnp.stack(sq + [nonfinite])
        for ax in axes:
            summed = lax.psum(summed, ax)
            ratio = lax.pmax(ratio, ax)
        bucket_sq = summed[:-1]
        return jnp.concatenate([
            jnp.stack([jnp.asarray(loss, f32).reshape(()),
                       jnp.sqrt(jnp.sum(bucket_sq)),
                       ratio,
                       summed[-1]]),
            jnp.sqrt(bucket_sq),
        ])


# ---------------------------------------------------------------------------
# compiled-HLO schedule inspector
# ---------------------------------------------------------------------------

# Reduce-class collectives carry gradients; gathers are the ZeRO forward
# leg (or parameter broadcast) and don't prove backward overlap.
_REDUCE_OPS = ("all-reduce-start", "all-reduce", "reduce-scatter")
_GATHER_OPS = ("all-gather-start", "all-gather")
_COMPUTE_OPS = ("fusion", "dot", "convolution", "custom-call")

_OP_RE = re.compile(
    r"=\s+(?:\()?(\w+)\[([0-9,]*)\][^=]*?\s"
    r"(all-reduce-start|all-reduce|reduce-scatter|all-gather-start|"
    r"all-gather|fusion|dot|convolution|custom-call)\("
)


@dataclass
class ScheduleReport:
    """What the scheduled module actually does with the gradient
    collectives.  ``in_backward`` counts reduce-class collectives that
    appear strictly before the last compute op preceding the *final*
    gradient collective — i.e. collectives with backward work scheduled
    after them to hide behind.  A monolithic end-of-backward reduce
    scores 0 there by construction."""

    collectives: List[dict]
    gradient_collectives: int
    gather_collectives: int
    compute_ops: int
    in_backward: int
    monolithic: bool

    def as_dict(self) -> dict:
        return {
            "gradient_collectives": self.gradient_collectives,
            "gather_collectives": self.gather_collectives,
            "compute_ops": self.compute_ops,
            "in_backward": self.in_backward,
            "monolithic": self.monolithic,
        }


def _entry_lines(text: str) -> List[str]:
    """The entry computation's instruction lines, in schedule order
    (compiled modules print ``is_scheduled=true``; instruction order IS
    the sequence the backend executes)."""
    lines = text.splitlines()
    for i, line in enumerate(lines):
        if line.startswith("ENTRY "):
            body = []
            for l in lines[i + 1:]:
                if l.startswith("}"):
                    break
                body.append(l)
            return body
    return lines


def inspect_schedule(compiled_or_text, *,
                     min_elements: int = 2) -> ScheduleReport:
    """Parse a compiled step's HLO and report where its gradient
    collectives sit relative to backward compute.

    Accepts a compiled executable (``fn.lower(...).compile()``), a
    lowered object, or the ``as_text()`` string.  ``min_elements``
    filters scalar control collectives (loss pmean, epoch-check lanes)
    out of the gradient count.
    """
    if hasattr(compiled_or_text, "compile"):
        compiled_or_text = compiled_or_text.compile()
    text = (compiled_or_text if isinstance(compiled_or_text, str)
            else compiled_or_text.as_text())

    ops: List[Tuple[str, int]] = []  # (category, elements)
    collectives: List[dict] = []
    for line in _entry_lines(text):
        m = _OP_RE.search(line)
        if not m:
            continue
        dtype, dims, opcode = m.groups()
        elements = int(np.prod([int(d) for d in dims.split(",") if d],
                               dtype=np.int64)) if dims else 1
        if opcode in _REDUCE_OPS and elements >= min_elements:
            cat = "reduce"
        elif opcode in _GATHER_OPS and elements >= min_elements:
            cat = "gather"
        elif opcode in _COMPUTE_OPS:
            cat = "compute"
        else:
            cat = "other"
        ops.append((cat, elements))
        if cat in ("reduce", "gather"):
            collectives.append({
                "index": len(ops) - 1,
                "opcode": opcode,
                "dtype": dtype,
                "elements": elements,
            })

    reduce_idx = [i for i, (c, _) in enumerate(ops) if c == "reduce"]
    compute_idx = [i for i, (c, _) in enumerate(ops) if c == "compute"]
    in_backward = 0
    if reduce_idx and compute_idx:
        last_reduce = reduce_idx[-1]
        pre = [i for i in compute_idx if i < last_reduce]
        if pre:
            anchor = pre[-1]
            in_backward = sum(1 for i in reduce_idx if i < anchor)
    return ScheduleReport(
        collectives=collectives,
        gradient_collectives=len(reduce_idx),
        gather_collectives=sum(1 for c, _ in ops if c == "gather"),
        compute_ops=len(compute_idx),
        in_backward=in_backward,
        monolithic=in_backward == 0,
    )


# ---------------------------------------------------------------------------
# donation audit
# ---------------------------------------------------------------------------


def donated_params(compiled_or_text) -> set:
    """Flattened parameter indices the compiled module aliases to
    outputs (``input_output_alias``) — the buffers XLA will actually
    reuse in place.  Donation silently degrades to a copy when shapes/
    layouts mismatch, so tests assert on THIS, not on having passed
    ``donate_argnums``."""
    if hasattr(compiled_or_text, "compile"):
        compiled_or_text = compiled_or_text.compile()
    text = (compiled_or_text if isinstance(compiled_or_text, str)
            else compiled_or_text.as_text())
    start = text.find("input_output_alias={")
    if start == -1:
        return set()
    i = start + len("input_output_alias=")
    depth = 0
    for j in range(i, len(text)):
        if text[j] == "{":
            depth += 1
        elif text[j] == "}":
            depth -= 1
            if depth == 0:
                inner = text[i + 1:j]
                return {int(m) for m in
                        re.findall(r"\(\s*(\d+)\s*,", inner)}
    return set()


def audit_donation(compiled_or_text, n_state_leaves: int) -> dict:
    """Donation report for a compiled train step: did at least the
    state's leaves get aliased end-to-end?  Returns
    ``{"donated": int, "expected": int, "ok": bool}``."""
    donated = donated_params(compiled_or_text)
    return {
        "donated": len(donated),
        "expected": int(n_state_leaves),
        "ok": len(donated) >= int(n_state_leaves),
    }
