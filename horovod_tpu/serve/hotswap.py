"""Live weight hot-swap: ship new checkpoints into a serving fleet
without a restart, with a provable single-version guarantee.

The train→serve loop this closes: a concurrently-training job commits
checkpoints through the PR-7 sharded-manifest format (every shard
checksummed, rank 0's manifest rename IS the commit point) into a
weights directory the serving fleet can read, optionally announcing
each version over the job's HMAC-signed KV store.  Serving ranks poll
the manifest between decode steps and flip atomically on a
version-stamped step.

**Single-version protocol.**  The swap rides the serving plane's
existing "all ranks agree to deviate" lane — the leader's per-step
schedule broadcast (the serving twin of the engine's replay epoch-check
lane).  Nothing here consults rank-local state to *decide* anything
(the HVD001/HVD010 discipline): the leader derives every transition
from shared data (the committed manifest, the ranks' prefetch votes in
epoch-scoped KV keys) and broadcasts it; followers only ever obey the
broadcast.

1. **poll** — every ``poll_steps`` serving steps the leader checks the
   announce key and the weights directory for a committed version newer
   than the incumbent.
2. **prefetch** — the leader broadcasts ``{"phase": "prefetch",
   "version": v}``; every rank (leader included) reassembles version
   ``v`` from its shards between decode steps, checksum-validating
   every shard against the manifest, and posts an ok/fail vote under an
   epoch-scoped key.  Serving continues on the incumbent weights — the
   staged tree is host-side only.
3. **flip** — once every live rank voted ok, the leader first writes
   the DURABLE version record (``serve/weight_version`` — the value
   epoch-start recovery converges on), then broadcasts ``{"phase":
   "flip", "version": v}``; every rank applies the staged tree before
   that step's admissions/decode.  Every rank therefore serves exactly
   one weight version at every step.
4. **rollback** — any failed vote (partial fetch, checksum mismatch,
   manifest gone) or a vote timeout makes the leader broadcast
   ``abort``: everyone drops the staged tree and keeps the incumbent.
   A rank that DIES mid-swap breaks the epoch instead; the new epoch's
   recovery doc carries the durable version record, so the re-formed
   fleet converges on exactly one version — the incumbent if the flip
   record was never written, the new version if it was.  Either way is
   a single version; a torn flip is unrepresentable.

Chaos point ``swap_commit`` (``action=swap_abort``) fires between a
successful prefetch and the flip application — the exact window the
convergence argument above must survive.

Honest limits: requests in flight ACROSS a committed flip continue
decoding under the new weights over a KV cache built by the old ones
(and an elastic replay re-prefills them wholly under the new version),
so their post-flip tokens are well-defined and identical on every rank
but not meaningful samples of either model — drain first if that
matters.  Requests admitted entirely under one version are bitwise
reproducible under that version.
"""

from __future__ import annotations

import os
import pickle
import time
from typing import Any, Optional, Tuple

from ..ckpt.replica import job_fingerprint
from ..ckpt.sharded import (
    ShardCorruptError,
    latest_step,
    save_sharded,
    restore_sharded,
)
from ..obs import get_registry
from ..obs import flightrec as obs_flightrec
from ..testing.faults import DEFAULT_EXIT_CODE, maybe_fail
from ..utils.logging import get_logger

LOG = get_logger("serve.hotswap")

__all__ = ["publish_weights", "SwapManager", "DEFAULT_POLL_STEPS",
           "ANNOUNCE_KEY", "VERSION_KEY"]

DEFAULT_POLL_STEPS = 16
# Leader steps a prefetch may stay short of full votes before the swap
# is rolled back.  Prefetch is synchronous between decode steps, so
# votes normally land by the next step; a rank that died instead breaks
# the epoch long before this trips.  Generous on purpose.
DEFAULT_VOTE_TIMEOUT_STEPS = 64

# Keys under the durable ``serve`` scope (frontend.SCOPE):
ANNOUNCE_KEY = "weights"         # publisher -> fleet: newest version
VERSION_KEY = "weight_version"   # leader's durable flip record


def publish_weights(directory: str, params: Any, version: int, *,
                    kv=None, extra: Optional[dict] = None) -> str:
    """Training-side publisher: commit ``params`` as weight version
    ``version`` (the sharded-checkpoint step number; must be newer than
    every version already published — versions are totally ordered).

    ``kv``: optionally a :class:`~..run.rendezvous.KVStoreClient` bound
    to the serving job's store — the committed version is then also
    announced over the signed KV path (stamped with the job
    fingerprint, so a recycled endpoint can never advertise a stale
    job's weights), which spares the serving leader a directory listing
    per poll and works when the publisher's clock beats the fleet's
    filesystem cache.  The manifest on disk remains the source of
    truth; an announce for a version the directory cannot serve is
    simply rolled back by the prefetch votes."""
    version = int(version)
    if version < 1:
        raise ValueError(
            f"weight version must be >= 1 (0 is the fleet's built-in "
            f"init-params version); got {version}"
        )
    path = save_sharded(
        directory, params, version, rank=0, world_size=1,
        extra={"weight_version": version, **(extra or {})},
    )
    if kv is not None:
        from .frontend import SCOPE  # noqa: PLC0415 - avoid cycle

        kv.put(SCOPE, ANNOUNCE_KEY, pickle.dumps(
            {"version": version, "fp": job_fingerprint(kv)}
        ))
    LOG.info("published weight version %d -> %s", version, path)
    return path


class SwapManager:
    """Per-rank hot-swap state rider for the serving loop.

    One instance lives for the whole serve_worker lifetime (versions
    survive epoch re-formation; staged-but-unflipped state does not).
    The leader additionally runs the poll/vote half through
    :meth:`leader_step`; every rank applies broadcasts through
    :meth:`apply`."""

    def __init__(self, directory: str, initial_params: Any, *,
                 poll_steps: int = DEFAULT_POLL_STEPS,
                 vote_timeout_steps: int = DEFAULT_VOTE_TIMEOUT_STEPS):
        self.directory = directory
        self.initial_params = initial_params
        self.poll_steps = max(int(poll_steps), 1)
        self.vote_timeout_steps = max(int(vote_timeout_steps), 2)
        self.version = 0
        self._staged: Optional[Tuple[int, Any]] = None
        # Leader-only: version awaiting votes, and the step the
        # prefetch broadcast went out (for the vote timeout).
        self._pending: Optional[int] = None
        self._pending_step = 0
        # Versions that failed a swap this epoch: do not re-offer them
        # until the epoch changes or a NEWER version appears, or a bad
        # checkpoint would be retried every poll forever.
        self._rejected: set = set()

    # --------------------------------------------------------- versions

    def load(self, version: int, target: Any) -> Any:
        """Version ``v``'s full param tree: the seed-derived init
        params for 0, the checksummed manifest reassembly otherwise
        (``target`` supplies the structure the manifest is validated
        against — a wrong-model checkpoint fails here, loudly)."""
        if version == 0:
            return self.initial_params
        return restore_sharded(self.directory, target=target,
                               step=version)

    def ensure_version(self, engine, version: int) -> None:
        """Epoch-start convergence: make this rank serve exactly
        ``version`` (the recovery doc's durable record).  A survivor
        already there pays nothing; a fresh respawn (or a survivor the
        flip never reached) loads it from the manifest."""
        version = int(version)
        if version == self.version:
            get_registry().gauge("serve.weight_version").set(version)
            return
        params = self.load(version, engine.params)
        engine.set_params(params)
        LOG.info("converged on weight version %d (was %d)",
                 version, self.version)
        self.version = version
        get_registry().gauge("serve.weight_version").set(version)

    def reset_epoch(self) -> None:
        """A world break abandons any in-progress swap: staged trees
        and pending votes are epoch-local (the votes' KV keys are
        epoch-scoped, so they die with the scope)."""
        self._staged = None
        self._pending = None
        self._rejected = set()

    # ------------------------------------------------------ leader half

    def poll_candidate(self, kv) -> Optional[int]:
        """Newest publishable version strictly above the incumbent, or
        None — from the signed KV announce when present (and stamped
        with THIS job's fingerprint), else from the directory listing.
        Shared data only: every rank WOULD reach the same answer; only
        the leader asks, and broadcasts what it found."""
        candidate: Optional[int] = None
        raw = kv.get(_scope(), ANNOUNCE_KEY)
        if raw is not None:
            try:
                doc = pickle.loads(raw)
                if doc.get("fp") == job_fingerprint(kv):
                    v = int(doc["version"])
                    if v > self.version:
                        candidate = v
            except Exception:
                LOG.warning("malformed weights announce; ignoring")
        disk = latest_step(self.directory, newer_than=self.version)
        if disk is not None and (candidate is None or disk > candidate):
            candidate = disk
        if candidate is not None and candidate in self._rejected:
            return None
        return candidate

    def leader_step(self, kv, scope: str, world, step: int
                    ) -> Optional[dict]:
        """The leader's per-step swap contribution to the schedule
        broadcast (sdoc["swap"]), or None.  Exactly one of
        prefetch/flip/abort per step."""
        if self._pending is not None:
            v = self._pending
            votes = {}
            for r in world:
                raw = kv.get(scope, f"swapok_{v}_{r}")
                if raw is None:
                    break
                votes[r] = raw == b"ok"
            if len(votes) == len(world):
                self._pending = None
                if all(votes.values()):
                    # Durable record FIRST, broadcast second: a death
                    # between the two leaves a recorded version nobody
                    # flipped to — epoch recovery then loads it
                    # everywhere, which is still exactly one version.
                    kv.put(_scope(), VERSION_KEY, str(v).encode())
                    return {"phase": "flip", "version": v}
                self._rejected.add(v)
                return {"phase": "abort", "version": v}
            if step - self._pending_step > self.vote_timeout_steps:
                self._pending = None
                self._rejected.add(v)
                LOG.warning(
                    "weight version %d prefetch votes incomplete after "
                    "%d steps; rolling back", v, self.vote_timeout_steps,
                )
                return {"phase": "abort", "version": v}
            return None
        if step % self.poll_steps == 0:
            v = self.poll_candidate(kv)
            if v is not None:
                self._pending = v
                self._pending_step = step
                return {"phase": "prefetch", "version": v}
        return None

    # ------------------------------------------------------- every rank

    def prefetch(self, version: int, target: Any) -> bool:
        """Stage version ``v`` host-side; False (never raises) on any
        doubt — a torn shard, a checksum mismatch, a manifest from a
        different model — so the vote can roll the fleet back."""
        t0 = time.monotonic()
        try:
            params = self.load(version, target)
        except (ShardCorruptError, FileNotFoundError, ValueError,
                RuntimeError, OSError) as exc:
            LOG.warning("weight version %d prefetch failed: %s",
                        version, exc)
            get_registry().counter("serve.swap_prefetch_failures").inc()
            self._staged = None
            return False
        self._staged = (int(version), params)
        get_registry().histogram("serve.swap_prefetch_ms").observe(
            (time.monotonic() - t0) * 1e3
        )
        return True

    def apply(self, swap_doc: dict, engine, kv, scope: str, rank: int,
              epoch: int, step: int) -> None:
        """Obey one broadcast swap transition (every rank, leader
        included — the leader votes through the same keys)."""
        reg = get_registry()
        phase = swap_doc["phase"]
        version = int(swap_doc["version"])
        if phase == "prefetch":
            ok = self.prefetch(version, engine.params)
            kv.put(scope, f"swapok_{version}_{rank}",
                   b"ok" if ok else b"fail")
        elif phase == "flip":
            # Chaos point: die between a successful prefetch and the
            # version flip — the single-version convergence window.
            # os._exit (no cleanup, no atexit): the injected death must
            # look like a hard mid-swap crash.
            if maybe_fail("swap_commit", step=step,
                          rank=rank) == "swap_abort":
                os._exit(DEFAULT_EXIT_CODE)
            if self._staged is not None and self._staged[0] == version:
                params = self._staged[1]
            else:
                # Defensive slow path (cannot happen under the vote
                # protocol: flip only follows this rank's ok vote):
                # correctness over latency.
                params = self.load(version, engine.params)
            engine.set_params(params)
            self._staged = None
            self.version = version
            reg.gauge("serve.weight_version").set(version)
            reg.counter("serve.swaps", outcome="committed").inc()
            obs_flightrec.record(
                "init", name="weight_swap", cycle=epoch,
                detail=f"v{version} at step {step}",
            )
            LOG.info("flipped to weight version %d at epoch %d step %d",
                     version, epoch, step)
        elif phase == "abort":
            self._staged = None
            self._rejected.add(version)
            reg.counter("serve.swaps", outcome="rollback").inc()
            LOG.warning(
                "weight version %d rolled back at epoch %d step %d; "
                "serving stays on v%d", version, epoch, step,
                self.version,
            )


def _scope() -> str:
    from .frontend import SCOPE  # noqa: PLC0415 - avoid import cycle

    return SCOPE
