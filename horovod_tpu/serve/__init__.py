"""Serving plane: continuous-batching inference on the training engine.

The "millions of users" half of the north star (ROADMAP item 2), built
out of the pieces the training stack already trusts:

* :mod:`.scheduler` — pure iteration-level admit/evict state machine
  (Orca-style) over a fixed slot pool; every rank derives the identical
  schedule (the serving HVD001 invariant).
* :mod:`.engine`    — compiled slot engine over the slot-based KV cache
  (models/decode.py): one ``decode_step`` shape for a churning mix,
  bucketed one-shot prefill for admissions.
* :mod:`.frontend`  — the sharded front door: F launcher-resident
  frontend pumps (rid-hash partitioned, heartbeat-supervised with
  takeover) totally order arrivals into per-shard durable logs over
  the launcher's HMAC-signed KV store; token streaming back to
  clients rides the same store.
* :mod:`.service`   — the SPMD serving loop on the elastic launcher
  (dead ranks respawn and replay in-flight requests from the durable
  log; zero dropped requests) and the :class:`ServeJob` python driver.
* :mod:`.paged`     — pure page allocator + per-slot block tables
  (vLLM-style paged KV): allocated bytes track tokens written,
  admission capacity is judged in free pages, and the allocator is a
  rank-deterministic state machine like the scheduler (HVD012).
* :mod:`.sampling`  — replicated per-request PRNG sampling: tokens
  keyed purely on (request id, emission index, serve seed), so
  sampled streams are identical on every rank and bit-exact across
  elastic replay.
* :mod:`.longctx`   — sequence-sharded slot caches for long-context
  requests (Ulysses all-to-all prefill, flash-merge decode).
* :mod:`.autoscale` — load-driven grow/shrink of the serving world
  through deliberately re-minted rendezvous epochs (pure
  hysteresis/cooldown/backoff policy + launcher controller).
* :mod:`.hotswap`   — live weight hot-swap from a concurrently-training
  publisher, single-version-guaranteed (poll manifest → prefetch +
  vote → version-stamped atomic flip, rollback on any doubt).

Quick start::

    from horovod_tpu.serve import ServeJob
    job = ServeJob({"size": "nano", "num_slots": 4}, np=2).start()
    rid = job.client.submit([5, 17, 3], max_new_tokens=8)
    print(job.client.result(rid)["tokens"])
    job.stop()
"""

from .autoscale import (  # noqa: F401
    AutoscaleConfig, AutoscalePolicy,
)
from .engine import SlotEngine  # noqa: F401
from .frontend import (  # noqa: F401
    FrontDoor, IngestPump, Rejection, RequestRejected, ServeClient,
    validate_request,
)
from .hotswap import SwapManager, publish_weights  # noqa: F401
from .paged import PagedKV, page_reject_reason, pages_for  # noqa: F401
from .scheduler import (  # noqa: F401
    ActiveSlot, Admission, Eviction, Request, SlotScheduler, TenantQoS,
)
from .service import DEFAULT_SPEC, ServeJob, serve_worker  # noqa: F401
