"""Long-context serving: sequence-sharded slot caches over the mesh.

A slot whose context exceeds one device's memory shards its K/V cache
along the SEQUENCE axis across a mesh axis — each device holds a
contiguous ``[b, S/P, hkv, hd]`` chunk.  Two attention schedules serve
that layout (both pinned against the replicated reference math by
tests/test_serve.py on the 8-device CPU mesh):

* :func:`ulysses_prefill_attention` — the prompt phase.  Queries exist
  at every position, so Jacobs et al.'s Ulysses reshard applies
  directly: all-to-all seq→heads, full-sequence attention on a head
  shard, all-to-all back (PAPERS.md; delegates to the existing
  ``parallel.ring_attention.ulysses_attention`` so serving and training
  share one implementation).

* :func:`sharded_decode_attention` — the decode phase.  One query per
  step makes the Ulysses reshard degenerate (an all-to-all of the whole
  cache per token), so the decode step instead computes flash-style
  partial softmax statistics ``(m, l, o)`` over the LOCAL cache chunk
  and merges them across the axis — the same online-softmax algebra
  ring_attention uses within a device, lifted to one collective
  exchange per step.  Bytes on the wire per step: O(b·h·hd), not
  O(S) — the cache never moves.

The default serving engine replicates slots (engine.py); this module is
the layout the engine grows into when a deployment pins
``HVDTPU_SERVE_SEQ_SHARDS`` — docs/inference.md states the integration
status honestly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["sharded_decode_attention", "ulysses_prefill_attention"]


def ulysses_prefill_attention(q, k, v, axis_name: str, *,
                              causal: bool = True):
    """Prefill attention for sequence-sharded prompts: ``q/k/v
    [b, s_local, h, hd]`` sharded along dim 1 inside ``shard_map``.
    One all-to-all turns the layout into full-sequence × heads/P,
    attention runs per head shard, and a second all-to-all restores
    sequence sharding.

    Same schedule as ``parallel.ring_attention.ulysses_attention`` with
    the inner softmax math shared (``local_attention``); the axis-size
    probe is spelled ``psum(1)`` so the serving path runs on the pinned
    jax version the training-side copy has drifted past.
    """
    from ..parallel.ring_attention import local_attention  # noqa: PLC0415

    size = lax.psum(1, axis_name)
    h = q.shape[2]
    if h % size != 0:
        raise ValueError(
            f"ulysses_prefill_attention requires heads ({h}) divisible "
            f"by the '{axis_name}' axis size ({size})"
        )

    def seq_to_heads(x):
        # [b, s/P, h, d] -> [b, s, h/P, d]
        return lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    def heads_to_seq(x):
        return lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    out = local_attention(
        seq_to_heads(q), seq_to_heads(k), seq_to_heads(v), causal=causal
    )
    return heads_to_seq(out)


def sharded_decode_attention(cfg, q, k_shard, v_shard, pos, axis_name: str):
    """One decode query per slot against a SEQUENCE-SHARDED slot cache.

    ``q [b, h, hd]`` (replicated), ``k_shard/v_shard [b, S/P, hkv, hd]``
    (this device's contiguous chunk), ``pos [b]`` per-slot GLOBAL write
    positions.  Call inside ``shard_map`` over ``axis_name``; returns
    the replicated ``[b, h, hd]`` attention output, bitwise-stable in
    the same sense as the replicated path (fp32 softmax math).

    Masking matches ``models.decode._attend_cached`` exactly: a chunk
    position's GLOBAL index ``offset + i`` is masked when it exceeds
    the slot's ``pos`` (and when it falls below the sliding-window
    band's lower edge).  A fully-masked chunk contributes ``l = 0`` and
    drops out of the merged softmax.
    """
    b, h, hd = q.shape
    s_local = k_shard.shape[1]
    group = h // cfg.kv_heads
    idx = lax.axis_index(axis_name)
    offset = idx * s_local

    qg = q.reshape(b, cfg.kv_heads, group, hd).astype(jnp.float32)
    kf = k_shard.astype(jnp.float32)
    vf = v_shard.astype(jnp.float32)
    st = jnp.einsum("bkgd,bskd->bkgs", qg, kf) * (hd ** -0.5)
    gidx = (offset + jnp.arange(s_local))[None, None, None, :]
    pb = pos[:, None, None, None]
    mask = gidx > pb
    if cfg.attention_window is not None:
        mask = mask | (gidx < pb - (cfg.attention_window - 1))
    st = jnp.where(mask, -jnp.inf, st)

    # Flash-style partial statistics over the local chunk.  -inf rows
    # (everything masked) yield m=-inf; exp(st - m) would be NaN, so
    # clamp the subtrahend — their l is exactly 0 and the merge ignores
    # them.
    m = jnp.max(st, axis=-1)                                   # [b,k,g]
    safe_m = jnp.where(jnp.isfinite(m), m, 0.0)
    e = jnp.exp(st - safe_m[..., None])
    e = jnp.where(jnp.isfinite(st), e, 0.0)
    l = jnp.sum(e, axis=-1)                                    # [b,k,g]
    o = jnp.einsum("bkgs,bskd->bkgd", e, vf)                   # [b,k,g,d]

    # Cross-shard merge: rescale every chunk's (l, o) to the global max
    # and reduce.  One pmax + two psums of O(b·h·hd) per step.  The
    # pmax must see only CONTRIBUTING chunks' maxima: a fully-masked
    # chunk's clamped m=0.0 would otherwise dominate whenever every
    # real score is far below zero, underflowing every scale factor
    # and silently zeroing the output.
    gm = lax.pmax(jnp.where(l > 0, safe_m, -jnp.inf), axis_name)
    safe_gm = jnp.where(jnp.isfinite(gm), gm, 0.0)
    scale = jnp.where(l > 0, jnp.exp(safe_m - safe_gm), 0.0)
    gl = lax.psum(l * scale, axis_name)
    go = lax.psum(o * scale[..., None], axis_name)
    out = go / jnp.maximum(gl, 1e-30)[..., None]
    return out.reshape(b, h, hd)
